"""Shard allocation: deciders + balanced allocator + reroute.

Analogue of cluster/routing/allocation/ (SURVEY.md §2.2): AllocationService.reroute
assigns UNASSIGNED shards (primaries first), applyStartedShards moves INITIALIZING →
STARTED, applyFailedShard fails a copy (promoting a replica to primary when the primary
dies). Placement is gated by a decider chain (ref: decider/*.java — 18 deciders; the
load-bearing ones implemented):

  SameShardDecider        — never two copies of a shard on one node
  ReplicaAfterPrimary     — replicas wait for an active primary
  EnableDecider           — cluster.routing.allocation.enable = all|primaries|none
  FilterDecider           — include/exclude by node name/attrs
  AwarenessDecider        — spread copies across zones (node attr)
  ThrottlingDecider       — bounded concurrent recoveries per node
  DiskThresholdDecider    — skip nodes over the disk watermark (injected usages)

and placed by BalancedShardsAllocator: weight(node) = shard_count + index_spread factor
(ref: allocator/BalancedShardsAllocator.java's weighted balance, simplified to its two
dominant terms). Pure functions over ClusterState — unit-testable with no nodes, the
same trick as ElasticsearchAllocationTestCase (SURVEY.md §4.5).
"""

from __future__ import annotations

import fnmatch
from dataclasses import replace

from ..common.logging import get_logger
from ..common.settings import Settings
from .state import (
    INITIALIZING,
    RELOCATING,
    STARTED,
    UNASSIGNED,
    ClusterState,
    IndexRoutingTable,
    IndexShardRoutingTable,
    ShardRouting,
)

YES, NO, THROTTLE = "YES", "NO", "THROTTLE"


class Decider:
    name = "base"

    def can_allocate(self, shard: ShardRouting, node_id: str, ctx: "AllocationContext") -> str:
        return YES

    def can_rebalance(self, shard: ShardRouting, ctx: "AllocationContext") -> str:
        """May this STARTED shard start relocating at all? (target-node fitness
        is can_allocate's job — ref: AllocationDecider.canRebalance)."""
        return YES


class SameShardDecider(Decider):
    name = "same_shard"

    def can_allocate(self, shard, node_id, ctx):
        for s in ctx.shards_on_node(node_id):
            if s.shard_key() == shard.shard_key():
                return NO
        return YES


class ReplicaAfterPrimaryDecider(Decider):
    name = "replica_after_primary_active"

    def can_allocate(self, shard, node_id, ctx):
        if shard.primary:
            return YES
        group = ctx.state.routing_table.index(shard.index).shard(shard.shard_id)
        p = group.primary
        return YES if p is not None and p.active else NO


class EnableDecider(Decider):
    name = "enable"

    def can_allocate(self, shard, node_id, ctx):
        mode = ctx.settings.get_str("cluster.routing.allocation.enable", "all")
        if mode == "none":
            return NO
        if mode == "primaries" and not shard.primary:
            return NO
        if mode == "new_primaries" and not shard.primary:
            return NO
        return YES


class FilterDecider(Decider):
    name = "filter"

    def can_allocate(self, shard, node_id, ctx):
        node = ctx.state.nodes.get(node_id)
        if node is None:
            return NO
        for scope, settings in (("cluster.routing.allocation", ctx.settings),
                                (f"index.routing.allocation", ctx.index_settings(shard.index))):
            for rule, positive in (("include", True), ("require", True), ("exclude", False)):
                prefix = f"{scope}.{rule}."
                for key in settings:
                    if not key.startswith(prefix):
                        continue
                    attr = key[len(prefix):]
                    patterns = [p.strip() for p in str(settings[key]).split(",") if p.strip()]
                    value = node.name if attr == "_name" else (
                        node.id if attr == "_id" else node.attr(attr, ""))
                    matched = any(fnmatch.fnmatch(str(value), p) for p in patterns)
                    if rule == "exclude" and matched:
                        return NO
                    if rule == "require" and not matched:
                        return NO
                    if rule == "include" and patterns and not matched:
                        return NO
        return YES


class AwarenessDecider(Decider):
    name = "awareness"

    def can_allocate(self, shard, node_id, ctx):
        attrs = ctx.settings.get_list("cluster.routing.allocation.awareness.attributes")
        if not attrs:
            return YES
        node = ctx.state.nodes.get(node_id)
        if node is None:
            return NO
        group = ctx.state.routing_table.index(shard.index).shard(shard.shard_id)
        copies = group.size()
        for attr in attrs:
            values = {n.attr(attr) for n in ctx.state.nodes.data_nodes() if n.attr(attr)}
            if not values:
                continue
            per_zone_cap = -(-copies // len(values))  # ceil
            my_zone = node.attr(attr)
            in_zone = sum(
                1 for s in group.assigned_shards()
                if s.node_id != shard.node_id
                and (n := ctx.state.nodes.get(s.node_id)) is not None
                and n.attr(attr) == my_zone
            )
            if in_zone >= per_zone_cap:
                return NO
        return YES


class ThrottlingDecider(Decider):
    name = "throttling"

    def can_allocate(self, shard, node_id, ctx):
        limit = ctx.settings.get_int(
            "cluster.routing.allocation.node_concurrent_recoveries", 2)
        initializing = sum(
            1 for s in ctx.shards_on_node(node_id) if s.state == INITIALIZING
        )
        return THROTTLE if initializing >= limit else YES


class DiskThresholdDecider(Decider):
    name = "disk_threshold"

    def can_allocate(self, shard, node_id, ctx):
        if not ctx.settings.get_bool("cluster.routing.allocation.disk.threshold_enabled", True):
            return YES
        usage = ctx.disk_usages.get(node_id)
        if usage is None:
            return YES
        high = ctx.settings.get_float("cluster.routing.allocation.disk.watermark.high", 0.90)
        return NO if usage >= high else YES


class ShardsLimitDecider(Decider):
    """ref: ShardsLimitAllocationDecider.java — per-index cap on shards per
    node (index.routing.allocation.total_shards_per_node, -1 = unlimited)."""

    name = "shards_limit"

    def can_allocate(self, shard, node_id, ctx):
        limit = ctx.index_settings(shard.index).get_int(
            "index.routing.allocation.total_shards_per_node", -1)
        if limit is None or limit <= 0:
            return YES
        on_node = sum(1 for s in ctx.shards_on_node(node_id)
                      if s.index == shard.index)
        return NO if on_node >= limit else YES


class SnapshotInProgressDecider(Decider):
    """ref: SnapshotInProgressAllocationDecider.java — a shard whose index is
    being snapshotted must not move (the snapshot streams the primary's store;
    relocation would yank the files out from under it)."""

    name = "snapshot_in_progress"

    def can_rebalance(self, shard, ctx):
        return NO if shard.index in ctx.snapshotting else YES

    def can_allocate(self, shard, node_id, ctx):
        # new UNASSIGNED copies are fine (they recover from the primary without
        # moving it); only the relocation of an existing copy is gated, which
        # can_rebalance already covers — mirror the reference's scope
        return YES


class NodeVersionDecider(Decider):
    """ref: NodeVersionAllocationDecider.java — during a rolling upgrade a
    replica must never land on an OLDER node than its primary's: segments only
    stream forward-compatibly."""

    name = "node_version"

    def can_allocate(self, shard, node_id, ctx):
        target = ctx.state.nodes.get(node_id)
        if target is None:
            return NO
        if shard.primary:
            return YES
        group = ctx.state.routing_table.index(shard.index).shard(shard.shard_id)
        p = group.primary
        if p is None or not p.assigned:
            return YES
        pnode = ctx.state.nodes.get(p.node_id)
        if pnode is None:
            return YES
        return NO if target.version_id < pnode.version_id else YES


class ClusterRebalanceDecider(Decider):
    """ref: ClusterRebalanceAllocationDecider.java —
    cluster.routing.allocation.allow_rebalance:
      always | indices_primaries_active | indices_all_active (default)."""

    name = "cluster_rebalance"

    def can_rebalance(self, shard, ctx):
        mode = ctx.settings.get_str(
            "cluster.routing.allocation.allow_rebalance", "indices_all_active")
        if mode == "always":
            return YES
        shards = list(ctx.state.routing_table.all_shards())
        if mode == "indices_primaries_active":
            ok = all(s.active for s in shards if s.primary)
        else:  # indices_all_active
            ok = all(s.active for s in shards)
        return YES if ok else NO


class ConcurrentRebalanceDecider(Decider):
    """ref: ConcurrentRebalanceAllocationDecider.java —
    cluster.routing.allocation.cluster_concurrent_rebalance (default 2)
    bounds in-flight relocations cluster-wide."""

    name = "concurrent_rebalance"

    def can_rebalance(self, shard, ctx):
        limit = ctx.settings.get_int(
            "cluster.routing.allocation.cluster_concurrent_rebalance", 2)
        if limit is None or limit < 0:
            return YES
        relocating = sum(1 for s in ctx.state.routing_table.all_shards()
                         if s.state == RELOCATING)
        return THROTTLE if relocating >= limit else YES


DEFAULT_DECIDERS = (
    SameShardDecider(),
    ReplicaAfterPrimaryDecider(),
    EnableDecider(),
    FilterDecider(),
    AwarenessDecider(),
    ThrottlingDecider(),
    DiskThresholdDecider(),
    ShardsLimitDecider(),
    SnapshotInProgressDecider(),
    NodeVersionDecider(),
    ClusterRebalanceDecider(),
    ConcurrentRebalanceDecider(),
)


class AllocationContext:
    def __init__(self, state: ClusterState, settings: Settings,
                 disk_usages: dict | None = None,
                 snapshotting: set | None = None):
        self.state = state
        self.settings = settings
        self.disk_usages = disk_usages or {}
        self.snapshotting = snapshotting or set()  # index names being snapshotted
        self._by_node: dict[str, list[ShardRouting]] = {}
        for s in state.routing_table.all_shards():
            if s.node_id:
                self._by_node.setdefault(s.node_id, []).append(s)

    def shards_on_node(self, node_id: str) -> list[ShardRouting]:
        return self._by_node.get(node_id, [])

    def index_settings(self, index: str) -> Settings:
        meta = self.state.metadata.index(index)
        return meta.settings if meta else Settings.EMPTY

    def replace_shard(self, old: ShardRouting, new: ShardRouting):
        if old.node_id:
            lst = self._by_node.get(old.node_id, [])
            if old in lst:
                lst.remove(old)
        if new.node_id:
            self._by_node.setdefault(new.node_id, []).append(new)


class AllocationService:
    """ref: AllocationService.java:52 — reroute/applyStartedShards/applyFailedShard."""

    def __init__(self, settings: Settings | None = None, deciders=DEFAULT_DECIDERS):
        self.settings = settings or Settings.EMPTY
        self.deciders = deciders
        self.logger = get_logger("cluster.allocation")
        self.disk_usages: dict[str, float] = {}
        # index names with a snapshot in flight (SnapshotsService maintains;
        # read by SnapshotInProgressDecider)
        self.snapshotting_indices: set[str] = set()

    # --- decider chain ------------------------------------------------------
    def _decide(self, shard: ShardRouting, node_id: str, ctx: AllocationContext) -> str:
        throttled = False
        for d in self.deciders:
            v = d.can_allocate(shard, node_id, ctx)
            if v == NO:
                return NO
            if v == THROTTLE:
                throttled = True
        return THROTTLE if throttled else YES

    def _decide_rebalance(self, shard: ShardRouting, ctx: AllocationContext) -> str:
        throttled = False
        for d in self.deciders:
            v = d.can_rebalance(shard, ctx)
            if v == NO:
                return NO
            if v == THROTTLE:
                throttled = True
        return THROTTLE if throttled else YES

    # --- weight (BalancedShardsAllocator, simplified) -----------------------
    @staticmethod
    def _weight(ctx: AllocationContext, node_id: str, index: str) -> float:
        shards_on = len(ctx.shards_on_node(node_id))
        index_on = sum(1 for s in ctx.shards_on_node(node_id) if s.index == index)
        return 0.45 * shards_on + 0.55 * index_on

    # --- operations ---------------------------------------------------------
    def reroute(self, state: ClusterState) -> ClusterState:
        """Assign as many UNASSIGNED shards as deciders allow (primaries
        first), then consider REBALANCING started replicas from heavy nodes to
        light ones (ref: BalancedShardsAllocator.balance, gated by the
        can_rebalance chain)."""
        ctx = AllocationContext(state, self._merged_settings(state),
                                self.disk_usages, self.snapshotting_indices)
        data_nodes = [n.id for n in state.nodes.data_nodes()]
        if not data_nodes:
            return state
        new_tables: dict[str, list[list[ShardRouting]]] = {}
        changed = False
        for name, table in state.routing_table.indices:
            groups = []
            for grp in table.shards:
                shards = list(grp.shards)
                for order in (True, False):  # primaries first, then replicas
                    for i, s in enumerate(shards):
                        if s.state != UNASSIGNED or s.primary != order:
                            continue
                        candidates = [
                            nid for nid in data_nodes
                            if self._decide(s, nid, ctx) == YES
                        ]
                        if not candidates:
                            continue
                        best = min(candidates,
                                   key=lambda nid: (self._weight(ctx, nid, s.index), nid))
                        new = replace(s, node_id=best, state=INITIALIZING,
                                      unassigned_reason=None)
                        shards[i] = new
                        ctx.replace_shard(s, new)
                        changed = True
                groups.append(shards)
            new_tables[name] = groups
        changed = self._rebalance(ctx, data_nodes, new_tables) or changed
        if not changed:
            return state
        return self._rebuild(state, new_tables)

    def _rebalance(self, ctx: AllocationContext, data_nodes: list,
                   new_tables: dict) -> bool:
        """One relocation per reroute when the node weights are lopsided:
        the heaviest node's most movable STARTED replica relocates to the
        lightest node (source → RELOCATING, a target copy INITIALIZING with
        relocating_node back-pointers — the reference's relocation pair).
        Primaries stay put (a deliberate simplification: primary relocation
        needs dual-primary handling the write path doesn't model)."""
        if len(data_nodes) < 2:
            return False
        threshold = ctx.settings.get_float(
            "cluster.routing.allocation.balance.threshold", 1.0)
        counts = {nid: len(ctx.shards_on_node(nid)) for nid in data_nodes}
        heavy = max(data_nodes, key=lambda n: (counts[n], n))
        light = min(data_nodes, key=lambda n: (counts[n], n))
        if counts[heavy] - counts[light] <= max(threshold, 1.0):
            return False
        for name, groups in new_tables.items():
            for shards in groups:
                for i, s in enumerate(shards):
                    if (s.state != STARTED or s.primary or s.node_id != heavy
                            or s.relocating_node is not None):
                        continue
                    if self._decide_rebalance(s, ctx) != YES:
                        continue
                    if self._decide(s, light, ctx) != YES:
                        continue
                    shards[i] = replace(s, state=RELOCATING,
                                        relocating_node=light)
                    target = replace(s, node_id=light, state=INITIALIZING,
                                     relocating_node=heavy)
                    shards.append(target)
                    ctx.replace_shard(s, shards[i])
                    ctx._by_node.setdefault(light, []).append(target)
                    return True
        return False

    def apply_started_shards(self, state: ClusterState, started: list[ShardRouting]) -> ClusterState:
        keys = {(s.index, s.shard_id, s.node_id) for s in started}
        new_tables = {}
        changed = False
        for name, table in state.routing_table.indices:
            groups = []
            for grp in table.shards:
                shards = []
                drop_relocation_sources = set()  # node ids whose handoff completed
                for s in grp.shards:
                    if s.state == INITIALIZING and (s.index, s.shard_id, s.node_id) in keys:
                        if s.relocating_node is not None:
                            # relocation target caught up: it takes over and the
                            # RELOCATING source copy retires (ref: routing
                            # relocation completion)
                            drop_relocation_sources.add(s.relocating_node)
                        shards.append(replace(s, state=STARTED,
                                              relocating_node=None))
                        changed = True
                    else:
                        shards.append(s)
                if drop_relocation_sources:
                    shards = [s for s in shards
                              if not (s.state == RELOCATING
                                      and s.node_id in drop_relocation_sources)]
                groups.append(shards)
            new_tables[name] = groups
        if not changed:
            return state
        return self.reroute(self._rebuild(state, new_tables))

    def apply_failed_shard(self, state: ClusterState, failed: ShardRouting) -> ClusterState:
        """Remove the failed copy; promote an active replica when a primary dies;
        schedule a fresh UNASSIGNED copy (ref: AllocationService.applyFailedShard:91).
        Relocation pairs unwind: a failed TARGET reverts its source to STARTED;
        a failed SOURCE also drops its half-recovered target."""
        new_tables = {}
        for name, table in state.routing_table.indices:
            groups = []
            for grp in table.shards:
                shards = list(grp.shards)
                hit = next((s for s in shards
                            if (s.index, s.shard_id, s.node_id)
                            == (failed.index, failed.shard_id, failed.node_id)), None)
                if (hit is not None and hit.state == INITIALIZING
                        and hit.relocating_node is not None):
                    # failed relocation target: revert the source, drop the target
                    shards = [
                        (replace(s, state=STARTED, relocating_node=None)
                         if s.state == RELOCATING and s.node_id == hit.relocating_node
                         else s)
                        for s in shards if s is not hit
                    ]
                    groups.append(shards)
                    continue
                if (hit is not None and hit.state == RELOCATING
                        and hit.relocating_node is not None):
                    # failed relocation source: its half-recovered target dies too
                    shards = [s for s in shards
                              if not (s.state == INITIALIZING
                                      and s.node_id == hit.relocating_node)]
                for i, s in enumerate(shards):
                    if (s.index, s.shard_id, s.node_id) == (failed.index, failed.shard_id, failed.node_id):
                        was_primary = s.primary
                        shards[i] = replace(s, node_id=None, state=UNASSIGNED,
                                            primary=False, unassigned_reason="failed")
                        if was_primary:
                            promoted = False
                            for j, r in enumerate(shards):
                                if j != i and r.active and not r.primary:
                                    shards[j] = replace(r, primary=True)
                                    promoted = True
                                    break
                            if not promoted:
                                # no live replica: the unassigned copy becomes the primary
                                shards[i] = replace(shards[i], primary=True)
                groups.append(shards)
            new_tables[name] = groups
        return self.reroute(self._rebuild(state, new_tables))

    def remove_node(self, state: ClusterState, node_id: str) -> ClusterState:
        """Node left/died: every shard on it fails (ref: node-leave handling)."""
        for s in list(state.routing_table.all_shards()):
            if s.node_id == node_id:
                state = self.apply_failed_shard(state, s)
        return state

    # --- helpers ------------------------------------------------------------
    def _merged_settings(self, state: ClusterState) -> Settings:
        return self.settings.merged(
            Settings.from_flat(dict(state.metadata.persistent_settings))
        ).merged(Settings.from_flat(dict(state.metadata.transient_settings)))

    @staticmethod
    def _rebuild(state: ClusterState, new_tables: dict) -> ClusterState:
        rt = state.routing_table
        for name, groups in new_tables.items():
            rt = rt.with_index(IndexRoutingTable(
                name, tuple(IndexShardRoutingTable(tuple(g)) for g in groups)))
        return state.next_version(routing_table=rt)


def new_index_routing(index: str, num_shards: int, num_replicas: int) -> IndexRoutingTable:
    groups = []
    for sid in range(num_shards):
        shards = [ShardRouting(index, sid, None, True, UNASSIGNED,
                               unassigned_reason="index_created")]
        for _ in range(num_replicas):
            shards.append(ShardRouting(index, sid, None, False, UNASSIGNED,
                                       unassigned_reason="index_created"))
        groups.append(IndexShardRoutingTable(tuple(shards)))
    return IndexRoutingTable(index, tuple(groups))
