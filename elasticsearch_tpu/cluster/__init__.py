from .state import (  # noqa: F401
    ClusterState,
    ClusterBlocks,
    DiscoveryNode,
    DiscoveryNodes,
    IndexMetaData,
    IndexRoutingTable,
    IndexShardRoutingTable,
    MetaData,
    RoutingTable,
    ShardRouting,
    UNASSIGNED,
    INITIALIZING,
    STARTED,
    RELOCATING,
)
from .routing import OperationRouting, djb2_hash  # noqa: F401
from .allocation import AllocationService  # noqa: F401
from .service import ClusterService  # noqa: F401
