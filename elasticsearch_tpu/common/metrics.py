"""Metrics primitives: counters, means, meters, EWMA, histograms.

Analogue of common/metrics/{CounterMetric,MeanMetric,MeterMetric,EWMA}.java. Thread-safe
via a lock per metric (the reference uses LongAdder/atomics). `HistogramMetric`
adds what the mean-only metrics cannot answer — tail percentiles (p50/p95/p99)
over fixed log-spaced buckets, lock-STRIPED so concurrent pool threads don't
serialize on one hot lock."""

from __future__ import annotations

import bisect
import math
import threading
import time


class CounterMetric:
    __slots__ = ("_lock", "_count")

    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0

    def inc(self, n: int = 1):
        with self._lock:
            self._count += n

    def dec(self, n: int = 1):
        with self._lock:
            self._count -= n

    @property
    def count(self) -> int:
        return self._count


class MeanMetric:
    """Tracks (count, sum) — e.g. query count + total time."""

    __slots__ = ("_lock", "_count", "_sum")

    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0
        self._sum = 0.0

    def inc(self, value: float):
        with self._lock:
            self._count += 1
            self._sum += value

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    @property
    def mean(self) -> float:
        return self._sum / self._count if self._count else 0.0


class EWMA:
    """Exponentially-weighted moving average (ref: common/metrics/EWMA.java)."""

    def __init__(self, alpha: float, interval_s: float):
        self._alpha = alpha
        self._interval = interval_s
        self._rate = 0.0
        self._uncounted = 0
        self._initialized = False
        self._lock = threading.Lock()

    @classmethod
    def one_minute(cls, tick_s: float = 5.0) -> "EWMA":
        return cls(1 - math.exp(-tick_s / 60.0), tick_s)

    def update(self, n: int = 1):
        with self._lock:
            self._uncounted += n

    def tick(self):
        with self._lock:
            instant_rate = self._uncounted / self._interval
            self._uncounted = 0
            if self._initialized:
                self._rate += self._alpha * (instant_rate - self._rate)
            else:
                self._rate = instant_rate
                self._initialized = True

    @property
    def rate(self) -> float:
        return self._rate


class MeterMetric:
    """Throughput meter with 1m EWMA (ref: common/metrics/MeterMetric.java)."""

    def __init__(self):
        self._counter = CounterMetric()
        self._ewma = EWMA.one_minute()
        self._start = time.monotonic()
        self._last_tick = self._start

    def mark(self, n: int = 1):
        self._counter.inc(n)
        self._ewma.update(n)
        now = time.monotonic()
        if now - self._last_tick >= 5.0:
            self._ewma.tick()
            self._last_tick = now

    @property
    def count(self) -> int:
        return self._counter.count

    @property
    def one_minute_rate(self) -> float:
        return self._ewma.rate

    @property
    def mean_rate(self) -> float:
        elapsed = time.monotonic() - self._start
        return self._counter.count / elapsed if elapsed > 0 else 0.0


class _HistogramStripe:
    """One stripe of a HistogramMetric: its own lock + counts. A thread maps
    to a stripe by identity, so concurrent observers mostly touch distinct
    locks (the LongAdder idea, sized for ~10s of pool threads)."""

    __slots__ = ("lock", "counts", "count", "sum")

    def __init__(self, n_buckets: int):
        self.lock = threading.Lock()
        self.counts = [0] * n_buckets
        self.count = 0
        self.sum = 0.0


class HistogramMetric:
    """Latency histogram over fixed log-spaced buckets (seconds).

    Default bounds double from 100µs to ~105s (21 bounds + overflow), which
    holds any serving-path latency this node can legally produce at <2x
    relative error per bucket — enough for p50/p95/p99 operator questions
    ("slow because queued or slow because device?") without per-sample
    storage. Percentiles interpolate linearly inside the winning bucket.

    Lock-striped: `observe` takes exactly one leaf stripe lock (never blocks,
    never dispatches — safe anywhere the TPU004/TPU011 rules reach);
    `snapshot`/`percentile` sum across stripes.
    """

    DEFAULT_BOUNDS = tuple(1e-4 * (2.0 ** i) for i in range(21))
    STRIPES = 8

    __slots__ = ("_bounds", "_stripes")

    def __init__(self, bounds=None):
        self._bounds = tuple(bounds) if bounds is not None else self.DEFAULT_BOUNDS
        n = len(self._bounds) + 1  # + overflow (+Inf) bucket
        self._stripes = [_HistogramStripe(n) for _ in range(self.STRIPES)]

    def observe(self, seconds: float) -> None:
        v = max(0.0, float(seconds))
        idx = bisect.bisect_left(self._bounds, v)
        # NOT `ident % STRIPES`: on glibc get_ident() is the page-aligned
        # pthread descriptor address, so the low bits are identical for every
        # thread and all observers would alias one stripe — shift past the
        # alignment before folding
        stripe = self._stripes[(threading.get_ident() >> 12) % self.STRIPES]
        with stripe.lock:
            stripe.counts[idx] += 1
            stripe.count += 1
            stripe.sum += v

    def snapshot(self) -> tuple[list[int], int, float]:
        """(per-bucket counts incl. overflow, total count, value sum)."""
        counts = [0] * (len(self._bounds) + 1)
        total = 0
        vsum = 0.0
        for stripe in self._stripes:
            with stripe.lock:
                for i, c in enumerate(stripe.counts):
                    counts[i] += c
                total += stripe.count
                vsum += stripe.sum
        return counts, total, vsum

    @property
    def count(self) -> int:
        return self.snapshot()[1]

    @property
    def sum(self) -> float:
        return self.snapshot()[2]

    def percentile(self, q: float) -> float:
        """q in (0,1] → seconds; 0.0 when empty."""
        counts, total, _ = self.snapshot()
        return self._percentile_from(counts, total, q)

    def _percentile_from(self, counts, total, q: float) -> float:
        if total <= 0:
            return 0.0
        target = q * total
        cum = 0
        for i, c in enumerate(counts):
            if c == 0:
                continue
            if cum + c >= target:
                lo = self._bounds[i - 1] if i > 0 else 0.0
                hi = self._bounds[i] if i < len(self._bounds) \
                    else self._bounds[-1] * 2.0
                return lo + (hi - lo) * (target - cum) / c
            cum += c
        return self._bounds[-1] * 2.0

    def stats(self) -> dict:
        """Summary for /_nodes/stats: count + mean/p50/p95/p99 in ms."""
        counts, total, vsum = self.snapshot()
        return {
            "count": total,
            "mean_ms": round(vsum / total * 1000.0, 3) if total else 0.0,
            "p50_ms": round(self._percentile_from(counts, total, 0.50) * 1000.0, 3),
            "p95_ms": round(self._percentile_from(counts, total, 0.95) * 1000.0, 3),
            "p99_ms": round(self._percentile_from(counts, total, 0.99) * 1000.0, 3),
        }

    def cumulative(self) -> tuple[list[tuple[float, int]], int, float]:
        """Prometheus view: ([(le_bound_seconds, cumulative_count)...] with a
        final (inf, total), total count, value sum)."""
        counts, total, vsum = self.snapshot()
        out = []
        cum = 0
        for bound, c in zip(self._bounds, counts):
            cum += c
            out.append((bound, cum))
        out.append((float("inf"), total))
        return out, total, vsum


class StopWatch:
    """Simple phase timer (ref: common/StopWatch.java) used by benches."""

    def __init__(self, name: str = ""):
        self.name = name
        self.tasks: list[tuple[str, float]] = []
        self._current: str | None = None
        self._start = 0.0

    def start(self, task: str = ""):
        self._current = task
        self._start = time.monotonic()
        return self

    def stop(self):
        assert self._current is not None
        self.tasks.append((self._current, time.monotonic() - self._start))
        self._current = None
        return self

    def total_time(self) -> float:
        return sum(t for _, t in self.tasks)
