"""Metrics primitives: counters, means, meters, EWMA.

Analogue of common/metrics/{CounterMetric,MeanMetric,MeterMetric,EWMA}.java. Thread-safe
via a lock per metric (the reference uses LongAdder/atomics)."""

from __future__ import annotations

import math
import threading
import time


class CounterMetric:
    __slots__ = ("_lock", "_count")

    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0

    def inc(self, n: int = 1):
        with self._lock:
            self._count += n

    def dec(self, n: int = 1):
        with self._lock:
            self._count -= n

    @property
    def count(self) -> int:
        return self._count


class MeanMetric:
    """Tracks (count, sum) — e.g. query count + total time."""

    __slots__ = ("_lock", "_count", "_sum")

    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0
        self._sum = 0.0

    def inc(self, value: float):
        with self._lock:
            self._count += 1
            self._sum += value

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    @property
    def mean(self) -> float:
        return self._sum / self._count if self._count else 0.0


class EWMA:
    """Exponentially-weighted moving average (ref: common/metrics/EWMA.java)."""

    def __init__(self, alpha: float, interval_s: float):
        self._alpha = alpha
        self._interval = interval_s
        self._rate = 0.0
        self._uncounted = 0
        self._initialized = False
        self._lock = threading.Lock()

    @classmethod
    def one_minute(cls, tick_s: float = 5.0) -> "EWMA":
        return cls(1 - math.exp(-tick_s / 60.0), tick_s)

    def update(self, n: int = 1):
        with self._lock:
            self._uncounted += n

    def tick(self):
        with self._lock:
            instant_rate = self._uncounted / self._interval
            self._uncounted = 0
            if self._initialized:
                self._rate += self._alpha * (instant_rate - self._rate)
            else:
                self._rate = instant_rate
                self._initialized = True

    @property
    def rate(self) -> float:
        return self._rate


class MeterMetric:
    """Throughput meter with 1m EWMA (ref: common/metrics/MeterMetric.java)."""

    def __init__(self):
        self._counter = CounterMetric()
        self._ewma = EWMA.one_minute()
        self._start = time.monotonic()
        self._last_tick = self._start

    def mark(self, n: int = 1):
        self._counter.inc(n)
        self._ewma.update(n)
        now = time.monotonic()
        if now - self._last_tick >= 5.0:
            self._ewma.tick()
            self._last_tick = now

    @property
    def count(self) -> int:
        return self._counter.count

    @property
    def one_minute_rate(self) -> float:
        return self._ewma.rate

    @property
    def mean_rate(self) -> float:
        elapsed = time.monotonic() - self._start
        return self._counter.count / elapsed if elapsed > 0 else 0.0


class StopWatch:
    """Simple phase timer (ref: common/StopWatch.java) used by benches."""

    def __init__(self, name: str = ""):
        self.name = name
        self.tasks: list[tuple[str, float]] = []
        self._current: str | None = None
        self._start = 0.0

    def start(self, task: str = ""):
        self._current = task
        self._start = time.monotonic()
        return self

    def stop(self):
        assert self._current is not None
        self.tasks.append((self._current, time.monotonic() - self._start))
        self._current = None
        return self

    def total_time(self) -> float:
        return sum(t for _, t in self.tasks)
