"""Hierarchical typed settings.

TPU-native analogue of common/settings/ImmutableSettings.java in the reference: flat
dotted keys, typed getters with defaults (`getAsInt/AsTime/AsBytesSize`), prefix slicing
(`getByPrefix`), group extraction, and a builder. Loaded from YAML + overrides by the node
(ref: node/internal/InternalSettingsPreparer.java). Dynamic (runtime-mutable) keys are
whitelisted through DynamicSettings, mirroring ClusterDynamicSettingsModule.
"""

from __future__ import annotations

import fnmatch
import json
import os
import re
from typing import Any, Callable, Iterator, Mapping

from .errors import IllegalArgumentError
from .units import parse_bytes, parse_time

_TRUE = {"true", "1", "on", "yes"}
_FALSE = {"false", "0", "off", "no"}


def _flatten_dict(obj, prefix: str, out: dict):
    if isinstance(obj, Mapping):
        for k, v in obj.items():
            _flatten_dict(v, f"{prefix}{k}." , out)
    elif isinstance(obj, (list, tuple)):
        out[prefix[:-1]] = list(obj)
    else:
        out[prefix[:-1]] = obj


class Settings(Mapping[str, Any]):
    """Immutable flat-keyed settings map with typed accessors."""

    EMPTY: "Settings"

    __slots__ = ("_map",)

    def __init__(self, data: Mapping[str, Any] | None = None):
        flat: dict[str, Any] = {}
        if data:
            _flatten_dict(dict(data), "", flat)
        object.__setattr__(self, "_map", flat)

    # Mapping protocol -------------------------------------------------------
    def __getitem__(self, key: str) -> Any:
        return self._map[key]

    def __iter__(self) -> Iterator[str]:
        return iter(self._map)

    def __len__(self) -> int:
        return len(self._map)

    def __repr__(self) -> str:
        return f"Settings({self._map!r})"

    # typed getters ----------------------------------------------------------
    def get(self, key: str, default=None):
        v = self._map.get(key, default)
        return v

    def get_str(self, key: str, default: str | None = None) -> str | None:
        v = self._map.get(key)
        return default if v is None else str(v)

    def get_int(self, key: str, default: int | None = None) -> int | None:
        v = self._map.get(key)
        if v is None:
            return default
        try:
            return int(v)
        except (TypeError, ValueError):
            raise IllegalArgumentError(f"failed to parse int setting [{key}] = [{v}]")

    def get_float(self, key: str, default: float | None = None) -> float | None:
        v = self._map.get(key)
        if v is None:
            return default
        try:
            return float(v)
        except (TypeError, ValueError):
            raise IllegalArgumentError(f"failed to parse float setting [{key}] = [{v}]")

    def get_bool(self, key: str, default: bool | None = None) -> bool | None:
        v = self._map.get(key)
        if v is None:
            return default
        if isinstance(v, bool):
            return v
        s = str(v).lower()
        if s in _TRUE:
            return True
        if s in _FALSE:
            return False
        raise IllegalArgumentError(f"failed to parse bool setting [{key}] = [{v}]")

    def get_time(self, key: str, default=None) -> float | None:
        v = self._map.get(key)
        if v is None:
            return parse_time(default) if isinstance(default, str) else default
        return parse_time(v)

    def get_bytes(self, key: str, default=None) -> int | None:
        v = self._map.get(key)
        if v is None:
            return parse_bytes(default) if isinstance(default, str) else default
        return parse_bytes(v)

    def get_list(self, key: str, default: list | None = None) -> list:
        v = self._map.get(key)
        if v is None:
            # also support key.0, key.1 style
            idx = 0
            items = []
            while f"{key}.{idx}" in self._map:
                items.append(self._map[f"{key}.{idx}"])
                idx += 1
            return items if items else (default or [])
        if isinstance(v, (list, tuple)):
            return list(v)
        return [p.strip() for p in str(v).split(",") if p.strip()]

    # structural -------------------------------------------------------------
    def by_prefix(self, prefix: str) -> "Settings":
        s = Settings()
        s._map.update({k[len(prefix):]: v for k, v in self._map.items() if k.startswith(prefix)})
        return s

    def filtered(self, predicate: Callable[[str], bool]) -> "Settings":
        s = Settings()
        s._map.update({k: v for k, v in self._map.items() if predicate(k)})
        return s

    def groups(self, prefix: str) -> dict[str, "Settings"]:
        """`groups("index.analysis.analyzer.")` → {"my_analyzer": Settings(...)}."""
        if not prefix.endswith("."):
            prefix += "."
        out: dict[str, Settings] = {}
        for k, v in self._map.items():
            if k.startswith(prefix):
                rest = k[len(prefix):]
                if "." in rest:
                    name, sub = rest.split(".", 1)
                    out.setdefault(name, Settings())._map[sub] = v
                else:
                    out.setdefault(rest, Settings())._map[""] = v
        return out

    def as_dict(self) -> dict[str, Any]:
        return dict(self._map)

    def as_structured(self) -> dict:
        """Re-nest flat keys into a tree (for REST responses)."""
        root: dict = {}
        for k, v in sorted(self._map.items()):
            parts = k.split(".")
            node = root
            for p in parts[:-1]:
                nxt = node.get(p)
                if not isinstance(nxt, dict):
                    nxt = {}
                    node[p] = nxt
                node = nxt
            node[parts[-1]] = v
        return root

    # building ---------------------------------------------------------------
    def merged(self, other: "Settings | Mapping | None") -> "Settings":
        if not other:
            return self
        s = Settings()
        s._map.update(self._map)
        if isinstance(other, Settings):
            s._map.update(other._map)
        else:
            _flatten_dict(dict(other), "", s._map)
        return s

    def without_prefix(self, prefix: str) -> "Settings":
        s = Settings()
        s._map.update({k: v for k, v in self._map.items() if not k.startswith(prefix)})
        return s

    @classmethod
    def of(cls, **kwargs) -> "Settings":
        s = cls()
        s._map.update({k.replace("__", "."): v for k, v in kwargs.items()})
        return s

    @classmethod
    def from_flat(cls, flat: Mapping[str, Any]) -> "Settings":
        s = cls()
        for k, v in flat.items():
            if isinstance(v, Mapping):
                _flatten_dict(v, k + ".", s._map)
            else:
                s._map[k] = v
        return s

    @classmethod
    def from_yaml(cls, path: str) -> "Settings":
        try:
            import yaml  # type: ignore

            with open(path) as f:
                data = yaml.safe_load(f) or {}
        except ImportError:
            with open(path) as f:
                data = _parse_simple_yaml(f.read())
        return cls(data)


def _parse_simple_yaml(text: str) -> dict:
    """Minimal YAML subset (nested maps, scalars, inline lists) — fallback when PyYAML
    is unavailable. Good enough for elasticsearch.yml-style config files."""
    root: dict = {}
    stack: list[tuple[int, dict]] = [(-1, root)]
    for raw in text.splitlines():
        if not raw.strip() or raw.lstrip().startswith("#"):
            continue
        indent = len(raw) - len(raw.lstrip())
        line = raw.strip()
        while stack and indent <= stack[-1][0]:
            stack.pop()
        parent = stack[-1][1] if stack else root
        if ":" not in line:
            continue
        key, _, val = line.partition(":")
        key, val = key.strip(), val.strip()
        if not val:
            child: dict = {}
            parent[key] = child
            stack.append((indent, child))
        else:
            if val.startswith("[") and val.endswith("]"):
                parent[key] = [p.strip().strip("'\"") for p in val[1:-1].split(",") if p.strip()]
            else:
                v = val.strip("'\"")
                parent[key] = v
    return root


Settings.EMPTY = Settings()


def prepare_settings(settings: Settings | Mapping | None = None,
                     config_path: str | None = None) -> Settings:
    """Assemble node settings: config file < explicit settings < env overrides.
    Mirrors node/internal/InternalSettingsPreparer.prepareSettings."""
    s = Settings.EMPTY
    if config_path and os.path.exists(config_path):
        s = s.merged(Settings.from_yaml(config_path))
    if settings:
        s = s.merged(settings if isinstance(settings, Settings) else Settings.from_flat(settings))
    env = os.environ.get("ESTPU_SETTINGS")
    if env:
        s = s.merged(Settings.from_flat(json.loads(env)))
    return s


class DynamicSettings:
    """Whitelist of runtime-updatable setting keys (supports * wildcards), with optional
    per-key validators. Mirrors cluster/settings/DynamicSettings.java."""

    def __init__(self):
        self._patterns: dict[str, Callable[[str, Any], str | None] | None] = {}

    def add(self, pattern: str, validator: Callable[[str, Any], str | None] | None = None):
        self._patterns[pattern] = validator
        return self

    def is_dynamic(self, key: str) -> bool:
        return any(
            key == p or fnmatch.fnmatch(key, p) or (p.endswith(".") and key.startswith(p))
            for p in self._patterns
        )

    def validate(self, key: str, value) -> str | None:
        for p, validator in self._patterns.items():
            if validator and (key == p or fnmatch.fnmatch(key, p)):
                return validator(key, value)
        return None


_INDEX_NAME_RE = re.compile(r"^[^A-Z\\/*?\"<>| ,#]+$")


def validate_index_name(name: str) -> None:
    # "_river" is the one leading-underscore exemption, exactly like the reference
    # (MetaDataCreateIndexService.validateIndexName:168 checks
    # !index.equals(riverIndexName) before rejecting '_'-prefixed names)
    if name == "_river":
        return
    if not name or name.startswith(("_", "-", "+")) or not _INDEX_NAME_RE.match(name):
        from .errors import InvalidIndexNameError

        raise InvalidIndexNameError(f"invalid index name [{name}]")
