"""XContent: pluggable content formats — JSON, SMILE, YAML, CBOR.

ref: common/xcontent/ (~4.6k LoC in the reference: XContentFactory auto-detection +
one XContent impl per format backed by Jackson). Here each format is a small
self-contained codec over Python objects:

- JSON: stdlib (the default, lenient variant handled at the REST layer)
- YAML: PyYAML safe load/dump
- CBOR: RFC 7049 encoder/decoder (major types 0-7, the JSON-compatible subset)
- SMILE: Jackson's binary JSON (":)\n" header; implemented from the published
  format spec, with shared-name/shared-value back-references DISABLED in the
  header flags — spec-allowed, and what the reference's SmileXContent generator
  writes by default for cross-version safety)

Auto-detection mirrors XContentFactory.xContent(bytes): SMILE by ":)" magic, CBOR
by the self-describe tag or a leading map/array major type, JSON by "{"/"[",
YAML by "---" or fallback.
"""

from __future__ import annotations

import json
import math
import struct

JSON, SMILE, YAML, CBOR = "json", "smile", "yaml", "cbor"

CONTENT_TYPES = {
    JSON: "application/json",
    SMILE: "application/smile",
    YAML: "application/yaml",
    CBOR: "application/cbor",
}

_SMILE_HEADER = b":)\n"


def from_content_type(ctype: str) -> str | None:
    c = (ctype or "").lower()
    if "smile" in c:
        return SMILE
    if "cbor" in c:
        return CBOR
    if "yaml" in c:
        return YAML
    if "json" in c:
        return JSON
    return None


def detect(raw: bytes) -> str:
    """Format sniffing (ref: XContentFactory.xContent(byte[]))."""
    if raw.startswith(_SMILE_HEADER):
        return SMILE
    if raw.startswith(b"\xd9\xd9\xf7"):  # CBOR self-describe tag 55799
        return CBOR
    head = raw.lstrip()[:3]
    if head[:1] in (b"{", b"["):
        return JSON
    if raw[:1] and (raw[0] >> 5) in (4, 5):
        # leading array/map major type — binary CBOR bodies from clients (no
        # printable-ASCII collision: 0x80+ is never a JSON/YAML first byte)
        return CBOR
    if head.startswith(b"---"):
        return YAML
    return JSON


def loads(raw: bytes, fmt: str):
    if fmt == JSON:
        return json.loads(raw.decode())
    if fmt == YAML:
        import yaml as _yaml

        return _yaml.safe_load(raw.decode())
    if fmt == CBOR:
        return cbor_loads(raw)
    if fmt == SMILE:
        return smile_loads(raw)
    raise ValueError(f"unknown xcontent format [{fmt}]")


def dumps(obj, fmt: str) -> bytes:
    if fmt == JSON:
        return json.dumps(obj).encode()
    if fmt == YAML:
        import yaml as _yaml

        return _yaml.safe_dump(obj, sort_keys=False).encode()
    if fmt == CBOR:
        return cbor_dumps(obj)
    if fmt == SMILE:
        return smile_dumps(obj)
    raise ValueError(f"unknown xcontent format [{fmt}]")


# ---------------------------------------------------------------------------
# CBOR (RFC 7049)
# ---------------------------------------------------------------------------


def _cbor_head(major: int, arg: int) -> bytes:
    if arg < 24:
        return bytes([(major << 5) | arg])
    if arg < 0x100:
        return bytes([(major << 5) | 24, arg])
    if arg < 0x10000:
        return bytes([(major << 5) | 25]) + arg.to_bytes(2, "big")
    if arg < 0x100000000:
        return bytes([(major << 5) | 26]) + arg.to_bytes(4, "big")
    return bytes([(major << 5) | 27]) + arg.to_bytes(8, "big")


def cbor_dumps(obj) -> bytes:
    out = bytearray()
    _cbor_enc(obj, out)
    return bytes(out)


def _cbor_enc(obj, out: bytearray):
    if obj is None:
        out.append(0xF6)
    elif obj is True:
        out.append(0xF5)
    elif obj is False:
        out.append(0xF4)
    elif isinstance(obj, int):
        if 0 <= obj < (1 << 64):
            out += _cbor_head(0, obj)
        elif -(1 << 64) <= obj < 0:
            out += _cbor_head(1, -1 - obj)
        else:  # RFC 7049 bignum: tag 2 (positive) / 3 (negative) + byte string
            n = obj if obj >= 0 else -1 - obj
            b = n.to_bytes((n.bit_length() + 7) // 8 or 1, "big")
            out += _cbor_head(6, 2 if obj >= 0 else 3)
            out += _cbor_head(2, len(b))
            out += b
    elif isinstance(obj, float):
        out.append(0xFB)
        out += struct.pack(">d", obj)
    elif isinstance(obj, bytes):
        out += _cbor_head(2, len(obj))
        out += obj
    elif isinstance(obj, str):
        b = obj.encode()
        out += _cbor_head(3, len(b))
        out += b
    elif isinstance(obj, (list, tuple)):
        out += _cbor_head(4, len(obj))
        for v in obj:
            _cbor_enc(v, out)
    elif isinstance(obj, dict):
        out += _cbor_head(5, len(obj))
        for k, v in obj.items():
            _cbor_enc(str(k), out)
            _cbor_enc(v, out)
    else:
        raise TypeError(f"cbor cannot encode {type(obj).__name__}")


def cbor_loads(raw: bytes):
    v, i = _cbor_dec(raw, 0)
    return v


def _cbor_arg(raw: bytes, i: int, info: int) -> tuple[int, int]:
    if info < 24:
        return info, i
    if info == 24:
        return raw[i], i + 1
    if info == 25:
        return int.from_bytes(raw[i: i + 2], "big"), i + 2
    if info == 26:
        return int.from_bytes(raw[i: i + 4], "big"), i + 4
    if info == 27:
        return int.from_bytes(raw[i: i + 8], "big"), i + 8
    if info == 31:
        return -1, i  # indefinite length
    raise ValueError(f"cbor: bad additional info {info}")


def _cbor_dec(raw: bytes, i: int):
    b = raw[i]
    i += 1
    major, info = b >> 5, b & 0x1F
    if major == 0:
        return _cbor_arg(raw, i, info)
    if major == 1:
        n, i = _cbor_arg(raw, i, info)
        return -1 - n, i
    if major == 2 or major == 3:
        n, i = _cbor_arg(raw, i, info)
        if n < 0:  # indefinite: concatenate chunks until break
            parts = []
            while raw[i] != 0xFF:
                p, i = _cbor_dec(raw, i)
                parts.append(p if isinstance(p, (bytes, str)) else bytes(p))
            i += 1
            joined = b"".join(p.encode() if isinstance(p, str) else p for p in parts)
            return joined.decode() if major == 3 else joined, i
        chunk = raw[i: i + n]
        i += n
        return (chunk.decode() if major == 3 else bytes(chunk)), i
    if major == 4:
        n, i = _cbor_arg(raw, i, info)
        out = []
        if n < 0:
            while raw[i] != 0xFF:
                v, i = _cbor_dec(raw, i)
                out.append(v)
            return out, i + 1
        for _ in range(n):
            v, i = _cbor_dec(raw, i)
            out.append(v)
        return out, i
    if major == 5:
        n, i = _cbor_arg(raw, i, info)
        d = {}
        if n < 0:
            while raw[i] != 0xFF:
                k, i = _cbor_dec(raw, i)
                v, i = _cbor_dec(raw, i)
                d[k] = v
            return d, i + 1
        for _ in range(n):
            k, i = _cbor_dec(raw, i)
            v, i = _cbor_dec(raw, i)
            d[k] = v
        return d, i
    if major == 6:
        tag, i = _cbor_arg(raw, i, info)
        v, i = _cbor_dec(raw, i)
        if tag == 2 and isinstance(v, bytes):  # positive bignum
            return int.from_bytes(v, "big"), i
        if tag == 3 and isinstance(v, bytes):  # negative bignum
            return -1 - int.from_bytes(v, "big"), i
        return v, i  # other tags (incl. self-describe) are transparent
    # major 7
    if info == 20:
        return False, i
    if info == 21:
        return True, i
    if info == 22 or info == 23:
        return None, i
    if info == 25:  # half float
        h = int.from_bytes(raw[i: i + 2], "big")
        i += 2
        sign = -1.0 if h & 0x8000 else 1.0
        exp = (h >> 10) & 0x1F
        frac = h & 0x3FF
        if exp == 0:
            val = frac * 2 ** -24
        elif exp == 31:
            val = math.inf if frac == 0 else math.nan
        else:
            val = (1 + frac * 2 ** -10) * 2 ** (exp - 15)
        return sign * val, i
    if info == 26:
        return struct.unpack(">f", raw[i: i + 4])[0], i + 4
    if info == 27:
        return struct.unpack(">d", raw[i: i + 8])[0], i + 8
    raise ValueError(f"cbor: bad simple value {info}")


# ---------------------------------------------------------------------------
# SMILE (Jackson binary JSON; shared references disabled)
# ---------------------------------------------------------------------------


def _smile_vint(n: int) -> bytes:
    """Smile VInt: big-endian 7-bit groups, LAST byte holds 6 bits + 0x80 marker."""
    out = [0x80 | (n & 0x3F)]
    n >>= 6
    while n:
        out.append(n & 0x7F)
        n >>= 7
    return bytes(reversed(out))


def _smile_read_vint(raw: bytes, i: int) -> tuple[int, int]:
    n = 0
    while True:
        b = raw[i]
        i += 1
        if b & 0x80:
            return (n << 6) | (b & 0x3F), i
        n = (n << 7) | b


def _zigzag(n: int) -> int:
    # arbitrary precision (Python ints are unbounded; a fixed 64-bit shift would
    # silently corrupt values beyond int64)
    return ((-n - 1) << 1) | 1 if n < 0 else n << 1


def _unzigzag(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


def _7bit_pack(data: bytes) -> bytes:
    """Big-endian 7-bits-per-byte expansion (floats travel this way in smile)."""
    n = int.from_bytes(data, "big")
    nbytes = (len(data) * 8 + 6) // 7
    return bytes((n >> (7 * (nbytes - 1 - j))) & 0x7F for j in range(nbytes))


def _7bit_unpack(chunk: bytes, nbytes: int) -> bytes:
    n = 0
    for b in chunk:
        n = (n << 7) | (b & 0x7F)
    return n.to_bytes(nbytes, "big") if nbytes else b""


def smile_dumps(obj) -> bytes:
    out = bytearray(_SMILE_HEADER)
    out.append(0x00)  # version 0; no raw binary, no shared names/values
    _smile_value(obj, out)
    return bytes(out)


def _smile_value(obj, out: bytearray):
    if obj is None:
        out.append(0x21)
    elif obj is True:
        out.append(0x23)
    elif obj is False:
        out.append(0x22)
    elif isinstance(obj, int):
        z = _zigzag(obj)
        if -16 <= obj <= 15:
            out.append(0xC0 + z)
        elif -(1 << 31) <= obj < (1 << 31):
            out.append(0x24)
            out += _smile_vint(z)
        else:
            # int64 token; beyond-64-bit values keep the same vint encoding (our
            # decoder reads it losslessly; spec BigInteger token not emitted)
            out.append(0x25)
            out += _smile_vint(z)
    elif isinstance(obj, float):
        out.append(0x29)
        out += _7bit_pack(struct.pack(">d", obj))
    elif isinstance(obj, str):
        b = obj.encode()
        is_ascii = len(b) == len(obj)
        if not obj:
            out.append(0x20)
        elif is_ascii and len(b) <= 32:
            out.append(0x40 + len(b) - 1)
            out += b
        elif is_ascii and len(b) <= 64:
            out.append(0x60 + len(b) - 33)
            out += b
        elif not is_ascii and 2 <= len(b) <= 33:
            out.append(0x80 + len(b) - 2)
            out += b
        elif not is_ascii and 34 <= len(b) <= 65:
            out.append(0xA0 + len(b) - 34)
            out += b
        else:
            out.append(0xE0 if is_ascii else 0xE4)
            out += b
            out.append(0xFC)  # string end marker
    elif isinstance(obj, (list, tuple)):
        out.append(0xF8)
        for v in obj:
            _smile_value(v, out)
        out.append(0xF9)
    elif isinstance(obj, dict):
        out.append(0xFA)
        for k, v in obj.items():
            _smile_key(str(k), out)
            _smile_value(v, out)
        out.append(0xFB)
    else:
        raise TypeError(f"smile cannot encode {type(obj).__name__}")


def _smile_key(key: str, out: bytearray):
    b = key.encode()
    is_ascii = len(b) == len(key)
    if not key:
        out.append(0x20)
    elif is_ascii and len(b) <= 64:
        out.append(0x80 + len(b) - 1)
        out += b
    elif not is_ascii and 2 <= len(b) <= 57:
        out.append(0xC0 + len(b) - 2)
        out += b
    else:
        out.append(0x34)  # long name
        out += b
        out.append(0xFC)


def smile_loads(raw: bytes):
    if not raw.startswith(_SMILE_HEADER):
        raise ValueError("not a smile document (missing :)\\n header)")
    v, _i = _smile_read_value(raw, 4)
    return v


def _smile_read_value(raw: bytes, i: int):
    t = raw[i]
    i += 1
    if t == 0x20:
        return "", i
    if t == 0x21:
        return None, i
    if t == 0x22:
        return False, i
    if t == 0x23:
        return True, i
    if t in (0x24, 0x25):
        z, i = _smile_read_vint(raw, i)
        return _unzigzag(z), i
    if t == 0x28:  # float32: 5 bytes of 7 bits
        return struct.unpack(">f", _7bit_unpack(raw[i: i + 5], 4))[0], i + 5
    if t == 0x29:  # float64: 10 bytes of 7 bits
        return struct.unpack(">d", _7bit_unpack(raw[i: i + 10], 8))[0], i + 10
    if 0x40 <= t <= 0x5F:
        n = t - 0x40 + 1
        return raw[i: i + n].decode(), i + n
    if 0x60 <= t <= 0x7F:
        n = t - 0x60 + 33
        return raw[i: i + n].decode(), i + n
    if 0x80 <= t <= 0x9F:
        n = t - 0x80 + 2
        return raw[i: i + n].decode(), i + n
    if 0xA0 <= t <= 0xBF:
        n = t - 0xA0 + 34
        return raw[i: i + n].decode(), i + n
    if 0xC0 <= t <= 0xDF:
        return _unzigzag(t - 0xC0), i
    if t in (0xE0, 0xE4):  # long string, 0xFC-terminated
        end = raw.index(0xFC, i)
        return raw[i:end].decode(), end + 1
    if t == 0xF8:
        out = []
        while raw[i] != 0xF9:
            v, i = _smile_read_value(raw, i)
            out.append(v)
        return out, i + 1
    if t == 0xFA:
        d = {}
        while raw[i] != 0xFB:
            k, i = _smile_read_key(raw, i)
            v, i = _smile_read_value(raw, i)
            d[k] = v
        return d, i + 1
    raise ValueError(f"smile: unsupported value token 0x{t:02x} at {i - 1}")


def _smile_read_key(raw: bytes, i: int):
    t = raw[i]
    i += 1
    if t == 0x20:
        return "", i
    if t == 0x34:
        end = raw.index(0xFC, i)
        return raw[i:end].decode(), end + 1
    if 0x80 <= t <= 0xBF:
        n = t - 0x80 + 1
        return raw[i: i + n].decode(), i + n
    if 0xC0 <= t <= 0xF7:
        n = t - 0xC0 + 2
        return raw[i: i + n].decode(), i + n
    raise ValueError(f"smile: unsupported key token 0x{t:02x} at {i - 1}")
