"""Retry with exponential backoff and decorrelated jitter, bounded by a deadline.

The write path's answer to a flaky interconnect (ref: the reference's
TransportShardReplicationOperationAction retry-on-cluster-state-change loop plus
the AWS architecture-blog "decorrelated jitter" schedule): transient transport
failures are retried with randomized, growing sleeps; everything else — version
conflicts, parse errors, validation — surfaces immediately, because retrying a
deterministic failure only burns the budget. The retry *budget* is a Deadline:
a retry schedule that outlives the request's time budget is worse than failing
fast, so every sleep is clamped to the remaining budget and exhaustion raises
the last transient error for the caller to report (never swallow).
"""

from __future__ import annotations

import random
import time

from .deadline import NO_DEADLINE, Deadline
from .errors import (
    ActionNotFoundError,
    ClusterBlockError,
    EngineClosedError,
    MasterNotDiscoveredError,
    NodeNotConnectedError,
    ReceiveTimeoutError,
    RejectedExecutionError,
    TransportError,
    UnavailableShardsError,
)

# Failures worth a second attempt: the remote may answer after a reconnect, a
# re-elected master, or a published cluster state. ActionNotFoundError is a
# TransportError subclass but deterministic (400) — excluded below.
# RejectedExecutionError is saturation, not breakage: the queue drains, and
# the backoff jitter is exactly what keeps the retry from re-creating the
# spike that filled it.
_TRANSIENT = (
    NodeNotConnectedError,
    ReceiveTimeoutError,
    TransportError,
    MasterNotDiscoveredError,
    UnavailableShardsError,
    EngineClosedError,
    RejectedExecutionError,
)


def is_transient(error: BaseException) -> bool:
    """Would the same call plausibly succeed against a healthier cluster?"""
    if isinstance(error, ActionNotFoundError):
        return False
    if isinstance(error, ClusterBlockError):
        return error.status == 503  # retryable blocks only (no master / recovering)
    # jax/XLA exceptions carry their own taxonomy (common/devicehealth):
    # RESOURCE_EXHAUSTED / timeout drains with pressure and is worth a backed-off
    # retry; an INTERNAL launch / transfer error is deterministic until the
    # executable or view is rebuilt — retrying it identically to a network drop
    # just burns the deadline. Lazy import: devicehealth imports RetryPolicy.
    from .devicehealth import classify_device_error

    device_cls = classify_device_error(error)
    if device_cls is not None:
        return device_cls == "transient"
    return isinstance(error, _TRANSIENT)


class RetryExhaustedError(TransportError):
    """All retry attempts failed (or the deadline ran out between them). Carries
    the last transient error as `cause` so shard-failed reports stay specific."""

    def __init__(self, message: str, *, cause: Exception | None = None,
                 attempts: int = 0):
        super().__init__(message, cause=cause)
        self.attempts = attempts


class RetryPolicy:
    """Decorrelated-jitter backoff: sleep_n = min(cap, uniform(base, 3 * sleep_{n-1})).

    Jitter is load-bearing, not cosmetic — on a replica fan-out every peer
    retries at once, and synchronized retries re-create the spike that caused
    the first failure. `rng` and `sleep` are injectable so tests pin the
    schedule without wall-clock waits.
    """

    def __init__(self, max_attempts: int = 3, base_s: float = 0.05,
                 cap_s: float = 1.0, rng: random.Random | None = None,
                 classify=is_transient, sleep=time.sleep):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        self.max_attempts = max_attempts
        self.base_s = base_s
        self.cap_s = cap_s
        self.rng = rng or random.Random()
        self.classify = classify
        self.sleep = sleep

    def next_backoff(self, prev_sleep_s: float | None) -> float:
        """One step of the decorrelated-jitter schedule. Always in
        [base_s, cap_s]; grows up to 3x the previous sleep."""
        prev = self.base_s if prev_sleep_s is None else prev_sleep_s
        return min(self.cap_s, self.rng.uniform(self.base_s,
                                                max(self.base_s, prev * 3.0)))

    def call(self, fn, *, deadline: Deadline = NO_DEADLINE, describe: str = "operation"):
        """Run `fn()` with retries. Raises the original error when it is not
        transient; raises RetryExhaustedError (cause = last transient error)
        when attempts or the deadline run out."""
        prev_sleep: float | None = None
        last_err: Exception | None = None
        made = 0  # attempts actually invoked (a pre-expired deadline makes none)
        for _ in range(self.max_attempts):
            if deadline.expired():
                break
            made += 1
            try:
                return fn()
            except Exception as e:  # noqa: BLE001 — classified right below
                if not self.classify(e):
                    raise
                last_err = e
            if made >= self.max_attempts:
                break
            prev_sleep = self.next_backoff(prev_sleep)
            pause = deadline.clamp(prev_sleep)
            if deadline.bounded and (pause is None or pause >= (deadline.remaining() or 0.0)):
                # the sleep alone would consume the whole budget — the retry
                # could never complete, so report exhaustion now
                break
            if pause:
                self.sleep(pause)
        detail = last_err if last_err is not None else \
            "deadline exhausted before any attempt"
        raise RetryExhaustedError(
            f"{describe} failed after {made} attempt(s): {detail}",
            cause=last_err, attempts=made)
