"""Binary wire/storage codec.

Analogue of common/io/stream/{StreamInput,StreamOutput}.java: variable-length ints,
length-prefixed UTF-8 strings, optional fields, maps/lists of primitives, and
version-conditional framing. Every transport request/response and every on-disk record
(translog ops, segment metadata, cluster state) goes through this codec, so a single
round-trip test covers the whole wire surface (the reference's AssertingLocalTransport
does exactly that — see SURVEY.md §4.3).
"""

from __future__ import annotations

import io
import struct
import zlib
from typing import Any

from .errors import SearchEngineError
from .tracing import TraceContext

_NULL = 0xFF


class StreamOutput:
    __slots__ = ("_buf",)

    def __init__(self):
        self._buf = io.BytesIO()

    # primitives -------------------------------------------------------------
    def write_byte(self, b: int):
        self._buf.write(bytes((b & 0xFF,)))

    def write_bool(self, v: bool):
        self.write_byte(1 if v else 0)

    def write_int(self, v: int):
        self._buf.write(struct.pack(">i", v))

    def write_long(self, v: int):
        self._buf.write(struct.pack(">q", v))

    def write_float(self, v: float):
        self._buf.write(struct.pack(">f", v))

    def write_double(self, v: float):
        self._buf.write(struct.pack(">d", v))

    def write_vint(self, v: int):
        """Unsigned varint, 7 bits per byte, little-group-first (Lucene/ES style)."""
        assert v >= 0, v
        while v & ~0x7F:
            self.write_byte((v & 0x7F) | 0x80)
            v >>= 7
        self.write_byte(v)

    def write_vlong(self, v: int):
        self.write_vint(v)

    def write_zlong(self, v: int):
        """Zig-zag signed varint."""
        self.write_vint((v << 1) if v >= 0 else ((-v) << 1) - 1)

    def write_bytes(self, b: bytes):
        self.write_vint(len(b))
        self._buf.write(b)

    def write_raw(self, b: bytes):
        self._buf.write(b)

    def write_string(self, s: str):
        self.write_bytes(s.encode("utf-8"))

    def write_optional_string(self, s: str | None):
        if s is None:
            self.write_bool(False)
        else:
            self.write_bool(True)
            self.write_string(s)

    def write_string_list(self, items):
        self.write_vint(len(items))
        for s in items:
            self.write_string(s)

    # generic ----------------------------------------------------------------
    def write_value(self, v: Any):
        """Tagged any-value encoding (analogue of StreamOutput.writeGenericValue)."""
        if v is None:
            self.write_byte(_NULL)
        elif isinstance(v, bool):
            self.write_byte(0)
            self.write_bool(v)
        elif isinstance(v, int):
            self.write_byte(1)
            self.write_zlong(v)
        elif isinstance(v, float):
            self.write_byte(2)
            self.write_double(v)
        elif isinstance(v, str):
            self.write_byte(3)
            self.write_string(v)
        elif isinstance(v, bytes):
            self.write_byte(4)
            self.write_bytes(v)
        elif isinstance(v, (list, tuple)):
            self.write_byte(5)
            self.write_vint(len(v))
            for item in v:
                self.write_value(item)
        elif isinstance(v, dict):
            self.write_byte(6)
            self.write_vint(len(v))
            for k, item in v.items():
                self.write_string(str(k))
                self.write_value(item)
        elif isinstance(v, TraceContext):
            # trace context rides request payloads as a typed value, so span
            # stitching crosses BOTH transports through this one codec
            # (common/tracing.py; in-process roundtrip and tcp.py frames)
            self.write_byte(7)
            self.write_string(v.trace_id)
            self.write_vlong(v.span_id)
        else:
            raise SearchEngineError(f"cannot serialize value of type {type(v)}")

    def write_map(self, d: dict):
        self.write_value(d)

    def bytes(self) -> bytes:
        return self._buf.getvalue()

    def bytes_with_checksum(self) -> bytes:
        payload = self.bytes()
        return payload + struct.pack(">I", zlib.crc32(payload) & 0xFFFFFFFF)


class StreamInput:
    __slots__ = ("_buf", "_len")

    def __init__(self, data: bytes):
        self._buf = io.BytesIO(data)
        self._len = len(data)

    @classmethod
    def with_checksum(cls, data: bytes) -> "StreamInput":
        if len(data) < 4:
            raise SearchEngineError("truncated checksummed stream")
        payload, crc = data[:-4], struct.unpack(">I", data[-4:])[0]
        if zlib.crc32(payload) & 0xFFFFFFFF != crc:
            raise SearchEngineError("checksum mismatch on stream")
        return cls(payload)

    def _read(self, n: int) -> bytes:
        b = self._buf.read(n)
        if len(b) != n:
            raise SearchEngineError("unexpected end of stream")
        return b

    def read_byte(self) -> int:
        return self._read(1)[0]

    def read_bool(self) -> bool:
        return self.read_byte() != 0

    def read_int(self) -> int:
        return struct.unpack(">i", self._read(4))[0]

    def read_long(self) -> int:
        return struct.unpack(">q", self._read(8))[0]

    def read_float(self) -> float:
        return struct.unpack(">f", self._read(4))[0]

    def read_double(self) -> float:
        return struct.unpack(">d", self._read(8))[0]

    def read_vint(self) -> int:
        out = 0
        shift = 0
        while True:
            b = self.read_byte()
            out |= (b & 0x7F) << shift
            if not b & 0x80:
                return out
            shift += 7

    def read_vlong(self) -> int:
        return self.read_vint()

    def read_zlong(self) -> int:
        v = self.read_vint()
        return (v >> 1) if not v & 1 else -((v + 1) >> 1)

    def read_bytes(self) -> bytes:
        return self._read(self.read_vint())

    def read_string(self) -> str:
        return self.read_bytes().decode("utf-8")

    def read_optional_string(self) -> str | None:
        return self.read_string() if self.read_bool() else None

    def read_string_list(self) -> list[str]:
        return [self.read_string() for _ in range(self.read_vint())]

    def read_value(self) -> Any:
        tag = self.read_byte()
        if tag == _NULL:
            return None
        if tag == 0:
            return self.read_bool()
        if tag == 1:
            return self.read_zlong()
        if tag == 2:
            return self.read_double()
        if tag == 3:
            return self.read_string()
        if tag == 4:
            return self.read_bytes()
        if tag == 5:
            return [self.read_value() for _ in range(self.read_vint())]
        if tag == 6:
            return {self.read_string(): self.read_value() for _ in range(self.read_vint())}
        if tag == 7:
            return TraceContext(self.read_string(), self.read_vlong())
        raise SearchEngineError(f"unknown value tag {tag}")

    def read_map(self) -> dict:
        v = self.read_value()
        assert isinstance(v, dict)
        return v

    def remaining(self) -> int:
        return self._len - self._buf.tell()


class Streamable:
    """Mixin: objects that serialize through StreamOutput/StreamInput."""

    def write_to(self, out: StreamOutput) -> None:  # pragma: no cover - interface
        raise NotImplementedError

    @classmethod
    def read_from(cls, inp: StreamInput):  # pragma: no cover - interface
        raise NotImplementedError

    def to_bytes(self) -> bytes:
        out = StreamOutput()
        self.write_to(out)
        return out.bytes()

    @classmethod
    def from_bytes(cls, data: bytes):
        return cls.read_from(StreamInput(data))
