"""Bounded-memory sketches for aggregations: HyperLogLog++ and a merging t-digest.

The reference snapshot (ES 2.0.0-SNAPSHOT, early 2014) predates the cardinality and
percentiles aggregations; later Elasticsearch ships them backed by HyperLogLog++
(Heule/Nunkesser/Hall 2013) and t-digest (Dunning/Ertl), with `precision_threshold`
and `compression` knobs. This module supplies those algorithms so the aggs this
framework already exposes stop holding every distinct value / every sample in memory
(unbounded on a 1M-unique field). Implementations are numpy-vectorized originals:

- HyperLogLogPlusPlus: dense registers (one uint8 per 2^p buckets), linear counting
  for the small range, merge = register max. Hashing is a vectorized 64-bit mix
  (splitmix finalizer over 8-byte chunk folding) — stable across processes, so
  sketches can cross the wire between nodes and still merge correctly.
- TDigest: the merging-digest variant with the k1 scale function, compressed by
  binning sorted samples at unit spacing in k-space; quantile() interpolates between
  centroid means. Memory is O(compression) regardless of sample count.
"""

from __future__ import annotations

import numpy as np

# ---------------------------------------------------------------------------
# stable vectorized 64-bit hashing
# ---------------------------------------------------------------------------

_M1 = np.uint64(0xBF58476D1CE4E5B9)
_M2 = np.uint64(0x94D049BB133111EB)
_GOLDEN = np.uint64(0x9E3779B97F4A7C15)


def _mix64(x: np.ndarray) -> np.ndarray:
    """splitmix64 finalizer — full avalanche on uint64 lanes."""
    x = x.astype(np.uint64, copy=True)
    x ^= x >> np.uint64(30)
    x *= _M1
    x ^= x >> np.uint64(27)
    x *= _M2
    x ^= x >> np.uint64(31)
    return x


def hash64_ints(values: np.ndarray) -> np.ndarray:
    """Stable 64-bit hashes for an int/float array (floats hashed by bit pattern,
    so 1.0 and 1 hash differently — distinct values, matching exact-set semantics
    for numeric doc values which are collected as floats consistently)."""
    a = np.asarray(values)
    if a.dtype.kind == "f":
        a = a.astype(np.float64)
        a = np.where(a == 0.0, 0.0, a)  # -0.0 == 0.0 must hash identically
        a = a.view(np.uint64)
    else:
        a = a.astype(np.int64).view(np.uint64)
    return _mix64(a)


def hash64_strs(values) -> np.ndarray:
    """Stable 64-bit hashes for a sequence of strings, vectorized by 8-byte chunks:
    encode to a padded byte matrix, fold each uint64 lane through the mixer, finalize
    with the length so prefixes don't collide with their zero-padded extensions."""
    if len(values) == 0:
        return np.zeros(0, dtype=np.uint64)
    bs = [v.encode("utf-8") if isinstance(v, str) else bytes(v) for v in values]
    out = np.zeros(len(bs), dtype=np.uint64)
    # bucket by chunk count so one oversized outlier can't force padding every value
    # to its width (a 64 KB value among 1M short ones would allocate a 64 GB matrix)
    nchunks = np.array([-(-len(b) // 8) for b in bs], dtype=np.int64)
    for width in np.unique(nchunks):
        sel = np.nonzero(nchunks == width)[0]
        w = max(int(width), 1)
        mat = np.zeros((len(sel), w * 8), dtype=np.uint8)
        lens = np.empty(len(sel), dtype=np.uint64)
        for row, i in enumerate(sel):
            b = bs[i]
            mat[row, : len(b)] = np.frombuffer(b, dtype=np.uint8)
            lens[row] = len(b)
        chunks = mat.view(np.uint64)  # [n, w]
        h = np.full(len(sel), _GOLDEN, dtype=np.uint64)
        for j in range(chunks.shape[1]):
            h = _mix64(h ^ chunks[:, j])
        out[sel] = _mix64(h ^ lens)
    return out


# ---------------------------------------------------------------------------
# HyperLogLog++
# ---------------------------------------------------------------------------

def precision_from_threshold(threshold: int) -> int:
    """Map the user-facing `precision_threshold` knob (counts up to the threshold
    should be near-exact; later-ES default 3000) to a register precision: linear
    counting is near-exact while the load factor stays low, so pick the smallest p
    with 2^p >= 3*threshold. Clamped to [4, 18] like the later-ES knob."""
    p = 4
    while (1 << p) < threshold * 3 and p < 18:
        p += 1
    return p


class HyperLogLogPlusPlus:
    """Dense HLL++ sketch. Memory = 2^precision bytes regardless of cardinality."""

    def __init__(self, precision: int = 14):
        if not 4 <= precision <= 18:
            raise ValueError(f"precision must be in [4,18], got {precision}")
        self.p = precision
        self.m = 1 << precision
        self.registers = np.zeros(self.m, dtype=np.uint8)

    # -- ingest ------------------------------------------------------------
    def add_hashes(self, hashes: np.ndarray):
        if len(hashes) == 0:
            return
        h = hashes.astype(np.uint64, copy=False)
        p = np.uint64(self.p)
        idx = (h >> np.uint64(64 - self.p)).astype(np.int64)
        # rank = leading zeros of the remaining 64-p bits, +1; the sentinel bit
        # caps the rank at (64-p)+1 when those bits are all zero
        w = (h << p) | np.uint64(1 << (self.p - 1))
        # floor(log2(w)) changes only at powers of two, so float64 rounding of the
        # uint64 can be off only when the top 53 bits are all ones — negligible
        rank = (np.uint64(64) - np.floor(np.log2(w.astype(np.float64))).astype(np.uint64)
                ).astype(np.uint8)
        # grouped max per register via sort + reduceat (ufunc.at is ~100x slower)
        order = np.argsort(idx, kind="stable")
        idx_s, rank_s = idx[order], rank[order]
        starts = np.concatenate([[0], np.nonzero(np.diff(idx_s))[0] + 1])
        regs = idx_s[starts]
        best = np.maximum.reduceat(rank_s, starts)
        self.registers[regs] = np.maximum(self.registers[regs], best)

    def add_values(self, values):
        if isinstance(values, np.ndarray) and values.dtype.kind in "ifu":
            self.add_hashes(hash64_ints(values))
        else:
            self.add_hashes(hash64_strs(list(values)))

    # -- estimate ----------------------------------------------------------
    def cardinality(self) -> int:
        regs = self.registers.astype(np.float64)
        m = float(self.m)
        alpha = {16: 0.673, 32: 0.697, 64: 0.709}.get(self.m, 0.7213 / (1 + 1.079 / m))
        raw = alpha * m * m / np.sum(np.exp2(-regs))
        zeros = int(np.count_nonzero(self.registers == 0))
        if raw <= 2.5 * m and zeros > 0:
            return int(round(m * np.log(m / zeros)))  # linear counting
        if raw > (1 << 32) / 30.0:
            return int(round(-(1 << 32) * np.log1p(-raw / (1 << 32))))
        return int(round(raw))

    # -- merge / wire ------------------------------------------------------
    def merge(self, other: "HyperLogLogPlusPlus"):
        if other.p != self.p:
            raise ValueError("cannot merge HLL sketches of different precision")
        np.maximum(self.registers, other.registers, out=self.registers)

    def __getstate__(self):
        return {"p": self.p, "registers": self.registers}

    def __setstate__(self, state):
        self.p = state["p"]
        self.m = 1 << self.p
        self.registers = state["registers"]


# ---------------------------------------------------------------------------
# merging t-digest
# ---------------------------------------------------------------------------

class TDigest:
    """Merging t-digest (Dunning): centroid sizes bounded by the k1 scale function
    k(q) = δ/(2π)·asin(2q−1), so tail quantiles stay sharp while the middle
    compresses. ~δ/2 centroids survive compression."""

    BUFFER = 8192

    def __init__(self, compression: float = 100.0):
        self.compression = float(compression)
        self.means = np.zeros(0, dtype=np.float64)
        self.weights = np.zeros(0, dtype=np.float64)
        self._buf: list[np.ndarray] = []
        self._buf_n = 0
        self.total = 0.0
        self._min = np.inf
        self._max = -np.inf

    # -- ingest ------------------------------------------------------------
    def add_values(self, values: np.ndarray):
        v = np.asarray(values, dtype=np.float64)
        if len(v) == 0:
            return
        self._min = min(self._min, float(v.min()))
        self._max = max(self._max, float(v.max()))
        self._buf.append(v)
        self._buf_n += len(v)
        self.total += float(len(v))
        if self._buf_n >= self.BUFFER:
            self._compress()

    def _k(self, q: np.ndarray) -> np.ndarray:
        return self.compression / (2 * np.pi) * np.arcsin(2 * np.clip(q, 0.0, 1.0) - 1)

    def _compress(self):
        if self._buf:
            bmeans = np.concatenate(self._buf)
            means = np.concatenate([self.means, bmeans])
            weights = np.concatenate([self.weights, np.ones(len(bmeans))])
            self._buf, self._buf_n = [], 0
        elif len(self.means) > self.compression:
            means, weights = self.means, self.weights
        else:
            return
        order = np.argsort(means, kind="stable")
        means, weights = means[order], weights[order]
        W = weights.sum()
        # bin sorted points at unit spacing in k-space: every bin's quantile width
        # then satisfies k(q_hi) - k(q_lo) <= 1, the merging-digest size bound
        q_mid = (np.cumsum(weights) - weights / 2) / W
        # half-unit spacing in k-space: ~δ centroids (vs ~δ/2 at unit spacing),
        # which is what keeps the pareto-tail q99 error under ~1%
        bins = np.floor(2.0 * (self._k(q_mid) - self._k(np.array([0.0]))[0])).astype(np.int64)
        starts = np.concatenate([[0], np.nonzero(np.diff(bins))[0] + 1])
        wsum = np.add.reduceat(weights, starts)
        msum = np.add.reduceat(weights * means, starts)
        self.means = msum / wsum
        self.weights = wsum

    # -- query -------------------------------------------------------------
    def quantile(self, q: float) -> float | None:
        self._compress()
        if len(self.means) == 0:
            return None
        if len(self.means) == 1:
            return float(self.means[0])
        q = min(max(float(q), 0.0), 1.0)
        target = q * self.total
        cum = np.cumsum(self.weights) - self.weights / 2
        if target <= cum[0]:
            frac = target / max(cum[0], 1e-12)
            return float(self._min + (self.means[0] - self._min) * frac)
        if target >= cum[-1]:
            span = self.total - cum[-1]
            frac = (target - cum[-1]) / max(span, 1e-12)
            return float(self.means[-1] + (self._max - self.means[-1]) * frac)
        i = int(np.searchsorted(cum, target, side="right") - 1)
        frac = (target - cum[i]) / max(cum[i + 1] - cum[i], 1e-12)
        return float(self.means[i] + (self.means[i + 1] - self.means[i]) * frac)

    # -- merge / wire ------------------------------------------------------
    def merge(self, other: "TDigest"):
        other._compress()
        if other.total == 0:
            return
        self._compress()
        self.means = np.concatenate([self.means, other.means])
        self.weights = np.concatenate([self.weights, other.weights])
        # concatenating two sorted runs yields an UNSORTED array; quantile()
        # interpolates assuming sorted means, and _compress() early-returns
        # without sorting when small — so restore the invariant here
        order = np.argsort(self.means, kind="stable")
        self.means, self.weights = self.means[order], self.weights[order]
        self.total += other.total
        self._min = min(self._min, other._min)
        self._max = max(self._max, other._max)
        if len(self.means) > 2 * self.compression:
            self._force_compress()

    def _force_compress(self):
        self._buf.append(np.zeros(0))  # non-empty buf list triggers the merge pass
        self._compress()

    def __getstate__(self):
        self._compress()
        return {"compression": self.compression, "means": self.means,
                "weights": self.weights, "total": self.total,
                "min": self._min, "max": self._max}

    def __setstate__(self, state):
        self.compression = state["compression"]
        self.means = state["means"]
        self.weights = state["weights"]
        self.total = state["total"]
        self._min = state["min"]
        self._max = state["max"]
        self._buf, self._buf_n = [], 0
