"""Compile warming: shape-driven executable pre-warming + autotuned bucket ladders.

Every first sighting of a (plan family × bucket shape) pays a full XLA compile on
the serving path — BENCH_WRITES' merge-window p99 cliff. This module is the
off-path answer (ROADMAP item 5), three legs sharing one registry:

  * **WarmSpec registry** — every kernel launch site records, once per distinct
    (site, static params, arg shapes/dtypes) signature, a JSON-able WarmSpec
    (`record_launch`). The warmer drains the registry on the `warmer` pool
    (`warm_cycle`): for each spec not yet executed in this process it rebuilds
    the jitted callable through a per-site builder and invokes it ONCE with
    zero-filled `jax.device_put` dummies under `compile_tag(family)`. Invoking
    the real callable (not `.lower().compile()`) is load-bearing: on jax 0.4.x
    an AOT-compiled executable does NOT populate the jit dispatch cache, so a
    later serving call would recompile anyway — the dummy invocation is what
    makes the next real call a cache hit. A spec recorded by a serving launch
    is already warm by construction (that launch populated the cache), so
    steady-state warm cycles do zero device work; only manifest-restored specs
    (restart) execute.
  * **Autotuned bucket ladders** (`BucketLadder`/`LadderBook`) — the fixed
    pow-2 `_pow2_bucket`/`_k_bucket` ladders become per-dimension ladders
    fitted to the observed shape histogram: bounded rung count, monotone,
    exact pow-2 fallback while cold (bit-identical to the old behavior until
    an autotune commits). Fits run off-path inside warm cycles and only
    commit past a sample floor AND a padding-waste improvement threshold, so
    committed rungs are stable — a refit mid-serving would re-cliff first
    sightings. tools/tpulint's compile-surface lattice knows `_ladder_bucket`
    as a bucketed classifier.
  * **Shape manifest persistence** — specs + ladders + mesh plan signatures
    persist to `<path.data>/compile_manifest.json` (atomic rename) on warm
    cycles and node close; a restarted node loads the manifest and its startup
    warm cycle replays exactly what production ran. Paired with the persistent
    XLA compilation cache (jaxenv.enable_persistent_compile_cache under
    `path.data`), the restart warm pays a disk deserialize, not a fleet
    recompile. NOTE: a persistent-cache HIT still emits a
    backend_compile_duration event (pxla wraps compile_or_get_cached), so the
    manifest replay — not the disk cache — is what buys the serving path its
    zero-event steady state.

Fault containment: each spec warms under its family's `compile:<family>`
device-health circuit — an open circuit skips the spec (never blocks serving),
and a warm failure records into the circuit off-path (devicehealth taxonomy).

Import discipline: this module imports stdlib only at module scope — ops/,
search/, and parallel/ modules import it (ladder call sites + builder
registration), so it must never import them back. jax imports are lazy inside
the warm path.
"""

from __future__ import annotations

import json
import os
import threading
from collections import OrderedDict
from dataclasses import dataclass

# ladder dimension vocabulary (fixed → bounded Prometheus label set):
#   q         query-count bucket (batcher flat/mesh coalescing, mesh Qp)
#   k         top-k bucket (batcher _k_bucket)
#   docs      segment doc_pad (device_index pack + mesh build)
#   nb        posting-block pad (device_index pack + mesh build)
#   terms     flat term-entry pad (scoring.build_term_batch, mesh assemble)
#   sparse_tb sparse per-query block-count bucket (plan_sparse_buckets)
#   sparse_qb sparse queries-per-bucket chunk (plan_sparse_buckets)
LADDER_DIMS = ("q", "k", "docs", "nb", "terms", "sparse_tb", "sparse_qb")


def _pow2(n: int, minimum: int) -> int:
    b = max(1, minimum)
    while b < n:
        b *= 2
    return b


class BucketLadder:
    """One dimension's bucket ladder: observed-value histogram + fitted rungs.

    `bucket(n, minimum)` is the hot-path call (one leaf lock, O(rungs) scan):
    it records n into a bounded histogram and returns the smallest committed
    rung ≥ n, falling back to the exact pow-2 ladder while cold or past the
    top rung. `autotune()` (warm cycle, off-path) fits ≤ max_rungs monotone
    rungs minimizing count-weighted padding waste over the histogram, and
    commits only when the fit beats pow-2 waste by `improvement` AND the
    histogram holds ≥ min_samples observations — committed rungs must be worth
    the one-time recompile their adoption costs."""

    HIST_CAP = 256  # distinct (rounded) values tracked; smallest-count evicts

    def __init__(self, dim: str, max_rungs: int = 8):
        self.dim = dim
        self.max_rungs = max(2, max_rungs)
        self._lock = threading.Lock()  # leaf: dict/tuple ops only
        self._hist: dict[int, int] = {}  # rounded value -> sightings
        self._total = 0
        self._rungs: tuple[int, ...] | None = None  # committed, sorted
        self._quantum = 1  # rounding lane (the call sites' `minimum`)
        self.commits = 0

    # -- hot path -------------------------------------------------------------
    def bucket(self, n: int, minimum: int) -> int:
        n = max(int(n), 1)
        q = max(int(minimum), 1)
        v = ((n + q - 1) // q) * q  # round up to the lane multiple
        with self._lock:
            self._quantum = q
            c = self._hist.get(v)
            if c is not None:
                self._hist[v] = c + 1
            elif len(self._hist) < self.HIST_CAP:
                self._hist[v] = 1
            else:  # evict the coldest rounded value (rare: cap overflow only)
                coldest = min(self._hist, key=self._hist.get)
                if self._hist[coldest] <= 1:
                    del self._hist[coldest]
                    self._hist[v] = 1
            self._total += 1
            rungs = self._rungs
        if rungs is not None:
            for r in rungs:
                if r >= n and r >= q:
                    return r
        return _pow2(n, q)

    # -- off-path fit ---------------------------------------------------------
    def autotune(self, min_samples: int, improvement: float) -> bool:
        """Fit and maybe commit; returns True when a new ladder committed."""
        with self._lock:
            if self._total < min_samples or not self._hist:
                return False
            items = sorted(self._hist.items())
            quantum = self._quantum
        vals = [v for v, _ in items]
        cnts = [c for _, c in items]
        pow2_waste = sum(c * (_pow2(v, quantum) - v)
                         for v, c in zip(vals, cnts))
        rungs = self._fit(vals, cnts)
        fit_waste = 0
        ri = 0
        for v, c in zip(vals, cnts):
            while rungs[ri] < v:
                ri += 1
            fit_waste += c * (rungs[ri] - v)
        # pow-2 waste can legitimately be 0 (every observed value already a
        # pow-2 lane multiple) — then there is nothing to win, keep fallback
        if pow2_waste <= 0 or fit_waste > pow2_waste * (1.0 - improvement):
            return False
        with self._lock:
            if tuple(rungs) == self._rungs:
                return False
            self._rungs = tuple(rungs)
            self.commits += 1
        return True

    def _fit(self, vals: list[int], cnts: list[int]) -> list[int]:
        """Weighted-waste optimal ≤ max_rungs rung placement (DP over the
        sorted distinct values; a rung at vals[j] covers every value ≤ it)."""
        m = len(vals)
        R = min(self.max_rungs, m)
        # prefix sums for O(1) segment waste: waste(i..j) = sum c_l*(v_j - v_l)
        pc = [0] * (m + 1)  # prefix counts
        pw = [0] * (m + 1)  # prefix c*v
        for i, (v, c) in enumerate(zip(vals, cnts)):
            pc[i + 1] = pc[i] + c
            pw[i + 1] = pw[i] + c * v

        def seg(i: int, j: int) -> int:  # values i..j inclusive, rung at v_j
            return vals[j] * (pc[j + 1] - pc[i]) - (pw[j + 1] - pw[i])

        INF = float("inf")
        dp = [[INF] * (R + 1) for _ in range(m)]
        arg = [[0] * (R + 1) for _ in range(m)]
        for j in range(m):
            dp[j][1] = seg(0, j)
            for r in range(2, R + 1):
                for i in range(j):
                    if dp[i][r - 1] == INF:
                        continue
                    cand = dp[i][r - 1] + seg(i + 1, j)
                    if cand < dp[j][r]:
                        dp[j][r] = cand
                        arg[j][r] = i
        best_r = min(range(1, R + 1), key=lambda r: dp[m - 1][r])
        rungs = []
        j, r = m - 1, best_r
        while r >= 1:
            rungs.append(vals[j])
            j, r = arg[j][r], r - 1
        return sorted(rungs)

    # -- persistence / stats --------------------------------------------------
    def to_json(self) -> dict:
        with self._lock:
            return {"hist": {str(v): c for v, c in self._hist.items()},
                    "total": self._total, "quantum": self._quantum,
                    "rungs": list(self._rungs) if self._rungs else None}

    def load_json(self, data: dict) -> None:
        with self._lock:
            for v, c in (data.get("hist") or {}).items():
                vi = int(v)
                self._hist[vi] = self._hist.get(vi, 0) + int(c)
            self._total += int(data.get("total", 0))
            self._quantum = int(data.get("quantum", self._quantum))
            rungs = data.get("rungs")
            if rungs and self._rungs is None:
                self._rungs = tuple(sorted(int(r) for r in rungs))

    def stats(self) -> dict:
        with self._lock:
            return {"observations": self._total,
                    "distinct": len(self._hist),
                    "rungs": list(self._rungs) if self._rungs else None,
                    "commits": self.commits}


class LadderBook:
    """The process's named ladders (LADDER_DIMS vocabulary). `bucket` is the
    single hot-path entry — ops/device_index._ladder_bucket delegates here."""

    def __init__(self):
        self._lock = threading.Lock()
        self._ladders: dict[str, BucketLadder] = {}
        self.max_rungs = 8

    def ladder(self, dim: str) -> BucketLadder:
        lad = self._ladders.get(dim)
        if lad is None:
            with self._lock:
                lad = self._ladders.setdefault(
                    dim, BucketLadder(dim, self.max_rungs))
        return lad

    def bucket(self, dim: str, n: int, minimum: int) -> int:
        return self.ladder(dim).bucket(n, minimum)

    def autotune_all(self, min_samples: int, improvement: float) -> int:
        return sum(1 for lad in list(self._ladders.values())
                   if lad.autotune(min_samples, improvement))

    def to_json(self) -> dict:
        return {dim: lad.to_json() for dim, lad in self._ladders.items()}

    def load_json(self, data: dict) -> None:
        for dim, frag in (data or {}).items():
            if dim in LADDER_DIMS:
                self.ladder(dim).load_json(frag)

    def stats(self) -> dict:
        return {dim: lad.stats() for dim, lad in self._ladders.items()}

    def reset(self) -> None:  # test hook
        with self._lock:
            self._ladders.clear()


LADDERS = LadderBook()


# ---------------------------------------------------------------------------
# argument-signature encoding: JSON-able, roundtrip-stable
# ---------------------------------------------------------------------------
# array leaf  -> {"s": [shape], "d": "<dtype str>"}
# literal     -> {"v": <int|float|bool|str|None>}  (static python args)
# tuple       -> {"t": [...]}   (tuple-vs-list matters: jit pytrees use tuples)
# list        -> [...]
# None        -> None


def encode_args(args) -> list:
    return [_encode(a) for a in args]


def _encode(a):
    if a is None:
        return None
    shape = getattr(a, "shape", None)
    if shape is not None and hasattr(a, "dtype"):
        return {"s": [int(d) for d in shape], "d": str(a.dtype)}
    if isinstance(a, tuple):
        return {"t": [_encode(x) for x in a]}
    if isinstance(a, list):
        return [_encode(x) for x in a]
    if isinstance(a, (bool, int, float, str)):
        return {"v": a}
    raise TypeError(f"unencodable launch arg of type {type(a).__name__}")


def shape_sig(args) -> tuple:
    """Hashable signature of encode_args — the registry's fast dedup key."""
    return tuple(_sig(a) for a in args)


def _sig(a):
    if a is None:
        return None
    shape = getattr(a, "shape", None)
    if shape is not None and hasattr(a, "dtype"):
        return (tuple(int(d) for d in shape), str(a.dtype))
    if isinstance(a, (tuple, list)):
        return (type(a).__name__,) + tuple(_sig(x) for x in a)
    return ("v", a)


def materialize(argspec: list):
    """Zero-filled device dummies for one encoded arg list — compilation (and
    the dispatch-cache key) depends on shapes/dtypes only, never values.
    Explicit device_put keeps the warm path legal under
    transfer_guard("disallow")."""
    import jax
    import numpy as np

    def mk(e):
        if e is None:
            return None
        if isinstance(e, dict):
            if "s" in e:
                return jax.device_put(
                    np.zeros(tuple(e["s"]), dtype=np.dtype(e["d"])))
            if "t" in e:
                return tuple(mk(x) for x in e["t"])
            return e.get("v")
        if isinstance(e, list):
            return [mk(x) for x in e]
        raise TypeError(f"bad argspec node: {e!r}")

    return [mk(e) for e in argspec]


def _freeze(x):
    """Params as recorded vs params as JSON-roundtripped must hash equal."""
    if isinstance(x, (tuple, list)):
        return tuple(_freeze(v) for v in x)
    if isinstance(x, dict):
        return tuple(sorted((k, _freeze(v)) for k, v in x.items()))
    return x


def _thaw_params(params):
    """JSON lists back to tuples (builder getters key caches on tuples)."""
    if isinstance(params, list):
        return tuple(_thaw_params(v) for v in params)
    return params


@dataclass
class WarmSpec:
    """One warmable executable: site names the builder, params feed it, and
    argspec shapes the dummy invocation."""

    site: str
    family: str
    params: tuple
    argspec: list

    def key(self) -> tuple:
        return (self.site, _freeze(self.params), _freeze_spec(self.argspec))

    def to_json(self) -> dict:
        return {"site": self.site, "family": self.family,
                "params": list(self.params), "args": self.argspec}

    @staticmethod
    def from_json(d: dict) -> "WarmSpec":
        return WarmSpec(site=str(d["site"]), family=str(d["family"]),
                        params=_thaw_params(d.get("params", [])),
                        argspec=d.get("args", []))


def _freeze_spec(argspec) -> tuple:
    def fz(e):
        if e is None:
            return None
        if isinstance(e, dict):
            if "s" in e:
                return (tuple(e["s"]), e["d"])
            if "t" in e:
                return ("tuple",) + tuple(fz(x) for x in e["t"])
            return ("v", e.get("v"))
        if isinstance(e, list):
            return ("list",) + tuple(fz(x) for x in e)
        return ("v", e)

    return tuple(fz(e) for e in argspec)


MANIFEST_NAME = "compile_manifest.json"
_MESH_RING = 4  # recent mesh plan batches kept per index


class CompileWarmRegistry:
    """Process-wide warm registry: spec capture, builders, warm cycles, the
    shape manifest, and mesh plan-signature rings. One instance (`REGISTRY`);
    nodes configure it with their settings/path.data (multi-node test
    processes share it — the union of observed shapes warms everywhere, which
    is exactly the fleet semantics)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.enabled = True
        self.persist = True
        self.max_specs = 256
        self.autotune_min_samples = 512
        self.autotune_improvement = 0.10
        self._builders: dict = {}
        self._specs: "OrderedDict[tuple, WarmSpec]" = OrderedDict()
        self._warmed: set = set()  # spec keys already executed in-process
        self._mesh: dict[str, list] = {}  # index -> [entry dicts], newest last
        self._mesh_plans: dict[str, list] = {}  # index -> live plan payloads
        self._dirty = False
        # counters (leaf lock)
        self.specs_recorded = 0
        self.specs_loaded = 0
        self.warmed_total = 0
        self.warm_failures = 0
        self.warm_skipped_circuit = 0
        self.warm_cycles = 0
        self.ladder_commits = 0
        self.manifest_saves = 0
        self.mesh_warms = 0
        self.mesh_warm_failures = 0
        self.last_reason = None
        # compile events observed by family×pool (jaxenv listener feed) — the
        # runtime proof of "pool=warmer/startup only" on a warmed node
        self.compiles_by_pool: dict[str, int] = {}

    # -- wiring ---------------------------------------------------------------
    def configure(self, settings, data_path: str | None) -> None:
        """Node-boot hook: read knobs, load this path's manifest, arm the
        persistent XLA compilation cache under path.data."""
        self.enabled = bool(settings.get_bool("node.compile_warming.enabled",
                                              True))
        self.persist = bool(settings.get_bool("node.compile_warming.persist",
                                              True))
        self.max_specs = max(16, settings.get_int(
            "node.compile_warming.max_specs", 256))
        self.autotune_min_samples = max(1, settings.get_int(
            "node.compile_warming.autotune_min_samples", 512))
        self.autotune_improvement = settings.get_float(
            "node.compile_warming.autotune_improvement", 0.10)
        LADDERS.max_rungs = max(2, settings.get_int(
            "node.compile_warming.max_rungs", 8))
        if not self.enabled or not data_path:
            return
        if self.persist:
            self.load_manifest(os.path.join(data_path, MANIFEST_NAME))
        if settings.get_bool("node.compile_cache.persist", True):
            from . import jaxenv

            jaxenv.enable_persistent_compile_cache(
                os.path.join(data_path, "jax_cache"))
        from . import jaxenv

        jaxenv.register_compile_observer(self._on_compile_event)

    def _on_compile_event(self, family: str, pool: str) -> None:
        """jaxenv compile-listener feed: per-pool attribution (warm-queue
        pressure signal — a compile on a serving pool is a cold spec the next
        warm cycle should already know about via record_launch)."""
        with self._lock:
            k = f"{family}/{pool}"
            self.compiles_by_pool[k] = self.compiles_by_pool.get(k, 0) + 1

    def builder(self, site: str):
        """Decorator: register `site`'s params -> jitted-callable builder."""

        def deco(fn):
            self._builders[site] = fn
            return fn

        return deco

    # -- capture (hot path: one sig walk + one dict hit per launch) -----------
    def record_launch(self, site: str, family: str, params: tuple,
                      args) -> None:
        if not self.enabled:
            return
        try:
            key = (site, _freeze(params), shape_sig(args))
        except Exception:  # noqa: BLE001 — unhashable arg: not warmable
            return
        with self._lock:
            if key in self._specs:
                self._warmed.add(key)
                self._specs.move_to_end(key)
                return
        # encode OUTSIDE the lock (slow path: first sighting only)
        try:
            spec = WarmSpec(site=site, family=family, params=_freeze(params),
                            argspec=encode_args(args))
        except TypeError:
            return
        with self._lock:
            if key in self._specs:
                return
            self._specs[key] = spec
            self._warmed.add(key)  # this launch itself populated the cache
            self.specs_recorded += 1
            self._dirty = True
            while len(self._specs) > self.max_specs:
                old, _ = self._specs.popitem(last=False)
                self._warmed.discard(old)

    # -- mesh plan signatures --------------------------------------------------
    def record_mesh(self, index: str, plans, k: int, plan_dicts) -> None:
        """Remember a recently served mesh batch: live plan objects for
        same-process executor-rebuild warming, JSON dicts for the manifest."""
        if not self.enabled:
            return
        entry = {"k": int(k), "plans": plan_dicts, "q": len(plan_dicts)}
        sig = (entry["q"], entry["k"],
               tuple(len(p.get("clauses", ())) for p in plan_dicts))
        with self._lock:
            ring = self._mesh.setdefault(index, [])
            sigs = [(e["q"], e["k"],
                     tuple(len(p.get("clauses", ())) for p in e["plans"]))
                    for e in ring]
            if sig in sigs:
                return
            ring.append(entry)
            del ring[:-_MESH_RING]
            live = self._mesh_plans.setdefault(index, [])
            live.append({"k": int(k), "plans": list(plans)})
            del live[:-_MESH_RING]
            self._dirty = True

    def mesh_entries(self, index: str):
        """(live plan payloads, manifest plan dicts) for one index — the
        executor-rebuild warm replays live payloads when present (same
        process), else the manifest dicts (restart)."""
        with self._lock:
            return (list(self._mesh_plans.get(index, ())),
                    list(self._mesh.get(index, ())))

    def note_mesh_warm(self, ok: bool) -> None:
        with self._lock:
            if ok:
                self.mesh_warms += 1
            else:
                self.mesh_warm_failures += 1

    # -- warm cycle (warmer pool only) ----------------------------------------
    def warm_cycle(self, reason: str, save_path: str | None = None) -> dict:
        """Autotune ladders, replay every not-yet-warm spec, persist the
        manifest. Runs on the warmer pool (node startup, searcher install,
        manual warm); never on a serving thread."""
        if not self.enabled:
            return {"warmed": 0, "failed": 0, "skipped": 0}
        from .devicehealth import DEVICE_HEALTH
        from .jaxenv import compile_tag

        committed = LADDERS.autotune_all(self.autotune_min_samples,
                                         self.autotune_improvement)
        with self._lock:
            self.ladder_commits += committed
            if committed:
                self._dirty = True
            pending = [(k, s) for k, s in self._specs.items()
                       if k not in self._warmed]
            self.warm_cycles += 1
            self.last_reason = reason
        # builders register at their module's import; after a restart the
        # manifest can hold specs for modules nothing imported yet — pull the
        # known builder homes in lazily (function scope: common/ never imports
        # ops/ at module scope)
        if any(self._builders.get(s.site) is None for _, s in pending):
            try:
                from ..ops import scoring  # noqa: F401 — registers scoring.*
            except Exception:  # noqa: BLE001 — missing deps: specs stay pending
                pass
        warmed = failed = skipped = 0
        for key, spec in pending:
            domain = f"compile:{spec.family}"
            if DEVICE_HEALTH.blocked((domain,)):
                skipped += 1
                continue
            build = self._builders.get(spec.site)
            if build is None:
                continue  # builder module not imported yet; next cycle
            try:
                import jax

                fn = build(spec.params)
                args = materialize(spec.argspec)
                with compile_tag(spec.family):
                    out = fn(*args)
                jax.block_until_ready(out)
            except Exception as e:  # noqa: BLE001 — warm failure is off-path
                failed += 1
                DEVICE_HEALTH.record_failure(domain, e)
                continue
            warmed += 1
            DEVICE_HEALTH.note_success((domain,))
            with self._lock:
                self._warmed.add(key)
        with self._lock:
            self.warmed_total += warmed
            self.warm_failures += failed
            self.warm_skipped_circuit += skipped
        if save_path and self.persist:
            self.save_manifest(os.path.join(save_path, MANIFEST_NAME))
        return {"warmed": warmed, "failed": failed, "skipped": skipped,
                "ladders_committed": committed}

    def pending_count(self) -> int:
        with self._lock:
            return sum(1 for k in self._specs if k not in self._warmed)

    # -- persistence -----------------------------------------------------------
    def save_manifest(self, path: str) -> None:
        with self._lock:
            if not self._dirty:
                return
            payload = {"version": 1,
                       "specs": [s.to_json() for s in self._specs.values()],
                       "ladders": LADDERS.to_json(),
                       "mesh": {i: list(r) for i, r in self._mesh.items()}}
            self._dirty = False
            self.manifest_saves += 1
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(payload, f)
            os.replace(tmp, path)
        except OSError:
            with self._lock:
                self._dirty = True  # retry on the next cycle/close

    def load_manifest(self, path: str) -> int:
        try:
            with open(path) as f:
                payload = json.load(f)
        except (OSError, ValueError):
            return 0
        LADDERS.load_json(payload.get("ladders") or {})
        loaded = 0
        for d in payload.get("specs", ()):
            try:
                spec = WarmSpec.from_json(d)
                key = spec.key()
            except (KeyError, TypeError):
                continue
            with self._lock:
                if key not in self._specs:
                    self._specs[key] = spec  # NOT in _warmed: startup warms it
                    loaded += 1
        with self._lock:
            for index, ring in (payload.get("mesh") or {}).items():
                cur = self._mesh.setdefault(index, [])
                for e in ring:
                    if e not in cur:
                        cur.append(e)
                del cur[:-_MESH_RING]
            self.specs_loaded += loaded
        return loaded

    def reset(self) -> None:
        """Test/bench hook: forget ALL in-process warm state. Paired with
        jax.clear_caches() (and a LADDERS.reset()) this simulates a process
        restart inside one interpreter — the restarted 'node' must re-earn
        its warmth from the manifest, exactly like a real rolling restart."""
        with self._lock:
            self._specs.clear()
            self._warmed.clear()
            self._mesh.clear()
            self._mesh_plans.clear()
            self._dirty = False
            self.specs_recorded = 0
            self.specs_loaded = 0
            self.warmed_total = 0
            self.warm_failures = 0
            self.warm_skipped_circuit = 0
            self.warm_cycles = 0
            self.ladder_commits = 0
            self.manifest_saves = 0
            self.mesh_warms = 0
            self.mesh_warm_failures = 0
            self.last_reason = None
            self.compiles_by_pool.clear()

    # -- observability ---------------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            return {
                "enabled": self.enabled,
                "specs": len(self._specs),
                "specs_recorded": self.specs_recorded,
                "specs_loaded": self.specs_loaded,
                "pending": sum(1 for k in self._specs
                               if k not in self._warmed),
                "warmed_total": self.warmed_total,
                "warm_failures": self.warm_failures,
                "warm_skipped_circuit": self.warm_skipped_circuit,
                "warm_cycles": self.warm_cycles,
                "last_reason": self.last_reason,
                "ladder_commits": self.ladder_commits,
                "manifest_saves": self.manifest_saves,
                "mesh_indices": len(self._mesh),
                "mesh_warms": self.mesh_warms,
                "mesh_warm_failures": self.mesh_warm_failures,
                "compiles_by_pool": dict(self.compiles_by_pool),
                "ladders": LADDERS.stats(),
            }


REGISTRY = CompileWarmRegistry()
