"""Monotonic per-request time budgets.

The analogue of the reference's TimeValue request timeouts + TimeLimitingCollector
(search/internal/ContextIndexSearcher wraps collection; REST parses `?timeout=`):
one `Deadline` object is created where the request enters the system and every
derived wait — per-attempt transport timeout, failover-chain cap, retry backoff,
per-segment collection check — is computed from its *remaining* budget instead of
a flat constant. That is what bounds tail latency end-to-end: k hung hops run
down one clock instead of stacking k fresh timeouts.

Rules:

- Deadlines are host-side only. They clamp work at segment granularity *between*
  device launches; a deadline check must never cross into traced/jit code (it
  would either retrace per call or freeze the first call's clock — tpulint
  TPU001/TPU002 territory). Launched device work always completes whole.
- Deadlines do not cross process boundaries as absolute times (monotonic clocks
  are per-process): the wire carries the remaining budget as a duration and the
  receiver restarts its own clock, like the reference shipping TimeValue and
  starting a fresh TimeLimitingCollector per shard.
"""

from __future__ import annotations

import re
import time

_TIMEVALUE_RE = re.compile(r"^\s*(-?\d+(?:\.\d+)?)\s*(ms|s|m|h|d|micros|nanos)?\s*$",
                           re.IGNORECASE)

_UNIT_S = {"nanos": 1e-9, "micros": 1e-6, "ms": 1e-3, "s": 1.0,
           "m": 60.0, "h": 3600.0, "d": 86400.0}


def parse_timevalue(value) -> float | None:
    """Parse a reference-style time value into seconds.

    Accepts "50ms" / "5s" / "1m" / "2h" strings; a bare number (or numeric
    string) is MILLISECONDS, matching the reference's request-body `timeout`
    field (TimeValue.parseTimeValue defaults to ms). None, "" and negative
    values (the reference's `-1` = unlimited) parse to None (no budget).
    """
    if value is None:
        return None
    if isinstance(value, bool):
        raise ValueError(f"cannot parse time value [{value!r}]")
    if isinstance(value, (int, float)):
        return None if value < 0 else float(value) / 1000.0
    m = _TIMEVALUE_RE.match(str(value))
    if not m:
        raise ValueError(f"cannot parse time value [{value!r}]")
    num = float(m.group(1))
    if num < 0:
        return None
    unit = (m.group(2) or "ms").lower()
    return num * _UNIT_S[unit]


class Deadline:
    """A monotonic point in time carrying a request's remaining budget.

    `Deadline.after(None)` is the unbounded deadline: it never expires and
    every clamp returns the caller's own timeout — callers never need to
    special-case "no timeout was requested".
    """

    __slots__ = ("_expires_at",)

    def __init__(self, expires_at: float | None):
        self._expires_at = expires_at

    @classmethod
    def after(cls, seconds: float | None) -> "Deadline":
        """Budget starting now; None = unbounded."""
        if seconds is None:
            return cls(None)
        return cls(time.monotonic() + max(0.0, float(seconds)))

    @property
    def bounded(self) -> bool:
        return self._expires_at is not None

    def remaining(self) -> float | None:
        """Seconds left (>= 0.0), or None when unbounded."""
        if self._expires_at is None:
            return None
        return max(0.0, self._expires_at - time.monotonic())

    def expired(self) -> bool:
        return self._expires_at is not None and time.monotonic() >= self._expires_at

    def clamp(self, timeout: float | None) -> float | None:
        """The tighter of `timeout` and the remaining budget.

        An expired deadline clamps to 0.0 — waits return immediately rather
        than raising here, so the *caller* decides how expiry surfaces (shard
        failure, partial result, retry exhaustion...).
        """
        rem = self.remaining()
        if rem is None:
            return timeout
        if timeout is None:
            return rem
        return min(float(timeout), rem)

    def __repr__(self) -> str:
        if self._expires_at is None:
            return "Deadline(unbounded)"
        return f"Deadline(remaining={self.remaining():.3f}s)"


#: Shared unbounded deadline — use as a default argument so call sites read
#: `deadline.clamp(...)` unconditionally.
NO_DEADLINE = Deadline(None)
