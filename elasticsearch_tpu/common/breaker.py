"""Memory circuit breaker.

Analogue of common/breaker/MemoryCircuitBreaker.java + the fielddata breaker service
(indices/fielddata/breaker/InternalCircuitBreakerService.java): estimates bytes before a
large allocation (device postings pack, fielddata load, aggregation arrays) and trips with
CircuitBreakingError instead of OOMing the host or HBM."""

from __future__ import annotations

import threading

from .errors import CircuitBreakingError
from .units import parse_ratio_or_bytes


class MemoryCircuitBreaker:
    def __init__(self, limit_bytes: int, overhead: float = 1.0, name: str = "fielddata"):
        self.name = name
        self.limit = int(limit_bytes)
        self.overhead = overhead
        self._used = 0
        self._trip_count = 0
        self._lock = threading.Lock()

    def add_estimate_and_maybe_break(self, bytes_: int, label: str = "") -> int:
        with self._lock:
            new_used = self._used + bytes_
            if self.limit > 0 and new_used * self.overhead > self.limit:
                self._trip_count += 1
                raise CircuitBreakingError(
                    f"[{self.name}] data for [{label}] would be larger than limit of "
                    f"[{self.limit}] bytes (estimated [{new_used}])"
                )
            self._used = new_used
            return self._used

    def add_without_breaking(self, bytes_: int) -> int:
        with self._lock:
            self._used += bytes_
            return self._used

    def release(self, bytes_: int):
        self.add_without_breaking(-bytes_)

    @property
    def used(self) -> int:
        return self._used

    @property
    def trip_count(self) -> int:
        return self._trip_count


class CircuitBreakerService:
    """Registry of named breakers; budget defaults follow the reference's
    indices.fielddata.breaker.limit (80% of heap → here: of a configured budget)."""

    def __init__(self, settings=None, total_budget_bytes: int = 8 << 30):
        from .settings import Settings

        settings = settings or Settings.EMPTY
        limit = parse_ratio_or_bytes(
            settings.get("indices.fielddata.breaker.limit"), total_budget_bytes, default="80%"
        )
        overhead = settings.get_float("indices.fielddata.breaker.overhead", 1.03)
        self.breakers: dict[str, MemoryCircuitBreaker] = {
            "fielddata": MemoryCircuitBreaker(limit, overhead, "fielddata"),
            "request": MemoryCircuitBreaker(
                parse_ratio_or_bytes(
                    settings.get("indices.breaker.request.limit"), total_budget_bytes, default="40%"
                ),
                1.0,
                "request",
            ),
        }

    def breaker(self, name: str = "fielddata") -> MemoryCircuitBreaker:
        return self.breakers[name]

    def stats(self) -> dict:
        return {
            name: {
                "limit_size_in_bytes": b.limit,
                "estimated_size_in_bytes": b.used,
                "overhead": b.overhead,
                "tripped": b.trip_count,
            }
            for name, b in self.breakers.items()
        }
