"""Hierarchical memory circuit breakers.

Analogue of common/breaker/MemoryCircuitBreaker.java + the breaker service
(indices/fielddata/breaker/InternalCircuitBreakerService.java, later
HierarchyCircuitBreakerService): estimate bytes BEFORE a large allocation
(host merge buffers, device-index packing, agg bucket materialization, mesh
result assembly, in-flight transport messages) and trip with
CircuitBreakingError (HTTP 429) instead of OOMing the host or HBM.

Hierarchy: every child breaker (`request`, `fielddata`, `in_flight_requests`)
has its own limit, and all children share ONE parent budget — a request that
fits its child limit still trips when the node as a whole is out of headroom.

Rules:

- estimate-before-allocate, release in `finally` — accounting is transient, so
  a drained node always returns to 0 estimated bytes;
- accounting is HOST-side only and must never run inside traced (jit/shard_map)
  code: a breaker call during tracing either freezes the first call's estimate
  into the program or retraces per request (tpulint TPU010 enforces this);
- lock order is child → parent, never the reverse — children never call into
  each other and the parent never calls into a child, so there is no cycle.
"""

from __future__ import annotations

import contextlib
import threading

from . import profile
from .errors import CircuitBreakingError
from .units import parse_bytes, parse_ratio_or_bytes


class MemoryCircuitBreaker:
    """One named breaker. `parent` (another MemoryCircuitBreaker, no parent of
    its own) is consulted AFTER the child's own limit passes, so a trip at
    either level leaves both levels' accounting untouched."""

    def __init__(self, limit_bytes: int, overhead: float = 1.0,
                 name: str = "fielddata",
                 parent: "MemoryCircuitBreaker | None" = None):
        self.name = name
        self.limit = int(limit_bytes)
        self.overhead = overhead
        self.parent = parent
        self._used = 0
        self._trip_count = 0
        self._leak_detected = 0
        self._lock = threading.Lock()

    def _check(self, new_used: int, label: str, child: str | None = None):
        """Raise (and count the trip) when `new_used` would exceed the limit.
        Caller holds self._lock."""
        if self.limit > 0 and new_used * self.overhead > self.limit:
            self._trip_count += 1
            who = f"[{self.name}]" if child is None else \
                f"[{self.name}] (via [{child}])"
            err = CircuitBreakingError(
                f"{who} data for [{label}] would be larger than limit of "
                f"[{self.limit}] bytes (estimated [{new_used}])")
            # WHICH breaker tripped decides degrade-vs-shed upstream: a
            # fielddata trip can fall back to the host scorer, a request or
            # parent trip means the node is out of budget and must 429
            err.breaker = self.name
            raise err

    def add_estimate_and_maybe_break(self, bytes_: int, label: str = "") -> int:
        """Reserve `bytes_` or raise CircuitBreakingError. The read-modify-write
        is fully under the lock: concurrent searches can never jointly blow
        past the limit between the check and the commit."""
        bytes_ = int(bytes_)
        if bytes_ < 0:
            self.release(-bytes_)
            return self._used
        with self._lock:
            new_used = self._used + bytes_
            self._check(new_used, label)
            if self.parent is not None:
                # child → parent lock order, always; a parent trip propagates
                # before the child commits, so nothing needs unwinding
                self.parent._add_from_child(bytes_, label, self.name)
            self._used = new_used
            return self._used

    def _add_from_child(self, bytes_: int, label: str, child: str) -> int:
        with self._lock:
            new_used = self._used + bytes_
            self._check(new_used, label, child=child)
            self._used = new_used
            return self._used

    def add_without_breaking(self, bytes_: int) -> int:
        """Adjust accounting without the limit check (post-hoc corrections).
        Negative amounts clamp at zero like release()."""
        bytes_ = int(bytes_)
        if bytes_ < 0:
            self.release(-bytes_)
            return self._used
        with self._lock:
            self._used += bytes_
        if self.parent is not None:
            self.parent.add_without_breaking(bytes_)
        return self._used

    def release(self, bytes_: int):
        """Return reserved bytes. Over-release (double release, or releasing
        more than held) clamps at zero and counts a leak instead of driving
        `used` negative — negative accounting silently inflates headroom for
        every later request, which is how a tracked budget rots."""
        bytes_ = int(bytes_)
        if bytes_ <= 0:
            return
        with self._lock:
            freed = min(bytes_, self._used)
            if freed < bytes_:
                self._leak_detected += 1
            self._used -= freed
        if self.parent is not None and freed:
            self.parent.release(freed)

    @property
    def used(self) -> int:
        return self._used

    @property
    def trip_count(self) -> int:
        return self._trip_count

    @property
    def leak_detected(self) -> int:
        return self._leak_detected

    def stats(self) -> dict:
        return {
            "limit": self.limit,
            "limit_size_in_bytes": self.limit,
            "estimated": self._used,
            "estimated_size_in_bytes": self._used,
            "overhead": self.overhead,
            "tripped": self._trip_count,
            "leak_detected": self._leak_detected,
        }


@contextlib.contextmanager
def reserve(breaker: MemoryCircuitBreaker | None, bytes_: int, label: str = ""):
    """Estimate-before-allocate scope: charge on entry, ALWAYS release on exit.

    `breaker=None` (an unwired context — unit tests, standalone shard work) is
    a no-op, so hot-spot call sites never need to special-case it. Must never
    wrap traced code (tpulint TPU010)."""
    if breaker is None or bytes_ <= 0:
        yield 0
        return
    breaker.add_estimate_and_maybe_break(int(bytes_), label)
    # profile attribution: a profiled request records every estimate it
    # reserved (which breaker, which label, how many bytes) — AFTER the
    # breaker granted it, so a tripped reservation is never reported as
    # consumed; one thread-local read on the unprofiled path
    prof = profile.current()
    if prof is not None:
        prof.breaker_reserve(breaker.name, label, int(bytes_))
    try:
        yield int(bytes_)
    finally:
        breaker.release(int(bytes_))


class CircuitBreakerService:
    """The node's breaker hierarchy. One parent budget
    (`indices.breaker.total.limit`, default 70% of the configured byte budget)
    over three children:

    - `request`     — per-request host materialization: merge buffers, dense
                      masks, agg bucket arrays, mesh assembly
                      (`indices.breaker.request.limit`, default 60%)
    - `fielddata`   — device-index column loads / segment packing
                      (`indices.fielddata.breaker.limit`, default 80%,
                      overhead `indices.fielddata.breaker.overhead` 1.03)
    - `in_flight_requests` — encoded transport message bytes currently in
                      flight (`network.breaker.inflight_requests.limit`,
                      default 100%)

    The byte budget itself comes from `indices.breaker.total_budget`
    ("64kb" / "2gb" / raw bytes; default = the `total_budget_bytes` argument) —
    chaos tests shrink it to force trips without gigabyte allocations."""

    def __init__(self, settings=None, total_budget_bytes: int = 8 << 30):
        from .settings import Settings

        settings = settings or Settings.EMPTY
        budget = parse_bytes(settings.get("indices.breaker.total_budget"),
                             default=int(total_budget_bytes))
        self.total_budget = budget
        self.parent = MemoryCircuitBreaker(
            parse_ratio_or_bytes(settings.get("indices.breaker.total.limit"),
                                 budget, default="70%"),
            1.0, "parent")
        overhead = settings.get_float("indices.fielddata.breaker.overhead", 1.03)
        self.breakers: dict[str, MemoryCircuitBreaker] = {
            "fielddata": MemoryCircuitBreaker(
                parse_ratio_or_bytes(
                    settings.get("indices.fielddata.breaker.limit"),
                    budget, default="80%"),
                overhead, "fielddata", parent=self.parent),
            "request": MemoryCircuitBreaker(
                parse_ratio_or_bytes(
                    settings.get("indices.breaker.request.limit"),
                    budget, default="60%"),
                1.0, "request", parent=self.parent),
            "in_flight_requests": MemoryCircuitBreaker(
                parse_ratio_or_bytes(
                    settings.get("network.breaker.inflight_requests.limit"),
                    budget, default="100%"),
                1.0, "in_flight_requests", parent=self.parent),
        }

    def breaker(self, name: str = "fielddata") -> MemoryCircuitBreaker:
        return self.breakers[name]

    def stats(self) -> dict:
        out = {name: b.stats() for name, b in self.breakers.items()}
        out["parent"] = self.parent.stats()
        return out
