"""Always-on query-shape insights — sampled-nothing, classified-everything.

PR 8/9 explain a request when it *asks* (`?trace=true`, `"profile": true`); a
production cluster is diagnosed from the other direction: which query SHAPES
dominate cost, what their tail looks like, whether they hit the caches or fall
off the fused path — continuously, with zero per-request opt-in. This module
classifies EVERY search into a bounded registry of plan shapes and accumulates
per-shape count / latency / queue / device-phase histograms, the
fused-vs-fallback outcome mix, and request-cache hit rates.

A *shape* is the request body's normalized clause STRUCTURE, never its
literals: `{"match": {"body": "alpha7"}}` and `{"match": {"body": "zebra"}}`
are one shape; `{"term": {...}}` vs `{"match": {...}}`, a 2-clause vs a
4-clause bool (power-of-two bucketed), `size: 0` vs a hit-bearing page are
distinct shapes. The canonicalization reuses the request-cache fingerprint
machinery (sorted keys, compact JSON, volatile execution knobs stripped —
search/request_cache.py) with literal values replaced by placeholders, so a
shape id is stable across key order, boosts, paging literals, and term text.

Hot-path contract (the PR-8/9 rule, verbatim):

- **Record-only hooks behind one thread-local/attr read.** The serving path
  carries an `Observation` in a thread-local exactly like tracing's span and
  profiling's collector; the batcher captures it at enqueue with one
  attribute read. An insights-disabled node pays one `getattr` per hook.
- **Zero added clocks.** Latency reuses the slowlog's existing
  `t_q`/`took_s` pair in `actions._s_query_phase`; queue time reuses the
  batcher's `t_enq`/collect clocks; device time rides the batch's existing
  single `jax.device_get` window (`_PendingFlat.pull_t0/t1` — stamped for
  tracing since PR 8). No path reads a clock it did not already read.
- **Zero added device syncs.** Everything here is host arithmetic.
- **Leaf locks only.** The registry lock guards dict/counter mutation;
  histograms use their own striped leaf locks, observed OUTSIDE the registry
  lock. Nothing under any lock blocks or dispatches.

Cardinality is bounded: the registry holds at most `search.insights.max_shapes`
(default 128) shapes, LRU-demoted past the cap (demoted shapes fold their
count/cost into a single `other` bucket, so totals stay honest), which also
bounds the `estpu_query_shape_*` Prometheus label sets. Surfaces:
`GET /_insights/queries` (top-N by cost), `/_nodes/stats` `search.shapes`,
and the Prometheus families (rest/controller.py).
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import threading
from collections import OrderedDict

from .metrics import HistogramMetric

# execution knobs that select HOW a request runs, not WHAT it computes —
# superset of the request cache's volatile set (trace is REST-level)
_VOLATILE_KEYS = ("profile", "request_cache", "timeout", "trace")

# dict keys whose scalar VALUES are structural (they change the plan shape),
# not literals: everything else scalar collapses to the "?" placeholder
_STRUCTURAL_VALUE_KEYS = frozenset({
    "order", "mode", "operator", "default_operator", "type", "score_mode",
    "boost_mode", "execution", "minimum_should_match", "analyzer", "field",
    "fields", "sort_mode", "lang",
})

# outcome vocabulary: search/service.SERVING_COUNTERS paths + the two
# insights-only outcomes. Bounded by construction (unknown strings are
# folded to "unknown" so a drifting caller can't grow the dict).
OUTCOMES = (
    "device_sparse", "device_filtered", "device_function_score",
    "device_aggs", "device_sort", "host", "mesh_spmd", "cache_hit",
    "error", "unknown",
)
_OUTCOME_SET = frozenset(OUTCOMES)


def _pow2(n: int) -> int:
    b = 1
    while b < n:
        b *= 2
    return b


def _normalize(value, key: str | None = None):
    """Structure-preserving, literal-erasing normalization of one body node.
    Lists of identically-shaped elements collapse to [shape, "xN"] with N
    power-of-two bucketed — a 5-term and a 7-term should-list share a shape,
    a 2-term and a 40-term one do not."""
    if isinstance(value, dict):
        return {k: _normalize(v, k) for k, v in sorted(value.items())
                if k not in _VOLATILE_KEYS}
    if isinstance(value, (list, tuple)):
        # elements inherit the parent key so LIST-valued structural keys
        # survive: multi_match over {"fields": ["title", "body"]} and over
        # {"fields": ["tag"]} are different plans, not one erased shape
        norm = [_normalize(v, key) for v in value]
        if len(norm) > 1 and all(n == norm[0] for n in norm):
            return [norm[0], f"x{_pow2(len(norm))}"]
        return norm
    if key in _STRUCTURAL_VALUE_KEYS:
        return value if isinstance(value, (str, int, bool)) else "?"
    return "?"


def normalize_shape(body: dict | None) -> dict:
    """The normalized plan shape of one search body: clause structure with
    literals erased, `size`/`from` reduced to the 0-vs-paged distinction the
    request-cache policy draws (a count/dashboard query and a hit-bearing
    page are different workloads even with identical clauses)."""
    body = body or {}
    shape = _normalize({k: v for k, v in body.items()
                        if k not in ("size", "from")})
    try:
        shape["size"] = 0 if int(body.get("size", 10) or 0) == 0 else "n"
    except (TypeError, ValueError):
        shape["size"] = "n"
    if body.get("from"):
        shape["from"] = "n"
    return shape


def shape_fingerprint(body: dict | None) -> tuple[str, dict]:
    """(shape id, normalized shape). The id is a 16-hex-char blake2b over the
    canonical JSON re-serialization of the normalized shape — same recipe as
    request_cache.request_fingerprint, over the shape instead of the body."""
    shape = normalize_shape(body)
    blob = json.dumps(shape, sort_keys=True, separators=(",", ":"),
                      default=str)
    return hashlib.blake2b(blob.encode("utf-8"),
                           digest_size=8).hexdigest(), shape


# ---------------------------------------------------------------------------
# per-request observation (thread-local, like tracing spans / profile
# collectors): the batcher and the serving-path outcome counter write into
# it; the query phase folds it into the registry when the request finishes
# ---------------------------------------------------------------------------

_local = threading.local()


class Observation:
    """One request's in-flight insight scratch. Single-writer per field by
    construction: `outcome` is written on the request thread
    (service._count), `queue_s`/`device_s`/`occupancy` on the batcher
    drainer BEFORE the item's future resolves (the Future provides the
    happens-before edge to the reader). Plain attribute writes — no locks."""

    __slots__ = ("outcome", "queue_s", "device_s", "occupancy")

    def __init__(self):
        self.outcome: str | None = None
        self.queue_s: float | None = None
        self.device_s: float | None = None
        self.occupancy: int | None = None


def current() -> Observation | None:
    """The thread's active observation, or None (one thread-local read —
    the whole cost of a hook on an insights-disabled node)."""
    return getattr(_local, "obs", None)


@contextlib.contextmanager
def activate(obs: Observation):
    """Make `obs` the thread's observation for the scope. Call sites only
    enter this when insights are enabled — the disabled path never pays the
    context manager."""
    prev = getattr(_local, "obs", None)
    _local.obs = obs
    try:
        yield obs
    finally:
        _local.obs = prev


# ---------------------------------------------------------------------------
# the bounded shape registry
# ---------------------------------------------------------------------------


class ShapeStats:
    """Accumulated telemetry of one resident shape. Counters mutate under the
    registry's leaf lock; the histograms carry their own striped leaf locks
    and are observed outside it."""

    __slots__ = ("shape", "count", "cost_ms", "cache_hits", "cache_misses",
                 "outcomes", "latency", "queue", "device", "coalesced")

    def __init__(self, shape: dict):
        self.shape = shape
        self.count = 0
        # accumulated cost, maintained UNDER the registry lock next to count
        # (histogram sums are observed outside it, so an LRU demotion racing
        # a recorder could lose their contribution — this total cannot)
        self.cost_ms = 0.0
        self.cache_hits = 0
        self.cache_misses = 0
        self.outcomes: dict[str, int] = {}
        self.latency = HistogramMetric()
        self.queue = HistogramMetric()
        self.device = HistogramMetric()
        self.coalesced = 0  # requests that rode a shared batcher launch

    def to_dict(self, shape_id: str) -> dict:
        lookups = self.cache_hits + self.cache_misses
        return {
            "shape_id": shape_id,
            "shape": self.shape,
            "count": self.count,
            "cost_ms": round(self.cost_ms, 3),
            "outcomes": dict(self.outcomes),
            "cache": {
                "hits": self.cache_hits,
                "misses": self.cache_misses,
                "hit_rate": round(self.cache_hits / lookups, 4) if lookups
                else 0.0,
            },
            "coalesced": self.coalesced,
            "latency": self.latency.stats(),
            "queue": self.queue.stats(),
            "device": self.device.stats(),
        }


class QueryShapeInsights:
    """Node-level bounded LRU registry of query shapes.

    `record()` is the one write entry point, called once per shard query
    phase from actions._s_query_phase with clocks that path already read.
    Reads (`top`, `stats`, `prom_series`) snapshot under the leaf lock and
    summarize outside it."""

    def __init__(self, settings=None):
        from .settings import Settings

        settings = settings or Settings.EMPTY
        self.enabled = bool(settings.get_bool("search.insights.enabled", True))
        self.max_shapes = max(1, settings.get_int(
            "search.insights.max_shapes", 128))
        self._lock = threading.Lock()
        self._shapes: "OrderedDict[str, ShapeStats]" = OrderedDict()
        self.demotions = 0
        # demoted shapes fold here so node totals stay honest after LRU churn
        self._other_count = 0
        self._other_cost_ms = 0.0

    def fingerprint(self, body: dict | None) -> tuple[str, dict]:
        # feed the top-k bucket ladder from the raw body (compilecache): this
        # runs on EVERY search — including request-cache hits that never reach
        # a device launch — so the autotuner's histogram sees the real query
        # mix, not just the cache-missing tail. Observation only: the bucket
        # result is discarded here (16 = batcher._K_MIN lane)
        if self.enabled:
            from .compilecache import LADDERS

            try:
                k = (int((body or {}).get("size", 10) or 0)
                     + int((body or {}).get("from", 0) or 0))
            except (TypeError, ValueError):
                k = 0
            if k > 0:
                LADDERS.bucket("k", k, 16)
        return shape_fingerprint(body)

    # -- write ---------------------------------------------------------------
    def record(self, shape_id: str, shape: dict, took_s: float | None = None,
               obs: Observation | None = None,
               cache: str | None = None) -> None:
        """Fold one finished shard query phase into its shape's stats.

        `took_s` is the slowlog's existing clock pair (None on the
        request-cache hit path, which reads no clock at all — a hit records
        count + cache attribution only). `cache` is "hit"/"miss"/None
        (ineligible). Histogram observes happen OUTSIDE the registry lock."""
        with self._lock:
            st = self._shapes.get(shape_id)
            if st is None:
                st = ShapeStats(shape)
                self._shapes[shape_id] = st
                while len(self._shapes) > self.max_shapes:
                    _sid, old = self._shapes.popitem(last=False)
                    self.demotions += 1
                    self._other_count += old.count
                    self._other_cost_ms += old.cost_ms
            else:
                self._shapes.move_to_end(shape_id)
            st.count += 1
            if took_s is not None:
                st.cost_ms += took_s * 1000.0
            if cache == "hit":
                st.cache_hits += 1
            elif cache == "miss":
                st.cache_misses += 1
            outcome = "cache_hit" if cache == "hit" else \
                (obs.outcome if obs is not None else None) or "unknown"
            if outcome not in _OUTCOME_SET:
                outcome = "unknown"
            st.outcomes[outcome] = st.outcomes.get(outcome, 0) + 1
            if obs is not None and obs.occupancy is not None \
                    and obs.occupancy > 1:
                st.coalesced += 1
        if took_s is not None:
            st.latency.observe(took_s)
        if obs is not None:
            if obs.queue_s is not None:
                st.queue.observe(obs.queue_s)
            if obs.device_s is not None:
                st.device.observe(obs.device_s)

    # -- read ----------------------------------------------------------------
    def _snapshot(self) -> list[tuple[str, ShapeStats]]:
        with self._lock:
            return list(self._shapes.items())

    def top(self, n: int = 10) -> list[dict]:
        """Top-N shapes by accumulated cost (total latency): the operator's
        'which queries are eating the cluster' read."""
        entries = [(sid, st, st.cost_ms) for sid, st in self._snapshot()]
        entries.sort(key=lambda e: -e[2])
        return [st.to_dict(sid) for sid, st, _cost in entries[: max(n, 0)]]

    def stats(self) -> dict:
        """The `/_nodes/stats` `search.shapes` section: registry occupancy +
        a compact top-5 (full entries via GET /_insights/queries)."""
        snap = self._snapshot()
        top = sorted(((sid, st) for sid, st in snap),
                     key=lambda e: -e[1].cost_ms)[:5]
        with self._lock:
            other = {"count": self._other_count,
                     "cost_ms": round(self._other_cost_ms, 3)}
            demotions = self.demotions
        return {
            "enabled": self.enabled,
            "shapes": len(snap),
            "max_shapes": self.max_shapes,
            "demotions": demotions,
            "other": other,
            "top": [{"shape_id": sid, "count": st.count,
                     "cost_ms": round(st.cost_ms, 3)}
                    for sid, st in top],
        }

    def prom_series(self) -> list[tuple[str, ShapeStats]]:
        """Resident shapes for the Prometheus exposition — at most
        `max_shapes` label values by construction (the LRU demotion IS the
        cardinality bound)."""
        return self._snapshot()

    def clear(self) -> None:
        with self._lock:
            self._shapes.clear()
            self.demotions = 0
            self._other_count = 0
            self._other_cost_ms = 0.0
