"""Geohash + shape geometry for the geo query family.

ref: the reference's geohash utilities (common/geo/GeoHashUtils.java) and the
geo_shape machinery (common/geo/builders/*, index/query/GeoShapeQueryParser.java:1,
GeohashCellFilter.java:1). The reference indexes shapes into Lucene spatial prefix
trees; here shapes are stored as per-doc columnar values and relations evaluate
host-side with exact computational geometry (filters are host-plane by design —
ARCHITECTURE.md), so there is no precision/distance-error knob to tune.
"""

from __future__ import annotations

import math

_BASE32 = "0123456789bcdefghjkmnpqrstuvwxyz"
_BASE32_IDX = {c: i for i, c in enumerate(_BASE32)}


def geohash_encode(lat: float, lon: float, precision: int = 12) -> str:
    """Standard geohash: interleaved lon/lat bisection bits, base32."""
    lat_lo, lat_hi = -90.0, 90.0
    lon_lo, lon_hi = -180.0, 180.0
    bits = []
    even = True
    while len(bits) < precision * 5:
        if even:
            mid = (lon_lo + lon_hi) / 2
            if lon >= mid:
                bits.append(1)
                lon_lo = mid
            else:
                bits.append(0)
                lon_hi = mid
        else:
            mid = (lat_lo + lat_hi) / 2
            if lat >= mid:
                bits.append(1)
                lat_lo = mid
            else:
                bits.append(0)
                lat_hi = mid
        even = not even
    out = []
    for i in range(0, len(bits), 5):
        v = 0
        for b in bits[i: i + 5]:
            v = (v << 1) | b
        out.append(_BASE32[v])
    return "".join(out)


def geohash_bbox(h: str) -> tuple[float, float, float, float]:
    """(lat_lo, lat_hi, lon_lo, lon_hi) of the cell."""
    if not h:
        raise ValueError("empty geohash")
    lat_lo, lat_hi = -90.0, 90.0
    lon_lo, lon_hi = -180.0, 180.0
    even = True
    for c in h:
        v = _BASE32_IDX[c]
        for shift in range(4, -1, -1):
            bit = (v >> shift) & 1
            if even:
                mid = (lon_lo + lon_hi) / 2
                if bit:
                    lon_lo = mid
                else:
                    lon_hi = mid
            else:
                mid = (lat_lo + lat_hi) / 2
                if bit:
                    lat_lo = mid
                else:
                    lat_hi = mid
            even = not even
    return lat_lo, lat_hi, lon_lo, lon_hi


def geohash_decode(h: str) -> tuple[float, float]:
    """Cell-center (lat, lon)."""
    lat_lo, lat_hi, lon_lo, lon_hi = geohash_bbox(h)
    return (lat_lo + lat_hi) / 2, (lon_lo + lon_hi) / 2


def geohash_neighbors(h: str) -> list[str]:
    """The 8 surrounding cells at the same precision (dateline-wrapped)."""
    lat_lo, lat_hi, lon_lo, lon_hi = geohash_bbox(h)
    dlat = lat_hi - lat_lo
    dlon = lon_hi - lon_lo
    clat, clon = (lat_lo + lat_hi) / 2, (lon_lo + lon_hi) / 2
    out = []
    for dy in (-1, 0, 1):
        for dx in (-1, 0, 1):
            if dx == 0 and dy == 0:
                continue
            lat = clat + dy * dlat
            lon = clon + dx * dlon
            if not -90.0 <= lat <= 90.0:
                continue
            if lon > 180.0:
                lon -= 360.0
            elif lon < -180.0:
                lon += 360.0
            out.append(geohash_encode(lat, lon, len(h)))
    return sorted(set(out))


# ---------------------------------------------------------------------------
# shapes: normalized form + relations
# ---------------------------------------------------------------------------
# normalized: ("point", (lon, lat))
#             ("envelope", (min_lon, min_lat, max_lon, max_lat))
#             ("polygon", [outer_ring, hole_ring...])  rings = [(lon, lat), ...]


def normalize_shape(spec: dict):
    """GeoJSON-ish {"type", "coordinates"} (ES envelope convention: upper-left,
    lower-right) → normalized tuple. Raises ValueError on unsupported types."""
    t = str(spec.get("type", "")).lower()
    coords = spec.get("coordinates")
    if coords is None:
        raise ValueError("shape requires [coordinates]")
    if t == "point":
        lon, lat = float(coords[0]), float(coords[1])
        return ("point", (lon, lat))
    if t == "envelope":
        (lon1, lat1), (lon2, lat2) = coords  # upper-left, lower-right (ES order)
        return ("envelope", (min(lon1, lon2), min(lat1, lat2),
                             max(lon1, lon2), max(lat1, lat2)))
    if t == "polygon":
        rings = []
        for ring in coords:
            pts = [(float(lon), float(lat)) for lon, lat in ring]
            if len(pts) >= 2 and pts[0] == pts[-1]:
                pts = pts[:-1]  # drop closing point
            if len(pts) < 3:
                raise ValueError("polygon ring needs >= 3 points")
            rings.append(pts)
        if not rings:
            raise ValueError("polygon requires at least the outer ring")
        return ("polygon", rings)
    raise ValueError(f"unsupported geo_shape type [{t}]")


def shape_bbox(shape):
    kind, data = shape
    if kind == "point":
        lon, lat = data
        return (lon, lat, lon, lat)
    if kind == "envelope":
        return data
    lons = [p[0] for p in data[0]]
    lats = [p[1] for p in data[0]]
    return (min(lons), min(lats), max(lons), max(lats))


def _bbox_overlap(a, b):
    return not (a[2] < b[0] or b[2] < a[0] or a[3] < b[1] or b[3] < a[1])


def _pt_in_ring(pt, ring) -> bool:
    """Ray cast; boundary points count as inside (matches the closed-region
    semantics of the reference's spatial intersects)."""
    x, y = pt
    inside = False
    n = len(ring)
    for i in range(n):
        x1, y1 = ring[i]
        x2, y2 = ring[(i + 1) % n]
        # on-segment check
        if (min(x1, x2) - 1e-12 <= x <= max(x1, x2) + 1e-12
                and min(y1, y2) - 1e-12 <= y <= max(y1, y2) + 1e-12):
            cross = (x2 - x1) * (y - y1) - (y2 - y1) * (x - x1)
            if abs(cross) < 1e-12:
                return True
        if (y1 > y) != (y2 > y):
            xin = (x2 - x1) * (y - y1) / (y2 - y1) + x1
            if x < xin:
                inside = not inside
    return inside


def _pt_in_poly(pt, rings) -> bool:
    if not _pt_in_ring(pt, rings[0]):
        return False
    return not any(_pt_in_ring(pt, hole) for hole in rings[1:])


def _segs_intersect(p1, p2, p3, p4) -> bool:
    def orient(a, b, c):
        v = (b[0] - a[0]) * (c[1] - a[1]) - (b[1] - a[1]) * (c[0] - a[0])
        return 0 if abs(v) < 1e-12 else (1 if v > 0 else -1)

    def on_seg(a, b, c):
        return (min(a[0], b[0]) - 1e-12 <= c[0] <= max(a[0], b[0]) + 1e-12
                and min(a[1], b[1]) - 1e-12 <= c[1] <= max(a[1], b[1]) + 1e-12)

    o1, o2 = orient(p1, p2, p3), orient(p1, p2, p4)
    o3, o4 = orient(p3, p4, p1), orient(p3, p4, p2)
    if o1 != o2 and o3 != o4:
        return True
    return ((o1 == 0 and on_seg(p1, p2, p3)) or (o2 == 0 and on_seg(p1, p2, p4))
            or (o3 == 0 and on_seg(p3, p4, p1)) or (o4 == 0 and on_seg(p3, p4, p2)))


def _env_ring(env):
    lo_lon, lo_lat, hi_lon, hi_lat = env
    return [(lo_lon, lo_lat), (hi_lon, lo_lat), (hi_lon, hi_lat), (lo_lon, hi_lat)]


def _ring_edges(ring):
    n = len(ring)
    return [(ring[i], ring[(i + 1) % n]) for i in range(n)]


def shapes_intersect(a, b) -> bool:
    """Exact intersects relation over {point, envelope, polygon}."""
    if not _bbox_overlap(shape_bbox(a), shape_bbox(b)):
        return False
    ka, kb = a[0], b[0]
    if ka == "point" and kb == "point":
        return (abs(a[1][0] - b[1][0]) < 1e-9) and (abs(a[1][1] - b[1][1]) < 1e-9)
    if ka == "point":
        return _shape_contains_pt(b, a[1])
    if kb == "point":
        return _shape_contains_pt(a, b[1])
    ring_a = _env_ring(a[1]) if ka == "envelope" else a[1][0]
    ring_b = _env_ring(b[1]) if kb == "envelope" else b[1][0]
    rings_a = [ring_a] if ka == "envelope" else a[1]
    rings_b = [ring_b] if kb == "envelope" else b[1]
    # any vertex containment either way, else any edge crossing
    if any(_pt_in_poly(p, rings_b) for p in ring_a):
        return True
    if any(_pt_in_poly(p, rings_a) for p in ring_b):
        return True
    return any(_segs_intersect(e1[0], e1[1], e2[0], e2[1])
               for e1 in _ring_edges(ring_a) for e2 in _ring_edges(ring_b))


def _shape_contains_pt(shape, pt) -> bool:
    kind, data = shape
    if kind == "point":
        return (abs(data[0] - pt[0]) < 1e-9) and (abs(data[1] - pt[1]) < 1e-9)
    if kind == "envelope":
        return data[0] - 1e-12 <= pt[0] <= data[2] + 1e-12 \
            and data[1] - 1e-12 <= pt[1] <= data[3] + 1e-12
    return _pt_in_poly(pt, data)


def shape_within(inner, outer) -> bool:
    """inner entirely within outer: every inner vertex inside (holes respected),
    no inner edge crossing ANY outer ring (boundary or hole), and no outer hole
    swallowed by inner (a hole inside inner means inner spans excluded area)."""
    ki = inner[0]
    if ki == "point":
        return _shape_contains_pt(outer, inner[1])
    ring_i = _env_ring(inner[1]) if ki == "envelope" else inner[1][0]
    ko = outer[0]
    if ko == "point":
        return False
    rings_o = [_env_ring(outer[1])] if ko == "envelope" else outer[1]
    if not all(_pt_in_poly(p, rings_o) for p in ring_i):
        return False
    for ring_o in rings_o:
        if any(_segs_intersect(e1[0], e1[1], e2[0], e2[1])
               for e1 in _ring_edges(ring_i) for e2 in _ring_edges(ring_o)
               if e1[0] not in (e2[0], e2[1]) and e1[1] not in (e2[0], e2[1])):
            return False
    return not any(_pt_in_ring(p, ring_i) for hole in rings_o[1:] for p in hole)


def haversine_m(lat1, lon1, lat2, lon2):
    """Great-circle metres (scalar)."""
    r = 6371000.0
    p1, p2 = math.radians(lat1), math.radians(lat2)
    dp = p2 - p1
    dl = math.radians(lon2 - lon1)
    h = math.sin(dp / 2) ** 2 + math.cos(p1) * math.cos(p2) * math.sin(dl / 2) ** 2
    return 2 * r * math.asin(math.sqrt(h))
