"""Runtime lock-trace sanitizer — the dynamic twin of tpulint TPU004/TPU011.

The static rules prove what the call graph CAN do; this module records what a
real run actually DID, the lockdep/ThreadSanitizer pairing the repo already
uses for transfers (tpulint TPU001 <-> transfer_guard) and retraces (TPU002
<-> compile-budget). Under `ESTPU_LOCKTRACE=1`:

- `threading.Lock` / `threading.RLock` construction is wrapped so every lock
  CREATED IN THIS REPO (creation site under elasticsearch_tpu/ or tests/ —
  jax/stdlib internals stay untraced and unperturbed) records per-thread
  acquisition order. Locks are aggregated by CONSTRUCTION SITE, lockdep's
  "lock class": every `MemoryCircuitBreaker._lock` is one node, which is also
  why a child->parent acquisition inside one hierarchy is a self-edge and
  ignored — instances of one class are layered by construction.
  `threading.Condition()` is covered transitively (its internal RLock comes
  from the patched factory).
- the lock-order graph accumulates over the whole run; `TRACER.check()` (the
  tests/conftest.py session gate) fails with a LockOrderViolation naming the
  acquisition sites of every edge on the first cycle found — the ABBA hazard
  is reported from any interleaving, deadlock never required.
- `jax.device_get` is wrapped to time pulls performed WHILE HOLDING a traced
  lock (`held_device_gets` / `held_device_get_max_ms` counters; sites longer
  than `ESTPU_LOCKTRACE_HELD_MS` land in `TRACER.long_held`) — the runtime
  form of TPU004's dispatch-under-lock rule.

Overhead is exactly zero when the knob is off: `maybe_install()` returns
without touching `threading`, and no wrapper exists anywhere on the lock path.
Counters surface through the existing sanitizer report (jaxenv.sanitize()
attaches a snapshot to SanitizerReport.locks).
"""

from __future__ import annotations

import os
import sys
import threading
import time

# saved BEFORE any patching; the tracer's own lock must never trace itself
_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock

_REPO_MARKERS = (f"{os.sep}elasticsearch_tpu{os.sep}", f"{os.sep}tests{os.sep}")
_SELF_FILE = os.path.abspath(__file__)


class LockOrderViolation(AssertionError):
    """The runtime lock-order graph contains a cycle — an ABBA deadlock is one
    unlucky interleaving away. The message names both acquisition sites."""


_REL_CACHE: dict = {}


def _rel(fn: str) -> str:
    r = _REL_CACHE.get(fn)
    if r is None:
        r = _REL_CACHE[fn] = os.path.relpath(fn)
    return r


def _creation_site() -> str | None:
    """file:line of the first frame outside this module and threading.py;
    None (= do not trace) when the lock is created outside the repo."""
    f = sys._getframe(2)
    while f is not None:
        fn = f.f_code.co_filename
        if fn != _SELF_FILE and f"{os.sep}threading.py" not in fn:
            if any(m in fn for m in _REPO_MARKERS) or \
                    "tpulint_fixtures" in fn:
                return f"{_rel(fn)}:{f.f_lineno}"
            return None
        f = f.f_back
    return None


def _acquire_site() -> tuple:
    """RAW (filename, lineno) — formatting (relpath hits getcwd) is deferred
    to first-edge-witness time; the per-acquisition cost is the frame walk
    alone."""
    f = sys._getframe(2)
    while f is not None:
        fn = f.f_code.co_filename
        if fn != _SELF_FILE and f"{os.sep}threading.py" not in fn:
            return (fn, f.f_lineno)
        f = f.f_back
    return ("<unknown>", 0)


def _fmt_site(raw) -> str:
    fn, line = raw
    return f"{_rel(fn)}:{line}" if line else fn


class LockTracer:
    """Process-wide recorder: per-thread held stacks + the order graph."""

    def __init__(self):
        self._glock = _REAL_LOCK()
        self._tls = threading.local()
        self.enabled = False
        self.held_ms_threshold = 0.0
        # (site_a, site_b) -> (acquire_site_a, acquire_site_b): first witness
        self.edges: dict = {}
        self.counters = {
            "locks_created": 0,
            "acquisitions": 0,
            "edges": 0,
            "held_device_gets": 0,
            "held_device_get_max_ms": 0.0,
        }
        self.long_held: list = []  # (lock_site, ms, what) above the threshold

    # -- per-thread stack -----------------------------------------------------
    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    # -- recording ------------------------------------------------------------
    def on_created(self) -> None:
        with self._glock:
            self.counters["locks_created"] += 1

    def on_acquired(self, lock_site: str, acq_raw: tuple) -> None:
        st = self._stack()
        with self._glock:
            self.counters["acquisitions"] += 1
            if st:
                outer_site, outer_raw = st[-1]
                if outer_site != lock_site:  # self-edge = layered instances/RLock
                    key = (outer_site, lock_site)
                    if key not in self.edges:
                        # first witness of this edge: only now pay relpath
                        self.edges[key] = (_fmt_site(outer_raw),
                                           _fmt_site(acq_raw))
                        self.counters["edges"] += 1
        st.append((lock_site, acq_raw))

    def on_released(self, lock_site: str) -> None:
        st = self._stack()
        for i in range(len(st) - 1, -1, -1):  # out-of-order release tolerated
            if st[i][0] == lock_site:
                del st[i]
                return

    def held(self) -> list:
        return [site for site, _acq in self._stack()]

    def note_held_dispatch(self, duration_s: float, what: str) -> None:
        st = self._stack()
        if not st:
            return
        ms = duration_s * 1000.0
        with self._glock:
            self.counters["held_device_gets"] += 1
            self.counters["held_device_get_max_ms"] = max(
                self.counters["held_device_get_max_ms"], round(ms, 3))
            if self.held_ms_threshold and ms > self.held_ms_threshold:
                self.long_held.append((st[-1][0], round(ms, 3), what))

    # -- the gate -------------------------------------------------------------
    def find_cycle(self) -> list | None:
        """A list of (a, b, acq_a, acq_b) edges forming a cycle, or None."""
        with self._glock:
            graph: dict = {}
            for (a, b) in self.edges:
                graph.setdefault(a, set()).add(b)
            edges = dict(self.edges)
        state: dict = {}  # 0 visiting, 1 done
        path: list = []

        def dfs(v):
            state[v] = 0
            path.append(v)
            for w in sorted(graph.get(v, ())):
                if state.get(w) == 0:
                    cyc = path[path.index(w):] + [w]
                    return [(a, b, *edges[(a, b)])
                            for a, b in zip(cyc, cyc[1:])]
                if w not in state:
                    found = dfs(w)
                    if found:
                        return found
            path.pop()
            state[v] = 1
            return None

        for v in sorted(graph):
            if v not in state:
                found = dfs(v)
                if found:
                    return found
        return None

    def check(self) -> None:
        cyc = self.find_cycle()
        if cyc:
            lines = [f"  `{a}` then `{b}`  (acquired at {acq_a} -> {acq_b})"
                     for (a, b, acq_a, acq_b) in cyc]
            raise LockOrderViolation(
                "lock-order cycle observed at runtime — an ABBA deadlock is "
                "one interleaving away:\n" + "\n".join(lines) +
                "\npick one global acquisition order (tpulint TPU004 is the "
                "static twin of this check)")

    def snapshot(self) -> dict:
        with self._glock:
            return {**self.counters, "long_held": list(self.long_held)}


TRACER = LockTracer()


class _TracedLock:
    """Delegating wrapper for Lock/RLock objects created in repo code."""

    __slots__ = ("_inner", "_site")

    def __init__(self, inner, site: str):
        self._inner = inner
        self._site = site

    def acquire(self, blocking=True, timeout=-1):
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            TRACER.on_acquired(self._site, _acquire_site())
        return ok

    def release(self):
        TRACER.on_released(self._site)
        self._inner.release()

    def __enter__(self):
        self._inner.acquire()
        TRACER.on_acquired(self._site, _acquire_site())
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._inner.locked()

    def __getattr__(self, name):
        # RLock internals Condition needs (_is_owned/_acquire_restore/
        # _release_save) delegate straight to the real lock: the wait-side
        # release/reacquire dance is internal to one condition and is not an
        # ordering event
        return getattr(self._inner, name)


def _traced_lock_factory():
    site = _creation_site()
    if site is None:
        return _REAL_LOCK()
    TRACER.on_created()
    return _TracedLock(_REAL_LOCK(), site)


def _traced_rlock_factory():
    site = _creation_site()
    if site is None:
        return _REAL_RLOCK()
    TRACER.on_created()
    return _TracedLock(_REAL_RLOCK(), site)


def _wrap_device_get() -> None:
    if "jax" not in sys.modules:
        return
    import jax

    real = jax.device_get
    if getattr(real, "_estpu_locktrace", False):
        return

    def device_get(x):
        t0 = time.perf_counter()
        try:
            return real(x)
        finally:
            TRACER.note_held_dispatch(time.perf_counter() - t0,
                                      "jax.device_get")

    device_get._estpu_locktrace = True
    jax.device_get = device_get


def install(held_ms_threshold: float | None = None) -> LockTracer:
    """Arm the tracer (idempotent). Prefer maybe_install() — the env knob."""
    if not TRACER.enabled:
        TRACER.enabled = True
        threading.Lock = _traced_lock_factory
        threading.RLock = _traced_rlock_factory
    if held_ms_threshold is not None:
        TRACER.held_ms_threshold = float(held_ms_threshold)
    _wrap_device_get()
    return TRACER


def maybe_install() -> LockTracer | None:
    """Install iff ESTPU_LOCKTRACE=1 (same env-knob conventions as
    ESTPU_SANITIZE / ESTPU_COMPILE_BUDGET). Threshold for long-held dispatch
    reporting: ESTPU_LOCKTRACE_HELD_MS (float ms; unset/0 = record only)."""
    if os.environ.get("ESTPU_LOCKTRACE", "") not in ("1", "on", "true"):
        return None
    return install(float(os.environ.get("ESTPU_LOCKTRACE_HELD_MS", "0") or 0))
