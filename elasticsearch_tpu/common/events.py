"""Cluster event journal + serving-path stall watchdog.

The reference dedicates whole subsystems to "what just went wrong on this
node" (pending-tasks, cluster health, the slowlog); what none of them give an
operator is a *causal record* of serving-path stalls: the batcher's drainer
wedged on a device pull, a pool's queue-wait p99 exploding, a breaker parked
just under its trip line, a lock held across something slow. This module is
that record:

- **EventJournal** — a bounded, rate-limited ring of typed events on each
  node. Every event carries (seq, epoch ts, node, type, severity, message,
  attrs); per-(type, key) rate limiting keeps a sustained condition from
  storming the ring (suppressed emissions are counted, never silently
  dropped). Remote events gossiped from other nodes land in the same ring
  (dedup'd by origin seq), so `GET /_events` on any node reads a
  cluster-wide, human-readable causal record.
- **StallWatchdog** — a management-pool periodic task comparing live
  in-flight state against *adaptive* thresholds:

    batch_stall       dispatched-unmerged batch age vs the batcher's own
                      service-time EWMA (DeviceBatcher.inflight() — a plain
                      unlocked read of drainer-written state)
    queue_spike       per-pool queue-wait p99 over the ticks SINCE THE LAST
                      CHECK (delta histograms — a lifetime p99 would take
                      minutes to notice a brown-out) vs a decayed baseline
    breaker_pressure  a breaker dwelling >= `dwell` consecutive ticks above
                      `high_ratio` of its limit (near-trip dwell is the
                      overload precursor a trip counter can't show)
    lock_stall        locktrace long-held counters growing, when
                      ESTPU_LOCKTRACE=1 armed the tracer (off = skipped)

Event type vocabulary (bounded — it is a Prometheus label):
  batch_stall | queue_spike | breaker_pressure | lock_stall | watchdog
  | device_degraded | device_recovered

Hot-path contract: the watchdog runs ON the management pool and reads
serving-side state as plain attributes or through existing leaf-locked
stats() calls — the serving path itself gains zero locks, zero clocks, zero
syncs from any of this. The journal lock is a leaf (dict/deque mutation
only); gossip sends happen from the watchdog tick, never a serving thread.
"""

from __future__ import annotations

import threading
import time
from collections import deque

EVENT_TYPES = ("batch_stall", "queue_spike", "breaker_pressure",
               "lock_stall", "watchdog",
               # device fault-domain circuit transitions (common/devicehealth):
               # a domain tripping open / a probe closing it again
               "device_degraded", "device_recovered")


class EventJournal:
    """Bounded per-node ring of typed cluster events (newest kept).

    `_lock` is a LEAF: deque/dict/counter mutation only — nothing under it
    blocks, dispatches, or calls out."""

    def __init__(self, settings=None, node_name: str = "node",
                 node_id: str = "node"):
        from .settings import Settings

        settings = settings or Settings.EMPTY
        self.node_name = node_name
        self.node_id = node_id
        self.size = max(8, settings.get_int("node.events.size", 256))
        # minimum seconds between two emissions of the same (type, key):
        # a wedged drainer must not write a 256-deep ring of one stall
        self.throttle_s = settings.get_time("node.events.throttle", 10.0)
        self._lock = threading.Lock()
        self._ring: deque[dict] = deque(maxlen=self.size)
        self._seq = 0
        self._last_emit: dict[tuple, float] = {}  # (type, key) -> monotonic
        self._remote_seen: dict[str, int] = {}  # origin node -> max seq
        self.emitted = 0
        self.suppressed = 0
        self.remote_ingested = 0
        self.remote_duplicates = 0
        self.by_type: dict[str, int] = {t: 0 for t in EVENT_TYPES}

    # -- write ---------------------------------------------------------------
    def publish(self, type_: str, message: str, severity: str = "warn",
                key: str | None = None, **attrs) -> dict | None:
        """Emit one local event; returns the event dict, or None when the
        (type, key) pair is inside its rate-limit window (counted)."""
        if type_ not in EVENT_TYPES:
            type_ = "watchdog"
        now = time.monotonic()
        with self._lock:
            rk = (type_, key)
            last = self._last_emit.get(rk)
            if last is not None and self.throttle_s \
                    and now - last < self.throttle_s:
                self.suppressed += 1
                return None
            self._last_emit[rk] = now
            # the rate-limit map must not grow one entry per transient key
            # forever (batch ids are unbounded) — drop expired windows
            if len(self._last_emit) > 4 * self.size:
                self._last_emit = {
                    k: v for k, v in self._last_emit.items()
                    if now - v < (self.throttle_s or 0.0)}
            self._seq += 1
            event = {
                "seq": self._seq,
                "ts": time.time(),
                "node": self.node_id,
                "node_name": self.node_name,
                "type": type_,
                "severity": severity,
                "message": message,
                "attrs": attrs,
            }
            self._ring.append(event)
            self.emitted += 1
            self.by_type[type_] = self.by_type.get(type_, 0) + 1
        return event

    def ingest(self, event: dict) -> bool:
        """A gossiped remote event lands in this node's ring (dedup'd by the
        origin's monotonically increasing seq). Returns True when stored."""
        if not isinstance(event, dict) or "seq" not in event:
            return False
        origin = str(event.get("node", "?"))
        if origin == self.node_id:
            return False  # our own event bounced back through the ring
        seq = int(event["seq"])
        stored = dict(event)
        try:
            # a ts-less/malformed remote event must not poison every future
            # events() sort for the ring's lifetime — stamp arrival time
            stored["ts"] = float(stored.get("ts") or 0.0) or time.time()
        except (TypeError, ValueError):
            stored["ts"] = time.time()
        with self._lock:
            if seq <= self._remote_seen.get(origin, 0):
                self.remote_duplicates += 1
                return False
            self._remote_seen[origin] = seq
            self._ring.append(stored)
            self.remote_ingested += 1
        return True

    # -- read ----------------------------------------------------------------
    def events(self, limit: int | None = None) -> list[dict]:
        """Newest first."""
        with self._lock:
            out = list(self._ring)
        out.sort(key=lambda e: -float(e.get("ts", 0.0)))
        return out if limit is None else out[: max(limit, 0)]

    def stats(self) -> dict:
        with self._lock:
            return {
                "size": self.size,
                "entries": len(self._ring),
                "emitted": self.emitted,
                "suppressed": self.suppressed,
                "remote_ingested": self.remote_ingested,
                "remote_duplicates": self.remote_duplicates,
                "by_type": dict(self.by_type),
            }


class StallWatchdog:
    """Management-pool watchdog: detects serving-path stalls from live state
    and journals typed, rate-limited events (gossiped to the other nodes so
    any coordinator's `/_events` shows the cluster-wide record).

    All thresholds are adaptive around signals the system already maintains
    (the batcher's service-time EWMA, each pool's queue-wait histogram, the
    breakers' own estimates) with settable floors — a cold node with no
    baseline falls back to the floors."""

    def __init__(self, node, settings=None):
        from .settings import Settings

        settings = settings or getattr(node, "settings", None) or Settings.EMPTY
        self.node = node
        self.enabled = bool(settings.get_bool("watchdog.enabled", True))
        self.interval_s = max(0.05, settings.get_time(
            "watchdog.interval", 1.0))
        # batch stall: age > max(min, factor x the batcher's own EWMA)
        self.batch_factor = settings.get_float("watchdog.batch_stall_factor",
                                               16.0)
        self.batch_min_s = settings.get_time("watchdog.batch_stall_min",
                                             0.5)
        # queue spike: delta-p99 > max(min, factor x decayed baseline),
        # needing at least min_samples completions since the last tick
        self.queue_factor = settings.get_float("watchdog.queue_p99_factor",
                                               4.0)
        self.queue_min_s = settings.get_time("watchdog.queue_p99_min", 0.25)
        self.queue_min_samples = settings.get_int(
            "watchdog.queue_min_samples", 8)
        # breaker dwell: >= dwell consecutive ticks above high_ratio
        self.breaker_high = settings.get_float("watchdog.breaker_high_ratio",
                                               0.85)
        self.breaker_dwell = max(1, settings.get_int(
            "watchdog.breaker_dwell_ticks", 2))
        self.ticks = 0
        self._task = None
        # per-pool delta-histogram state + decayed p99 baseline
        self._pool_counts: dict[str, list[int]] = {}
        self._pool_totals: dict[str, int] = {}
        self._pool_baseline: dict[str, float] = {}
        self._breaker_dwell: dict[str, int] = {}
        # locktrace growth watermarks
        self._held_gets = 0
        self._long_held = 0

    # -- lifecycle -----------------------------------------------------------
    def start(self):
        if not self.enabled or self._task is not None:
            return self
        self._task = self.node.threadpool.schedule_with_fixed_delay(
            self.interval_s, self.tick, name="management")
        return self

    def stop(self):
        if self._task is not None:
            self._task.cancel()
            self._task = None

    # -- the tick ------------------------------------------------------------
    def tick(self):
        """One watchdog pass. Runs on the management pool; every read below
        is a plain attribute read or an existing leaf-locked stats() call —
        never a serving-path lock, clock, or device touch."""
        self.ticks += 1
        try:
            self._check_batch_stall()
            self._check_queue_waits()
            self._check_breakers()
            self._check_locktrace()
        except Exception:  # noqa: BLE001 — a broken check must not kill the
            # schedule; the next tick retries (and the scheduler survives)
            from .logging import get_logger

            get_logger("watchdog").warning("watchdog tick failed",
                                           exc_info=True)

    def _emit(self, type_: str, message: str, key: str | None = None,
              **attrs):
        journal = getattr(self.node, "events", None)
        if journal is None:
            return
        event = journal.publish(type_, message, key=key, **attrs)
        if event is not None:
            self._gossip(event)

    def _gossip(self, event: dict):
        """Best-effort push of one event to every other cluster node (their
        journals dedup by origin seq). Fire-and-forget sends from the
        watchdog tick — the serving path is never involved."""
        try:
            from ..actions import A_EVENTS_PUBLISH

            state = self.node.cluster_service.state
            for n in state.nodes.nodes:
                if n.id == self.node.node_id:
                    continue
                try:
                    self.node.transport.send_request(
                        n, A_EVENTS_PUBLISH, {"event": event})
                except Exception:  # noqa: BLE001 — a dropping peer is the
                    continue       # journal's business, not the watchdog's
        except Exception:  # noqa: BLE001 — no cluster service / shutdown race
            pass

    # -- checks --------------------------------------------------------------
    def _check_batch_stall(self):
        batcher = getattr(self.node, "search_batcher", None)
        if batcher is None:
            return
        snap = batcher.inflight()
        if snap is None:
            return
        ewma = float(getattr(batcher, "_ewma_cost", 0.0))
        threshold = max(self.batch_min_s, self.batch_factor * ewma)
        if snap["age_s"] <= threshold:
            return
        self._emit(
            "batch_stall",
            f"batch [{snap['batch']}] on [{snap['shard']}] dispatched "
            f"{snap['age_s'] * 1000:.0f}ms ago and not merged "
            f"(EWMA {ewma * 1000:.1f}ms, occupancy {snap['occupancy']})",
            key=f"batch:{snap['batch']}",
            batch=snap["batch"], shard=snap["shard"],
            family=snap["family"], occupancy=snap["occupancy"],
            age_ms=round(snap["age_s"] * 1000.0, 1),
            ewma_ms=round(ewma * 1000.0, 3))

    def _check_queue_waits(self):
        pools = self.node.threadpool.pool_histograms()
        for name, hist in pools.items():
            counts, total, _sum = hist.snapshot()
            prev_counts = self._pool_counts.get(name)
            prev_total = self._pool_totals.get(name, 0)
            self._pool_counts[name] = counts
            self._pool_totals[name] = total
            if prev_counts is None:
                continue
            delta_total = total - prev_total
            if delta_total < self.queue_min_samples:
                continue
            delta = [c - p for c, p in zip(counts, prev_counts)]
            p99 = hist._percentile_from(delta, delta_total, 0.99)
            baseline = self._pool_baseline.get(name)
            threshold = self.queue_min_s if baseline is None else \
                max(self.queue_min_s, self.queue_factor * baseline)
            # decayed baseline learns AFTER the comparison, so a spike can't
            # teach itself normal within one tick
            self._pool_baseline[name] = p99 if baseline is None else \
                0.2 * p99 + 0.8 * baseline
            if p99 > threshold:
                self._emit(
                    "queue_spike",
                    f"pool [{name}] queue-wait p99 {p99 * 1000:.1f}ms over "
                    f"the last tick ({delta_total} tasks; baseline "
                    f"{(baseline or 0.0) * 1000:.1f}ms)",
                    key=f"pool:{name}", pool=name,
                    p99_ms=round(p99 * 1000.0, 2),
                    baseline_ms=round((baseline or 0.0) * 1000.0, 2),
                    tasks=delta_total)

    def _check_breakers(self):
        breakers = getattr(self.node, "breakers", None)
        if breakers is None:
            return
        for name, b in breakers.stats().items():
            limit = b.get("limit", 0) or 0
            ratio = (b.get("estimated", 0) / limit) if limit > 0 else 0.0
            if ratio >= self.breaker_high:
                dwell = self._breaker_dwell.get(name, 0) + 1
                self._breaker_dwell[name] = dwell
                if dwell >= self.breaker_dwell:
                    self._emit(
                        "breaker_pressure",
                        f"breaker [{name}] at {ratio * 100:.0f}% of its "
                        f"limit for {dwell} watchdog periods (near-trip "
                        f"dwell)",
                        key=f"breaker:{name}", breaker=name,
                        ratio=round(ratio, 4), dwell_ticks=dwell,
                        estimated=b.get("estimated", 0),
                        limit=limit)
            else:
                self._breaker_dwell[name] = 0

    def _check_locktrace(self):
        from .locktrace import TRACER

        if not TRACER.enabled:
            return
        snap = TRACER.snapshot()
        held = int(snap.get("held_device_gets", 0))
        long_held = len(snap.get("long_held", ()))
        grew_held = held - self._held_gets
        grew_long = long_held - self._long_held
        self._held_gets = held
        self._long_held = long_held
        if grew_held > 0 or grew_long > 0:
            worst = snap.get("long_held", [])[-1] if grew_long > 0 else None
            self._emit(
                "lock_stall",
                f"{grew_held} device pull(s) timed under a held lock, "
                f"{grew_long} above the long-held threshold"
                + (f" (worst: {worst[0]} {worst[1]}ms)" if worst else ""),
                key="locktrace",
                held_device_gets=held, long_held=long_held,
                max_ms=snap.get("held_device_get_max_ms", 0.0))

    def stats(self) -> dict:
        return {
            "enabled": self.enabled,
            "interval_s": self.interval_s,
            "ticks": self.ticks,
            "thresholds": {
                "batch_stall_factor": self.batch_factor,
                "batch_stall_min_ms": round(self.batch_min_s * 1000.0, 1),
                "queue_p99_factor": self.queue_factor,
                "queue_p99_min_ms": round(self.queue_min_s * 1000.0, 1),
                "breaker_high_ratio": self.breaker_high,
                "breaker_dwell_ticks": self.breaker_dwell,
            },
            "baselines": {
                "queue_p99_ms": {
                    name: round(v * 1000.0, 3)
                    for name, v in sorted(self._pool_baseline.items())},
            },
        }
