"""Request-scoped distributed tracing: the node's Dapper-style span substrate.

Every search that is SAMPLED gets a tree of spans covering the whole serving
path — REST ingress → coordinator fan-out → transport wire → shard query phase
→ batcher (enqueue-wait / dispatch / merge) → the batch's ONE device pull —
with the trace context stitched across nodes through the existing binary wire
codec (common/stream.py serializes `TraceContext` as a typed value, so the
context rides the same request payloads the transport already round-trips).

Design rules (the repo's device + lock discipline applies to tracing too):

- **Near-zero overhead when off.** Sampling is decided ONCE at trace start;
  an unsampled request gets the `NOOP_SPAN`/`NOOP_TRACE` singletons whose
  every method is a constant no-op — no allocation, no locking, no clock
  reads on the unsampled path beyond one `random()` at ingress.
- **No extra device syncs.** Span end-times come from host monotonic clocks
  around operations the serving path performs ANYWAY — in particular the
  device span's end rides the batch's existing single `jax.device_get`
  (search/execute._merge_flat_plain stamps pull timestamps on the pending
  handle). Tracing never calls `block_until_ready` per span; the opt-in
  `ESTPU_TRACE_SYNC=1` precise mode (bench/debug only) is the ONE exception,
  and it lives in the batcher drainer, not in span code.
- **Lock discipline (TPU004/TPU011-TPU013).** Trace/ring locks are leaves:
  span recording only appends to lists under its own lock — it never blocks,
  never dispatches device work, never acquires another lock while held.

Sampling knobs: `ESTPU_TRACE` env (=1 arms rate 1.0 — the CI leg) overrides
`search.trace.sample_rate` (default 0.0 — off). `?trace=true` on `_search`
force-samples that one request regardless of the rate and returns its span
tree inline (the reference's later `profile` API shape). Finished traces land
in a bounded per-node ring buffer (`search.trace.ring_size`, default 128)
served by `GET /_traces`; live traces show in `GET /_tasks`.
"""

from __future__ import annotations

import contextlib
import itertools
import os
import random
import threading
import time
from collections import deque
from dataclasses import dataclass

# the request-dict key the transport layer injects the wire context under
# (handlers read it with .get(); unknown keys are ignored everywhere else)
TRACE_WIRE_KEY = "_trace"


@dataclass(frozen=True)
class TraceContext:
    """The cross-node wire form of a trace: which trace, which parent span.

    Serialized by common/stream.py as a typed value (tag 7), so it crosses
    the in-process roundtrip AND the TCP frame through the same codec every
    other payload uses — no side-channel headers."""

    trace_id: str
    span_id: int


# ---------------------------------------------------------------------------
# thread-local activation (how spans flow down a call stack without plumbing)
# ---------------------------------------------------------------------------

_local = threading.local()


def current_span():
    """The thread's active span: a real span, the (falsy) NOOP span when an
    upstream layer already DECLINED sampling for this request, or None when
    no tracing decision has been made on this thread. Cross-thread handoff
    (the batcher drainer) is explicit: items capture this at enqueue time."""
    return getattr(_local, "span", None)


@contextlib.contextmanager
def activate(span):
    """Make `span` the thread's current span for the scope. A NOOP span is
    stored as-is: it still deactivates tracing for the scope (a child of a
    noop must not resurrect the thread-local of an outer sampled request),
    but it also marks the sampling decision as already made — a downstream
    layer that would otherwise root its own trace (the coordinator under
    REST ingress) sees the noop and must NOT roll the sampling dice a
    second time."""
    prev = getattr(_local, "span", None)
    _local.span = span
    try:
        yield span
    finally:
        _local.span = prev


def wire_context(span) -> TraceContext | None:
    """The context to ship with an outbound request parented at `span` —
    the ONE construction site for the wire shape (transport injection and
    Tracer.wire_context both route here)."""
    if not span:
        return None
    return TraceContext(span.trace.trace_id, span.span_id)


def sync_armed() -> bool:
    """ESTPU_TRACE_SYNC=1: precise device timing for bench/debug — the batcher
    drainer blocks until the dispatched launches complete so the dispatch span
    measures true device time. NEVER the default: it serializes the
    double-buffered dispatch/merge overlap."""
    return os.environ.get("ESTPU_TRACE_SYNC", "") == "1"


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------


def _new_id() -> int:
    return random.getrandbits(63)


class Span:
    """One timed operation in a trace. Mutation is single-writer (the owning
    thread); the append into the trace happens under the trace's leaf lock."""

    __slots__ = ("trace", "name", "span_id", "parent_id", "t0", "t1", "tags")

    def __init__(self, trace: "Trace", name: str, parent_id: int | None,
                 t0: float | None = None):
        self.trace = trace
        self.name = name
        self.span_id = _new_id()
        self.parent_id = parent_id
        self.t0 = time.monotonic() if t0 is None else t0
        self.t1: float | None = None
        self.tags: dict = {}
        trace._opened(self)

    def __bool__(self) -> bool:
        return True

    def tag(self, **kv) -> "Span":
        self.tags.update(kv)
        return self

    def child(self, name: str) -> "Span":
        return Span(self.trace, name, self.span_id)

    def record(self, name: str, t0: float, t1: float, **tags) -> "Span":
        """One-shot child with explicit host-monotonic endpoints — how the
        batcher attributes a shared batch's phase timings back to every
        coalesced member request without per-member clock reads. Born
        finished: it skips the open-registry round-trip (it could never show
        in /_tasks) so the drainer pays ONE lock acquisition per member
        phase, not two."""
        sp = object.__new__(Span)
        sp.trace = self.trace
        sp.name = name
        sp.span_id = _new_id()
        sp.parent_id = self.span_id
        sp.t0 = t0
        sp.t1 = t1
        sp.tags = dict(tags)
        self.trace._record_finished(sp)
        return sp

    def end(self, t1: float | None = None) -> None:
        if self.t1 is not None:
            return  # idempotent — races between timer and response paths
        self.t1 = time.monotonic() if t1 is None else t1
        self.trace._closed(self)

    def to_dict(self) -> dict:
        t1 = self.t1 if self.t1 is not None else time.monotonic()
        return {
            "id": self.span_id,
            "parent": self.parent_id,
            "name": self.name,
            "node": self.trace.node_name,
            "t0": self.t0,
            "t1": t1,
            "duration_ms": round((t1 - self.t0) * 1000.0, 4),
            "tags": dict(self.tags),
        }

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, *exc) -> None:
        self.end()


class _NoopSpan:
    """Falsy span that swallows everything — the unsampled fast path."""

    __slots__ = ()

    def __bool__(self) -> bool:
        return False

    def tag(self, **kv) -> "_NoopSpan":
        return self

    def child(self, name: str) -> "_NoopSpan":
        return self

    def record(self, name: str, t0: float, t1: float, **tags) -> "_NoopSpan":
        return self

    def end(self, t1: float | None = None) -> None:
        pass

    def to_dict(self) -> dict:
        return {}

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> None:
        pass


NOOP_SPAN = _NoopSpan()


# ---------------------------------------------------------------------------
# traces
# ---------------------------------------------------------------------------


class Trace:
    """All spans of one sampled request on one node. The root span ending
    finishes the trace: it is snapshotted into the tracer's ring buffer and
    dropped from the in-flight registry."""

    __slots__ = ("tracer", "trace_id", "node_name", "started_at", "root",
                 "_lock", "_spans", "_open", "_finished", "_in_ring", "_seq")

    def __init__(self, tracer: "Tracer", name: str,
                 trace_id: str | None = None, parent_id: int | None = None):
        self.tracer = tracer
        # not uuid4: ~30us/call vs ~1us for getrandbits, and a trace id only
        # needs uniqueness, not RFC-4122 shape — this runs once per sampled
        # request at ingress
        self.trace_id = trace_id or f"{random.getrandbits(64):016x}"
        self.node_name = tracer.node_name
        self.started_at = time.time()
        self._lock = threading.Lock()  # leaf lock: list appends only
        self._spans: list[dict] = []  # finished spans (+ stitched remote ones)
        self._open: dict[int, Span] = {}
        self._finished = False  # root closed (guarded by _lock)
        self._in_ring = False  # snapshot committed (guarded by tracer ring lock)
        self._seq = next(tracer._trace_seq)  # ring identity (trace_id repeats
        # within one tracer when two local shards continue the same trace)
        self.root = Span(self, name, parent_id)

    def __bool__(self) -> bool:
        return True

    def span(self, name: str, parent: Span | None = None) -> Span:
        p = parent if parent is not None else self.root
        return Span(self, name, p.span_id)

    # -- span bookkeeping (called by Span; record-only, never blocks) --------
    def _opened(self, span: Span) -> None:
        with self._lock:
            self._open[span.span_id] = span

    def _closed(self, span: Span) -> None:
        with self._lock:
            self._open.pop(span.span_id, None)
            self._spans.append(span.to_dict())
            late = self._finished and span is not self.root
        if span is self.root:
            self.tracer._finish(self)
        elif late:
            # a span ending AFTER the root closed (a timed-out shard
            # attempt's transport span, ended when the late response or
            # transport error finally resolves its future) would otherwise
            # miss the ring snapshot — same refresh as a late add_remote
            self.tracer._restitch(self)

    def _record_finished(self, span: Span) -> None:
        """Append a span born finished (Span.record) — one lock acquisition,
        no open-registry traffic. Same late-refresh rule as _closed."""
        with self._lock:
            self._spans.append(span.to_dict())
            late = self._finished
        if late:
            self.tracer._restitch(self)

    def add_remote(self, span_dicts) -> None:
        """Stitch spans a remote node returned inline (the shard query
        response carries its span list back to the coordinator). A late
        stitch — the coordinator backstop abandoned the chain, the root
        already closed, and the shard's response only arrived afterwards —
        refreshes the ring snapshot so the spans still reach /_traces."""
        if not span_dicts:
            return
        clean = [dict(s) for s in span_dicts if isinstance(s, dict)]
        with self._lock:
            self._spans.extend(clean)
            late = self._finished
        if late:
            self.tracer._restitch(self)

    def span_dicts(self) -> list[dict]:
        with self._lock:
            return list(self._spans)

    def current_name(self) -> str:
        """Name of the most recently opened still-open span (for /_tasks)."""
        with self._lock:
            if not self._open:
                return self.root.name
            return max(self._open.values(), key=lambda s: s.t0).name

    def to_dict(self) -> dict:
        spans = self.span_dicts()
        root = self.root.to_dict()
        return {
            "trace_id": self.trace_id,
            "node": self.node_name,
            "name": self.root.name,
            "start_ts_ms": int(self.started_at * 1000),
            "duration_ms": root["duration_ms"],
            "spans": spans,
        }


class _NoopTrace:
    __slots__ = ()

    root = NOOP_SPAN
    trace_id = None

    def __bool__(self) -> bool:
        return False

    def span(self, name: str, parent=None) -> _NoopSpan:
        return NOOP_SPAN

    def add_remote(self, span_dicts) -> None:
        pass

    def span_dicts(self) -> list:
        return []

    def to_dict(self) -> dict:
        return {}


NOOP_TRACE = _NoopTrace()


def span_tree(spans: list[dict]) -> dict | None:
    """Nest a flat span list into the root's tree (children sorted by start).
    Spans whose parent is absent (cross-node stitches of a dropped hop) attach
    to the root so nothing silently vanishes from the inline view."""
    if not spans:
        return None
    by_id = {s["id"]: {**s, "children": []} for s in spans}
    root = None
    orphans = []
    for node in by_id.values():
        parent = by_id.get(node["parent"]) if node["parent"] is not None else None
        if parent is not None:
            parent["children"].append(node)
        elif root is None and node["parent"] is None:
            root = node
        else:
            orphans.append(node)
    if root is None:  # no local root (shouldn't happen) — oldest span wins
        root = min(by_id.values(), key=lambda s: s["t0"])
        orphans = [n for n in orphans if n is not root]
    root["children"].extend(orphans)
    for node in by_id.values():
        node["children"].sort(key=lambda s: s["t0"])
    return root


def phase_breakdown(trace) -> dict:
    """queue/device/merge milliseconds extracted from a trace's batcher spans —
    the slowlog's joinable per-phase line. `device` is the batch's single
    device pull; `merge` is the host-side fan-out time around it."""
    queue = device = merge = 0.0
    for s in (trace.span_dicts() if trace else []):
        name = s.get("name")
        if name == "batcher.queue":
            queue += s["duration_ms"]
        elif name == "device_pull":
            device += s["duration_ms"]
        elif name == "batcher.merge":
            merge += s["duration_ms"]
    return {"queue_ms": round(queue, 3), "device_ms": round(device, 3),
            "merge_ms": round(max(merge - device, 0.0), 3)}


# ---------------------------------------------------------------------------
# tracer (per node)
# ---------------------------------------------------------------------------


class Tracer:
    """Per-node sampling decision + ring buffer + in-flight registry."""

    def __init__(self, settings=None, node_name: str = "node"):
        from .settings import Settings

        settings = settings or Settings.EMPTY
        env = os.environ.get("ESTPU_TRACE", "").strip()
        if env:
            if env.lower() in ("1", "true", "on"):
                rate = 1.0
            else:
                try:
                    rate = float(env)
                except ValueError:
                    rate = 0.0
        else:
            rate = settings.get_float("search.trace.sample_rate", 0.0) or 0.0
        self.sample_rate = min(max(rate, 0.0), 1.0)
        self.node_name = node_name
        ring = max(1, settings.get_int("search.trace.ring_size", 128))
        # entries are (trace seq, snapshot) pairs — the seq lets a late
        # remote stitch find and refresh ITS entry (trace_id alone is not
        # unique within a ring: two local shards continuing one trace)
        self._ring: deque[tuple[int, dict]] = deque(maxlen=ring)
        self._ring_lock = threading.Lock()
        self._trace_seq = itertools.count(1)
        self._inflight: dict[int, Trace] = {}
        self._inflight_lock = threading.Lock()
        self._sampled_total = 0
        self._finished_total = 0
        # ring-pressure counters (guarded by _ring_lock): a bounded ring that
        # silently forgets traces is an observability hole — surface how many
        # finished traces were evicted, and how many late remote stitches
        # arrived after their entry was already gone
        self._ring_evicted = 0
        self._stitch_dropped = 0

    # -- starting / continuing ----------------------------------------------
    def _sampled(self) -> bool:
        r = self.sample_rate
        return r > 0.0 and (r >= 1.0 or random.random() < r)

    def start_trace(self, name: str, force: bool = False):
        """Root a new trace here (REST ingress / coordinator). `force=True` is
        the `?trace=true` override — sampled regardless of the rate."""
        if not force and not self._sampled():
            return NOOP_TRACE
        return self._register(Trace(self, name))

    def continue_trace(self, wire, name: str):
        """Continue a trace whose context arrived over the wire (shard side).
        The sender only injects context for sampled traces, so arrival of a
        context IS the sampling decision."""
        if wire is None:
            return NOOP_TRACE
        if isinstance(wire, TraceContext):
            tid, sid = wire.trace_id, wire.span_id
        elif isinstance(wire, dict) and wire.get("tid"):
            tid, sid = str(wire["tid"]), int(wire.get("sid") or 0) or None
        else:
            return NOOP_TRACE
        return self._register(Trace(self, name, trace_id=tid, parent_id=sid))

    def wire_context(self, span) -> TraceContext | None:
        """The context to ship with an outbound request parented at `span`."""
        return wire_context(span)

    def _register(self, trace: Trace) -> Trace:
        with self._inflight_lock:
            self._inflight[id(trace)] = trace
            self._sampled_total += 1
        return trace

    def _finish(self, trace: Trace) -> None:
        """Root span ended: snapshot OUTSIDE the locks, then record."""
        with self._inflight_lock:
            self._inflight.pop(id(trace), None)
        with trace._lock:
            trace._finished = True  # set BEFORE snapshotting: a remote
            # stitch that lands after this flag re-snapshots via _restitch
        snap = trace.to_dict()
        with self._ring_lock:
            if len(self._ring) == self._ring.maxlen:
                self._ring_evicted += 1  # the append below pushes one out
            self._ring.append((trace._seq, snap))
            trace._in_ring = True
            self._finished_total += 1
        # backstop for the snapshot→commit window: a stitch in between saw
        # _finished=True but found no ring entry to refresh yet
        if len(trace.span_dicts()) != len(snap["spans"]):
            self._restitch(trace)

    def _restitch(self, trace: Trace) -> None:
        """Replace a finished trace's ring snapshot with a fuller one (spans
        stitched after the root closed). Replace-only: an entry the bounded
        ring already evicted stays evicted; span lists only grow, so the
        longer snapshot wins regardless of commit order."""
        snap = trace.to_dict()
        with self._ring_lock:
            if not trace._in_ring:
                return  # _finish has not committed yet; its backstop re-runs
            for i in range(len(self._ring) - 1, -1, -1):
                seq, old = self._ring[i]
                if seq == trace._seq:
                    if len(old["spans"]) < len(snap["spans"]):
                        self._ring[i] = (seq, snap)
                    return
            # the bounded ring already evicted this trace: the late stitch's
            # spans are dropped by design (replace-only) — count the drop so
            # /_traces pressure is visible instead of silent
            self._stitch_dropped += 1

    # -- observability surfaces ---------------------------------------------
    def traces(self, limit: int | None = None) -> list[dict]:
        """Finished traces, newest first; `limit` caps the count (0 = none)."""
        with self._ring_lock:
            out = [snap for _seq, snap in self._ring]
        out.reverse()
        return out if limit is None else out[:max(0, limit)]

    def tasks(self) -> list[dict]:
        """Live in-flight traces: current span, elapsed, cancellable=false
        (cancellation is a later PR — the field pins the API shape now)."""
        with self._inflight_lock:
            live = list(self._inflight.values())
        now = time.monotonic()
        return [{
            "trace_id": t.trace_id,
            "name": t.root.name,
            "node": t.node_name,
            "current_span": t.current_name(),
            "running_time_ms": round((now - t.root.t0) * 1000.0, 3),
            "start_ts_ms": int(t.started_at * 1000),
            "cancellable": False,
        } for t in live]

    def stats(self) -> dict:
        with self._ring_lock:
            ring_len = len(self._ring)
            finished = self._finished_total
            ring_evicted = self._ring_evicted
            stitch_dropped = self._stitch_dropped
        with self._inflight_lock:
            sampled = self._sampled_total
            in_flight = len(self._inflight)
        return {
            "sample_rate": self.sample_rate,
            "sampled": sampled,
            "finished": finished,
            "in_flight": in_flight,
            "ring": ring_len,
            "ring_size": self._ring.maxlen,
            "ring_evicted": ring_evicted,
            "late_stitch_dropped": stitch_dropped,
        }
