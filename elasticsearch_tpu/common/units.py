"""Byte-size and time-value units.

TPU-native analogue of common/unit/ByteSizeValue.java and TimeValue.java in the reference:
settings accept "1gb", "512mb", "30s", "5m" style strings everywhere.
"""

from __future__ import annotations

import re

from .errors import IllegalArgumentError

_BYTE_SUFFIXES = {
    "b": 1,
    "k": 1024,
    "kb": 1024,
    "m": 1024**2,
    "mb": 1024**2,
    "g": 1024**3,
    "gb": 1024**3,
    "t": 1024**4,
    "tb": 1024**4,
    "p": 1024**5,
    "pb": 1024**5,
}

_TIME_SUFFIXES = {
    "nanos": 1e-9,
    "micros": 1e-6,
    "ms": 1e-3,
    "s": 1.0,
    "m": 60.0,
    "h": 3600.0,
    "d": 86400.0,
    "w": 604800.0,
}

_NUM_RE = re.compile(r"^\s*(-?[\d.]+)\s*([a-zA-Z%]*)\s*$")


def parse_bytes(value, default: int | None = None) -> int:
    """Parse "512mb" → bytes. Ints pass through."""
    if value is None:
        if default is None:
            raise IllegalArgumentError("missing byte size value")
        return default
    if isinstance(value, (int, float)):
        return int(value)
    m = _NUM_RE.match(str(value))
    if not m:
        raise IllegalArgumentError(f"failed to parse byte size [{value}]")
    num, suffix = m.groups()
    suffix = suffix.lower()
    if suffix and suffix not in _BYTE_SUFFIXES:
        raise IllegalArgumentError(f"unknown byte size unit [{suffix}] in [{value}]")
    return int(float(num) * _BYTE_SUFFIXES.get(suffix, 1))


def parse_time(value, default: float | None = None) -> float:
    """Parse "30s"/"5m"/"200ms" → seconds (float). Bare numbers are milliseconds,
    matching the reference's TimeValue default unit."""
    if value is None:
        if default is None:
            raise IllegalArgumentError("missing time value")
        return default
    if isinstance(value, (int, float)):
        return float(value) / 1000.0
    s = str(value)
    if s == "-1":
        return -1.0
    m = _NUM_RE.match(s)
    if not m:
        raise IllegalArgumentError(f"failed to parse time value [{value}]")
    num, suffix = m.groups()
    suffix = suffix.lower()
    if not suffix:
        return float(num) / 1000.0
    if suffix not in _TIME_SUFFIXES:
        raise IllegalArgumentError(f"unknown time unit [{suffix}] in [{value}]")
    return float(num) * _TIME_SUFFIXES[suffix]


def parse_ratio_or_bytes(value, total: int, default=None):
    """Parse either a percentage ("85%") against `total` or an absolute byte size.
    Used by the circuit breaker and disk-threshold allocation decider."""
    if value is None:
        value = default
    s = str(value)
    if s.endswith("%"):
        return int(total * float(s[:-1]) / 100.0)
    return parse_bytes(value)


def format_bytes(n: int) -> str:
    for suffix, mult in (("pb", 1024**5), ("tb", 1024**4), ("gb", 1024**3), ("mb", 1024**2), ("kb", 1024)):
        if n >= mult:
            return f"{n / mult:.1f}{suffix}"
    return f"{n}b"


def format_time(seconds: float) -> str:
    if seconds >= 3600:
        return f"{seconds / 3600:.1f}h"
    if seconds >= 60:
        return f"{seconds / 60:.1f}m"
    if seconds >= 1:
        return f"{seconds:.1f}s"
    return f"{seconds * 1000:.0f}ms"
