"""JAX platform selection for this container.

The image pins JAX_PLATFORMS to a real-TPU plugin and imports jax at interpreter
startup via a sitecustomize hook, so an environ set alone does not stick — the live
jax config must be updated too, or jax.devices() blocks initializing the TPU backend
even when the caller wants a CPU mesh. One helper so the recipe can't drift between
the test conftest, the driver entry, and the bench fallback.
"""

from __future__ import annotations

import os
import re


def force_cpu_platform(n_devices: int | None = None) -> None:
    """Pin jax to the CPU backend, optionally with n virtual host devices.

    Safe to call before or after `import jax` (but before first device use). An
    existing --xla_force_host_platform_device_count flag is replaced, not skipped —
    a pre-pinned smaller count would otherwise defeat the requested mesh size.
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    if n_devices is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        flag = f"--xla_force_host_platform_device_count={n_devices}"
        if "xla_force_host_platform_device_count" in flags:
            flags = re.sub(r"--xla_force_host_platform_device_count=\d+", flag, flags)
        else:
            flags = (flags + " " + flag).strip()
        os.environ["XLA_FLAGS"] = flags

    import jax

    jax.config.update("jax_platforms", "cpu")
