"""JAX platform selection + runtime sanitizer for this container.

The image pins JAX_PLATFORMS to a real-TPU plugin and imports jax at interpreter
startup via a sitecustomize hook, so an environ set alone does not stick — the live
jax config must be updated too, or jax.devices() blocks initializing the TPU backend
even when the caller wants a CPU mesh. One helper so the recipe can't drift between
the test conftest, the driver entry, and the bench fallback.

This module is the ONLY sanctioned writer of JAX_PLATFORMS / jax_platforms /
XLA_FLAGS — tools/tpulint rule TPU005 enforces that statically.

It also hosts the runtime half of the tpulint story: `sanitize()` arms
jax.transfer_guard around a query phase and counts compile events, so tests can
assert a per-phase compile budget and a zero-implicit-transfer invariant — the
dynamic check backing the static TPU001/TPU002 rules (see tests/test_sanitizer.py
and the `jax_sanitizer` conftest fixture).
"""

from __future__ import annotations

import contextlib
import os
import re
import sys
import threading
from dataclasses import dataclass, field


def force_cpu_platform(n_devices: int | None = None) -> None:
    """Pin jax to the CPU backend, optionally with n virtual host devices.

    Safe to call before or after `import jax` (but before first device use). An
    existing --xla_force_host_platform_device_count flag is replaced, not skipped —
    a pre-pinned smaller count would otherwise defeat the requested mesh size.
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    if n_devices is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        flag = f"--xla_force_host_platform_device_count={n_devices}"
        if "xla_force_host_platform_device_count" in flags:
            flags = re.sub(r"--xla_force_host_platform_device_count=\d+", flag, flags)
        else:
            flags = (flags + " " + flag).strip()
        os.environ["XLA_FLAGS"] = flags

    import jax

    jax.config.update("jax_platforms", "cpu")


# the persistent-compilation-cache directory this process is armed with (None
# = not armed). Re-arming with the SAME dir is a no-op, so multi-boot test
# processes don't thrash jax's cache state on every node construction.
_persistent_cache_dir: str | None = None


def enable_persistent_compile_cache(cache_dir: str) -> bool:
    """Point jax's persistent compilation cache at `cache_dir` (node wiring
    puts it under path.data) so a process restart deserializes executables
    from disk instead of re-running XLA. Thresholds drop to zero — serving
    kernels on the CPU test backend compile in milliseconds and must still
    persist, or the restart warm cycle re-pays full compiles.

    Best-effort by design: this flips jax config (sanctioned here — see the
    module docstring's single-writer rule) and, when the directory CHANGES
    mid-process, resets jax's cache singleton so the new dir takes effect
    (jax checks the config once, at first compile). Any failure leaves the
    cache disabled/stale, never breaks serving. NOTE a persistent-cache HIT
    still emits a backend_compile_duration event (pxla times
    compile_or_get_cached wholesale), so compile counting is unchanged by
    arming this — the disk cache makes warm-cycle replays cheap, it does not
    hide them from the sanitizer."""
    global _persistent_cache_dir

    if not cache_dir or _persistent_cache_dir == cache_dir:
        return _persistent_cache_dir is not None
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir", cache_dir)
        for knob, val in (("jax_persistent_cache_min_compile_time_secs", 0.0),
                          ("jax_persistent_cache_min_entry_size_bytes", 0)):
            try:
                jax.config.update(knob, val)
            except (AttributeError, ValueError):  # knob absent in this jax
                pass
        _persistent_cache_dir = cache_dir
        try:
            # jax reads the dir once, at its first cache use — a compile may
            # already have happened (test suites boot nodes mid-process), so
            # drop the singleton and let the next compile re-initialize
            # against the new dir. Private, hence double-guarded: worst case
            # the previous (or no) dir sticks and only warm cost is lost.
            from jax._src import compilation_cache as _cc

            _cc.reset_cache()
        except Exception:  # noqa: BLE001
            pass
        return True
    except Exception:  # noqa: BLE001 — no jax / unknown config: stay off
        return False


# ---------------------------------------------------------------------------
# runtime sanitizer: transfer guard + compile-event counting
# ---------------------------------------------------------------------------

# every backend compile emits exactly one of these duration events
# (jax 0.4.x: /jax/core/compile/backend_compile_duration); counting them is
# backend-agnostic and — unlike parsing jax_log_compiles output — race-free
_COMPILE_EVENT_SUBSTR = "backend_compile"


@dataclass
class SanitizerReport:
    """What happened inside one sanitize() scope."""

    compiles: int = 0
    compile_events: list = field(default_factory=list)  # (event_key,) per compile
    # lock-trace counters (common/locktrace.py) snapshotted on scope exit when
    # ESTPU_LOCKTRACE=1 armed the tracer; None when the tracer is off
    locks: dict | None = None
    # collective-trace counters (common/meshtrace.py) snapshotted on scope
    # exit when ESTPU_MESHTRACE=1 armed the tracer; None when the tracer is off
    mesh: dict | None = None

    def note(self, key: str) -> None:
        self.compiles += 1
        self.compile_events.append(key)


# thread-local plan-family tag for compile attribution: the launch sites in
# search/execute.py (and the mesh dispatch) wrap their kernel calls in
# compile_tag("sparse"|"dense"|...), and since XLA compiles synchronously on
# the triggering thread, the listener below can bucket every compile event by
# the plan family that caused it — the device capacity ledger's "who is
# eating my compile budget" signal. Fixed vocabulary, so the per-family
# counter dict (and its Prometheus label set) is bounded by construction.
_tag_local = threading.local()

COMPILE_FAMILIES = ("sparse", "dense", "function_score", "filtered",
                    "sorted", "aggs", "percolate", "mesh", "compact",
                    "pack", "untagged")
_FAMILY_SET = frozenset(COMPILE_FAMILIES)


@contextlib.contextmanager
def compile_tag(tag: str):
    """Attribute backend compiles triggered inside the scope to `tag` (one
    thread-local write per batch launch — never per posting, never per doc).
    OUTERMOST scope wins: the workload that triggered the launch owns its
    compiles — a percolation's inner sparse-kernel launch stays "percolate",
    not "sparse"."""
    prev = getattr(_tag_local, "tag", None)
    if prev is not None:
        yield
        return
    _tag_local.tag = tag if tag in _FAMILY_SET else "untagged"
    try:
        yield
    finally:
        _tag_local.tag = None


def current_compile_family() -> str | None:
    """The compile_tag family active on this thread (None outside any scope)
    — compilecache.record_launch attributes specs to the workload that
    actually triggered the launch (percolate owning its inner sparse, etc.)."""
    return getattr(_tag_local, "tag", None)


def _pool_label() -> str:
    """Which named threadpool the current thread belongs to — pool workers are
    named "estpu[<pool>]_N" (threadpool._BoundedPool); anything else reads as
    "other". The compile listener's pool attribution: the warmed-node
    invariant is that steady-state compile events show pool=warmer/merge
    only (same parse as device_index._pool_label, kept local so this module
    stays import-leaf)."""
    name = threading.current_thread().name
    if name.startswith("estpu[") and "]" in name:
        return name[len("estpu["): name.index("]")]
    return "other"


# untagged-origin capture: bounded — a runaway untagged site can't grow the
# dict past this many distinct call sites
_ORIGIN_CAP = 64


def _package_origin() -> str | None:
    """First stack frame inside elasticsearch_tpu/ (this module excluded) on
    the thread that triggered an untagged compile — names the launch site that
    compiled outside every compile_tag scope. Test-local eager jnp compiles
    have no package frame and return None: the conftest compile_surface_gate
    only fails on PACKAGE-originated untagged compiles."""
    marker = os.sep + "elasticsearch_tpu" + os.sep
    f = sys._getframe(1)
    while f is not None:
        fn = f.f_code.co_filename
        i = fn.find(marker)
        if i >= 0 and not fn.endswith("jaxenv.py"):
            return f"{fn[i + 1:]}:{f.f_lineno}"
        f = f.f_back
    return None


class _CompileCounter:
    """Process-wide compile-event listener fanning out to active scopes.

    jax.monitoring has register-only semantics (no unregister), so ONE listener
    is installed lazily and forever; scopes subscribe/unsubscribe from it.
    Thread-safe: serving runs queries from pool threads.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._installed = False
        self._active: list[SanitizerReport] = []
        # process-lifetime compile-event count (since the listener was first
        # installed) — the Prometheus estpu_jax_compile_events_total series
        self.total = 0
        # plan-family attribution (compile_tag): family -> count
        self.by_family: dict = {}
        # untagged-compile origin sites ("path:line" -> count), recorded only
        # when record_untagged_origins() armed it — the runtime twin of the
        # compile-surface manifest's families cross-check
        self.untagged_origins: dict = {}
        self._record_origins = False
        # threadpool attribution (pool -> count): the compile-warming
        # invariant's runtime surface — a warmed node's steady-state events
        # must all land on warmer/merge pools, never a serving pool
        self.by_pool: dict = {}
        # external observers fed OUTSIDE the lock, e.g. the compilecache
        # warm-queue feed (family, pool) per compile event. Append-only like
        # jax.monitoring itself; exceptions are swallowed — telemetry must
        # never break a compile.
        self.observers: list = []

    def _listener(self, key: str, duration: float, **_kw) -> None:
        if _COMPILE_EVENT_SUBSTR not in key:
            return
        family = getattr(_tag_local, "tag", None) or "untagged"
        # stack walk OUTSIDE the lock — frame inspection is slow-path work and
        # must not extend the critical section other compiling threads share
        origin = _package_origin() \
            if family == "untagged" and self._record_origins else None
        pool = _pool_label()
        # note() under the lock: concurrent pool-thread compiles must not lose
        # increments, or a blown budget could pass silently
        with self._lock:
            self.total += 1
            self.by_family[family] = self.by_family.get(family, 0) + 1
            self.by_pool[pool] = self.by_pool.get(pool, 0) + 1
            if origin is not None and (origin in self.untagged_origins
                                       or len(self.untagged_origins)
                                       < _ORIGIN_CAP):
                self.untagged_origins[origin] = \
                    self.untagged_origins.get(origin, 0) + 1
            for r in self._active:
                r.note(key)
            observers = list(self.observers)
        for cb in observers:
            try:
                cb(family, pool)
            except Exception:  # noqa: BLE001
                pass

    def ensure_installed(self) -> None:
        import jax.monitoring

        with self._lock:
            if not self._installed:
                jax.monitoring.register_event_duration_secs_listener(self._listener)
                self._installed = True

    def subscribe(self, report: SanitizerReport) -> None:
        self.ensure_installed()
        with self._lock:
            self._active.append(report)

    def unsubscribe(self, report: SanitizerReport) -> None:
        with self._lock:
            if report in self._active:
                self._active.remove(report)


_counter = _CompileCounter()


def compile_events_total() -> int:
    """Process-lifetime backend-compile count for telemetry (Prometheus /
    /_nodes/stats). Installs the process-wide listener on first call; counts
    start from then — a warmed node therefore reads ~0, and any growth IS a
    retrace signal worth alerting on."""
    try:
        _counter.ensure_installed()
    except Exception:  # noqa: BLE001 — no jax in this process: count stays 0
        pass
    return _counter.total


def compile_events_by_family() -> dict:
    """Process-lifetime backend-compile counts bucketed by the plan family
    that triggered them (compile_tag scopes at the kernel launch sites) —
    the device capacity ledger's compile attribution. Keys are drawn from
    COMPILE_FAMILIES, so the dict (and its Prometheus label set) is bounded."""
    try:
        _counter.ensure_installed()
    except Exception:  # noqa: BLE001 — no jax in this process: empty
        pass
    with _counter._lock:
        return dict(_counter.by_family)


def compile_events_by_pool() -> dict:
    """Process-lifetime backend-compile counts bucketed by the threadpool the
    triggering thread belonged to ("estpu[<pool>]" worker naming; "other" for
    non-pool threads). On a warmed node every increment outside
    warmer/merge is an on-path compile stall — the compile-warming
    acceptance invariant reads this surface."""
    try:
        _counter.ensure_installed()
    except Exception:  # noqa: BLE001 — no jax in this process: empty
        pass
    with _counter._lock:
        return dict(_counter.by_pool)


def register_compile_observer(cb) -> None:
    """Register `cb(family, pool)` to run after every backend-compile event
    (outside the counter lock). Register-only, deduplicated by identity —
    mirrors jax.monitoring's own semantics. The compilecache registry feeds
    its warm queue from here."""
    try:
        _counter.ensure_installed()
    except Exception:  # noqa: BLE001 — no jax: nothing will ever fire
        pass
    with _counter._lock:
        if cb not in _counter.observers:
            _counter.observers.append(cb)


def record_untagged_origins(enable: bool = True) -> None:
    """Arm (or disarm) package-origin capture for untagged compile events: the
    listener walks the triggering thread's stack and records the first
    elasticsearch_tpu/ frame per event. Used by the conftest
    compile_surface_gate — a tier-1 run must end with zero package-originated
    untagged compiles, i.e. every package launch site sits under a
    compile_tag scope registered in tools/compile_surface.json."""
    try:
        _counter.ensure_installed()
    except Exception:  # noqa: BLE001 — no jax in this process: nothing to arm
        pass
    _counter._record_origins = enable


def untagged_package_origins() -> dict:
    """{"path:line": count} for untagged compiles whose stack crossed the
    package, since record_untagged_origins() armed capture. Empty when every
    package-originated compile carried a compile_tag family."""
    with _counter._lock:
        return dict(_counter.untagged_origins)


class CompileBudgetExceeded(AssertionError):
    """Raised when a sanitize(max_compiles=N) scope observed more than N
    backend compiles — a retrace hazard made loud (tpulint TPU002's runtime
    twin)."""


_UNSET = object()


@contextlib.contextmanager
def sanitize(max_compiles: int | None | object = _UNSET,
             transfers: str | None = None):
    """Arm the JAX runtime sanitizers around a query phase.

    - transfer guard at level `transfers` ("disallow" = implicit transfers
      raise; explicit jax.device_put/device_get stay legal, so correctly
      batched host pulls pass while a stray float(device_scalar) fails;
      "log" = warn only; "off" = disabled),
    - compile-event counting: the yielded SanitizerReport carries .compiles;
      if max_compiles is not None the scope raises CompileBudgetExceeded on
      exit when the budget was blown.

    Defaults come from the environment so the conftest gate, CI, and ad-hoc
    debugging share one knob (the tpulint baseline is empty, so "disallow"
    is the standing mode — ROADMAP burn-down item, PR 2):

      ESTPU_SANITIZE        transfer level when `transfers` is None
                            (default "disallow"; set =log as the escape
                            hatch while debugging a new implicit transfer,
                            =off to disarm entirely)
      ESTPU_COMPILE_BUDGET  int; when `max_compiles` is not given, a HARD
                            per-scope ceiling — the scope raises
                            CompileBudgetExceeded beyond it (empty/unset =
                            count but don't enforce)

    Usage (the test-harness invariant: a warmed query path neither recompiles
    nor implicitly transfers):

        with sanitize(max_compiles=0) as rep:
            run_query_again()
        assert rep.compiles == 0  # implied by max_compiles=0
    """
    import jax

    if transfers is None:
        transfers = os.environ.get("ESTPU_SANITIZE", "disallow")
    if max_compiles is _UNSET:
        budget = os.environ.get("ESTPU_COMPILE_BUDGET")
        max_compiles = int(budget) if budget else None

    report = SanitizerReport()
    _counter.subscribe(report)
    guard = (jax.transfer_guard(transfers) if transfers != "off"
             else contextlib.nullcontext())
    try:
        with guard:
            yield report
    finally:
        _counter.unsubscribe(report)
        from .locktrace import TRACER
        from .meshtrace import TRACER as MESH_TRACER

        if TRACER.enabled:
            report.locks = TRACER.snapshot()
        if MESH_TRACER.enabled:
            report.mesh = MESH_TRACER.snapshot()
    if max_compiles is not None and report.compiles > max_compiles:
        raise CompileBudgetExceeded(
            f"compile budget exceeded: {report.compiles} backend compile(s) "
            f"observed, budget {max_compiles} — a shape/static-arg drifted and "
            f"the executable cache missed (events: {report.compile_events})")
