"""Request-scoped search profiling — the white-box `profile` API substrate.

Where tracing (common/tracing.py) answers *where* a request spent its time
(spans across REST → coordinator → shard → batcher → device pull), the
profiler answers *why*: which clause, which segment, which execution path
(fused Pallas vs composed sparse vs dense fallback vs host scorer), which
cache miss (segment pack, SimTables swap, lazy dense-plane fault, scratch
checkout) made it expensive, and how many postings/blocks/bytes the plan
actually touched. The response shape is the reference's `profile` section:
per-shard entries merged next to `_shards` by the coordinator.

Design rules (the repo's device + lock discipline applies here too):

- **Zero overhead when off.** A hook is one thread-local read and a None
  check — no allocation, no locking, no clock reads on the unprofiled path.
  `activate(None)` is never entered: call sites branch on the collector
  before wrapping, so an unprofiled request touches this module only through
  `current()`.
- **Sync only when opted in.** Profiled requests get precise per-phase
  device timings by blocking on the dispatched launches (the
  `ESTPU_TRACE_SYNC` pattern from the tracing layer, but PER REQUEST —
  legal because `"profile": true` is the opt-in). The unprofiled serving
  path adds ZERO device syncs (pinned by tests/test_profile.py).
- **Batcher interaction is explicit.** A profiled request bypasses the
  cross-request DeviceBatcher (recorded as `batcher: {bypassed, reason:
  "profile"}`) so its device phases are its own, not a coalesced batch's —
  and so the collector stays single-writer: execution never leaves the
  request thread, which is why recording needs no locks.
- **Record under leaf code only.** Hooks append to plain lists/dicts owned
  by one thread; they never block, never dispatch device work, and never
  run under a lock that isn't their caller's own leaf lock.

Fallback-reason vocabulary (ARCHITECTURE.md "Profile API"): why a query
left the fused device path —
  numeric_term, fuzzy_match, bool_filter_clause, non_term_subclause,
  must_not_only, function_score_no_query, function_score_ineligible,
  non_flat_subquery, similarity_not_fused, unsupported_query:<Type>,
  device_disabled, features:<f1,f2,...>, device_error:<Type>.
"""

from __future__ import annotations

import contextlib
import threading
import time

_local = threading.local()


def current() -> "ProfileCollector | None":
    """The thread's active collector, or None when the request is unprofiled
    (the common case — one thread-local read, nothing else)."""
    return getattr(_local, "prof", None)


@contextlib.contextmanager
def activate(prof: "ProfileCollector"):
    """Make `prof` the thread's collector for the scope. Call sites only
    enter this when a collector exists — the unprofiled path never pays the
    context manager."""
    prev = getattr(_local, "prof", None)
    _local.prof = prof
    try:
        yield prof
    finally:
        _local.prof = prev


# per-segment keys that accumulate across multiple launches of one request
# (e.g. the agg launch + the post-filter hit launch touch the same segment
# twice); everything else is identity info and overwrites
_ADDITIVE = {"blocks_scanned", "postings_scanned", "staged_bytes", "ms",
             "launches", "dense_overflow", "buckets"}


class ProfileCollector:
    """One shard-scoped (or mesh-launch-scoped) profile of one request.

    Single-writer by construction: profiled requests bypass the batcher, so
    every hook fires on the request thread — recording is plain appends with
    no locks. All recorded values are plain Python scalars so the result
    crosses the wire through the binary codec and renders as JSON unchanged.
    """

    MAX_EVENTS = 256  # cache-attribution events kept (drops counted)
    MAX_RESERVATIONS = 128  # breaker reservations kept (drops counted)

    __slots__ = ("node", "index", "shard", "t0", "_phases", "_plan",
                 "_outcome", "_fallback", "_segments", "_seg_order",
                 "_events", "_events_dropped", "_breakers", "_breaker_bytes",
                 "_breakers_dropped", "_batcher", "_mesh")

    def __init__(self, node: str = "node", index: str = "", shard: int = 0):
        self.node = node
        self.index = index
        self.shard = shard
        self.t0 = time.monotonic()
        self._phases: dict[str, float] = {}  # name -> ms
        self._plan: dict | None = None
        self._outcome: str | None = None
        self._fallback: str | None = None
        self._segments: dict[int, dict] = {}  # gen -> record
        self._seg_order: list[int] = []
        self._events: list[dict] = []
        self._events_dropped = 0
        self._breakers: list[dict] = []
        self._breaker_bytes = 0
        self._breakers_dropped = 0
        self._batcher: dict | None = None
        self._mesh: dict | None = None

    # -- phases --------------------------------------------------------------
    def phase_s(self, name: str, seconds: float) -> None:
        """Accumulate wall time into a named phase (seconds in, ms out)."""
        self._phases[name] = self._phases.get(name, 0.0) + seconds * 1000.0

    # -- plan ----------------------------------------------------------------
    def set_plan(self, plan: dict) -> None:
        """First writer wins — the query-phase entry point records the plan
        once; later re-lowerings (device-agg probes etc.) must not clobber."""
        if self._plan is None:
            self._plan = plan

    def outcome(self, path: str) -> None:
        """The resolved execution path (service.SERVING_COUNTERS vocabulary
        plus "mesh_spmd"); first writer wins."""
        if self._outcome is None:
            self._outcome = path

    def fallback(self, reason: str) -> None:
        """Why the fused device path was declined (module vocabulary)."""
        if self._fallback is None:
            self._fallback = reason

    # -- per-segment counters ------------------------------------------------
    def segment(self, gen: int, **kv) -> None:
        """Merge counters into the per-segment record: _ADDITIVE keys sum
        across launches, identity keys (path, tf_layout, docs) overwrite."""
        d = self._segments.get(gen)
        if d is None:
            d = {"generation": int(gen)}
            self._segments[gen] = d
            self._seg_order.append(gen)
        for k, v in kv.items():
            if k in _ADDITIVE and k in d:
                d[k] = d[k] + v
            else:
                d[k] = v

    # -- cache attribution / breaker accounting ------------------------------
    def event(self, kind: str, **kv) -> None:
        """A cache-attribution event (packed_segment hit/pack, sim_tables
        hit/swap, blk_freqs resident/fault, scratch reuse/alloc,
        device_error, mesh_executor hit/build)."""
        if len(self._events) < self.MAX_EVENTS:
            self._events.append({"kind": kind, **kv})
        else:
            self._events_dropped += 1

    def breaker_reserve(self, breaker: str, label: str, nbytes: int) -> None:
        self._breaker_bytes += int(nbytes)
        if len(self._breakers) < self.MAX_RESERVATIONS:
            self._breakers.append({"breaker": breaker, "label": label,
                                   "bytes": int(nbytes)})
        else:
            self._breakers_dropped += 1

    # -- batcher / mesh ------------------------------------------------------
    def batcher_bypass(self, reason: str) -> None:
        self._batcher = {"bypassed": True, "reason": reason}

    def mesh_info(self, **kv) -> None:
        self._mesh = {**(self._mesh or {}), **kv}

    # -- assembly ------------------------------------------------------------
    def to_dict(self) -> dict:
        phases = {k: round(v, 4) for k, v in self._phases.items()}
        phases["total"] = round((time.monotonic() - self.t0) * 1000.0, 4)
        segments = []
        for g in self._seg_order:
            d = dict(self._segments[g])
            for k, v in d.items():
                if isinstance(v, float):
                    d[k] = round(v, 4)
            segments.append(d)
        plan = {"outcome": self._outcome or "unknown",
                "fallback_reason": self._fallback}
        if self._plan:
            plan.update(self._plan)
        out = {
            "id": f"[{self.node}][{self.index}][{self.shard}]",
            "node": self.node,
            "index": self.index,
            "shard": int(self.shard),
            "plan": plan,
            "segments": segments,
            "phases_ms": phases,
            "cache": {"events": list(self._events),
                      "dropped": self._events_dropped},
            "breakers": {"reservations": list(self._breakers),
                         "reserved_bytes_total": self._breaker_bytes,
                         "dropped": self._breakers_dropped},
        }
        if self._batcher is not None:
            out["batcher"] = self._batcher
        if self._mesh is not None:
            out["mesh"] = self._mesh
        return out
