"""Lucene SmallFloat byte315 codec — exact re-implementation.

Lucene 4.7 stores per-document field-length norms as ONE BYTE via
SmallFloat.floatToByte315 (3 mantissa bits, 5 exponent bits, exponent zero-point 15).
Both DefaultSimilarity (TF-IDF) and BM25Similarity encode norms through this codec, so
reproducing Lucene's exact hit ordering REQUIRES quantizing norms identically
(see SURVEY.md §7 "Hard parts": 1-byte norm quantization).

Reference behavior: org.apache.lucene.util.SmallFloat (Lucene 4.7.0 jar in
/root/reference/pom.xml:33); consumed by DefaultSimilarity.encodeNormValue and
BM25Similarity.encodeNormValue.

Implemented here from the IEEE-754 definition (float bits >> 21, rebased exponent), not
translated code: vectorized over numpy arrays for whole-segment encoding.
"""

from __future__ import annotations

import numpy as np


def float_to_byte315(f: np.ndarray | float) -> np.ndarray:
    """Encode float32 → uint8 with 3 mantissa bits / 5 exponent bits / zero-exp 15."""
    arr = np.atleast_1d(np.asarray(f, dtype=np.float32))
    bits = arr.view(np.int32)
    small = bits >> 21  # 24-3 mantissa shift
    floor = (63 - 15) << 3
    out = np.empty(arr.shape, dtype=np.uint8)
    too_small = small <= floor
    too_large = small >= floor + 0x100
    mid = ~(too_small | too_large)
    # underflow → 0 for non-positive, 1 for tiny positives (matches reference semantics)
    out[too_small] = np.where(bits[too_small] <= 0, 0, 1).astype(np.uint8)
    out[too_large] = 255
    out[mid] = (small[mid] - floor).astype(np.uint8)
    return out


def byte315_to_float(b: np.ndarray | int) -> np.ndarray:
    """Decode uint8 → float32. byte315_to_float(float_to_byte315(x)) quantizes x."""
    barr = np.atleast_1d(np.asarray(b, dtype=np.uint8))
    bits = (barr.astype(np.int32) << 21) + (((63 - 15) << 24))
    out = bits.view(np.float32).copy()
    out[barr == 0] = 0.0
    return out


# Precomputed 256-entry decode table — same trick as Lucene's NORM_TABLE caches.
NORM_TABLE: np.ndarray = byte315_to_float(np.arange(256, dtype=np.uint8))


def encode_norm(num_terms: np.ndarray | int, boost: float = 1.0) -> np.ndarray:
    """Norm byte for a document field with `num_terms` tokens:
    encode(boost / sqrt(numTerms)). Shared by TF-IDF and BM25 similarities."""
    n = np.maximum(np.atleast_1d(np.asarray(num_terms, dtype=np.float64)), 0)
    with np.errstate(divide="ignore"):
        f = np.where(n > 0, boost / np.sqrt(n), 0.0).astype(np.float32)
    return float_to_byte315(f)


def jnp_norm_table():
    """Device-side byte315 decode table: jnp float32 [256], the device twin of
    NORM_TABLE. Built fresh per call (it is a 1 KB constant — callers that trace
    it into a jitted program get it folded as a compile-time constant; eager
    callers pay one explicit 1 KB upload). Kept out of module import so merely
    importing the codec never touches a device."""
    import jax.numpy as jnp

    from .jaxenv import compile_tag

    # compile_tag: eager table uploads are codec/packing work — outermost
    # scope wins, so a traced caller (the mesh program) keeps its own family.
    with compile_tag("pack"):
        return jnp.asarray(NORM_TABLE.astype(np.float32))


def jnp_byte315_to_float(b):
    """Device byte315 decode: uint8/int array → float32 via the 256-entry
    table gather, bitwise-identical to host byte315_to_float. The reference
    form of the decode the kernels inline themselves — the sparse scan gathers
    jnp_norm_table-derived SimTables rows, the mesh program uses
    jnp_norm_table directly — pinned against the host codec by
    tests/test_quantized_postings.py. jnp.take, not fancy indexing: this may
    run eagerly, where fancy indexing routes a scalar through an implicit
    transfer the sanitizer rejects."""
    import jax.numpy as jnp

    from .jaxenv import compile_tag

    with compile_tag("pack"):
        return jnp.take(jnp_norm_table(), jnp.asarray(b).astype(jnp.int32))


def jnp_doclen_table():
    """Device-side BM25 doc-length table: jnp float32 [256], the device twin of
    decode_norm_doclen over all bytes (dl = 1/f², byte 0 → length 0)."""
    import jax.numpy as jnp

    from .jaxenv import compile_tag

    with compile_tag("pack"):
        return jnp.asarray(decode_norm_doclen(np.arange(256, dtype=np.uint8)))


def decode_norm_tfidf(norm_byte: np.ndarray) -> np.ndarray:
    """TF-IDF: decoded norm multiplies the score directly."""
    return NORM_TABLE[np.asarray(norm_byte, dtype=np.uint8)]


def decode_norm_doclen(norm_byte: np.ndarray) -> np.ndarray:
    """BM25: decoded value f represents boost/sqrt(len); doc length = 1/f² (quantized).
    Bytes decoding to 0 (empty field) get length 0."""
    f = NORM_TABLE[np.asarray(norm_byte, dtype=np.uint8)]
    with np.errstate(divide="ignore"):
        dl = np.where(f > 0, 1.0 / (f * f), 0.0)
    return dl.astype(np.float32)
