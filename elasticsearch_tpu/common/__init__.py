from .settings import Settings, DynamicSettings, prepare_settings  # noqa: F401
from .errors import SearchEngineError  # noqa: F401
