"""Name-expression matching shared by admin APIs.

The reference resolves `_all` / `*` / comma lists / wildcards uniformly across
aliases, warmers, types, settings and template names (MetaData.concreteIndices and
friends); this is that matcher, factored once.
"""

from __future__ import annotations

import fnmatch


def is_pattern(expr) -> bool:
    s = str(expr)
    return s in ("_all", "*", "") or "*" in s or "," in s


def split_names(expr) -> list[str]:
    if isinstance(expr, list):
        return [str(p) for p in expr]
    return [p.strip() for p in str(expr).split(",") if p.strip()]


def name_matches(name: str, expr) -> bool:
    """Does `name` match a name expression (_all / * / comma list / wildcards)?"""
    if expr in (None, "_all", "*", ""):
        return True
    return any(name == p or fnmatch.fnmatch(name, p) for p in split_names(expr))
