"""Runtime collective-trace sanitizer — the dynamic twin of tpulint TPU014-016.

The static SPMD rules (tools/tpulint/spmd.py) prove what a mesh program CAN
do; this module records what each trace actually DID, completing the repo's
static/runtime pairings (TPU001 <-> transfer_guard, TPU002 <-> compile
budget, TPU004/TPU011 <-> locktrace). The hazard: on a multi-host fleet every
process traces the SAME program, and if host-divergent state (wall clock, env,
unseeded RNG) steers the trace, processes enqueue DIFFERENT collective launch
sequences — the mesh deadlocks on the first mismatched collective, with no
error message, on hardware only. Under `ESTPU_MESHTRACE=1`:

- `shard_map` (jax.shard_map and jax.experimental.shard_map.shard_map) is
  wrapped so each traced mesh program records its collective launch sequence:
  every patched `jax.lax` collective (psum/pmax/pmin/pmean/all_gather/
  all_to_all/ppermute/psum_scatter/axis_index) appends a
  (primitive, axis, shape, call site) entry while the program body is being
  traced. Sequences are aggregated per PROGRAM KEY — (qualname, closure-cell
  fingerprint, local arg shapes/dtypes) — so the factory pattern
  (mesh_search._mesh_score_program closes over static config; different
  configs legitimately emit different sequences) gets one node per variant
  instead of a false "divergence" between them.
- every later launch of the same key is compared against the first recorded
  sequence; any difference in the (primitive, axis, shape) triples is a
  mismatch, reported with BOTH call sites at the first divergence point.
- the session gate (tests/conftest.py) calls `TRACER.replay_all()` then
  `TRACER.check()`: replay re-traces every registered program via
  `jax.eval_shape` at teardown time — a program whose trace depends on
  wall-clock/env state diverges from its original recording exactly the way a
  second host would, so single-process CI catches the multi-host deadlock.
  check() raises CollectiveTraceMismatch naming both sites.

Overhead is exactly zero when the knob is off: `maybe_install()` returns
without importing or touching jax. When on, the cost is trace-time only —
compiled executions never re-enter the Python wrappers. Counters surface
through the existing sanitizer report (jaxenv.sanitize() attaches a snapshot
to SanitizerReport.mesh).
"""

from __future__ import annotations

import functools
import os
import sys
import threading

# the tracer's own lock must stay a REAL lock even under ESTPU_LOCKTRACE
_REAL_LOCK = threading.Lock

_REPO_MARKERS = (f"{os.sep}elasticsearch_tpu{os.sep}", f"{os.sep}tests{os.sep}")
_SELF_FILE = os.path.abspath(__file__)

COLLECTIVES = ("psum", "pmax", "pmin", "pmean", "all_gather", "all_to_all",
               "ppermute", "psum_scatter", "axis_index")


class CollectiveTraceMismatch(AssertionError):
    """Two traces of one mesh program enqueued different collective
    sequences — on a multi-host fleet this is a silent SPMD deadlock. The
    message names the first differing collective site in BOTH traces."""


_REL_CACHE: dict = {}


def _rel(fn: str) -> str:
    r = _REL_CACHE.get(fn)
    if r is None:
        r = _REL_CACHE[fn] = os.path.relpath(fn)
    return r


def _call_site() -> str:
    """file:line of the first repo frame below the patched collective —
    the line inside the mesh program that launched it."""
    f = sys._getframe(2)
    while f is not None:
        fn = f.f_code.co_filename
        if fn != _SELF_FILE and (any(m in fn for m in _REPO_MARKERS)
                                 or "tpulint_fixtures" in fn):
            return f"{_rel(fn)}:{f.f_lineno}"
        f = f.f_back
    return "<external>"


def _value_tag(v, depth: int = 0) -> str:
    """Stable fingerprint for one closure cell / static argument. Containers
    recurse (bounded depth/width): a factory's static config often rides in a
    list of nested tuples (mesh_search bucket_specs), and two variants that
    fingerprint identically would false-positive as a collective-sequence
    divergence between them."""
    shape = getattr(v, "shape", None)
    dtype = getattr(v, "dtype", None)
    # callable guard: a module cell (numpy) exposes shape/dtype as FUNCTIONS
    if shape is not None and dtype is not None and not callable(shape):
        try:
            return f"arr[{tuple(shape)}:{dtype}]"
        except TypeError:
            pass
    if isinstance(v, (int, float, bool, str, bytes, type(None))):
        return repr(v)
    if depth < 3 and isinstance(v, (list, tuple)):
        kind = "t" if isinstance(v, tuple) else "l"
        inner = ",".join(_value_tag(e, depth + 1) for e in v[:16])
        return f"{kind}({inner}{',...' if len(v) > 16 else ''})"
    if depth < 3 and isinstance(v, dict):
        items = sorted(v.items(), key=lambda kv: repr(kv[0]))[:16]
        inner = ",".join(f"{k!r}:{_value_tag(e, depth + 1)}" for k, e in items)
        return f"d({inner}{',...' if len(v) > 16 else ''})"
    return type(v).__name__


def _closure_fp(fn) -> tuple:
    cells = getattr(fn, "__closure__", None) or ()
    out = []
    for c in cells:
        try:
            out.append(_value_tag(c.cell_contents))
        except ValueError:  # empty cell
            out.append("<empty>")
    return tuple(out)


def _args_fp(args, kwargs) -> tuple:
    out = [_value_tag(a) for a in args]
    out.extend(f"{k}={_value_tag(v)}" for k, v in sorted(kwargs.items()))
    return tuple(out)


def _program_key(fn, args, kwargs) -> tuple:
    return (getattr(fn, "__qualname__", repr(fn)), _closure_fp(fn),
            _args_fp(args, kwargs))


def _axis_of(name: str, args, kwargs):
    if "axis_name" in kwargs:
        return str(kwargs["axis_name"])
    idx = 0 if name == "axis_index" else 1
    if len(args) > idx:
        return str(args[idx])
    return "?"


class MeshTracer:
    """Process-wide recorder: per-thread active-program stacks, the
    per-program first-witness sequences, and the replay registry."""

    def __init__(self):
        self._glock = _REAL_LOCK()
        self._tls = threading.local()
        self.enabled = False
        # program key -> first recorded sequence of (prim, axis, shape, site)
        self.programs: dict = {}
        # replay registry: outer key -> (f, sm_args, sm_kwargs, arg specs)
        self.replayable: dict = {}
        self.mismatches: list = []
        self.counters = {
            "programs": 0,
            "launches": 0,
            "collectives": 0,
            "mismatches": 0,
            "replayed": 0,
            "replay_errors": 0,
        }

    # -- per-thread active-program stack --------------------------------------
    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def push_program(self) -> list:
        seq: list = []
        self._stack().append(seq)
        return seq

    def pop_program(self) -> list:
        return self._stack().pop()

    def on_collective(self, prim: str, axis, shape) -> None:
        st = self._stack()
        if st:
            st[-1].append((prim, axis, shape, _call_site()))

    # -- aggregation ----------------------------------------------------------
    def on_program(self, key: tuple, seq: list) -> None:
        with self._glock:
            self.counters["launches"] += 1
            self.counters["collectives"] += len(seq)
            prev = self.programs.get(key)
            if prev is None:
                self.programs[key] = seq
                self.counters["programs"] += 1
                return
            if [e[:3] for e in prev] != [e[:3] for e in seq]:
                self.counters["mismatches"] += 1
                self.mismatches.append(self._describe(key, prev, seq))

    @staticmethod
    def _describe(key: tuple, prev: list, seq: list) -> dict:
        i = 0
        while i < len(prev) and i < len(seq) and prev[i][:3] == seq[i][:3]:
            i += 1

        def ent(s, j):
            if j < len(s):
                prim, axis, shape, site = s[j]
                return {"prim": f"lax.{prim}", "axis": axis,
                        "shape": list(shape), "site": site}
            return {"prim": "<end of sequence>", "axis": "", "shape": [],
                    "site": s[-1][3] if s else "<none>"}

        return {"program": key[0], "index": i,
                "first": ent(prev, i), "second": ent(seq, i)}

    # -- replay ---------------------------------------------------------------
    def register_replay(self, key: tuple, f, sm_args: tuple, sm_kwargs: dict,
                        specs: tuple) -> None:
        with self._glock:
            if key not in self.replayable:
                self.replayable[key] = (f, sm_args, sm_kwargs, specs)

    def replay_all(self) -> None:
        """Re-trace every registered mesh program via jax.eval_shape. A
        program whose trace rides host-divergent state (clock/env) diverges
        from its original recording here exactly as it would on another host;
        the divergence lands in self.mismatches for check()."""
        with self._glock:
            entries = list(self.replayable.values())
        if not entries:
            return
        import jax
        for f, sm_args, sm_kwargs, specs in entries:
            try:
                wrapped = _REAL_SHARD_MAP(_shim(f), *sm_args, **sm_kwargs)
                jax.eval_shape(wrapped, *specs)
                with self._glock:
                    self.counters["replayed"] += 1
            except Exception:
                with self._glock:
                    self.counters["replay_errors"] += 1

    # -- the gate -------------------------------------------------------------
    def check(self) -> None:
        with self._glock:
            mms = list(self.mismatches)
        if mms:
            lines = []
            for m in mms:
                a, b = m["first"], m["second"]
                lines.append(
                    f"  program `{m['program']}` diverges at collective "
                    f"#{m['index']}:\n"
                    f"    one trace launched {a['prim']}(axis={a['axis']!r}, "
                    f"shape={tuple(a['shape'])}) at {a['site']}\n"
                    f"    another trace launched {b['prim']}(axis="
                    f"{b['axis']!r}, shape={tuple(b['shape'])}) at "
                    f"{b['site']}")
            raise CollectiveTraceMismatch(
                "collective launch sequences diverged between traces of the "
                "same mesh program — on a multi-host fleet every process "
                "must enqueue the identical sequence or the mesh deadlocks:\n"
                + "\n".join(lines) +
                "\nhoist host-dependent branches out of the device program "
                "(tpulint TPU014/TPU016 are the static twins of this check)")

    def snapshot(self) -> dict:
        with self._glock:
            return {**self.counters, "mismatches_detail": list(self.mismatches)}


TRACER = MeshTracer()

_REAL_SHARD_MAP = None  # the unpatched shard_map, set by install()


def _shim(f):
    """Wrap the user's mesh program so its trace records a collective
    sequence under the program's key (computed from the per-shard view)."""

    @functools.wraps(f)
    def recorded(*args, **kwargs):
        key = _program_key(f, args, kwargs)
        TRACER.push_program()
        try:
            out = f(*args, **kwargs)
        finally:
            seq = TRACER.pop_program()
        TRACER.on_program(key, seq)
        return out

    return recorded


def _spec_of(a):
    shape = getattr(a, "shape", None)
    dtype = getattr(a, "dtype", None)
    if shape is not None and dtype is not None:
        import jax
        return jax.ShapeDtypeStruct(tuple(shape), dtype)
    return a


def _wrap_shard_map(real):
    @functools.wraps(real)
    def shard_map(f, *sm_args, **sm_kwargs):
        mapped = real(_shim(f), *sm_args, **sm_kwargs)

        @functools.wraps(f)
        def dispatch(*args, **kwargs):
            # register for session-end replay once per (program, arg-shape)
            # variant; under jit the args are tracers, whose shape/dtype is
            # exactly what eval_shape needs — no device traffic here
            specs = tuple(_spec_of(a) for a in args)
            key = (_program_key(f, (), {}), _args_fp(specs, {}))
            TRACER.register_replay(key, f, sm_args, sm_kwargs, specs)
            return mapped(*args, **kwargs)

        return dispatch

    shard_map._estpu_meshtrace = True
    return shard_map


def _wrap_collective(lax_mod, name: str) -> None:
    real = getattr(lax_mod, name, None)
    if real is None or getattr(real, "_estpu_meshtrace", False):
        return

    @functools.wraps(real)
    def collective(*args, **kwargs):
        TRACER.on_collective(
            name, _axis_of(name, args, kwargs),
            tuple(getattr(args[0], "shape", ())) if args else ())
        return real(*args, **kwargs)

    collective._estpu_meshtrace = True
    setattr(lax_mod, name, collective)


def install() -> MeshTracer:
    """Arm the tracer (idempotent). Prefer maybe_install() — the env knob.
    Must run after jax is importable; patches jax.lax collectives plus every
    public shard_map entry point. The wrappers carry functools.wraps, so
    signature sniffing (mesh_search probes shard_map for check_vma) still
    resolves through __wrapped__."""
    global _REAL_SHARD_MAP
    if TRACER.enabled:
        return TRACER
    import jax
    from jax.experimental import shard_map as sm_mod

    for name in COLLECTIVES:
        _wrap_collective(jax.lax, name)

    real = getattr(jax, "shard_map", None) or sm_mod.shard_map
    if not getattr(real, "_estpu_meshtrace", False):
        _REAL_SHARD_MAP = real
        patched = _wrap_shard_map(real)
        if getattr(jax, "shard_map", None) is not None:
            jax.shard_map = patched
        sm_mod.shard_map = patched
    TRACER.enabled = True
    return TRACER


def maybe_install() -> MeshTracer | None:
    """Install iff ESTPU_MESHTRACE=1 (same env-knob conventions as
    ESTPU_SANITIZE / ESTPU_LOCKTRACE). Zero cost when off: jax is neither
    imported nor touched."""
    if os.environ.get("ESTPU_MESHTRACE", "") not in ("1", "on", "true"):
        return None
    return install()
