"""Logging facade.

Analogue of common/logging/ESLogger.java + Loggers.java: component loggers with optional
node/index/shard prefixes, and dynamically updatable levels (the reference exposes
`logger.*` cluster settings; we expose set_level)."""

from __future__ import annotations

import logging
import sys

_ROOT = "estpu"
_configured = False


def _ensure_configured():
    global _configured
    if _configured:
        return
    root = logging.getLogger(_ROOT)
    if not root.handlers:
        h = logging.StreamHandler(sys.stderr)
        h.setFormatter(
            logging.Formatter("[%(asctime)s][%(levelname)-5s][%(name)s] %(message)s", "%Y-%m-%dT%H:%M:%S")
        )
        root.addHandler(h)
        root.setLevel(logging.INFO)
        root.propagate = False
    _configured = True


class PrefixLogger(logging.LoggerAdapter):
    def process(self, msg, kwargs):
        return f"{self.extra['prefix']} {msg}", kwargs


def get_logger(component: str, node: str | None = None, shard=None):
    """`get_logger("index.engine", node="node_1", shard=("idx", 3))` →
    logger named estpu.index.engine with "[node_1][idx][3]" prefix."""
    _ensure_configured()
    logger = logging.getLogger(f"{_ROOT}.{component}")
    prefix_parts = []
    if node:
        prefix_parts.append(f"[{node}]")
    if shard is not None:
        index, shard_id = shard
        prefix_parts.append(f"[{index}][{shard_id}]")
    if prefix_parts:
        return PrefixLogger(logger, {"prefix": "".join(prefix_parts)})
    return logger


def set_level(component: str, level: str):
    """Dynamically change a component's level ("logger.index.engine": "debug")."""
    _ensure_configured()
    name = _ROOT if component in ("", "_root") else f"{_ROOT}.{component}"
    logging.getLogger(name).setLevel(getattr(logging, level.upper()))


def apply_logger_settings(settings):
    for key, value in settings.as_dict().items():
        if key.startswith("logger."):
            set_level(key[len("logger."):], str(value))
        elif key == "logger":
            set_level("", str(value))
