"""Exception hierarchy.

TPU-native analogue of the reference's ElasticsearchException tree
(/root/reference/src/main/java/org/elasticsearch/ElasticsearchException.java and the
per-subsystem subclasses). Each exception knows its REST status so the HTTP layer can map
failures to structured JSON errors the way rest/BytesRestResponse does.
"""

from __future__ import annotations


class SearchEngineError(Exception):
    """Root of the framework exception tree."""

    status = 500

    def __init__(self, message: str = "", *, cause: Exception | None = None):
        super().__init__(message)
        self.message = message
        self.cause = cause

    def wire_name(self) -> str:
        """Error type as exposed on the API — the reference publishes *Exception names
        (e.g. RoutingMissingException) and clients/tests match on them."""
        name = type(self).__name__
        return name[:-len("Error")] + "Exception" if name.endswith("Error") else name

    def to_dict(self) -> dict:
        d = {"type": self.wire_name(), "reason": self.message}
        if self.cause is not None:
            d["caused_by"] = {"type": type(self.cause).__name__, "reason": str(self.cause)}
        return d

    def es1_string(self) -> str:
        """ES 1.x single-string error rendering, `Type[message]` with nested causes —
        the shape the reference puts in per-item errors (msearch/mpercolate/bulk)."""
        out = f"{self.wire_name()}[{self.message}]"
        if self.cause is not None:
            inner = (self.cause.es1_string() if isinstance(self.cause, SearchEngineError)
                     else f"{type(self.cause).__name__}[{self.cause}]")
            out += f"; nested: {inner}"
        return out


class IllegalArgumentError(SearchEngineError):
    status = 400


class ParsingError(IllegalArgumentError):
    """Bad query / mapping / settings body (ref: QueryParsingException, MapperParsingException)."""


class MapperParsingError(ParsingError):
    pass


class QueryParsingError(ParsingError):
    pass


class DocumentMissingError(SearchEngineError):
    status = 404


class IndexMissingError(SearchEngineError):
    status = 404

    def __init__(self, index: str):
        super().__init__(f"[{index}] missing")
        self.index = index


class NodeMissingError(SearchEngineError):
    """A node-addressed API named an id/name no cluster node answers to
    (e.g. GET /_cluster/stats/nodes/{node_id} with an unknown id)."""

    status = 404

    def __init__(self, node_id: str):
        super().__init__(f"node [{node_id}] missing")
        self.node_id = node_id


class IndexAlreadyExistsError(SearchEngineError):
    status = 400

    def __init__(self, index: str):
        super().__init__(f"index [{index}] already exists")
        self.index = index


class TypeMissingError(SearchEngineError):
    status = 404


class ShardNotFoundError(SearchEngineError):
    status = 404


class IndexShardMissingError(ShardNotFoundError):
    pass


class IllegalIndexShardStateError(SearchEngineError):
    status = 409


class VersionConflictError(SearchEngineError):
    """Optimistic-concurrency failure (ref: index/engine/VersionConflictEngineException.java)."""

    status = 409

    def __init__(self, uid: str, current: int, provided: int):
        super().__init__(
            f"version conflict for [{uid}]: current [{current}], provided [{provided}]"
        )
        self.current = current
        self.provided = provided


class DocumentAlreadyExistsError(SearchEngineError):
    status = 409


class EngineClosedError(SearchEngineError):
    status = 503


class FlushNotAllowedError(SearchEngineError):
    status = 503


class NodeNotConnectedError(SearchEngineError):
    status = 503


class TransportError(SearchEngineError):
    status = 503


class ActionNotFoundError(TransportError):
    status = 400


class ReceiveTimeoutError(TransportError):
    status = 503


class MasterNotDiscoveredError(SearchEngineError):
    status = 503


class NoNodeAvailableError(SearchEngineError):
    """Every connected node refused or timed out (ref: the TransportClient's
    NoNodeAvailableException, client/transport/TransportClientNodesService.java)."""

    status = 503


class ClusterBlockError(SearchEngineError):
    """Operation rejected by a cluster-level block (ref: cluster/block/ClusterBlockException.java).

    Status follows the reference: retryable blocks (no master / state not recovered)
    → 503, non-retryable blocks (index closed / read-only) → 403 FORBIDDEN."""

    RETRYABLE = {"no_master", "state_not_recovered"}

    def __init__(self, blocks):
        super().__init__(f"blocked by: {[str(b) for b in blocks]}")
        self.blocks = blocks
        self.status = 503 if all(
            (b[0] if isinstance(b, tuple) else str(b)) in self.RETRYABLE
            for b in blocks) else 403


class NoShardAvailableError(SearchEngineError):
    status = 503


class UnavailableShardsError(SearchEngineError):
    status = 503


class ReduceSearchPhaseError(SearchEngineError):
    pass


class SearchPhaseExecutionError(SearchEngineError):
    status = 503

    def __init__(self, phase: str, message: str, shard_failures=()):
        super().__init__(f"phase [{phase}] failed: {message}")
        self.phase = phase
        self.shard_failures = list(shard_failures)


class SearchContextMissingError(SearchEngineError):
    status = 404

    def __init__(self, context_id: int):
        super().__init__(f"no search context found for id [{context_id}]")


class CircuitBreakingError(SearchEngineError):
    """Memory circuit breaker tripped (ref: common/breaker/CircuitBreakingException.java).

    429: the node is out of memory headroom, not broken — clients should back
    off and retry after `retry_after_s` (surfaced as the Retry-After header).
    `breaker` names the tripped breaker ("request"/"fielddata"/"parent"/...)
    so serving paths can distinguish degradable fielddata trips from
    must-shed request/parent trips."""

    status = 429
    retry_after_s = 1.0
    breaker: str | None = None


class RejectedExecutionError(SearchEngineError):
    """A bounded executor queue (or admission control) rejected the task
    (ref: EsRejectedExecutionException out of EsThreadPoolExecutor). Transient
    by definition — the same work succeeds on a less-saturated node — so
    common/retry.py classifies it retryable, and the REST layer maps it to
    429 with a Retry-After hint."""

    status = 429
    retry_after_s = 1.0


class SnapshotError(SearchEngineError):
    pass


class SnapshotMissingError(SnapshotError):
    status = 404


class RepositoryError(SearchEngineError):
    pass


class RepositoryMissingError(RepositoryError):
    status = 404


class InvalidAliasNameError(IllegalArgumentError):
    pass


class AliasesMissingError(SearchEngineError):
    status = 404

    def __init__(self, aliases):
        super().__init__(f"aliases {list(aliases)} missing")
        self.aliases = list(aliases)


class IndexTemplateMissingError(SearchEngineError):
    status = 404

    def __init__(self, name):
        super().__init__(f"index_template [{name}] missing")


class IndexWarmerMissingError(SearchEngineError):
    status = 404

    def __init__(self, name):
        super().__init__(f"index_warmer [{name}] missing")


class ActionRequestValidationError(IllegalArgumentError):
    """Request failed client-side validation (ref: action/ActionRequestValidationException)."""


class AlreadyExpiredError(SearchEngineError):
    """Doc with _ttl already expired at index time (ref: index/AlreadyExpiredException)."""

    status = 400


class InvalidIndexNameError(IllegalArgumentError):
    pass


class InvalidTypeNameError(IllegalArgumentError):
    pass


class ScriptError(SearchEngineError):
    status = 400


class PercolateError(SearchEngineError):
    pass


class TimestampParsingError(ParsingError):
    pass


class RoutingMissingError(IllegalArgumentError):
    def __init__(self, index: str, type_: str, id_: str):
        super().__init__(f"routing is required for [{index}]/[{type_}]/[{id_}]")
