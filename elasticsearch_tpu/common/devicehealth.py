"""Device fault domains: classify-and-contain accelerator failures.

Every resilience layer so far (deadlines/retries, breakers, hedging, the stall
watchdog) defends against *host-side* failure; this module makes the device
itself just another failure domain, exactly like the reference treats a shard
copy (per-copy `_shards.failures`, failover chains). Four domains cover the
serving stack's device touchpoints:

- ``pack:<index>``    — segment packing (ops/device_index pack/compact/remask)
- ``compile:<family>``— a compile family's launch (sparse/dense/mesh/...)
- ``mesh:<index>``    — the SPMD mesh executor for one index
- ``pull:<index>``    — the batched device_get that lands results on the host

Each domain carries a circuit: closed → open (after classified failures) →
half-open (one probe admitted per decorrelated-jitter backoff window, schedule
from common/retry.RetryPolicy.next_backoff) → closed again on a clean probe.
An OPEN domain never 500s a search: the serving path degrades to the
bitwise-identical host scorer / composed path and marks the shard result
``degraded`` so `_shards` stays honest.

Classification (`classify_device_error`): jax/XLA runtime errors split into
``transient`` (RESOURCE_EXHAUSTED / OOM, DEADLINE_EXCEEDED, UNAVAILABLE —
pressure that drains) vs ``persistent`` (INTERNAL launch failures, transfer
errors, FAILED_PRECONDITION, poisoned executables — broken until re-built).
A persistent error trips its domain immediately; transients need
``TRANSIENT_STRIKES`` consecutive hits. Non-device exceptions classify to
``None`` and never move a circuit — a host-side bug must not quarantine the
accelerator.

Hot-path contract (the standing telemetry rule): when every domain is closed a
health check is ONE plain attribute read (`any_open`), no lock, no clock.
Locks and monotonic reads happen only in degraded states; `_lock` is a leaf
(journal publishes happen outside it) so locktrace stays clean.
"""

from __future__ import annotations

import logging
import random
import threading
import time

from .retry import RetryPolicy

logger = logging.getLogger("elasticsearch_tpu.devicehealth")

CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

# XLA status prefixes (jaxlib surfaces them verbatim in the message:
# "RESOURCE_EXHAUSTED: Out of memory while trying to allocate ...").
_TRANSIENT_STATUSES = (
    "RESOURCE_EXHAUSTED", "DEADLINE_EXCEEDED", "UNAVAILABLE", "ABORTED",
    "CANCELLED",
)
_TRANSIENT_PHRASES = ("OUT OF MEMORY", "RESOURCE EXHAUSTED", "OOM")


def _is_device_error(error: BaseException) -> bool:
    """Duck-typed XlaRuntimeError/JaxRuntimeError detection — jaxlib moves the
    class between releases and this module must stay importable before jax."""
    for t in type(error).__mro__:
        if t.__name__ in ("XlaRuntimeError", "JaxRuntimeError"):
            return True
        if getattr(t, "__module__", "").split(".")[0] in ("jaxlib", "jax"):
            return True
    return False


def classify_device_error(error: BaseException) -> str | None:
    """"transient" | "persistent" for device/XLA failures, None otherwise.

    Transient: the same launch plausibly succeeds once pressure drains (OOM /
    resource-exhausted, timeout, device temporarily unavailable). Persistent:
    launch/transfer errors and poisoned executables (INTERNAL,
    FAILED_PRECONDITION, INVALID_ARGUMENT, ...) — retrying without a rebuild
    just burns the budget."""
    if not isinstance(error, BaseException) or not _is_device_error(error):
        return None
    up = str(error).upper()
    head = up.split(":", 1)[0].strip()
    if head in _TRANSIENT_STATUSES:
        return "transient"
    if any(s in up for s in _TRANSIENT_STATUSES) or \
            any(p in up for p in _TRANSIENT_PHRASES):
        return "transient"
    return "persistent"


def tag_domain(error: BaseException, domain: str) -> BaseException:
    """Stamp `error` with the fault domain of the seam that raised/observed
    it. First (narrowest) tag wins — an exception crossing several wrappers
    keeps the most specific attribution. Returns `error` so call sites can
    `raise tag_domain(e, ...)` without losing the traceback."""
    if getattr(error, "_estpu_device_domain", None) is None:
        try:
            error._estpu_device_domain = domain
        except Exception:  # noqa: BLE001 — __slots__-ed exotic exceptions
            pass
    return error


class _DomainCircuit:
    """One fault domain's breaker state. Mutated only under DeviceHealth._lock."""

    __slots__ = ("domain", "state", "strikes", "failures", "trips", "probes",
                 "recoveries", "backoff_s", "probe_at", "last_error")

    def __init__(self, domain: str):
        self.domain = domain
        self.state = CLOSED
        self.strikes = 0        # consecutive classified failures while closed
        self.failures = 0
        self.trips = 0
        self.probes = 0
        self.recoveries = 0
        self.backoff_s = 0.0
        self.probe_at = 0.0     # monotonic time the next probe is admitted
        self.last_error = None


class DeviceHealth:
    """Per-fault-domain circuit tracker with probed recovery.

    `any_open` is THE hot-path read: a plain bool, True iff at least one
    domain is not closed. `dirty` (also a plain bool) is True once any domain
    ever recorded a failure, so the success hook costs one attr read on a
    never-failed process. Everything else — probe scheduling, trip/recover
    transitions, stats — takes the leaf `_lock`, and journal publishers run
    OUTSIDE it (journal locks are their own leaves)."""

    TRANSIENT_STRIKES = 3   # consecutive transients to trip a closed domain

    def __init__(self, base_s: float = 0.05, cap_s: float = 5.0,
                 rng: random.Random | None = None, clock=time.monotonic):
        self.any_open = False   # the one hot-path read
        self.dirty = False      # any failure ever recorded (success fast path)
        self._lock = threading.Lock()
        self._domains: dict[str, _DomainCircuit] = {}
        self._policy = RetryPolicy(base_s=base_s, cap_s=cap_s, rng=rng)
        self._clock = clock
        self._publishers: dict[object, object] = {}
        self._failures = {"transient": 0, "persistent": 0}
        self._trips = 0
        self._probes = 0
        self._recoveries = 0

    # --- gate + probe admission (degraded states only) ----------------------
    def blocked(self, domains) -> str | None:
        """First domain that is open (probe window not yet due) — the caller
        degrades to the host path naming it — or None: every listed domain is
        closed, or due for a probe THIS caller was just admitted as. Call only
        after reading `any_open` (the closed-world fast path is the caller's
        one attr read)."""
        if not self.any_open:
            return None
        now = None
        with self._lock:
            for d in domains:
                c = self._domains.get(d)
                if c is None or c.state == CLOSED:
                    continue
                if now is None:
                    now = self._clock()
                if now >= c.probe_at:
                    # admit ONE probe: concurrent callers keep degrading until
                    # it reports (note_success closes / record_failure
                    # re-opens); a probe that never reports — lost thread —
                    # re-arms at the next backoff window rather than wedging
                    # the domain half-open forever
                    c.state = HALF_OPEN
                    c.probes += 1
                    self._probes += 1
                    c.probe_at = now + max(c.backoff_s, self._policy.base_s)
                    continue
                return d
        return None

    # --- outcome recording --------------------------------------------------
    def record_failure(self, domain: str, error: BaseException) -> str | None:
        """Classify `error` and advance `domain`'s circuit. Returns the
        classification ("transient"/"persistent") or None when the error is
        not a device failure (circuit untouched)."""
        cls = classify_device_error(error)
        if cls is None:
            return None
        events = []
        with self._lock:
            self.dirty = True
            c = self._domains.get(domain)
            if c is None:
                c = self._domains[domain] = _DomainCircuit(domain)
            self._failures[cls] += 1
            c.failures += 1
            c.last_error = f"{type(error).__name__}: {error}"[:240]
            if c.state == HALF_OPEN:
                # failed probe: back to open with a grown jitter window
                c.state = OPEN
                c.backoff_s = self._policy.next_backoff(c.backoff_s)
                c.probe_at = self._clock() + c.backoff_s
            elif c.state == CLOSED:
                # a persistent error spends the whole strike budget at once
                c.strikes += 1 if cls == "transient" else self.TRANSIENT_STRIKES
                if c.strikes >= self.TRANSIENT_STRIKES:
                    c.state = OPEN
                    c.trips += 1
                    self._trips += 1
                    c.backoff_s = self._policy.next_backoff(None)
                    c.probe_at = self._clock() + c.backoff_s
                    self.any_open = True
                    events.append((
                        "device_degraded", domain, "warn",
                        f"device domain [{domain}] tripped ({cls}): "
                        f"{c.last_error} — serving degrades to the host path",
                        {"domain": domain, "classification": cls,
                         "failures": c.failures}))
            # already OPEN: count it; the probe scheduler owns transitions
        for ev in events:
            self._publish(*ev)
        return cls

    def note_success(self, domains) -> None:
        """Clean device outcome for `domains`: resets closed-domain strikes and
        closes a half-open domain (the probe reported back healthy). One attr
        read when no failure was ever recorded."""
        if not self.dirty:
            return
        events = []
        with self._lock:
            for d in domains:
                c = self._domains.get(d)
                if c is None:
                    continue
                if c.state == CLOSED:
                    c.strikes = 0
                elif c.state == HALF_OPEN:
                    c.state = CLOSED
                    c.strikes = 0
                    c.backoff_s = 0.0
                    c.recoveries += 1
                    self._recoveries += 1
                    events.append((
                        "device_recovered", d, "info",
                        f"device domain [{d}] probe succeeded — device path "
                        f"restored", {"domain": d, "probes": c.probes}))
                # OPEN + success = a straggler launched before the trip; the
                # half-open probe protocol owns closing, not stragglers
            if events:
                self.any_open = any(c.state != CLOSED
                                    for c in self._domains.values())
        for ev in events:
            self._publish(*ev)

    # --- event publishing (outside the leaf lock) ---------------------------
    def register_publisher(self, key, publish) -> None:
        """`publish(type_, message, severity=..., key=..., **attrs)` — the
        EventJournal.publish signature; a node registers its journal so
        trip/recover transitions land next to watchdog events."""
        with self._lock:
            self._publishers[key] = publish

    def unregister_publisher(self, key) -> None:
        with self._lock:
            self._publishers.pop(key, None)

    def _publish(self, type_, domain, severity, message, attrs) -> None:
        log = logger.warning if severity == "warn" else logger.info
        log("%s: %s", type_, message)
        for publish in list(self._publishers.values()):
            try:
                publish(type_, message, severity=severity, key=domain, **attrs)
            except Exception:  # noqa: BLE001 — telemetry must not fail serving
                logger.exception("device-health event publish failed")

    # --- introspection ------------------------------------------------------
    def state(self, domain: str) -> str:
        with self._lock:
            c = self._domains.get(domain)
            return CLOSED if c is None else c.state

    def stats(self) -> dict:
        with self._lock:
            return {
                "any_open": self.any_open,
                "failures": dict(self._failures),
                "trips": self._trips,
                "probes": self._probes,
                "recoveries": self._recoveries,
                "domains": {
                    d: {"state": c.state, "failures": c.failures,
                        "trips": c.trips, "probes": c.probes,
                        "recoveries": c.recoveries,
                        "backoff_ms": round(c.backoff_s * 1000.0, 1),
                        "last_error": c.last_error}
                    for d, c in sorted(self._domains.items())
                },
            }

    def reset(self) -> None:
        """Forget every domain and counter (test isolation; publishers stay)."""
        with self._lock:
            self._domains.clear()
            self._failures = {"transient": 0, "persistent": 0}
            self._trips = self._probes = self._recoveries = 0
            self.any_open = False
            self.dirty = False


# Process-wide singleton, like SERVING_COUNTERS / DEVICE_PULL: the serving path
# (search/service.py module functions, execute.py) has no node handle, and the
# device being probed is per-process anyway.
DEVICE_HEALTH = DeviceHealth()
