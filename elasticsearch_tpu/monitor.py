"""Monitor service: OS / process / fs / runtime metrics.

Analogue of monitor/ (SURVEY.md §2.9): the reference loads native Sigar libraries for
os/process/network stats with pure-Java fallbacks; here the native source of truth is
/proc (what Sigar reads underneath) plus resource/os modules — no JVM, so "jvm stats"
map to the Python runtime + the JAX device: heap → RSS, GC → gc module, plus TPU HBM
numbers from jax's memory_stats when a device is live.
"""

from __future__ import annotations

import gc
import os
import resource
import time


def os_stats(proc: str = "/proc") -> dict:
    """`proc` overrides the procfs root so tests can feed canned fixtures
    (tests/test_monitor.py) — production always reads the real /proc."""
    out: dict = {"timestamp": int(time.time() * 1000)}
    try:
        load = os.getloadavg()
        out["load_average"] = list(load)
    except OSError:
        pass
    try:
        with open(os.path.join(proc, "meminfo")) as fh:
            mem = {}
            for line in fh:
                parts = line.split()
                if parts[0].rstrip(":") in ("MemTotal", "MemFree", "MemAvailable",
                                            "SwapTotal", "SwapFree"):
                    mem[parts[0].rstrip(":")] = int(parts[1]) * 1024
        out["mem"] = {
            "total_in_bytes": mem.get("MemTotal", 0),
            "free_in_bytes": mem.get("MemFree", 0),
            "available_in_bytes": mem.get("MemAvailable", 0),
        }
        out["swap"] = {
            "total_in_bytes": mem.get("SwapTotal", 0),
            "free_in_bytes": mem.get("SwapFree", 0),
        }
    except OSError:
        pass
    out["cpu"] = {"count": os.cpu_count()}
    return out


def process_stats(proc: str = "/proc") -> dict:
    """`proc` overrides the procfs root (canned fixtures in tests)."""
    ru = resource.getrusage(resource.RUSAGE_SELF)
    out = {
        "timestamp": int(time.time() * 1000),
        "id": os.getpid(),
        "mem": {"resident_in_bytes": ru.ru_maxrss * 1024},
        "cpu": {
            "user_in_millis": int(ru.ru_utime * 1000),
            "sys_in_millis": int(ru.ru_stime * 1000),
            "total_in_millis": int((ru.ru_utime + ru.ru_stime) * 1000),
        },
    }
    try:
        with open(os.path.join(proc, "self", "status")) as fh:
            for line in fh:
                if line.startswith("Threads:"):
                    out["threads"] = int(line.split()[1])
                elif line.startswith("VmRSS:"):
                    out["mem"]["resident_in_bytes"] = int(line.split()[1]) * 1024
    except OSError:
        pass
    try:
        out["open_file_descriptors"] = len(os.listdir(
            os.path.join(proc, "self", "fd")))
        out["max_file_descriptors"] = resource.getrlimit(resource.RLIMIT_NOFILE)[0]
    except OSError:
        pass
    return out


def fs_stats(paths: list[str]) -> dict:
    data = []
    for p in paths:
        try:
            st = os.statvfs(p)
            data.append({
                "path": p,
                "total_in_bytes": st.f_blocks * st.f_frsize,
                "free_in_bytes": st.f_bfree * st.f_frsize,
                "available_in_bytes": st.f_bavail * st.f_frsize,
            })
        except OSError:
            continue
    return {"timestamp": int(time.time() * 1000), "data": data}


def runtime_stats() -> dict:
    """The "jvm stats" analogue: Python runtime + (when live) the TPU device."""
    import sys

    counts = gc.get_count()
    out = {
        "timestamp": int(time.time() * 1000),
        "runtime": "python",
        "version": sys.version.split()[0],
        "gc": {"collections": gc.get_stats()[-1].get("collections", 0)
               if gc.get_stats() else 0, "pending": sum(counts)},
        "uptime_in_millis": int(time.monotonic() * 1000),
    }
    try:
        import jax

        devices = jax.devices()
        dev_stats = []
        for d in devices:
            entry = {"platform": d.platform, "device": str(d)}
            ms = getattr(d, "memory_stats", None)
            if callable(ms):
                try:
                    stats = ms() or {}
                    entry["hbm_bytes_in_use"] = stats.get("bytes_in_use")
                    entry["hbm_bytes_limit"] = stats.get("bytes_limit")
                except Exception:  # noqa: BLE001
                    pass
            dev_stats.append(entry)
        out["devices"] = dev_stats
    except Exception:  # noqa: BLE001 — no device backend in this process
        out["devices"] = []
    return out


class MonitorService:
    def __init__(self, node):
        self.node = node

    def sections(self) -> dict:
        """Monitor stats as name -> thunk, so `/_nodes/stats/{metric}` can
        build ONLY the requested sections (each is its own procfs read)."""
        return {
            "os": os_stats,
            "process": process_stats,
            "fs": lambda: fs_stats([self.node.data_path]),
            "runtime": runtime_stats,
        }

    def full_stats(self) -> dict:
        return {name: build() for name, build in self.sections().items()}
