from .controller import RestController, RestRequest, build_rest_controller  # noqa: F401
