"""REST layer: path-template routing + handlers for the API surface.

Analogue of rest/ (89 Rest*Action handler classes + RestController — SURVEY.md §2.7),
with the reference's `rest-api-spec/api/*.json` as the endpoint contract: methods, path
templates with {placeholders}, query params, JSON bodies, structured errors with HTTP
status codes, and the `_cat` plain-text ops APIs.

Handlers call the node Client — REST is a thin adapter exactly as in the reference
(RestController.dispatchRequest → client.*).
"""

from __future__ import annotations

import json
import os
import re
import time
from dataclasses import dataclass, field as dc_field
from typing import Callable

from ..common import tracing
from ..common.errors import SearchEngineError


@dataclass
class RestRequest:
    method: str
    path: str
    params: dict = dc_field(default_factory=dict)
    body: dict | list | str | None = None
    path_params: dict = dc_field(default_factory=dict)

    def param(self, name: str, default=None):
        # a blank value (a bare `?from` token surfaced by the http layer)
        # reads as ABSENT for valued params — only flags may be bare, and
        # they read presence via bool_param below
        v = self.path_params.get(name) or self.params.get(name)
        return default if v is None or v == "" else v

    def bool_param(self, name: str, default=False) -> bool:
        if name not in self.params and not self.path_params.get(name):
            return default
        v = self.path_params.get(name) or self.params.get(name)
        return str(v).lower() in ("true", "1", "")


@dataclass
class RestResponse:
    status: int
    body: object
    content_type: str = "application/json"
    # extra response headers (e.g. Retry-After on 429) — emitted verbatim by
    # http/server.py
    headers: dict = dc_field(default_factory=dict)

    def payload(self) -> bytes:
        if isinstance(self.body, (bytes,)):
            return self.body
        if isinstance(self.body, str):
            return self.body.encode()
        return json.dumps(self.body).encode()


class RestController:
    """register(method, "/{index}/{type}/_search", handler) + dispatch."""

    def __init__(self):
        self._routes: dict[str, list[tuple[re.Pattern, list[str], Callable]]] = {}

    def register(self, method: str, template: str, handler: Callable):
        names = re.findall(r"\{(\w+)\}", template)
        pattern = re.sub(r"\{(\w+)\}", r"([^/]+)", template.rstrip("/") or "/")
        compiled = re.compile("^" + pattern + "/?$")
        for m in method.split(","):
            self._routes.setdefault(m.strip().upper(), []).append(
                (compiled, names, handler))

    def dispatch(self, request: RestRequest) -> RestResponse:
        routes = self._routes.get(request.method, []) + (
            self._routes.get("GET", []) if request.method == "HEAD" else [])
        path = request.path.rstrip("/") or "/"
        best = None
        for pattern, names, handler in routes:
            m = pattern.match(path)
            if m:
                # prefer routes with fewer wildcards (literal match wins)
                score = len(names)
                if best is None or score < best[0]:
                    best = (score, m, names, handler)
        if best is None:
            return RestResponse(400, {"error": f"No handler found for uri [{request.path}] "
                                               f"and method [{request.method}]"})
        _, m, names, handler = best
        request.path_params = dict(zip(names, m.groups()))
        try:
            result = handler(request)
            if isinstance(result, RestResponse):
                return result
            return RestResponse(200, result)
        except SearchEngineError as e:
            headers = {}
            if e.status == 429:
                # overload rejections (breaker trip / queue rejection /
                # admission control) carry a backoff hint: the 429 contract is
                # "come back later", and Retry-After says when (whole seconds,
                # rounded up, at least 1 — RFC 7231 delta-seconds)
                import math

                headers["Retry-After"] = str(max(
                    1, int(math.ceil(getattr(e, "retry_after_s", 1.0)))))
            return RestResponse(e.status, {"error": e.to_dict(),
                                           "status": e.status}, headers=headers)
        except Exception as e:  # noqa: BLE001
            return RestResponse(500, {"error": {"type": type(e).__name__,
                                                "reason": str(e)}, "status": 500})


def _parse_body(request: RestRequest) -> dict:
    if request.body is None or request.body == "":
        return {}
    if isinstance(request.body, (dict, list)):
        return request.body
    try:
        return json.loads(request.body)
    except ValueError:
        return json.loads(_lenient_to_strict_json(request.body))


def _lenient_to_strict_json(text: str) -> str:
    """The reference's JSON parser accepts unquoted field names and single-quoted
    strings (Jackson ALLOW_UNQUOTED_FIELD_NAMES/ALLOW_SINGLE_QUOTES, enabled by
    common/xcontent JsonXContent); rewrite such input to strict JSON."""
    out = []
    i, n = 0, len(text)
    bare = re.compile(r"[A-Za-z_$][A-Za-z0-9_$.\-]*")
    number = re.compile(r"-?\d+(\.\d+)?([eE][+-]?\d+)?")
    while i < n:
        c = text[i]
        if c == "-" or c.isdigit():
            m = number.match(text, i)
            if m:
                out.append(m.group(0))
                i = m.end()
                continue
        if c == '"':  # standard string: copy verbatim incl. escapes
            j = i + 1
            while j < n:
                if text[j] == "\\":
                    j += 2
                    continue
                if text[j] == '"':
                    break
                j += 1
            out.append(text[i:j + 1])
            i = j + 1
        elif c == "'":  # single-quoted string → double-quoted
            j = i + 1
            buf = []
            while j < n and text[j] != "'":
                if text[j] == "\\" and j + 1 < n:
                    buf.append(text[j:j + 2])
                    j += 2
                    continue
                buf.append(text[j])
                j += 1
            out.append(json.dumps("".join(buf)))
            i = j + 1
        else:
            m = bare.match(text, i)
            if m:
                tok = m.group(0)
                out.append(tok if tok in ("true", "false", "null")
                           else json.dumps(tok))
                i = m.end()
            else:
                out.append(c)
                i += 1
    return "".join(out)


def _prom_labels(labels: dict) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _prom_num(v) -> str:
    if v == float("inf"):
        return "+Inf"
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return repr(v) if isinstance(v, float) else str(v)


class _PromWriter:
    """Prometheus text exposition v0.0.4 assembler: one # TYPE header per
    family (emitted lazily on first sample), histogram families rendered from
    HistogramMetric.cumulative()."""

    def __init__(self):
        self.lines: list[str] = []
        self._typed: set[str] = set()

    def _type(self, name: str, typ: str):
        if name not in self._typed:
            self._typed.add(name)
            self.lines.append(f"# TYPE {name} {typ}")

    def sample(self, name: str, typ: str, value, **labels):
        self._type(name, typ)
        self.lines.append(f"{name}{_prom_labels(labels)} {_prom_num(value)}")

    def declare(self, name: str, typ: str):
        """Force a family's # TYPE header even with zero samples this scrape —
        a contiguity-strict scraper still learns the name exists (used for
        label sets that are empty on a healthy node, e.g. device domains)."""
        self._type(name, typ)

    def gauge(self, name: str, value, **labels):
        self.sample(name, "gauge", value, **labels)

    def counter(self, name: str, value, **labels):
        self.sample(name, "counter", value, **labels)

    def histogram(self, name: str, hist, **labels):
        self._type(name, "histogram")
        buckets, total, vsum = hist.cumulative()
        for bound, cum in buckets:
            self.lines.append(
                f"{name}_bucket{_prom_labels({**labels, 'le': _prom_num(bound)})}"
                f" {cum}")
        self.lines.append(f"{name}_sum{_prom_labels(labels)} {_prom_num(vsum)}")
        self.lines.append(f"{name}_count{_prom_labels(labels)} {total}")

    def text(self) -> str:
        return "\n".join(self.lines) + "\n"


def _prometheus_text(node) -> str:
    """GET /_prometheus/metrics: the node's serving telemetry in Prometheus
    text format — breakers, thread pools (+queue-wait histograms), batcher,
    admission control, search latency, query-shape insights
    (common/insights — label sets bounded by the registry's LRU demotion),
    the device capacity ledger (per-index tier gauges + pack counters,
    capped at telemetry.device.max_label_indices), compile events total +
    by triggering plan family (common/jaxenv), HBM resident bytes
    (ops/device_index), tracer counters, and the event journal / watchdog
    counters (common/events — fixed type vocabulary)."""
    from ..common.jaxenv import compile_events_by_family, compile_events_total
    from ..ops.device_index import capacity_report

    w = _PromWriter()
    # one loop PER FAMILY, not per breaker/pool: the text exposition requires
    # all samples of a metric name to form one contiguous group — interleaved
    # families pass the classic scraper but fail promtool / OpenMetrics-strict
    # ingesters, which drop the whole scrape
    breakers = node.breakers.stats()
    for bname, b in breakers.items():
        w.gauge("estpu_breaker_limit_bytes", b["limit"], breaker=bname)
    for bname, b in breakers.items():
        w.gauge("estpu_breaker_estimated_bytes", b["estimated"], breaker=bname)
    for bname, b in breakers.items():
        w.counter("estpu_breaker_tripped_total", b["tripped"], breaker=bname)
    for bname, b in breakers.items():
        w.counter("estpu_breaker_leaks_total", b.get("leak_detected", 0),
                  breaker=bname)
    pools = node.threadpool.stats()
    for pool, s in pools.items():
        w.gauge("estpu_threadpool_threads", s["threads"], pool=pool)
    for pool, s in pools.items():
        w.gauge("estpu_threadpool_active", s["active"], pool=pool)
    for pool, s in pools.items():
        w.gauge("estpu_threadpool_queue", s["queue"], pool=pool)
    for pool, s in pools.items():
        w.counter("estpu_threadpool_rejected_total", s["rejected"], pool=pool)
    for pool, s in pools.items():
        w.counter("estpu_threadpool_completed_total", s["completed"], pool=pool)
    for pool, hist in node.threadpool.pool_histograms().items():
        w.histogram("estpu_threadpool_queue_wait_seconds", hist, pool=pool)
    bs = node.search_batcher.stats()
    w.counter("estpu_batcher_launches_total", bs["launches"])
    w.counter("estpu_batcher_coalesced_total", bs["coalesced"])
    w.counter("estpu_batcher_bypassed_total", bs["bypassed"])
    w.counter("estpu_batcher_splits_total", bs["splits"])
    for reason in ("full", "linger", "deadline", "pending"):
        w.counter("estpu_batcher_flushes_total", bs[f"{reason}_flushes"],
                  reason=reason)
    w.gauge("estpu_batcher_queue", bs["queue"])
    w.histogram("estpu_batcher_batch_seconds", node.search_batcher.service_hist)
    w.histogram("estpu_search_latency_seconds", node.actions.search_latency)
    w.histogram("estpu_admission_shard_phase_seconds",
                node.actions.admission.histogram)
    w.counter("estpu_admission_rejected_total",
              node.actions.admission.rejected.count)
    # adaptive replica selection + hedged shard requests (cluster/stats.py):
    # the hedge counters answer "is tail-tolerance working / is the budget
    # saturating", the per-copy rank gauges expose WHY routing prefers a
    # copy. One loop per family keeps each family contiguous.
    ar = node.adaptive_routing.stats()
    hs = ar["hedges"]
    w.counter("estpu_search_hedges_issued_total", hs["issued"])
    w.counter("estpu_search_hedges_won_total", hs["won"])
    w.counter("estpu_search_hedges_budget_exhausted_total",
              hs["budget_exhausted"])
    w.gauge("estpu_search_hedges_budget_tokens", hs["tokens"])
    copies = ar["copies"]
    for ckey, c in copies.items():
        w.gauge("estpu_routing_rank_ewma_seconds", c["ewma_ms"] / 1000.0,
                copy=ckey)
    for ckey, c in copies.items():
        w.gauge("estpu_routing_rank_queue", c["queue"], copy=ckey)
    for ckey, c in copies.items():
        w.gauge("estpu_routing_rank_outstanding", c["outstanding"], copy=ckey)
    for ckey, c in copies.items():
        w.gauge("estpu_routing_rank_failures", c["failures"], copy=ckey)
    w.counter("estpu_routing_probes_total", ar["probes"])
    w.gauge("estpu_routing_quarantined", ar["quarantined"])
    # multi-tier caching (ISSUE 11): per-tier hit/miss/store/evict counters +
    # resident-byte gauges — `rate(hits)/rate(hits+misses)` is the live hit
    # rate; the bytes gauges sit next to the breaker gauges they are
    # accounted on (request_cache → request, filter_cache → fielddata). One
    # emission per family keeps each contiguous (OpenMetrics-strict rule).
    rcs = node.request_cache.stats()
    w.counter("estpu_request_cache_hits_total", rcs["hits"])
    w.counter("estpu_request_cache_misses_total", rcs["misses"])
    w.counter("estpu_request_cache_stores_total", rcs["stores"])
    w.counter("estpu_request_cache_evictions_total", rcs["evictions"])
    w.counter("estpu_request_cache_invalidations_total",
              rcs["invalidations"])
    w.gauge("estpu_request_cache_bytes", rcs["memory_size_in_bytes"])
    w.gauge("estpu_request_cache_entries", rcs["entries"])
    fcs = node.filter_cache.stats()
    w.counter("estpu_filter_cache_hits_total", fcs["hits"])
    w.counter("estpu_filter_cache_misses_total", fcs["misses"])
    w.counter("estpu_filter_cache_builds_total", fcs["builds"])
    w.counter("estpu_filter_cache_evictions_total", fcs["evictions"])
    w.gauge("estpu_filter_cache_bytes", fcs["memory_size_in_bytes"])
    w.gauge("estpu_filter_cache_masks", fcs["masks"])
    # always-on query-shape insights (common/insights.py): label cardinality
    # is bounded by the registry's LRU demotion (≤ search.insights.max_shapes
    # shape ids per family — the demotion counter shows when churn exceeds
    # residency). One loop per family: contiguity is the strict-parser rule.
    shapes = node.insights.prom_series()
    for sid, st in shapes:
        w.counter("estpu_query_shape_count_total", st.count, shape=sid)
    for sid, st in shapes:
        w.counter("estpu_query_shape_cost_seconds_total",
                  round(st.cost_ms / 1000.0, 6), shape=sid)
    for sid, st in shapes:
        w.counter("estpu_query_shape_device_seconds_total",
                  round(st.device.sum, 6), shape=sid)
    for sid, st in shapes:
        w.counter("estpu_query_shape_cache_hits_total", st.cache_hits,
                  shape=sid)
    w.counter("estpu_query_shape_demotions_total", node.insights.demotions)
    # device capacity ledger (ops/device_index.capacity_report): per-index
    # HBM residency by tier + pack rollups. Cardinality is bounded twice:
    # labels exist only for LIVE indices (deleted indices vanish from the
    # walk and the pack ledger forgets them), and the emission caps at
    # `telemetry.device.max_label_indices` (top residents win; the overflow
    # is counted, never silently dropped).
    cap = max(1, node.settings.get_int("telemetry.device.max_label_indices",
                                       64))
    report = capacity_report(node.indices)
    ranked = sorted(report["indices"].items(),
                    key=lambda kv: -kv[1]["total_bytes"])
    emitted, omitted = ranked[:cap], ranked[cap:]
    for iname, entry in emitted:
        for tier in ("postings", "dense_plane", "sim_tables", "agg_rows",
                     "norms", "filter_masks"):
            w.gauge("estpu_device_index_bytes",
                    entry["totals"].get(tier, 0), index=iname, tier=tier)
    for iname, entry in emitted:
        # every ledger kind counts as pack work (full + delta + remask +
        # compaction — ISSUE 14 grew the vocabulary; this counter keeps its
        # "total pack events" meaning)
        w.counter("estpu_device_pack_total",
                  sum(entry["pack"].get(k, 0)
                      for k in ("packs", "delta_packs", "remasks",
                                "compacts")), index=iname)
    for iname, entry in emitted:
        w.counter("estpu_device_pack_seconds_total",
                  round(entry["pack"].get("pack_ms_total", 0.0) / 1000.0, 6),
                  index=iname)
    w.gauge("estpu_device_ledger_omitted_indices", len(omitted))
    w.counter("estpu_jax_compile_events_total", compile_events_total())
    # compile events by triggering plan family (jaxenv.compile_tag at the
    # kernel launch sites) — the FULL fixed vocabulary is emitted (zeros
    # included) so the label set is stable and bounded by construction
    from ..common.jaxenv import COMPILE_FAMILIES

    by_family = compile_events_by_family()
    for family in COMPILE_FAMILIES:
        w.counter("estpu_jax_compile_family_total",
                  by_family.get(family, 0), family=family)
    # compile events by OBSERVING POOL (jaxenv._pool_label thread-name parse):
    # the warmed-node invariant made scrapable — steady state puts every
    # compile on warmer/startup labels, serving pools read 0. Labels are
    # bounded (fixed threadpool names + "other"); declared so the family
    # exists before the first compile
    from ..common.jaxenv import compile_events_by_pool

    w.declare("estpu_jax_compile_pool_total", "counter")
    for pool, n in sorted(compile_events_by_pool().items()):
        w.counter("estpu_jax_compile_pool_total", n, pool=pool)
    # compile-warming registry (common/compilecache via node.compile_warming):
    # spec inventory + warm-cycle outcomes + ladder/manifest churn
    cw = node.compile_warming.stats()
    w.gauge("estpu_compile_warm_specs", cw["specs"])
    w.gauge("estpu_compile_warm_pending", cw["pending"])
    w.counter("estpu_compile_warm_total", cw["warmed_total"])
    w.counter("estpu_compile_warm_failures_total", cw["warm_failures"])
    w.counter("estpu_compile_warm_skipped_total", cw["warm_skipped_circuit"])
    w.counter("estpu_compile_warm_cycles_total", cw["warm_cycles"])
    w.counter("estpu_compile_warm_ladder_commits_total", cw["ladder_commits"])
    w.counter("estpu_compile_warm_manifest_saves_total", cw["manifest_saves"])
    w.counter("estpu_compile_warm_mesh_total", cw["mesh_warms"])
    w.counter("estpu_compile_warm_mesh_failures_total",
              cw["mesh_warm_failures"])
    # HBM postings gauge derived from the capacity report computed above —
    # postings + dense_plane tiers ARE packed_resident_bytes over the live
    # packed segments (one engine/segment walk per scrape, not two)
    w.gauge("estpu_hbm_resident_bytes",
            sum(e["totals"].get("postings", 0)
                + e["totals"].get("dense_plane", 0)
                for e in report["indices"].values()))
    # device fault domains (common/devicehealth): classified failure counters
    # (fixed class vocabulary, zeros included), circuit transitions, and a
    # per-domain state gauge (0=closed 1=half_open 2=open). Domain labels are
    # bounded by construction — indices × the fixed compile-family vocabulary
    # — and only appear once a domain has recorded a failure; the family is
    # DECLARED even when empty so dashboards can reference it on healthy nodes
    from ..common.devicehealth import DEVICE_HEALTH, HALF_OPEN, OPEN

    dh = DEVICE_HEALTH.stats()
    for cls in ("transient", "persistent"):
        w.counter("estpu_device_fault_total", dh["failures"].get(cls, 0),
                  **{"class": cls})
    w.counter("estpu_device_fault_trips_total", dh["trips"])
    w.counter("estpu_device_fault_probes_total", dh["probes"])
    w.counter("estpu_device_fault_recoveries_total", dh["recoveries"])
    w.declare("estpu_device_domain_state", "gauge")
    _state_num = {OPEN: 2, HALF_OPEN: 1}
    for dname, dstat in dh["domains"].items():
        w.gauge("estpu_device_domain_state",
                _state_num.get(dstat["state"], 0), domain=dname)
    # stall watchdog + event journal (common/events.py): per-type emission
    # counters (fixed EVENT_TYPES vocabulary) + suppression/ring pressure
    es = node.events.stats()
    for etype, n in sorted(es["by_type"].items()):
        w.counter("estpu_events_emitted_total", n, type=etype)
    w.counter("estpu_events_suppressed_total", es["suppressed"])
    w.gauge("estpu_events_ring_entries", es["entries"])
    w.counter("estpu_watchdog_ticks_total", node.watchdog.ticks)
    ts = node.tracer.stats()
    w.counter("estpu_traces_sampled_total", ts["sampled"])
    w.counter("estpu_traces_finished_total", ts["finished"])
    w.gauge("estpu_traces_in_flight", ts["in_flight"])
    # ring pressure: finished traces the bounded ring evicted, and late
    # remote stitches that arrived after their entry was already gone — a
    # scraper alerting on these knows /_traces is lossy before users do
    w.counter("estpu_traces_ring_evicted_total", ts["ring_evicted"])
    w.counter("estpu_traces_late_stitch_dropped_total",
              ts["late_stitch_dropped"])
    return w.text()


def _size_param(req: RestRequest, endpoint: str, default=None):
    """Shared `?size=` parsing for the telemetry read surfaces
    (/_traces, /_insights/queries, /_events): non-int or negative → 400."""
    from ..common.errors import IllegalArgumentError

    raw = req.param("size")
    if raw is None:
        return default
    try:
        size = int(raw)
    except (TypeError, ValueError):
        raise IllegalArgumentError(
            f"invalid size [{raw}] for [{endpoint}]") from None
    if size < 0:
        raise IllegalArgumentError(
            f"size must be >= 0 for [{endpoint}], got [{size}]")
    return size


def build_rest_controller(node) -> RestController:
    client = node.client()
    rc = RestController()
    scroll_registry: dict[str, tuple] = {}

    # --- root / ping --------------------------------------------------------
    def root(req):
        from ..version import CURRENT

        return {
            "status": 200,
            "name": node.name,
            "version": {
                "number": str(CURRENT),
                "build_snapshot": True,
                # the device-index core stands in for Lucene (SURVEY.md §2.8)
                "lucene_version": str(CURRENT),
            },
            "tagline": "You Know, for Search (TPU-native)",
        }

    rc.register("GET,HEAD", "/", root)

    # --- document CRUD ------------------------------------------------------
    def doc_index(req):
        body = _parse_body(req)
        r = client.index(
            req.path_params["index"], req.path_params["type"], body,
            id=req.path_params.get("id"), routing=req.param("routing"),
            version=int(req.param("version")) if req.param("version") else None,
            version_type=req.param("version_type", "internal"),
            op_type=req.param("op_type", "index"),
            refresh=req.bool_param("refresh"),
            parent=req.param("parent"), timestamp=req.param("timestamp"),
            ttl=req.param("ttl"),
        )
        return RestResponse(201 if r.get("created") else 200, r)

    rc.register("PUT,POST", "/{index}/{type}/{id}", doc_index)
    rc.register("POST", "/{index}/{type}", doc_index)

    def doc_create(req):
        body = _parse_body(req)
        r = client.create(req.path_params["index"], req.path_params["type"], body,
                          id=req.path_params["id"], routing=req.param("routing"),
                          parent=req.param("parent"),
                          version=int(req.param("version")) if req.param("version")
                          else None,
                          version_type=req.param("version_type", "internal"),
                          refresh=req.bool_param("refresh"),
                          timestamp=req.param("timestamp"), ttl=req.param("ttl"))
        return RestResponse(201, r)

    rc.register("PUT,POST", "/{index}/{type}/{id}/_create", doc_create)

    def _render_get(req, r):
        from ..actions import _extract_fields, filter_source

        if not r["found"]:
            return RestResponse(404, {"_index": r.get("_index"),
                                      "_type": r.get("_type"),
                                      "_id": r.get("_id"), "found": False})
        out = {k: v for k, v in r.items()
               if k in ("_index", "_type", "_id", "_version", "found")}
        fields = req.param("fields")
        src_param = req.param("_source")
        includes = req.param("_source_include")
        excludes = req.param("_source_exclude")
        want_source = True
        if fields:
            fdict, fsrc = _extract_fields(r, fields)
            if fdict:
                out["fields"] = fdict
            want_source = fsrc is not None or src_param not in (None, "false")
            if src_param is None and fsrc is None:
                want_source = False
        if src_param is not None and str(src_param).lower() == "false":
            want_source = False
        src = r.get("_source")
        if want_source and src is not None:
            if src_param not in (None, "true", "false", True, False) or includes \
                    or excludes:
                inc = includes
                if src_param not in (None, "true", "false", True, False):
                    inc = src_param
                src = filter_source(src, inc, excludes)
            out["_source"] = src
        return RestResponse(200, out)

    def doc_get(req):
        r = client.get(req.path_params["index"], req.path_params["type"],
                       req.path_params["id"], routing=req.param("routing"),
                       parent=req.param("parent"),
                       realtime=req.bool_param("realtime", True),
                       refresh=req.bool_param("refresh"),
                       preference=req.param("preference"))
        return _render_get(req, r)

    rc.register("GET,HEAD", "/{index}/{type}/{id}", doc_get)

    def doc_source(req):
        r = client.get(req.path_params["index"], req.path_params["type"],
                       req.path_params["id"], routing=req.param("routing"),
                       parent=req.param("parent"),
                       realtime=req.bool_param("realtime", True),
                       refresh=req.bool_param("refresh"))
        if not r["found"]:
            return RestResponse(404, {"found": False})
        from ..actions import filter_source

        src = r["_source"]
        if req.param("_source_include") or req.param("_source_exclude"):
            src = filter_source(src, req.param("_source_include"),
                                req.param("_source_exclude"))
        return src

    rc.register("GET,HEAD", "/{index}/{type}/{id}/_source", doc_source)

    def doc_delete(req):
        r = client.delete(req.path_params["index"], req.path_params["type"],
                          req.path_params["id"], routing=req.param("routing"),
                          parent=req.param("parent"),
                          version=int(req.param("version")) if req.param("version")
                          else None,
                          version_type=req.param("version_type", "internal"),
                          refresh=req.bool_param("refresh"))
        return RestResponse(200 if r["found"] else 404, r)

    rc.register("DELETE", "/{index}/{type}/{id}", doc_delete)

    def doc_update(req):
        body = _parse_body(req)
        # script/lang/params may arrive as query params (ref: RestUpdateAction)
        if req.param("script") is not None:
            body.setdefault("script", req.param("script"))
        if req.param("lang") is not None:
            body.setdefault("lang", req.param("lang"))
        return client.update(req.path_params["index"], req.path_params["type"],
                             req.path_params["id"], body,
                             routing=req.param("routing"),
                             parent=req.param("parent"),
                             refresh=req.bool_param("refresh"),
                             fields=req.param("fields"),
                             ttl=req.param("ttl"),
                             timestamp=req.param("timestamp"),
                             version=int(req.param("version"))
                             if req.param("version") else None,
                             version_type=req.param("version_type", "internal"),
                             retry_on_conflict=int(req.param("retry_on_conflict", 0)))

    rc.register("POST", "/{index}/{type}/{id}/_update", doc_update)

    def mget(req):
        body = _parse_body(req)
        default_index = body.get("index") or req.path_params.get("index")
        default_type = body.get("type") or req.path_params.get("type")
        docs = body.get("docs")
        if docs is None and "ids" in body:
            docs = [{"_index": default_index, "_type": default_type, "_id": i}
                    for i in body["ids"]]
        # request-level params are per-doc defaults (ref: RestMultiGetAction)
        source_param = req.param("_source")
        if source_param in ("true", "false"):
            source_param = source_param == "true"
        elif isinstance(source_param, str):
            source_param = source_param.split(",")
        if req.param("_source_include") or req.param("_source_exclude"):
            source_param = {
                "include": str(req.param("_source_include")).split(",")
                if req.param("_source_include") else [],
                "exclude": str(req.param("_source_exclude")).split(",")
                if req.param("_source_exclude") else []}
        for d in docs or []:
            if not d.get("_index") and default_index:
                d["_index"] = default_index
            if not d.get("_type") and default_type:
                d["_type"] = default_type
            if req.param("fields") is not None:
                d.setdefault("fields", str(req.param("fields")).split(","))
            if source_param is not None:
                d.setdefault("_source", source_param)
            if req.param("realtime") is not None:
                d.setdefault("realtime", req.bool_param("realtime", True))
            if req.param("refresh") is not None:
                d.setdefault("refresh", req.bool_param("refresh"))
            if req.param("routing") is not None:
                d.setdefault("routing", req.param("routing"))
        return client.mget(docs or [])

    rc.register("GET,POST", "/_mget", mget)
    rc.register("GET,POST", "/{index}/_mget", mget)
    rc.register("GET,POST", "/{index}/{type}/_mget", mget)

    _BULK_OPS = ("index", "create", "update", "delete")

    def bulk(req):
        # Normalize every accepted body shape (ndjson string, list of strings,
        # list of pre-parsed objects) into one stream of parsed JSON objects.
        stream = []
        if isinstance(req.body, list):
            for item in req.body:
                if isinstance(item, str):
                    stream.extend(json.loads(ln) for ln in item.split("\n") if ln.strip())
                else:
                    stream.append(item)
        else:
            raw = req.body if isinstance(req.body, str) else ""
            stream = [json.loads(ln) for ln in raw.split("\n") if ln.strip()]
        operations = []
        i = 0
        while i < len(stream):
            action = stream[i]
            if not isinstance(action, dict) or len(action) != 1 or next(iter(action)) not in _BULK_OPS:
                from ..common.errors import IllegalArgumentError
                raise IllegalArgumentError(
                    f"Malformed action/metadata line [{i + 1}], expected one of {_BULK_OPS}")
            (op, meta), = action.items()
            meta = dict(meta) if isinstance(meta, dict) else {}
            meta.setdefault("_index", req.path_params.get("index"))
            meta.setdefault("_type", req.path_params.get("type", "_default_"))
            entry = {"action": {op: meta}}
            i += 1
            if op != "delete":
                entry["source"] = stream[i] if i < len(stream) else {}
                i += 1
            operations.append(entry)
        return client.bulk(operations, refresh=req.bool_param("refresh"))

    rc.register("POST,PUT", "/_bulk", bulk)
    rc.register("POST,PUT", "/{index}/_bulk", bulk)
    rc.register("POST,PUT", "/{index}/{type}/_bulk", bulk)

    # --- search -------------------------------------------------------------
    def _search_body(req):
        body = _parse_body(req)
        if req.param("q"):
            body = dict(body)
            body["query"] = {"query_string": {"query": req.param("q")}}
        for p in ("from", "size"):
            if req.param(p) is not None:
                body[p] = int(req.param(p))
        if req.param("sort"):
            body["sort"] = [
                ({s.split(":")[0]: s.split(":")[1]} if ":" in s else s)
                for s in str(req.param("sort")).split(",")
            ]
        if req.param("_source") is not None:
            sp = req.param("_source")
            if sp in ("true", "false"):
                body["_source"] = sp == "true"
            else:
                body["_source"] = str(sp).split(",")
        if req.param("_source_include") or req.param("_source_exclude"):
            # query params override the body directive (ref: RestSearchAction
            # fetchSource handling)
            body["_source"] = {
                "includes": str(req.param("_source_include")).split(",")
                if req.param("_source_include") else [],
                "excludes": str(req.param("_source_exclude")).split(",")
                if req.param("_source_exclude") else []}
        if req.param("fields") is not None:
            body["fields"] = str(req.param("fields")).split(",")
        if req.param("timeout") is not None:
            # `?timeout=50ms` enters the one per-request Deadline here (ref:
            # RestSearchAction parsing timeout into the SearchSourceBuilder);
            # parse_search_body turns it into ParsedSearchRequest.timeout_s
            body["timeout"] = req.param("timeout")
        if req.param("profile") is not None:
            # `?profile=true` arms the white-box execution profiler — same
            # knob as the body's `"profile": true` (common/profile.py); the
            # per-shard collectors merge into a top-level `profile` section
            body["profile"] = req.bool_param("profile")
        if req.param("request_cache") is not None:
            # `?request_cache=true|false` overrides the shard request cache's
            # default size==0-only policy (search/request_cache.cache_policy);
            # rides the body so the coordinator→shard hop carries it for free
            body["request_cache"] = req.bool_param("request_cache")
        return body

    def search(req):
        body = _search_body(req)
        index = req.path_params.get("index", "_all")
        search_type = req.param("search_type", "query_then_fetch")
        scroll = req.param("scroll")
        # REST ingress roots the request's trace: `?trace=true` force-samples
        # and returns the stitched span tree inline (the `profile` API shape);
        # otherwise the tracer's sampling rate decides and the trace only
        # lands in the /_traces ring. The scroll branch roots here too — the
        # initial scan/scroll search is a normal fan-out, only pagination of
        # the buffered hits (the /_search/scroll handler) is untraced.
        want_trace = req.bool_param("trace")
        trace = node.tracer.start_trace("rest", force=want_trace)
        root = trace.root.tag(path=req.path, index=index)
        try:
            with tracing.activate(root):
                if scroll:
                    r = _scrolled_search(index, body, scroll,
                                         scan=search_type == "scan")
                else:
                    r = client.search(index, body,
                                      search_type=search_type,
                                      routing=req.param("routing"),
                                      preference=req.param("preference"))
        finally:
            root.end()
        if want_trace and trace:
            r = dict(r)
            r["trace"] = {"trace_id": trace.trace_id,
                          "tree": tracing.span_tree(trace.span_dicts())}
        return r

    def _scrolled_search(index, body, keep_alive, scan=False):
        import uuid as _uuid

        r = client.search(index, {**body, "from": 0,
                                  "size": max(body.get("size", 10), 10) * 10})
        sid = _uuid.uuid4().hex
        size = body.get("size", 10)
        hits = r["hits"]["hits"]
        # scan: the initial response carries no hits; pages come from scroll calls
        # (ref: search/scan/ScanContext.java — doc-order pagination)
        pos = 0 if scan else size
        scroll_registry[sid] = (hits, size, pos)
        r["_scroll_id"] = sid
        r["hits"]["hits"] = [] if scan else hits[:size]
        return r

    def scroll(req):
        body = _parse_body(req) if not (
            isinstance(req.body, str) and req.body and not req.body.lstrip().startswith("{")) else {}
        sid = (req.path_params.get("scroll_id") or body.get("scroll_id")
               or req.param("scroll_id") or (
                   req.body.strip() if isinstance(req.body, str) and req.body and
                   not req.body.lstrip().startswith("{") else None))
        if sid not in scroll_registry:
            from ..common.errors import SearchContextMissingError

            raise SearchContextMissingError(0)
        hits, size, pos = scroll_registry[sid]
        page = hits[pos: pos + size]
        scroll_registry[sid] = (hits, size, pos + size)
        return {"_scroll_id": sid, "hits": {"total": len(hits), "hits": page},
                "timed_out": False, "_shards": {"total": 1, "successful": 1, "failed": 0}}

    rc.register("GET,POST", "/{index}/_search", search)
    rc.register("GET,POST", "/{index}/{type}/_search", search)
    rc.register("GET,POST", "/_search", search)
    rc.register("GET,POST", "/_search/scroll", scroll)
    rc.register("GET,POST", "/_search/scroll/{scroll_id}", scroll)

    def clear_scroll(req):
        sids = []
        if req.path_params.get("scroll_id"):
            sids = req.path_params["scroll_id"].split(",")
        else:
            body = _parse_body(req)
            sids = body.get("scroll_id", [])
            if isinstance(sids, str):
                sids = sids.split(",")
        for sid in sids:
            scroll_registry.pop(sid, None)
        return {"succeeded": True}

    rc.register("DELETE", "/_search/scroll", clear_scroll)
    rc.register("DELETE", "/_search/scroll/{scroll_id}", clear_scroll)

    def msearch(req):
        raw = req.body if isinstance(req.body, str) else ""
        lines = [ln for ln in raw.split("\n") if ln.strip()]
        requests = []
        for i in range(0, len(lines) - 1, 2):
            requests.append((json.loads(lines[i]), json.loads(lines[i + 1])))
        return client.msearch(requests)

    rc.register("GET,POST", "/_msearch", msearch)
    rc.register("GET,POST", "/{index}/_msearch", msearch)

    def count(req):
        body = _search_body(req)
        return client.count(req.path_params.get("index", "_all"), body)

    rc.register("GET,POST", "/_count", count)
    rc.register("GET,POST", "/{index}/_count", count)
    rc.register("GET,POST", "/{index}/{type}/_count", count)

    def suggest(req):
        return client.suggest(req.path_params.get("index", "_all"), _parse_body(req))

    rc.register("GET,POST", "/_suggest", suggest)
    rc.register("GET,POST", "/{index}/_suggest", suggest)

    def explain(req):
        body = _parse_body(req)
        if req.param("q"):
            body = {"query": {"query_string": {"query": req.param("q")}}}
        out = client.explain(req.path_params["index"], req.path_params["type"],
                             req.path_params["id"], body)
        # _source/fields params attach a get section (ref: RestExplainAction fetchSource)
        if (req.param("_source") is not None or req.param("_source_include")
                or req.param("_source_exclude") or req.param("fields")):
            g = client.get(req.path_params["index"], req.path_params["type"],
                           req.path_params["id"], routing=req.param("routing"))
            if g.get("found"):
                rendered = _render_get(req, g).body
                get_sec = {"found": True}
                for k in ("fields", "_source"):
                    if k in rendered:
                        get_sec[k] = rendered[k]
                out["get"] = get_sec
        return out

    rc.register("GET,POST", "/{index}/{type}/{id}/_explain", explain)

    def termvector(req):
        body = _parse_body(req)
        fields = req.param("fields")
        return client.termvector(
            req.path_params["index"], req.path_params["type"], req.path_params["id"],
            routing=req.param("routing"),
            fields=fields.split(",") if fields else body.get("fields"),
            positions=req.bool_param("positions", True),
            offsets=req.bool_param("offsets", True),
            term_statistics=req.bool_param("term_statistics", False),
            field_statistics=req.bool_param("field_statistics", True))

    rc.register("GET,POST", "/{index}/{type}/{id}/_termvector", termvector)
    rc.register("GET,POST", "/{index}/{type}/{id}/_termvectors", termvector)

    def mtermvectors(req):
        body = _parse_body(req)
        docs = body.get("docs", [])
        ids = body.get("ids") or (
            str(req.param("ids")).split(",") if req.param("ids") else [])
        docs = docs + [{"_id": i} for i in ids]
        for d in docs:
            d.setdefault("_index", req.path_params.get("index"))
            d.setdefault("_type", req.path_params.get("type", "_all"))
            # query params are per-doc defaults (ref: RestMultiTermVectorsAction)
            for flag, dflt in (("term_statistics", False), ("field_statistics", True),
                               ("positions", True), ("offsets", True)):
                if req.param(flag) is not None:
                    d.setdefault(flag, req.bool_param(flag, dflt))
            if req.param("routing") is not None:
                d.setdefault("routing", req.param("routing"))
            if req.param("fields") is not None:
                d.setdefault("fields", str(req.param("fields")).split(","))
        return client.mtermvectors(docs)

    rc.register("GET,POST", "/_mtermvectors", mtermvectors)
    rc.register("GET,POST", "/{index}/_mtermvectors", mtermvectors)
    rc.register("GET,POST", "/{index}/{type}/_mtermvectors", mtermvectors)

    def mlt(req):
        body = _parse_body(req)
        fields = req.param("mlt_fields")
        params = {k: req.param(k) for k in
                  ("min_term_freq", "min_doc_freq", "max_query_terms")}
        params = {k: int(v) for k, v in params.items() if v is not None}
        return client.mlt(
            req.path_params["index"], req.path_params["type"], req.path_params["id"],
            mlt_fields=fields.split(",") if fields else None,
            search_body=body or None, routing=req.param("routing"), **params)

    rc.register("GET,POST", "/{index}/{type}/{id}/_mlt", mlt)

    def validate_query(req):
        body = _parse_body(req)
        try:
            from ..search.queries import parse_query as pq

            pq(body.get("query"))
            return {"valid": True, "_shards": {"total": 1, "successful": 1, "failed": 0}}
        except SearchEngineError as e:
            return {"valid": False, "explanations": [{"error": str(e)}]}

    rc.register("GET,POST", "/{index}/_validate/query", validate_query)
    rc.register("GET,POST", "/_validate/query", validate_query)

    def delete_by_query(req):
        return client.delete_by_query(req.path_params["index"], _search_body(req))

    rc.register("DELETE", "/{index}/_query", delete_by_query)
    rc.register("DELETE", "/{index}/{type}/_query", delete_by_query)

    # --- indices admin ------------------------------------------------------
    def index_create(req):
        return client.create_index(req.path_params["index"], _parse_body(req))

    def index_delete(req):
        return client.delete_index(req.path_params["index"])

    def index_exists(req):
        return RestResponse(200 if client.exists_index(req.path_params["index"]) else 404,
                            "")

    rc.register("PUT,POST", "/{index}", index_create)
    rc.register("DELETE", "/{index}", index_delete)
    rc.register("HEAD", "/{index}", index_exists)
    rc.register("POST", "/{index}/_open", lambda r: client.open_index(r.path_params["index"]))
    rc.register("POST", "/{index}/_close", lambda r: client.close_index(r.path_params["index"]))

    def put_mapping(req):
        return client.put_mapping(req.path_params.get("index"),
                                  req.path_params["type"], _parse_body(req))

    def delete_mapping(req):
        return client.delete_mapping(req.path_params["index"], req.path_params["type"])

    for suffix in ("_mapping", "_mappings"):
        rc.register("PUT,POST", "/{index}/{type}/" + suffix, put_mapping)
        rc.register("PUT,POST", "/{index}/" + suffix + "/{type}", put_mapping)
        rc.register("PUT,POST", "/" + suffix + "/{type}", put_mapping)
        rc.register("DELETE", "/{index}/{type}/" + suffix, delete_mapping)
        rc.register("DELETE", "/{index}/" + suffix + "/{type}", delete_mapping)
    rc.register("GET", "/{index}/_mapping",
                lambda r: client.get_mapping(r.path_params["index"]))
    rc.register("GET", "/{index}/{type}/_mapping",
                lambda r: client.get_mapping(r.path_params["index"], r.path_params["type"]))
    rc.register("GET", "/{index}/_mapping/{type}",
                lambda r: client.get_mapping(r.path_params["index"], r.path_params["type"]))
    rc.register("GET", "/_mapping", lambda r: client.get_mapping())
    rc.register("GET", "/_mapping/{type}",
                lambda r: client.get_mapping(None, r.path_params["type"]))

    def get_field_mapping(req):
        return client.get_field_mapping(
            req.path_params.get("index"), req.path_params.get("type"),
            req.path_params.get("field"),
            include_defaults=req.bool_param("include_defaults"))

    rc.register("GET", "/_mapping/field/{field}", get_field_mapping)
    rc.register("GET", "/{index}/_mapping/field/{field}", get_field_mapping)
    rc.register("GET", "/_mapping/{type}/field/{field}", get_field_mapping)
    rc.register("GET", "/{index}/_mapping/{type}/field/{field}", get_field_mapping)

    def exists_type(req):
        ok = client.exists_type(req.path_params["index"], req.path_params["type"])
        return RestResponse(200 if ok else 404, "")

    rc.register("HEAD", "/{index}/{type}", exists_type)

    rc.register("PUT", "/{index}/_settings",
                lambda r: client.update_settings(r.path_params["index"], _parse_body(r)))
    rc.register("PUT", "/_settings",
                lambda r: client.update_settings(None, _parse_body(r)))
    rc.register("GET", "/{index}/_settings",
                lambda r: client.get_settings(r.path_params["index"]))
    rc.register("GET", "/{index}/_settings/{name}",
                lambda r: client.get_settings(r.path_params["index"],
                                              r.path_params["name"]))
    rc.register("GET", "/_settings", lambda r: client.get_settings())
    rc.register("GET", "/_settings/{name}",
                lambda r: client.get_settings(None, r.path_params["name"]))

    rc.register("POST", "/_aliases", lambda r: client.update_aliases(_parse_body(r)))
    rc.register("GET", "/_aliases", lambda r: client.get_aliases())
    rc.register("GET", "/{index}/_aliases", lambda r: client.get_aliases(r.path_params["index"]))

    def put_alias(req):
        return client.update_aliases({"actions": [{"add": {
            "index": req.path_params.get("index", "_all"),
            "alias": req.path_params["name"], **_parse_body(req)}}]})

    def get_alias(req):
        return client.get_alias(req.path_params.get("index"),
                                req.path_params.get("name"))

    def get_aliases(req):
        return client.get_aliases(req.path_params.get("index"),
                                  req.path_params.get("name"))

    def exists_alias(req):
        ok = client.exists_alias(req.path_params.get("index"),
                                 req.path_params.get("name"))
        return RestResponse(200 if ok else 404, "")

    for suffix in ("_alias", "_aliases"):
        rc.register("PUT,POST", "/{index}/" + suffix + "/{name}", put_alias)
        rc.register("PUT,POST", "/" + suffix + "/{name}", put_alias)
        rc.register("DELETE", "/{index}/" + suffix + "/{name}",
                    lambda r: client.update_aliases({"actions": [{"remove": {
                        "index": r.path_params["index"],
                        "alias": r.path_params["name"]}}]}))
    rc.register("GET", "/_alias", get_alias)
    rc.register("GET", "/_alias/{name}", get_alias)
    rc.register("GET", "/{index}/_alias", get_alias)
    rc.register("GET", "/{index}/_alias/{name}", get_alias)
    rc.register("GET", "/_aliases/{name}", get_aliases)
    rc.register("GET", "/{index}/_aliases/{name}", get_aliases)
    rc.register("HEAD", "/_alias/{name}", exists_alias)
    rc.register("HEAD", "/{index}/_alias", exists_alias)
    rc.register("HEAD", "/{index}/_alias/{name}", exists_alias)

    rc.register("PUT,POST", "/_template/{name}",
                lambda r: client.put_template(r.path_params["name"], _parse_body(r)))
    rc.register("DELETE", "/_template/{name}",
                lambda r: client.delete_template(r.path_params["name"]))
    rc.register("GET", "/_template/{name}",
                lambda r: client.get_template(r.path_params["name"]))
    rc.register("GET", "/_template", lambda r: client.get_template())

    for op in ("refresh", "flush", "optimize"):
        rc.register("POST,GET", f"/_{op}",
                    (lambda o: lambda r: getattr(client, o)(None))(op))
        rc.register("POST,GET", "/{index}/_" + op,
                    (lambda o: lambda r: getattr(client, o)(r.path_params["index"]))(op))
    def cache_clear(req):
        """POST /_cache/clear (+ index-scoped): `?request=` / `?filter=`
        select tiers (both default true — the reference's all-tiers form);
        response is the broadcast `_shards` shape."""
        kwargs = {}
        if req.param("request") is not None:
            kwargs["request"] = req.bool_param("request")
        if req.param("filter") is not None:
            kwargs["filter"] = req.bool_param("filter")
        return client.clear_cache(req.path_params.get("index"), **kwargs)

    rc.register("POST", "/_cache/clear", cache_clear)
    rc.register("POST", "/{index}/_cache/clear", cache_clear)

    def analyze(req):
        """ref: RestAnalyzeAction — analyzer by name, ad-hoc tokenizer+filters chain,
        or a mapped field's analyzer when index+field are given."""
        body = _parse_body(req)
        text = body.get("text") or req.param("text") or (
            req.body if isinstance(req.body, str) and not req.body.startswith("{") else "")
        analyzer_name = body.get("analyzer") or req.param("analyzer")
        field = body.get("field") or req.param("field")
        tokenizer_name = body.get("tokenizer") or req.param("tokenizer")
        raw_filters = (body.get("filters") or body.get("token_filters")
                       or req.param("filters") or req.param("token_filters"))
        from ..analysis.core import (
            TOKENIZERS, TOKEN_FILTERS, _PARAMETRIC_FILTERS, Analyzer, get_analyzer)
        from ..common.errors import IllegalArgumentError
        from ..common.settings import Settings as _Settings

        svc = None
        index = req.path_params.get("index")
        if index:
            names = node.cluster_service.state.metadata.resolve_indices(index)
            svc = node.indices.index_service(names[0])
        if tokenizer_name:
            tk = TOKENIZERS.get(tokenizer_name)
            if tk is None:
                raise IllegalArgumentError(f"unknown tokenizer [{tokenizer_name}]")
            names_list = ([f.strip() for f in str(raw_filters).split(",") if f.strip()]
                          if isinstance(raw_filters, str) else list(raw_filters or []))
            filters = []
            for fn in names_list:
                if fn in TOKEN_FILTERS:
                    filters.append(TOKEN_FILTERS[fn])
                elif fn in _PARAMETRIC_FILTERS:
                    filters.append(_PARAMETRIC_FILTERS[fn](_Settings.EMPTY))
                else:
                    raise IllegalArgumentError(f"unknown token filter [{fn}]")
            a = Analyzer("_custom_", tk, filters)
        elif field and svc is not None:
            ms = svc.mapper_service
            ft = ms.field_type(field)
            if ft is not None and ft.is_text and ft.index == "not_analyzed":
                a = get_analyzer("keyword")
            elif ft is not None and ft.is_text:
                a = ms.analysis.analyzer(ft.analyzer)
            else:
                a = ms.analysis.analyzer("default")
        elif analyzer_name:
            a = (svc.mapper_service.analysis.analyzer(analyzer_name) if svc is not None
                 else get_analyzer(analyzer_name))
        else:
            a = (svc.mapper_service.analysis.analyzer("default") if svc is not None
                 else get_analyzer("standard"))
        return {"tokens": [
            {"token": t.term, "start_offset": t.start, "end_offset": t.end,
             "type": "<ALPHANUM>", "position": t.position + 1}
            for t in a.analyze(text if isinstance(text, str) else " ".join(text))
        ]}

    rc.register("GET,POST", "/_analyze", analyze)
    rc.register("GET,POST", "/{index}/_analyze", analyze)

    rc.register("GET", "/_stats", lambda r: {"indices": client.stats()})
    rc.register("GET", "/{index}/_stats",
                lambda r: {"indices": client.stats(r.path_params["index"])})
    # real segment introspection (no longer an alias of _stats): per-shard
    # per-segment packed-layout report — see Client.segments
    rc.register("GET", "/_segments", lambda r: client.segments())
    rc.register("GET", "/{index}/_segments",
                lambda r: client.segments(r.path_params["index"]))

    # --- cluster admin ------------------------------------------------------
    rc.register("GET", "/_cluster/health",
                lambda r: client.cluster_health(
                    wait_for_status=r.param("wait_for_status"),
                    timeout=float(str(r.param("timeout", "10")).rstrip("s"))))
    rc.register("GET", "/_cluster/health/{index}",
                lambda r: client.cluster_health(index=r.path_params["index"]))
    rc.register("GET", "/_cluster/state",
                lambda r: client.cluster_state(index_templates=r.param("index_templates")))
    rc.register("GET", "/_cluster/state/{metric}",
                lambda r: client.cluster_state(metric=r.path_params["metric"],
                                               index_templates=r.param("index_templates")))
    rc.register("GET", "/_cluster/state/{metric}/{index}",
                lambda r: client.cluster_state(metric=r.path_params["metric"],
                                               index=r.path_params["index"],
                                               index_templates=r.param("index_templates")))
    rc.register("GET", "/_cluster/pending_tasks", lambda r: client.pending_tasks())
    rc.register("GET", "/_cluster/stats", lambda r: client.cluster_stats())
    # `{node_id}` REALLY filters now (comma list of ids or names, unknown id
    # → 404 NodeMissingError) — it used to share the unfiltered handler and
    # silently return the whole-cluster rollup
    rc.register("GET", "/_cluster/stats/nodes/{node_id}",
                lambda r: client.cluster_stats(
                    node_id=r.path_params["node_id"]))
    # node shutdown (ref: cluster.nodes.shutdown spec + RestNodesShutdownAction)
    rc.register("POST", "/_shutdown",
                lambda r: client.nodes_shutdown(None))
    rc.register("POST", "/_cluster/nodes/_shutdown",
                lambda r: client.nodes_shutdown(None))
    rc.register("POST", "/_cluster/nodes/{node_id}/_shutdown",
                lambda r: client.nodes_shutdown(r.path_params["node_id"]))
    rc.register("PUT", "/_cluster/settings",
                lambda r: client.cluster_update_settings(
                    _parse_body(r), flat=r.bool_param("flat_settings")))
    rc.register("GET", "/_cluster/settings",
                lambda r: client.cluster_get_settings(flat=r.bool_param("flat_settings")))
    rc.register("POST", "/_cluster/reroute",
                lambda r: client.cluster_reroute(_parse_body(r)))
    rc.register("GET", "/_nodes", lambda r: client.nodes_info())
    # `{metric}` REALLY filters now (comma list of stats sections; unknown
    # metric → 400) — it used to share the unfiltered handler and silently
    # return everything
    rc.register("GET", "/_nodes/stats", lambda r: client.nodes_stats())
    rc.register("GET", "/_nodes/stats/{metric}",
                lambda r: client.nodes_stats(metric=r.path_params["metric"]))
    rc.register("GET", "/_nodes/{node_id}/stats", lambda r: client.nodes_stats())
    rc.register("GET", "/_nodes/{node_id}/stats/{metric}",
                lambda r: client.nodes_stats(metric=r.path_params["metric"]))
    rc.register("GET", "/_cluster/nodes/hot_threads", lambda r: _hot_threads(r))
    rc.register("GET", "/_nodes/hot_threads", lambda r: _hot_threads(r))

    # --- tracing / telemetry (common/tracing.py) ----------------------------
    def get_traces(req):
        """Ring buffer of finished traces on THIS node, newest first."""
        traces = node.tracer.traces(_size_param(req, "/_traces"))
        return {"node": node.node_id, "total": len(traces),
                "tracing": node.tracer.stats(), "traces": traces}

    def get_tasks(req):
        """Live in-flight traced tasks (current span, elapsed;
        cancellable=false until a cancellation PR wires the flag up)."""
        return {"nodes": {node.node_id: {"name": node.name,
                                         "tasks": node.tracer.tasks()}}}

    def get_insights(req):
        """Always-on query-shape insights (common/insights.py): the top-N
        shapes by accumulated cost, full histograms included — the operator's
        'which queries are eating the cluster' view, joinable to the slowlog
        via the shape id."""
        limit = _size_param(req, "/_insights/queries", default=10)
        return {"node": node.node_id,
                "insights": node.insights.stats(),
                "shapes": node.insights.top(limit)}

    def get_events(req):
        """The cluster event journal (common/events.py): typed, rate-limited
        stall/pressure events, cluster-wide by default (`?local=true` reads
        only this node's ring)."""
        return client.cluster_events(size=_size_param(req, "/_events"),
                                     local=req.bool_param("local"))

    rc.register("GET", "/_insights/queries", get_insights)
    rc.register("GET", "/_events", get_events)
    rc.register("GET", "/_traces", get_traces)
    rc.register("GET", "/_tasks", get_tasks)
    rc.register("GET", "/_prometheus/metrics",
                lambda r: RestResponse(200, _prometheus_text(node),
                                       content_type="text/plain; version=0.0.4"))

    # device-side tracing (SURVEY §5.1 TPU mapping: the profiler role hot_threads
    # plays for host threads, jax.profiler plays for the XLA programs — captures
    # an XPlane trace of the query-phase kernels viewable in tensorboard/xprof)
    profiler_state = {"dir": None}

    def _profiler_start(req):
        import jax

        if profiler_state["dir"] is not None:
            return RestResponse(400, {"error": "profiler already running",
                                      "dir": profiler_state["dir"], "status": 400})
        body = _parse_body(req)
        trace_dir = body.get("dir") or os.path.join(
            node.data_path or ".", "profiler",
            time.strftime("%Y%m%d-%H%M%S"))
        os.makedirs(trace_dir, exist_ok=True)
        jax.profiler.start_trace(trace_dir)
        profiler_state["dir"] = trace_dir
        return {"started": True, "dir": trace_dir}

    def _profiler_stop(req):
        import jax

        if profiler_state["dir"] is None:
            return RestResponse(400, {"error": "profiler not running", "status": 400})
        jax.profiler.stop_trace()
        trace_dir, profiler_state["dir"] = profiler_state["dir"], None
        files = []
        for root_, _d, fs in os.walk(trace_dir):
            files.extend(os.path.join(root_, f) for f in fs)
        return {"stopped": True, "dir": trace_dir, "files": sorted(files)}

    rc.register("POST", "/_nodes/_local/profiler/start", _profiler_start)
    rc.register("POST", "/_nodes/_local/profiler/stop", _profiler_stop)

    # top-of-stack functions that mean "parked, not working": a thread whose
    # frame sits in one of these across BOTH snapshots with no CPU accrued is
    # idle (pool workers waiting for tasks, the scheduler loop, acceptors)
    _IDLE_FRAME_FUNCS = frozenset({
        "wait", "_wait_for_tstate_lock", "select", "poll", "epoll", "accept",
        "get", "sleep", "_recv_bytes", "recv", "recv_into", "readinto",
        "read", "park", "acquire", "_eintr_retry", "kqueue",
    })

    def _thread_cpu_ticks():
        """Per-native-thread (utime+stime) ticks from /proc/self/task/<tid>/stat
        — the real busyness signal; {} when procfs is unavailable (non-Linux:
        the frame-diff heuristic alone ranks)."""
        ticks = {}
        try:
            for tid in os.listdir("/proc/self/task"):
                try:
                    with open(f"/proc/self/task/{tid}/stat") as fh:
                        stat = fh.read()
                    # comm may contain spaces — fields start after the ')'
                    fields = stat.rsplit(")", 1)[1].split()
                    ticks[int(tid)] = int(fields[11]) + int(fields[12])
                except (OSError, ValueError, IndexError):
                    continue
        except OSError:
            return {}
        return ticks

    def _hot_threads(req):
        """ref: monitor/jvm/HotThreads — two-snapshot sampling over
        `?interval=` (default 500ms): per-thread CPU ticks from procfs plus
        stack frames at both endpoints, ranked by observed busyness; idle/
        parked threads (no CPU, same wait-frame at both snapshots) are
        skipped; `?threads=` bounds the report (default 3)."""
        import sys
        import traceback

        import threading as _th

        from ..common.deadline import parse_timevalue

        try:
            interval_s = parse_timevalue(req.param("interval", "500ms"))
            n_threads = int(req.param("threads", 3))
        except (TypeError, ValueError) as e:
            from ..common.errors import IllegalArgumentError

            raise IllegalArgumentError(
                f"bad hot_threads parameter: {e}") from None
        if interval_s is None or interval_s < 0:
            interval_s = 0.5
        interval_s = min(interval_s, 30.0)  # a typo must not park the handler

        me = _th.get_ident()
        ticks0 = _thread_cpu_ticks()
        frames0 = {tid: (id(f), f.f_lasti, f.f_lineno, f.f_code.co_name)
                   for tid, f in sys._current_frames().items()}
        time.sleep(interval_s)
        ticks1 = _thread_cpu_ticks()
        frames1 = dict(sys._current_frames())
        threads = {t.ident: t for t in _th.enumerate()}
        clk_tck = 100.0
        try:
            clk_tck = float(os.sysconf("SC_CLK_TCK")) or 100.0
        except (OSError, ValueError, AttributeError):
            pass

        ranked = []
        for tid, frame in frames1.items():
            if tid == me:
                continue  # the handler thread is busy by construction
            t = threads.get(tid)
            native = getattr(t, "native_id", None) if t is not None else None
            dticks = (ticks1.get(native, 0) - ticks0.get(native, 0)) \
                if native is not None and ticks0 else 0
            cpu_pct = min(100.0, (dticks / clk_tck) / max(interval_s, 1e-6)
                          * 100.0)
            f0 = frames0.get(tid)
            sig1 = (id(frame), frame.f_lasti, frame.f_lineno,
                    frame.f_code.co_name)
            advanced = f0 is None or f0[:3] != sig1[:3]
            parked = (not advanced and dticks == 0
                      and sig1[3] in _IDLE_FRAME_FUNCS)
            if parked:
                continue  # idle/parked threads never make the report
            # busyness order: real CPU first, then frame advance as the
            # tie-break signal procfs can't see (a thread may burn its ticks
            # between the two reads)
            ranked.append((cpu_pct, 1 if advanced else 0, tid, frame))
        ranked.sort(key=lambda e: (-e[0], -e[1],
                                   threads.get(e[2]).name
                                   if threads.get(e[2]) else str(e[2])))

        out = [f"::: [{node.name}] hot_threads: interval={interval_s * 1000:.0f}ms, "
               f"busiest {min(n_threads, len(ranked))} of {len(frames1)} "
               f"threads ({len(frames1) - 1 - len(ranked)} idle/parked skipped)"]
        for cpu_pct, advanced, tid, frame in ranked[: max(n_threads, 0)]:
            name = threads[tid].name if tid in threads else str(tid)
            state = "running" if advanced else "stalled"
            stack = "".join(traceback.format_stack(frame, limit=10))
            out.append(f"   {cpu_pct:.1f}% cpu usage ({state}) by thread "
                       f"'{name}'\n{stack}")
        return RestResponse(200, "\n".join(out) + "\n",
                            content_type="text/plain")

    # --- _cat APIs (plain text ops views — ref: rest/action/cat/) -----------
    # Shared table renderer (ref: rest/action/support/RestTable.java): ?help lists
    # columns, ?v adds a header row, ?h= selects columns by name or alias.
    def _cat_table(req, columns, rows):
        # columns: (name, alias, help_text); rows: dicts keyed by column name
        if req.bool_param("help"):
            text = "".join(f"{name} | {alias or name} | {help_}\n"
                           for name, alias, help_ in columns)
            return RestResponse(200, text, content_type="text/plain")
        by_key = {}
        for c in columns:
            by_key[c[0]] = c
            if c[1]:
                by_key.setdefault(c[1], c)
        if req.param("h"):
            selected = [(h, by_key[h]) for h in str(req.param("h")).split(",")
                        if h in by_key]
        else:
            selected = [(c[0], c) for c in columns]
        table = []
        if req.bool_param("v"):
            table.append([disp for disp, _ in selected])
        for row in rows:
            table.append([str(row.get(c[0], "")) for _, c in selected])
        if not table:
            return RestResponse(200, "", content_type="text/plain")
        widths = [max(len(r[i]) for r in table) for i in range(len(selected))]
        # numbers right-align, text left-aligns (ref: RestTable cell alignment)
        num_col = [all(r[i].replace(".", "", 1).isdigit()
                       for r in (table[1:] if req.bool_param("v") else table)
                       if r[i] != "")
                   for i in range(len(selected))]
        lines = []
        for ri, r in enumerate(table):
            is_header = req.bool_param("v") and ri == 0
            cells = [cell.ljust(w) if is_header or not num_col[i]
                     else cell.rjust(w)
                     for i, (cell, w) in enumerate(zip(r, widths))]
            lines.append(" ".join(cells) + " ")
        return RestResponse(200, "".join(ln + "\n" for ln in lines),
                            content_type="text/plain")

    from ..common.units import format_bytes as _fmt_bytes

    def _node_host_ip():
        import socket

        try:
            host = socket.gethostname()
        except OSError:
            host = "localhost"
        return host, "127.0.0.1"

    def cat_health(req):
        from ..common.devicehealth import CLOSED, DEVICE_HEALTH

        h = client.cluster_health()
        # tail column: device fault domains currently not closed (serving
        # degraded to the host path there) — "device_ok" when every domain
        # is healthy, else e.g. "device_degraded:pull:idx,mesh:idx"
        if not DEVICE_HEALTH.any_open:
            dev = "device_ok"
        else:
            open_domains = sorted(
                d for d, st in DEVICE_HEALTH.stats()["domains"].items()
                if st["state"] != CLOSED)
            dev = ("device_degraded:" + ",".join(open_domains)
                   if open_domains else "device_ok")
        return RestResponse(200, f"{h['cluster_name']} {h['status']} "
                                 f"{h['number_of_nodes']} {h['number_of_data_nodes']} "
                                 f"{h['active_shards']} {h['unassigned_shards']} "
                                 f"{dev}\n",
                            content_type="text/plain")

    def cat_nodes(req):
        state = node.cluster_service.state
        lines = []
        for n in state.nodes.nodes:
            marker = "*" if n.id == state.nodes.master_id else "-"
            lines.append(f"{n.name} {marker} {n.transport_address} "
                         f"master_eligible={n.master_eligible} data={n.data}")
        return RestResponse(200, "\n".join(lines) + "\n", content_type="text/plain")

    def cat_indices(req):
        state = node.cluster_service.state
        lines = []
        for name in state.metadata.index_names():
            meta = state.metadata.index(name)
            h = client.cluster_health(index=name)
            try:
                cnt = client.count(name)["count"]
            except SearchEngineError:
                cnt = "-"
            lines.append(f"{h['status']} {name} {meta.number_of_shards} "
                         f"{meta.number_of_replicas} {cnt}")
        return RestResponse(200, "\n".join(lines) + "\n", content_type="text/plain")

    def cat_shards(req):
        state = node.cluster_service.state
        host, ip = _node_host_ip()
        local_stats = node.indices.stats()
        index_filter = req.path_params.get("index")
        wanted_indices = set(state.metadata.resolve_indices(index_filter)) \
            if index_filter else None
        rows = []
        for s in state.routing_table.all_shards():
            if wanted_indices is not None and s.index not in wanted_indices:
                continue
            row = {"index": s.index, "shard": s.shard_id,
                   "prirep": "p" if s.primary else "r", "state": s.state}
            if s.node_id is not None:
                n = state.nodes.get(s.node_id)
                row["node"] = n.name if n else s.node_id
                row["ip"] = ip
                st = (local_stats.get(s.index, {}).get("shards", {})
                      .get(s.shard_id))
                if st:
                    row["docs"] = st["docs"]["count"]
                    import os as _os

                    path = _os.path.join(node.data_path, "indices", s.index,
                                         str(s.shard_id))
                    size = 0
                    for dp, _, fs in _os.walk(path):
                        for f in fs:
                            try:
                                size += _os.path.getsize(_os.path.join(dp, f))
                            except OSError:
                                pass
                    row["store"] = _fmt_bytes(size)
            rows.append(row)
        return _cat_table(req, [
            ("index", "i", "index name"), ("shard", "s", "shard id"),
            ("prirep", "p", "primary or replica"), ("state", "st", "shard state"),
            ("docs", "d", "number of docs"), ("store", "sto", "store size"),
            ("ip", None, "node ip"), ("node", "n", "node name"),
        ], rows)

    def cat_master(req):
        state = node.cluster_service.state
        m = state.nodes.master
        return RestResponse(200, f"{m.id} {m.name}\n" if m else "-\n",
                            content_type="text/plain")

    def cat_allocation(req):
        import shutil as _shutil

        state = node.cluster_service.state
        counts: dict[str, int] = {}
        for s in state.routing_table.all_shards():
            if s.node_id:
                counts[s.node_id] = counts.get(s.node_id, 0) + 1
        node_filter = req.path_params.get("node_id")
        host, ip = _node_host_ip()
        rows = []
        unassigned = sum(1 for s in state.routing_table.all_shards()
                         if s.node_id is None)
        for n in state.nodes.nodes:
            if node_filter and node_filter not in ("_all",):
                if node_filter == "_master":
                    if n.id != state.nodes.master_id:
                        continue
                elif node_filter not in (n.id, n.name):
                    continue
            try:
                du = _shutil.disk_usage(node.data_path)
                used, avail, total = du.used, du.free, du.total
            except OSError:
                used = avail = total = 0
            unit = req.param("bytes")  # raw integers in a fixed unit when given
            div = {"b": 1, "k": 1024, "m": 1024 ** 2, "g": 1024 ** 3,
                   "t": 1024 ** 4}.get(unit)
            fmt = (lambda v: str(int(v / div))) if div else _fmt_bytes
            rows.append({
                "shards": counts.get(n.id, 0),
                "disk.used": fmt(used), "disk.avail": fmt(avail),
                "disk.total": fmt(total),
                "disk.percent": int(used * 100 / total) if total else 0,
                "host": host, "ip": ip, "node": n.name,
            })
        if unassigned and not node_filter:
            rows.append({"shards": unassigned, "node": "UNASSIGNED"})
        return _cat_table(req, [
            ("shards", None, "number of shards on node"),
            ("disk.used", "du", "disk used"),
            ("disk.avail", "da", "disk available"),
            ("disk.total", "dt", "total disk capacity"),
            ("disk.percent", "dp", "percent of disk used"),
            ("host", "h", "host name"), ("ip", None, "ip address"),
            ("node", "n", "node name"),
        ], rows)

    def cat_count(req):
        import time as _time

        index = req.path_params.get("index")
        c = client.count(index or "_all")["count"]
        now = int(_time.time())
        return _cat_table(req, [
            ("epoch", "t", "seconds since 1970-01-01 00:00:00"),
            ("timestamp", "ts", "time in HH:MM:SS"),
            ("count", "dc", "the document count"),
        ], [{"epoch": now,
             "timestamp": _time.strftime("%H:%M:%S", _time.localtime(now)),
             "count": c}])

    def cat_aliases(req):
        rows = []
        for index, spec in client.get_aliases(
                None, req.path_params.get("name")).items():
            for alias, aspec in spec["aliases"].items():
                rows.append({
                    "alias": alias, "index": index,
                    "filter": "*" if aspec.get("filter") else "-",
                    "routing.index": aspec.get("index_routing", "-"),
                    "routing.search": aspec.get("search_routing", "-"),
                })
        return _cat_table(req, [
            ("alias", "a", "alias name"), ("index", "i", "index the alias points to"),
            ("filter", "f", "whether the alias has a filter"),
            ("routing.index", "ri", "index routing"),
            ("routing.search", "rs", "search routing"),
        ], rows)

    def cat_pending_tasks(req):
        tasks = client.pending_tasks()["tasks"]
        lines = [f"{t['priority']} {t['time_in_queue_millis']}ms {t['source']}"
                 for t in tasks]
        return RestResponse(200, "\n".join(lines) + "\n", content_type="text/plain")

    def cat_recovery(req):
        lines = []
        for index, spec in node.indices.stats().items():
            for sid, st in spec["shards"].items():
                lines.append(f"{index} {sid} {st['state']} "
                             f"docs={st['docs']['count']}")
        return RestResponse(200, "\n".join(lines) + "\n", content_type="text/plain")

    _POOL_ALIASES = {
        "bulk": "b", "flush": "f", "generic": "ge", "get": "g", "index": "i",
        "management": "ma", "merge": "m", "optimize": "o", "percolate": "p",
        "refresh": "r", "search": "s", "snapshot": "sn", "suggest": "su",
        "warmer": "w",
    }

    def cat_thread_pool(req):
        import os as _os

        host, ip = _node_host_ip()
        stats = node.threadpool.stats()
        columns = [
            ("pid", None, "process id"), ("id", None, "node id"),
            ("host", "h", "host name"), ("ip", "i", "ip address"),
            ("port", "po", "bound transport port"),
        ]
        pool_cols = []
        for pool, alias in _POOL_ALIASES.items():
            pool_cols += [
                (f"{pool}.active", f"{alias}a", f"number of active {pool} threads"),
                (f"{pool}.queue", f"{alias}q", f"number of {pool} threads in queue"),
                (f"{pool}.rejected", f"{alias}r", f"number of rejected {pool} threads"),
            ]
        columns += pool_cols
        node_id = node.node_id if req.bool_param("full_id") else node.node_id[:4]
        row = {"pid": _os.getpid(), "id": node_id, "host": host, "ip": ip,
               "port": 9300}
        for pool in _POOL_ALIASES:
            st = stats.get(pool, {})
            row[f"{pool}.active"] = st.get("active", 0)
            row[f"{pool}.queue"] = st.get("queue", 0)
            row[f"{pool}.rejected"] = st.get("rejected", 0)
        # default view: host/ip + bulk, index, search activity (ref: RestThreadPoolAction)
        default = [columns[2], columns[3]] + [
            c for c in pool_cols if c[0].split(".")[0] in ("bulk", "index", "search")]
        if req.param("h") or req.bool_param("help"):
            return _cat_table(req, columns, [row])
        return _cat_table(req, default, [row])

    def cat_batcher(req):
        """Cross-request micro-batching at a glance: launches vs coalesced
        requests, mean occupancy, and which flush trigger is firing — the
        operator's first read on whether concurrent load is actually
        coalescing (search/batcher.py; full counters in /_nodes/stats)."""
        host, ip = _node_host_ip()
        st = node.search_batcher.stats()
        columns = [
            ("host", "h", "host name"), ("ip", "i", "ip address"),
            ("launches", "l", "coalesced device launches"),
            ("coalesced", "c", "requests served via coalesced launches"),
            ("occupancy_mean", "o", "mean requests per launch"),
            ("full_flushes", "ff", "flushes on batch-full"),
            ("linger_flushes", "lf", "flushes on linger expiry"),
            ("deadline_flushes", "df", "flushes on request deadline"),
            ("queue", "q", "plans waiting to coalesce"),
            ("bypassed", "by", "requests served outside the batcher"),
        ]
        row = {"host": host, "ip": ip}
        row.update({name: st.get(name, 0) for (name, _a, _d) in columns[2:]})
        return _cat_table(req, columns, [row])

    def cat_caches(req):
        """Per-tier cache occupancy at a glance (request cache + device
        filter cache): entries/bytes against the configured bound, hit rate,
        and eviction pressure — full counters in /_nodes/stats indices.*."""
        host, ip = _node_host_ip()
        columns = [
            ("host", "h", "host name"), ("ip", "i", "ip address"),
            ("tier", "t", "cache tier (request|filter)"),
            ("entries", "e", "resident entries/masks"),
            ("bytes", "b", "resident bytes"),
            ("limit", "lb", "configured byte bound (- = breaker-bounded)"),
            ("hits", "ht", "lookup hits"),
            ("misses", "ms", "lookup misses"),
            ("hit_rate", "hr", "lifetime hit rate"),
            ("evictions", "ev", "evicted entries"),
        ]
        rcs = node.request_cache.stats()
        fcs = node.filter_cache.stats()
        rows = [
            {"host": host, "ip": ip, "tier": "request",
             "entries": rcs["entries"],
             "bytes": rcs["memory_size_in_bytes"],
             "limit": rcs["limit_size_in_bytes"],
             "hits": rcs["hits"], "misses": rcs["misses"],
             "hit_rate": rcs["hit_rate"], "evictions": rcs["evictions"]},
            {"host": host, "ip": ip, "tier": "filter",
             "entries": fcs["masks"],
             "bytes": fcs["memory_size_in_bytes"], "limit": "-",
             "hits": fcs["hits"], "misses": fcs["misses"],
             "hit_rate": fcs["hit_rate"], "evictions": fcs["evictions"]},
        ]
        return _cat_table(req, columns, rows)

    def cat_segments(req):
        """Per-segment table view of Client.segments: doc/postings counts +
        the quantized device layout (tf rung, bytes/posting, resident bytes,
        dense-plane state) — the operator's HBM-budget at-a-glance read."""
        rows = []
        for index, ispec in client.segments(
                req.path_params.get("index")).get("indices", {}).items():
            for sid, copies in sorted(ispec["shards"].items(),
                                      key=lambda kv: int(kv[0])):
                for copy in copies:
                    prirep = "p" if copy["routing"]["primary"] else "r"
                    for seg_name, seg in sorted(
                            copy["segments"].items(),
                            key=lambda kv: kv[1]["generation"]):
                        dev = seg.get("device") or {}
                        rows.append({
                            "index": index, "shard": sid, "prirep": prirep,
                            "segment": seg_name,
                            "generation": seg["generation"],
                            "docs.count": seg["num_docs"],
                            "docs.deleted": seg["deleted_docs"],
                            "postings": seg["postings"],
                            "packed": str(bool(dev.get("packed"))).lower(),
                            "tf.layout": dev.get("tf_layout", "-"),
                            "bytes.posting": dev.get("bytes_per_posting", "-"),
                            "size": (_fmt_bytes(dev["resident_bytes"])
                                     if dev.get("packed") else "-"),
                            "dense.plane": dev.get("dense_plane", "-"),
                            "searchable": "true",
                        })
        return _cat_table(req, [
            ("index", "i", "index name"), ("shard", "s", "shard id"),
            ("prirep", "p", "primary or replica"),
            ("segment", "seg", "segment name"),
            ("generation", "g", "segment generation"),
            ("docs.count", "dc", "number of live docs"),
            ("docs.deleted", "dd", "number of deleted docs"),
            ("postings", "po", "postings in the segment"),
            ("packed", "pk", "device-packed"),
            ("tf.layout", "tf", "quantized tf plane rung (u8/i16/f32)"),
            ("bytes.posting", "bp", "resident bytes per posting"),
            ("size", "sz", "device-resident postings bytes"),
            ("dense.plane", "dp", "dense f32 plane resident or lazy"),
            ("searchable", "se", "segment is searchable"),
        ], rows)

    def cat_events(req):
        """Cluster event journal at a glance (common/events.py): one row per
        typed watchdog event, newest first — the human-readable causal
        record behind adaptive routing's health signals."""
        import time as _time

        rows = []
        for e in client.cluster_events(local=req.bool_param("local"))["events"]:
            attrs = e.get("attrs") or {}
            rows.append({
                "timestamp": _time.strftime(
                    "%H:%M:%S", _time.localtime(float(e.get("ts", 0.0)))),
                "node": e.get("node_name") or e.get("node", "-"),
                "type": e.get("type", "-"),
                "severity": e.get("severity", "-"),
                "shard": attrs.get("shard", attrs.get("pool",
                                                      attrs.get("breaker",
                                                                "-"))),
                "message": e.get("message", ""),
            })
        return _cat_table(req, [
            ("timestamp", "ts", "event time (HH:MM:SS)"),
            ("node", "n", "originating node"),
            ("type", "t", "event type"),
            ("severity", "sev", "info or warn"),
            ("shard", "s", "subject (shard/pool/breaker)"),
            ("message", "m", "human-readable event message"),
        ], rows)

    # --- percolate -----------------------------------------------------------
    def percolate(req):
        return node.percolator.percolate(
            req.path_params["index"], _parse_body(req),
            doc_type=req.path_params["type"], doc_id=req.param("id"),
            version=req.param("version"),
            percolate_index=req.param("percolate_index"),
            percolate_type=req.param("percolate_type"))

    rc.register("GET,POST", "/{index}/{type}/_percolate", percolate)
    rc.register("GET,POST", "/{index}/{type}/{id}/_percolate",
                lambda r: node.percolator.percolate(
                    r.path_params["index"], _parse_body(r),
                    doc_type=r.path_params["type"], doc_id=r.path_params["id"],
                    version=r.param("version"),
                    percolate_index=r.param("percolate_index"),
                    percolate_type=r.param("percolate_type")))
    rc.register("GET,POST", "/{index}/{type}/_percolate/count",
                lambda r: node.percolator.count_percolate(
                    r.path_params["index"], _parse_body(r),
                    doc_type=r.path_params["type"]))
    rc.register("GET,POST", "/{index}/{type}/{id}/_percolate/count",
                lambda r: node.percolator.count_percolate(
                    r.path_params["index"], _parse_body(r),
                    doc_type=r.path_params["type"], doc_id=r.path_params["id"]))

    def mpercolate(req):
        raw = req.body if isinstance(req.body, str) else ""
        lines = [ln for ln in raw.split("\n") if ln.strip()]
        requests = []
        for i in range(0, len(lines) - 1, 2):
            requests.append((json.loads(lines[i]), json.loads(lines[i + 1])))
        return node.percolator.multi_percolate(
            requests, default_index=req.path_params.get("index"),
            default_type=req.path_params.get("type"))

    rc.register("GET,POST", "/_mpercolate", mpercolate)
    rc.register("GET,POST", "/{index}/_mpercolate", mpercolate)
    rc.register("GET,POST", "/{index}/{type}/_mpercolate", mpercolate)

    # --- warmers -------------------------------------------------------------
    def put_warmer(req):
        return client.put_warmer(req.path_params.get("index"),
                                 req.path_params["name"], _parse_body(req),
                                 doc_type=req.path_params.get("type"))

    def get_warmer(req):
        return client.get_warmer(req.path_params.get("index"),
                                 req.path_params.get("name"))

    for suffix in ("_warmer", "_warmers"):
        rc.register("PUT,POST", "/" + suffix + "/{name}", put_warmer)
        rc.register("PUT,POST", "/{index}/" + suffix + "/{name}", put_warmer)
        rc.register("PUT,POST", "/{index}/{type}/" + suffix + "/{name}", put_warmer)
        rc.register("DELETE", "/{index}/" + suffix + "/{name}",
                    lambda r: client.delete_warmer(r.path_params["index"],
                                                   r.path_params["name"]))
    rc.register("GET", "/_warmer", get_warmer)
    rc.register("GET", "/_warmer/{name}", get_warmer)
    rc.register("GET", "/{index}/_warmer", get_warmer)
    rc.register("GET", "/{index}/_warmer/{name}", get_warmer)
    rc.register("GET", "/{index}/{type}/_warmer/{name}", get_warmer)

    # --- legacy status + gateway snapshot ------------------------------------
    rc.register("GET", "/_status", lambda r: client.indices_status())
    rc.register("GET", "/{index}/_status",
                lambda r: client.indices_status(r.path_params["index"]))
    rc.register("POST", "/_gateway/snapshot", lambda r: client.gateway_snapshot())
    rc.register("POST", "/{index}/_gateway/snapshot",
                lambda r: client.gateway_snapshot(r.path_params["index"]))

    # --- snapshot/restore ----------------------------------------------------
    rc.register("PUT,POST", "/_snapshot/{repo}",
                lambda r: client.put_repository(r.path_params["repo"], _parse_body(r)))
    rc.register("GET", "/_snapshot", lambda r: client.get_repository())
    rc.register("GET", "/_snapshot/{repo}",
                lambda r: client.get_repository(r.path_params["repo"]))
    rc.register("DELETE", "/_snapshot/{repo}",
                lambda r: client.delete_repository(r.path_params["repo"]))
    rc.register("POST", "/_snapshot/{repo}/_verify",
                lambda r: client.verify_repository(r.path_params["repo"]))
    rc.register("PUT", "/_snapshot/{repo}/{snapshot}",
                lambda r: client.create_snapshot(r.path_params["repo"],
                                                 r.path_params["snapshot"],
                                                 _parse_body(r)))
    rc.register("GET", "/_snapshot/{repo}/{snapshot}",
                lambda r: client.get_snapshots(r.path_params["repo"],
                                               r.path_params["snapshot"]))
    rc.register("GET", "/_snapshot/{repo}/{snapshot}/_status",
                lambda r: client.snapshot_status(r.path_params["repo"],
                                                 r.path_params["snapshot"]))
    rc.register("DELETE", "/_snapshot/{repo}/{snapshot}",
                lambda r: client.delete_snapshot(r.path_params["repo"],
                                                 r.path_params["snapshot"]))
    rc.register("POST", "/_snapshot/{repo}/{snapshot}/_restore",
                lambda r: client.restore_snapshot(r.path_params["repo"],
                                                  r.path_params["snapshot"],
                                                  _parse_body(r)))

    rc.register("GET", "/_cat/health", cat_health)
    rc.register("GET", "/_cat/nodes", cat_nodes)
    rc.register("GET", "/_cat/indices", cat_indices)
    rc.register("GET", "/_cat/shards", cat_shards)
    rc.register("GET", "/_cat/shards/{index}", cat_shards)
    rc.register("GET", "/_cat/master", cat_master)
    rc.register("GET", "/_cat/allocation", cat_allocation)
    rc.register("GET", "/_cat/allocation/{node_id}", cat_allocation)
    rc.register("GET", "/_cat/count", cat_count)
    rc.register("GET", "/_cat/count/{index}", cat_count)
    rc.register("GET", "/_cat/aliases", cat_aliases)
    rc.register("GET", "/_cat/aliases/{name}", cat_aliases)
    rc.register("GET", "/_cat/pending_tasks", cat_pending_tasks)
    rc.register("GET", "/_cat/recovery", cat_recovery)
    rc.register("GET", "/_cat/thread_pool", cat_thread_pool)
    rc.register("GET", "/_cat/batcher", cat_batcher)
    rc.register("GET", "/_cat/caches", cat_caches)
    rc.register("GET", "/_cat/segments", cat_segments)
    rc.register("GET", "/_cat/segments/{index}", cat_segments)
    rc.register("GET", "/_cat/events", cat_events)
    rc.register("GET", "/_cat", lambda r: RestResponse(
        200, "".join(f"/_cat/{n}\n" for n in (
            "health", "nodes", "indices", "shards", "master", "allocation", "count",
            "aliases", "pending_tasks", "recovery", "thread_pool", "batcher",
            "caches", "segments", "events")),
        content_type="text/plain"))

    # plugin-contributed routes (ref: plugins contribute REST handlers)
    if getattr(node, "plugins", None) is not None:
        node.plugins.rest_routes(rc, node)
    return rc
