"""REST layer: path-template routing + handlers for the API surface.

Analogue of rest/ (89 Rest*Action handler classes + RestController — SURVEY.md §2.7),
with the reference's `rest-api-spec/api/*.json` as the endpoint contract: methods, path
templates with {placeholders}, query params, JSON bodies, structured errors with HTTP
status codes, and the `_cat` plain-text ops APIs.

Handlers call the node Client — REST is a thin adapter exactly as in the reference
(RestController.dispatchRequest → client.*).
"""

from __future__ import annotations

import json
import re
from dataclasses import dataclass, field as dc_field
from typing import Callable

from ..common.errors import SearchEngineError


@dataclass
class RestRequest:
    method: str
    path: str
    params: dict = dc_field(default_factory=dict)
    body: dict | list | str | None = None
    path_params: dict = dc_field(default_factory=dict)

    def param(self, name: str, default=None):
        return self.path_params.get(name) or self.params.get(name, default)

    def bool_param(self, name: str, default=False) -> bool:
        v = self.param(name)
        if v is None:
            return default
        return str(v).lower() in ("true", "1", "")


@dataclass
class RestResponse:
    status: int
    body: object
    content_type: str = "application/json"

    def payload(self) -> bytes:
        if isinstance(self.body, (bytes,)):
            return self.body
        if isinstance(self.body, str):
            return self.body.encode()
        return json.dumps(self.body).encode()


class RestController:
    """register(method, "/{index}/{type}/_search", handler) + dispatch."""

    def __init__(self):
        self._routes: dict[str, list[tuple[re.Pattern, list[str], Callable]]] = {}

    def register(self, method: str, template: str, handler: Callable):
        names = re.findall(r"\{(\w+)\}", template)
        pattern = re.sub(r"\{(\w+)\}", r"([^/]+)", template.rstrip("/") or "/")
        compiled = re.compile("^" + pattern + "/?$")
        for m in method.split(","):
            self._routes.setdefault(m.strip().upper(), []).append(
                (compiled, names, handler))

    def dispatch(self, request: RestRequest) -> RestResponse:
        routes = self._routes.get(request.method, []) + (
            self._routes.get("GET", []) if request.method == "HEAD" else [])
        path = request.path.rstrip("/") or "/"
        best = None
        for pattern, names, handler in routes:
            m = pattern.match(path)
            if m:
                # prefer routes with fewer wildcards (literal match wins)
                score = len(names)
                if best is None or score < best[0]:
                    best = (score, m, names, handler)
        if best is None:
            return RestResponse(400, {"error": f"No handler found for uri [{request.path}] "
                                               f"and method [{request.method}]"})
        _, m, names, handler = best
        request.path_params = dict(zip(names, m.groups()))
        try:
            result = handler(request)
            if isinstance(result, RestResponse):
                return result
            return RestResponse(200, result)
        except SearchEngineError as e:
            return RestResponse(e.status, {"error": e.to_dict(), "status": e.status})
        except Exception as e:  # noqa: BLE001
            return RestResponse(500, {"error": {"type": type(e).__name__,
                                                "reason": str(e)}, "status": 500})


def _parse_body(request: RestRequest) -> dict:
    if request.body is None or request.body == "":
        return {}
    if isinstance(request.body, (dict, list)):
        return request.body
    return json.loads(request.body)


def build_rest_controller(node) -> RestController:
    client = node.client()
    rc = RestController()
    scroll_registry: dict[str, tuple] = {}

    # --- root / ping --------------------------------------------------------
    def root(req):
        from ..version import CURRENT

        return {
            "status": 200,
            "name": node.name,
            "version": {"number": str(CURRENT)},
            "tagline": "You Know, for Search (TPU-native)",
        }

    rc.register("GET,HEAD", "/", root)

    # --- document CRUD ------------------------------------------------------
    def doc_index(req):
        body = _parse_body(req)
        r = client.index(
            req.path_params["index"], req.path_params["type"], body,
            id=req.path_params.get("id"), routing=req.param("routing"),
            version=int(req.param("version")) if req.param("version") else None,
            version_type=req.param("version_type", "internal"),
            op_type=req.param("op_type", "index"),
            refresh=req.bool_param("refresh"),
            parent=req.param("parent"), timestamp=req.param("timestamp"),
            ttl=req.param("ttl"),
        )
        return RestResponse(201 if r.get("created") else 200, r)

    rc.register("PUT,POST", "/{index}/{type}/{id}", doc_index)
    rc.register("POST", "/{index}/{type}", doc_index)

    def doc_create(req):
        body = _parse_body(req)
        r = client.create(req.path_params["index"], req.path_params["type"], body,
                          id=req.path_params["id"], routing=req.param("routing"),
                          parent=req.param("parent"),
                          refresh=req.bool_param("refresh"),
                          timestamp=req.param("timestamp"), ttl=req.param("ttl"))
        return RestResponse(201, r)

    rc.register("PUT,POST", "/{index}/{type}/{id}/_create", doc_create)

    def _render_get(req, r):
        from ..actions import _extract_fields, filter_source

        if not r["found"]:
            return RestResponse(404, {"_index": r.get("_index"),
                                      "_type": r.get("_type"),
                                      "_id": r.get("_id"), "found": False})
        out = {k: v for k, v in r.items()
               if k in ("_index", "_type", "_id", "_version", "found")}
        fields = req.param("fields")
        src_param = req.param("_source")
        includes = req.param("_source_include")
        excludes = req.param("_source_exclude")
        want_source = True
        if fields:
            fdict, fsrc = _extract_fields(r, fields)
            if fdict:
                out["fields"] = fdict
            want_source = fsrc is not None or src_param not in (None, "false")
            if src_param is None and fsrc is None:
                want_source = False
        if src_param is not None and str(src_param).lower() == "false":
            want_source = False
        src = r.get("_source")
        if want_source and src is not None:
            if src_param not in (None, "true", "false", True, False) or includes \
                    or excludes:
                inc = includes
                if src_param not in (None, "true", "false", True, False):
                    inc = src_param
                src = filter_source(src, inc, excludes)
            out["_source"] = src
        return RestResponse(200, out)

    def doc_get(req):
        r = client.get(req.path_params["index"], req.path_params["type"],
                       req.path_params["id"], routing=req.param("routing"),
                       parent=req.param("parent"),
                       realtime=req.bool_param("realtime", True),
                       preference=req.param("preference"))
        return _render_get(req, r)

    rc.register("GET,HEAD", "/{index}/{type}/{id}", doc_get)

    def doc_source(req):
        r = client.get(req.path_params["index"], req.path_params["type"],
                       req.path_params["id"], routing=req.param("routing"),
                       parent=req.param("parent"))
        if not r["found"]:
            return RestResponse(404, {"found": False})
        from ..actions import filter_source

        src = r["_source"]
        if req.param("_source_include") or req.param("_source_exclude"):
            src = filter_source(src, req.param("_source_include"),
                                req.param("_source_exclude"))
        return src

    rc.register("GET,HEAD", "/{index}/{type}/{id}/_source", doc_source)

    def doc_delete(req):
        r = client.delete(req.path_params["index"], req.path_params["type"],
                          req.path_params["id"], routing=req.param("routing"),
                          parent=req.param("parent"),
                          version=int(req.param("version")) if req.param("version")
                          else None,
                          refresh=req.bool_param("refresh"))
        return RestResponse(200 if r["found"] else 404, r)

    rc.register("DELETE", "/{index}/{type}/{id}", doc_delete)

    def doc_update(req):
        body = _parse_body(req)
        return client.update(req.path_params["index"], req.path_params["type"],
                             req.path_params["id"], body,
                             routing=req.param("routing"),
                             parent=req.param("parent"),
                             refresh=req.bool_param("refresh"),
                             fields=req.param("fields"),
                             ttl=req.param("ttl"),
                             timestamp=req.param("timestamp"),
                             version=int(req.param("version"))
                             if req.param("version") else None,
                             version_type=req.param("version_type", "internal"),
                             retry_on_conflict=int(req.param("retry_on_conflict", 0)))

    rc.register("POST", "/{index}/{type}/{id}/_update", doc_update)

    def mget(req):
        body = _parse_body(req)
        default_index = body.get("index") or req.path_params.get("index")
        default_type = body.get("type") or req.path_params.get("type")
        docs = body.get("docs")
        if docs is None and "ids" in body:
            docs = [{"_index": default_index, "_type": default_type, "_id": i}
                    for i in body["ids"]]
        for d in docs or []:
            if not d.get("_index") and default_index:
                d["_index"] = default_index
            if not d.get("_type") and default_type:
                d["_type"] = default_type
        return client.mget(docs or [])

    rc.register("GET,POST", "/_mget", mget)
    rc.register("GET,POST", "/{index}/_mget", mget)
    rc.register("GET,POST", "/{index}/{type}/_mget", mget)

    def bulk(req):
        raw = req.body if isinstance(req.body, str) else ""
        operations = []
        if isinstance(req.body, list):  # pre-parsed
            operations = req.body
        else:
            lines = [ln for ln in raw.split("\n") if ln.strip()]
            i = 0
            while i < len(lines):
                action = json.loads(lines[i])
                (op, meta), = action.items()
                meta.setdefault("_index", req.path_params.get("index"))
                meta.setdefault("_type", req.path_params.get("type", "_default_"))
                entry = {"action": action}
                i += 1
                if op != "delete":
                    entry["source"] = json.loads(lines[i]) if i < len(lines) else {}
                    i += 1
                operations.append(entry)
        return client.bulk(operations, refresh=req.bool_param("refresh"))

    rc.register("POST,PUT", "/_bulk", bulk)
    rc.register("POST,PUT", "/{index}/_bulk", bulk)
    rc.register("POST,PUT", "/{index}/{type}/_bulk", bulk)

    # --- search -------------------------------------------------------------
    def _search_body(req):
        body = _parse_body(req)
        if req.param("q"):
            body = dict(body)
            body["query"] = {"query_string": {"query": req.param("q")}}
        for p in ("from", "size"):
            if req.param(p) is not None:
                body[p] = int(req.param(p))
        if req.param("sort"):
            body["sort"] = [
                ({s.split(":")[0]: s.split(":")[1]} if ":" in s else s)
                for s in str(req.param("sort")).split(",")
            ]
        return body

    def search(req):
        body = _search_body(req)
        index = req.path_params.get("index", "_all")
        scroll = req.param("scroll")
        if scroll:
            return _scrolled_search(index, body, scroll)
        return client.search(index, body,
                             search_type=req.param("search_type", "query_then_fetch"),
                             routing=req.param("routing"),
                             preference=req.param("preference"))

    def _scrolled_search(index, body, keep_alive):
        import uuid as _uuid

        r = client.search(index, {**body, "from": 0,
                                  "size": max(body.get("size", 10), 10) * 10})
        sid = _uuid.uuid4().hex
        size = body.get("size", 10)
        hits = r["hits"]["hits"]
        scroll_registry[sid] = (hits, size, size)
        r["_scroll_id"] = sid
        r["hits"]["hits"] = hits[:size]
        return r

    def scroll(req):
        body = _parse_body(req)
        sid = body.get("scroll_id") or req.param("scroll_id") or (
            req.body if isinstance(req.body, str) and req.body and
            not req.body.startswith("{") else None)
        if sid not in scroll_registry:
            from ..common.errors import SearchContextMissingError

            raise SearchContextMissingError(0)
        hits, size, pos = scroll_registry[sid]
        page = hits[pos: pos + size]
        scroll_registry[sid] = (hits, size, pos + size)
        return {"_scroll_id": sid, "hits": {"total": len(hits), "hits": page},
                "timed_out": False, "_shards": {"total": 1, "successful": 1, "failed": 0}}

    rc.register("GET,POST", "/{index}/_search", search)
    rc.register("GET,POST", "/{index}/{type}/_search", search)
    rc.register("GET,POST", "/_search", search)
    rc.register("GET,POST", "/_search/scroll", scroll)

    def clear_scroll(req):
        body = _parse_body(req)
        for sid in body.get("scroll_id", []):
            scroll_registry.pop(sid, None)
        return {"succeeded": True}

    rc.register("DELETE", "/_search/scroll", clear_scroll)

    def msearch(req):
        raw = req.body if isinstance(req.body, str) else ""
        lines = [ln for ln in raw.split("\n") if ln.strip()]
        requests = []
        for i in range(0, len(lines) - 1, 2):
            requests.append((json.loads(lines[i]), json.loads(lines[i + 1])))
        return client.msearch(requests)

    rc.register("GET,POST", "/_msearch", msearch)
    rc.register("GET,POST", "/{index}/_msearch", msearch)

    def count(req):
        body = _search_body(req)
        return client.count(req.path_params.get("index", "_all"), body)

    rc.register("GET,POST", "/_count", count)
    rc.register("GET,POST", "/{index}/_count", count)
    rc.register("GET,POST", "/{index}/{type}/_count", count)

    def suggest(req):
        return client.suggest(req.path_params.get("index", "_all"), _parse_body(req))

    rc.register("GET,POST", "/_suggest", suggest)
    rc.register("GET,POST", "/{index}/_suggest", suggest)

    def explain(req):
        return client.explain(req.path_params["index"], req.path_params["type"],
                              req.path_params["id"], _parse_body(req))

    rc.register("GET,POST", "/{index}/{type}/{id}/_explain", explain)

    def termvector(req):
        body = _parse_body(req)
        fields = req.param("fields")
        return client.termvector(
            req.path_params["index"], req.path_params["type"], req.path_params["id"],
            routing=req.param("routing"),
            fields=fields.split(",") if fields else body.get("fields"),
            positions=req.bool_param("positions", True),
            offsets=req.bool_param("offsets", True),
            term_statistics=req.bool_param("term_statistics", False),
            field_statistics=req.bool_param("field_statistics", True))

    rc.register("GET,POST", "/{index}/{type}/{id}/_termvector", termvector)
    rc.register("GET,POST", "/{index}/{type}/{id}/_termvectors", termvector)

    def mtermvectors(req):
        body = _parse_body(req)
        docs = body.get("docs", [])
        for d in docs:
            d.setdefault("_index", req.path_params.get("index"))
            d.setdefault("_type", req.path_params.get("type", "_all"))
        return client.mtermvectors(docs)

    rc.register("GET,POST", "/_mtermvectors", mtermvectors)
    rc.register("GET,POST", "/{index}/_mtermvectors", mtermvectors)
    rc.register("GET,POST", "/{index}/{type}/_mtermvectors", mtermvectors)

    def mlt(req):
        body = _parse_body(req)
        fields = req.param("mlt_fields")
        params = {k: req.param(k) for k in
                  ("min_term_freq", "min_doc_freq", "max_query_terms")}
        params = {k: int(v) for k, v in params.items() if v is not None}
        return client.mlt(
            req.path_params["index"], req.path_params["type"], req.path_params["id"],
            mlt_fields=fields.split(",") if fields else None,
            search_body=body or None, routing=req.param("routing"), **params)

    rc.register("GET,POST", "/{index}/{type}/{id}/_mlt", mlt)

    def validate_query(req):
        body = _parse_body(req)
        try:
            from ..search.queries import parse_query as pq

            pq(body.get("query"))
            return {"valid": True, "_shards": {"total": 1, "successful": 1, "failed": 0}}
        except SearchEngineError as e:
            return {"valid": False, "explanations": [{"error": str(e)}]}

    rc.register("GET,POST", "/{index}/_validate/query", validate_query)
    rc.register("GET,POST", "/_validate/query", validate_query)

    def delete_by_query(req):
        return client.delete_by_query(req.path_params["index"], _search_body(req))

    rc.register("DELETE", "/{index}/_query", delete_by_query)
    rc.register("DELETE", "/{index}/{type}/_query", delete_by_query)

    # --- indices admin ------------------------------------------------------
    def index_create(req):
        return client.create_index(req.path_params["index"], _parse_body(req))

    def index_delete(req):
        return client.delete_index(req.path_params["index"])

    def index_exists(req):
        return RestResponse(200 if client.exists_index(req.path_params["index"]) else 404,
                            "")

    rc.register("PUT,POST", "/{index}", index_create)
    rc.register("DELETE", "/{index}", index_delete)
    rc.register("HEAD", "/{index}", index_exists)
    rc.register("POST", "/{index}/_open", lambda r: client.open_index(r.path_params["index"]))
    rc.register("POST", "/{index}/_close", lambda r: client.close_index(r.path_params["index"]))

    def put_mapping(req):
        return client.put_mapping(req.path_params.get("index"),
                                  req.path_params["type"], _parse_body(req))

    def delete_mapping(req):
        return client.delete_mapping(req.path_params["index"], req.path_params["type"])

    for suffix in ("_mapping", "_mappings"):
        rc.register("PUT,POST", "/{index}/{type}/" + suffix, put_mapping)
        rc.register("PUT,POST", "/{index}/" + suffix + "/{type}", put_mapping)
        rc.register("PUT,POST", "/" + suffix + "/{type}", put_mapping)
        rc.register("DELETE", "/{index}/{type}/" + suffix, delete_mapping)
        rc.register("DELETE", "/{index}/" + suffix + "/{type}", delete_mapping)
    rc.register("GET", "/{index}/_mapping",
                lambda r: client.get_mapping(r.path_params["index"]))
    rc.register("GET", "/{index}/{type}/_mapping",
                lambda r: client.get_mapping(r.path_params["index"], r.path_params["type"]))
    rc.register("GET", "/{index}/_mapping/{type}",
                lambda r: client.get_mapping(r.path_params["index"], r.path_params["type"]))
    rc.register("GET", "/_mapping", lambda r: client.get_mapping())

    def get_field_mapping(req):
        return client.get_field_mapping(
            req.path_params.get("index"), req.path_params.get("type"),
            req.path_params.get("field"),
            include_defaults=req.bool_param("include_defaults"))

    rc.register("GET", "/_mapping/field/{field}", get_field_mapping)
    rc.register("GET", "/{index}/_mapping/field/{field}", get_field_mapping)
    rc.register("GET", "/_mapping/{type}/field/{field}", get_field_mapping)
    rc.register("GET", "/{index}/_mapping/{type}/field/{field}", get_field_mapping)

    def exists_type(req):
        ok = client.exists_type(req.path_params["index"], req.path_params["type"])
        return RestResponse(200 if ok else 404, "")

    rc.register("HEAD", "/{index}/{type}", exists_type)

    rc.register("PUT", "/{index}/_settings",
                lambda r: client.update_settings(r.path_params["index"], _parse_body(r)))
    rc.register("PUT", "/_settings",
                lambda r: client.update_settings(None, _parse_body(r)))
    rc.register("GET", "/{index}/_settings",
                lambda r: client.get_settings(r.path_params["index"]))
    rc.register("GET", "/{index}/_settings/{name}",
                lambda r: client.get_settings(r.path_params["index"],
                                              r.path_params["name"]))
    rc.register("GET", "/_settings", lambda r: client.get_settings())
    rc.register("GET", "/_settings/{name}",
                lambda r: client.get_settings(None, r.path_params["name"]))

    rc.register("POST", "/_aliases", lambda r: client.update_aliases(_parse_body(r)))
    rc.register("GET", "/_aliases", lambda r: client.get_aliases())
    rc.register("GET", "/{index}/_aliases", lambda r: client.get_aliases(r.path_params["index"]))

    def put_alias(req):
        return client.update_aliases({"actions": [{"add": {
            "index": req.path_params.get("index", "_all"),
            "alias": req.path_params["name"], **_parse_body(req)}}]})

    def get_alias(req):
        return client.get_aliases(req.path_params.get("index"),
                                  req.path_params.get("name"))

    def exists_alias(req):
        ok = client.exists_alias(req.path_params.get("index"),
                                 req.path_params.get("name"))
        return RestResponse(200 if ok else 404, "")

    for suffix in ("_alias", "_aliases"):
        rc.register("PUT,POST", "/{index}/" + suffix + "/{name}", put_alias)
        rc.register("PUT,POST", "/" + suffix + "/{name}", put_alias)
        rc.register("DELETE", "/{index}/" + suffix + "/{name}",
                    lambda r: client.update_aliases({"actions": [{"remove": {
                        "index": r.path_params["index"],
                        "alias": r.path_params["name"]}}]}))
    rc.register("GET", "/_alias", get_alias)
    rc.register("GET", "/_alias/{name}", get_alias)
    rc.register("GET", "/{index}/_alias", get_alias)
    rc.register("GET", "/{index}/_alias/{name}", get_alias)
    rc.register("HEAD", "/_alias/{name}", exists_alias)
    rc.register("HEAD", "/{index}/_alias", exists_alias)
    rc.register("HEAD", "/{index}/_alias/{name}", exists_alias)

    rc.register("PUT,POST", "/_template/{name}",
                lambda r: client.put_template(r.path_params["name"], _parse_body(r)))
    rc.register("DELETE", "/_template/{name}",
                lambda r: client.delete_template(r.path_params["name"]))
    rc.register("GET", "/_template/{name}",
                lambda r: client.get_template(r.path_params["name"]))
    rc.register("GET", "/_template", lambda r: client.get_template())

    for op in ("refresh", "flush", "optimize"):
        rc.register("POST,GET", f"/_{op}",
                    (lambda o: lambda r: getattr(client, o)(None))(op))
        rc.register("POST,GET", "/{index}/_" + op,
                    (lambda o: lambda r: getattr(client, o)(r.path_params["index"]))(op))
    rc.register("POST", "/_cache/clear", lambda r: client.clear_cache())
    rc.register("POST", "/{index}/_cache/clear",
                lambda r: client.clear_cache(r.path_params["index"]))

    def analyze(req):
        body = _parse_body(req)
        text = body.get("text") or req.param("text") or (
            req.body if isinstance(req.body, str) and not req.body.startswith("{") else "")
        analyzer_name = body.get("analyzer") or req.param("analyzer") or "standard"
        from ..analysis import get_analyzer

        a = get_analyzer(analyzer_name)
        return {"tokens": [
            {"token": t.term, "start_offset": t.start, "end_offset": t.end,
             "type": "<ALPHANUM>", "position": t.position + 1}
            for t in a.analyze(text if isinstance(text, str) else " ".join(text))
        ]}

    rc.register("GET,POST", "/_analyze", analyze)
    rc.register("GET,POST", "/{index}/_analyze", analyze)

    rc.register("GET", "/_stats", lambda r: {"indices": client.stats()})
    rc.register("GET", "/{index}/_stats",
                lambda r: {"indices": client.stats(r.path_params["index"])})
    rc.register("GET", "/_segments", lambda r: {"indices": client.stats()})

    # --- cluster admin ------------------------------------------------------
    rc.register("GET", "/_cluster/health",
                lambda r: client.cluster_health(
                    wait_for_status=r.param("wait_for_status"),
                    timeout=float(str(r.param("timeout", "10")).rstrip("s"))))
    rc.register("GET", "/_cluster/health/{index}",
                lambda r: client.cluster_health(index=r.path_params["index"]))
    rc.register("GET", "/_cluster/state", lambda r: client.cluster_state())
    rc.register("GET", "/_cluster/state/{metric}",
                lambda r: client.cluster_state(metric=r.path_params["metric"]))
    rc.register("GET", "/_cluster/state/{metric}/{index}",
                lambda r: client.cluster_state(metric=r.path_params["metric"],
                                               index=r.path_params["index"]))
    rc.register("GET", "/_cluster/pending_tasks", lambda r: client.pending_tasks())
    rc.register("PUT", "/_cluster/settings",
                lambda r: client.cluster_update_settings(_parse_body(r)))
    rc.register("POST", "/_cluster/reroute",
                lambda r: client.cluster_reroute(_parse_body(r)))
    rc.register("GET", "/_nodes", lambda r: client.nodes_info())
    rc.register("GET", "/_nodes/stats", lambda r: client.nodes_stats())
    rc.register("GET", "/_cluster/nodes/hot_threads", lambda r: _hot_threads())
    rc.register("GET", "/_nodes/hot_threads", lambda r: _hot_threads())

    def _hot_threads():
        """ref: monitor/jvm/HotThreads — stacks of the busiest threads."""
        import sys
        import traceback

        out = []
        frames = sys._current_frames()
        import threading as _th

        names = {t.ident: t.name for t in _th.enumerate()}
        for tid, frame in list(frames.items())[:10]:
            stack = "".join(traceback.format_stack(frame, limit=8))
            out.append(f"::: [{names.get(tid, tid)}]\n{stack}")
        return RestResponse(200, "\n".join(out), content_type="text/plain")

    # --- _cat APIs (plain text ops views — ref: rest/action/cat/) -----------
    def cat_health(req):
        h = client.cluster_health()
        return RestResponse(200, f"{h['cluster_name']} {h['status']} "
                                 f"{h['number_of_nodes']} {h['number_of_data_nodes']} "
                                 f"{h['active_shards']} {h['unassigned_shards']}\n",
                            content_type="text/plain")

    def cat_nodes(req):
        state = node.cluster_service.state
        lines = []
        for n in state.nodes.nodes:
            marker = "*" if n.id == state.nodes.master_id else "-"
            lines.append(f"{n.name} {marker} {n.transport_address} "
                         f"master_eligible={n.master_eligible} data={n.data}")
        return RestResponse(200, "\n".join(lines) + "\n", content_type="text/plain")

    def cat_indices(req):
        state = node.cluster_service.state
        lines = []
        for name in state.metadata.index_names():
            meta = state.metadata.index(name)
            h = client.cluster_health(index=name)
            try:
                cnt = client.count(name)["count"]
            except SearchEngineError:
                cnt = "-"
            lines.append(f"{h['status']} {name} {meta.number_of_shards} "
                         f"{meta.number_of_replicas} {cnt}")
        return RestResponse(200, "\n".join(lines) + "\n", content_type="text/plain")

    def cat_shards(req):
        state = node.cluster_service.state
        lines = []
        for s in state.routing_table.all_shards():
            kind = "p" if s.primary else "r"
            lines.append(f"{s.index} {s.shard_id} {kind} {s.state} {s.node_id or '-'}")
        return RestResponse(200, "\n".join(lines) + "\n", content_type="text/plain")

    def cat_master(req):
        state = node.cluster_service.state
        m = state.nodes.master
        return RestResponse(200, f"{m.id} {m.name}\n" if m else "-\n",
                            content_type="text/plain")

    def cat_allocation(req):
        state = node.cluster_service.state
        counts: dict[str, int] = {}
        for s in state.routing_table.all_shards():
            if s.node_id:
                counts[s.node_id] = counts.get(s.node_id, 0) + 1
        lines = [f"{nid} {cnt}" for nid, cnt in sorted(counts.items())]
        return RestResponse(200, "\n".join(lines) + "\n", content_type="text/plain")

    def cat_count(req):
        index = req.path_params.get("index")
        c = client.count(index or "_all")["count"]
        return RestResponse(200, f"{c}\n", content_type="text/plain")

    def cat_aliases(req):
        lines = []
        for index, spec in client.get_aliases().items():
            for alias in spec["aliases"]:
                lines.append(f"{alias} {index}")
        return RestResponse(200, "\n".join(lines) + "\n", content_type="text/plain")

    def cat_pending_tasks(req):
        tasks = client.pending_tasks()["tasks"]
        lines = [f"{t['priority']} {t['time_in_queue_millis']}ms {t['source']}"
                 for t in tasks]
        return RestResponse(200, "\n".join(lines) + "\n", content_type="text/plain")

    def cat_recovery(req):
        lines = []
        for index, spec in node.indices.stats().items():
            for sid, st in spec["shards"].items():
                lines.append(f"{index} {sid} {st['state']} "
                             f"docs={st['docs']['count']}")
        return RestResponse(200, "\n".join(lines) + "\n", content_type="text/plain")

    def cat_thread_pool(req):
        lines = [f"{name} {st['threads']} {st['completed']}"
                 for name, st in node.threadpool.stats().items()]
        return RestResponse(200, "\n".join(lines) + "\n", content_type="text/plain")

    # --- percolate -----------------------------------------------------------
    def percolate(req):
        return node.percolator.percolate(
            req.path_params["index"], _parse_body(req),
            doc_type=req.path_params["type"], doc_id=req.param("id"),
            version=req.param("version"),
            percolate_index=req.param("percolate_index"),
            percolate_type=req.param("percolate_type"))

    rc.register("GET,POST", "/{index}/{type}/_percolate", percolate)
    rc.register("GET,POST", "/{index}/{type}/{id}/_percolate",
                lambda r: node.percolator.percolate(
                    r.path_params["index"], _parse_body(r),
                    doc_type=r.path_params["type"], doc_id=r.path_params["id"],
                    version=r.param("version"),
                    percolate_index=r.param("percolate_index"),
                    percolate_type=r.param("percolate_type")))
    rc.register("GET,POST", "/{index}/{type}/_percolate/count",
                lambda r: node.percolator.count_percolate(
                    r.path_params["index"], _parse_body(r),
                    doc_type=r.path_params["type"]))
    rc.register("GET,POST", "/{index}/{type}/{id}/_percolate/count",
                lambda r: node.percolator.count_percolate(
                    r.path_params["index"], _parse_body(r),
                    doc_type=r.path_params["type"], doc_id=r.path_params["id"]))

    def mpercolate(req):
        raw = req.body if isinstance(req.body, str) else ""
        lines = [ln for ln in raw.split("\n") if ln.strip()]
        requests = []
        for i in range(0, len(lines) - 1, 2):
            requests.append((json.loads(lines[i]), json.loads(lines[i + 1])))
        return node.percolator.multi_percolate(
            requests, default_index=req.path_params.get("index"),
            default_type=req.path_params.get("type"))

    rc.register("GET,POST", "/_mpercolate", mpercolate)
    rc.register("GET,POST", "/{index}/_mpercolate", mpercolate)
    rc.register("GET,POST", "/{index}/{type}/_mpercolate", mpercolate)

    # --- warmers -------------------------------------------------------------
    def put_warmer(req):
        return client.put_warmer(req.path_params.get("index"),
                                 req.path_params["name"], _parse_body(req),
                                 doc_type=req.path_params.get("type"))

    def get_warmer(req):
        return client.get_warmer(req.path_params.get("index"),
                                 req.path_params.get("name"))

    for suffix in ("_warmer", "_warmers"):
        rc.register("PUT,POST", "/" + suffix + "/{name}", put_warmer)
        rc.register("PUT,POST", "/{index}/" + suffix + "/{name}", put_warmer)
        rc.register("PUT,POST", "/{index}/{type}/" + suffix + "/{name}", put_warmer)
        rc.register("DELETE", "/{index}/" + suffix + "/{name}",
                    lambda r: client.delete_warmer(r.path_params["index"],
                                                   r.path_params["name"]))
    rc.register("GET", "/_warmer", get_warmer)
    rc.register("GET", "/_warmer/{name}", get_warmer)
    rc.register("GET", "/{index}/_warmer", get_warmer)
    rc.register("GET", "/{index}/_warmer/{name}", get_warmer)
    rc.register("GET", "/{index}/{type}/_warmer/{name}", get_warmer)

    # --- legacy status + gateway snapshot ------------------------------------
    rc.register("GET", "/_status", lambda r: client.indices_status())
    rc.register("GET", "/{index}/_status",
                lambda r: client.indices_status(r.path_params["index"]))
    rc.register("POST", "/_gateway/snapshot", lambda r: client.gateway_snapshot())
    rc.register("POST", "/{index}/_gateway/snapshot",
                lambda r: client.gateway_snapshot(r.path_params["index"]))

    # --- snapshot/restore ----------------------------------------------------
    rc.register("PUT,POST", "/_snapshot/{repo}",
                lambda r: client.put_repository(r.path_params["repo"], _parse_body(r)))
    rc.register("GET", "/_snapshot", lambda r: client.get_repository())
    rc.register("GET", "/_snapshot/{repo}",
                lambda r: client.get_repository(r.path_params["repo"]))
    rc.register("DELETE", "/_snapshot/{repo}",
                lambda r: client.delete_repository(r.path_params["repo"]))
    rc.register("POST", "/_snapshot/{repo}/_verify",
                lambda r: client.verify_repository(r.path_params["repo"]))
    rc.register("PUT", "/_snapshot/{repo}/{snapshot}",
                lambda r: client.create_snapshot(r.path_params["repo"],
                                                 r.path_params["snapshot"],
                                                 _parse_body(r)))
    rc.register("GET", "/_snapshot/{repo}/{snapshot}",
                lambda r: client.get_snapshots(r.path_params["repo"],
                                               r.path_params["snapshot"]))
    rc.register("GET", "/_snapshot/{repo}/{snapshot}/_status",
                lambda r: client.snapshot_status(r.path_params["repo"],
                                                 r.path_params["snapshot"]))
    rc.register("DELETE", "/_snapshot/{repo}/{snapshot}",
                lambda r: client.delete_snapshot(r.path_params["repo"],
                                                 r.path_params["snapshot"]))
    rc.register("POST", "/_snapshot/{repo}/{snapshot}/_restore",
                lambda r: client.restore_snapshot(r.path_params["repo"],
                                                  r.path_params["snapshot"],
                                                  _parse_body(r)))

    rc.register("GET", "/_cat/health", cat_health)
    rc.register("GET", "/_cat/nodes", cat_nodes)
    rc.register("GET", "/_cat/indices", cat_indices)
    rc.register("GET", "/_cat/shards", cat_shards)
    rc.register("GET", "/_cat/master", cat_master)
    rc.register("GET", "/_cat/allocation", cat_allocation)
    rc.register("GET", "/_cat/count", cat_count)
    rc.register("GET", "/_cat/count/{index}", cat_count)
    rc.register("GET", "/_cat/aliases", cat_aliases)
    rc.register("GET", "/_cat/pending_tasks", cat_pending_tasks)
    rc.register("GET", "/_cat/recovery", cat_recovery)
    rc.register("GET", "/_cat/thread_pool", cat_thread_pool)
    rc.register("GET", "/_cat", lambda r: RestResponse(
        200, "".join(f"/_cat/{n}\n" for n in (
            "health", "nodes", "indices", "shards", "master", "allocation", "count",
            "aliases", "pending_tasks", "recovery", "thread_pool")),
        content_type="text/plain"))

    # plugin-contributed routes (ref: plugins contribute REST handlers)
    if getattr(node, "plugins", None) is not None:
        node.plugins.rest_routes(rc, node)
    return rc
