"""Snapshot / restore over blobstore repositories.

Analogue of snapshots/ + repositories/ + common/blobstore/ (SURVEY.md §2.13/§5.4.3):
- Repository: named blob container (fs impl — the reference's FsRepository; the URL
  read-only variant is `FsRepository(readonly=True)`).
- Snapshots are INCREMENTAL per shard: segment files are copied by (name, checksum);
  files already present in the repo from earlier snapshots are reused
  (BlobStoreIndexShardRepository semantics).
- Snapshot metadata carries the cluster MetaData subset (settings/mappings/aliases) so
  restore can recreate indices wholesale (RestoreService).
- Coordination: master-driven; each primary shard is snapshotted/restored via a shard
  transport action on its owning node (cluster-state-tracked in the reference; here the
  master action drives shards synchronously and records state in the repo).
"""

from __future__ import annotations

import json
import os
import shutil
import time

from .common.errors import (
    RepositoryMissingError,
    SearchEngineError,
    SnapshotError,
    SnapshotMissingError,
)
from .common.logging import get_logger

A_SNAPSHOT_SHARD = "internal:snapshot/shard/create"
A_RESTORE_SHARD = "internal:snapshot/shard/restore"


class FsRepository:
    """ref: repositories/fs/FsRepository.java — a directory of blobs + metadata."""

    type = "fs"

    def __init__(self, name: str, location: str, readonly: bool = False):
        if "://" in location:
            # regression guard: a URL passed as an fs location used to be
            # makedirs()'d literally, leaking an `http:` dir at the cwd root
            raise SnapshotError(
                f"fs repository location [{location}] is a URL — use a "
                f"[url] type repository for read-only URL access")
        self.name = name
        self.location = location
        self.readonly = readonly
        os.makedirs(os.path.join(location, "blobs"), exist_ok=True)
        os.makedirs(os.path.join(location, "snapshots"), exist_ok=True)

    # blob layer -------------------------------------------------------------
    def blob_path(self, checksum: int, name: str) -> str:
        return os.path.join(self.location, "blobs", f"{checksum}_{name}")

    def put_file(self, src_path: str, name: str, checksum: int) -> str:
        if self.readonly:
            raise SnapshotError(f"repository [{self.name}] is readonly")
        dst = self.blob_path(checksum, name)
        if not os.path.exists(dst):  # incremental: identical blob reused
            shutil.copyfile(src_path, dst)
        return os.path.basename(dst)

    def get_file(self, blob_name: str, dst_path: str):
        src = os.path.join(self.location, "blobs", blob_name)
        shutil.copyfile(src, dst_path)

    # snapshot metadata -------------------------------------------------------
    def snapshot_meta_path(self, snapshot: str) -> str:
        return os.path.join(self.location, "snapshots", f"{snapshot}.json")

    def write_snapshot(self, snapshot: str, meta: dict):
        if self.readonly:
            raise SnapshotError(f"repository [{self.name}] is readonly")
        with open(self.snapshot_meta_path(snapshot), "w") as fh:
            json.dump(meta, fh)
        self._write_index()

    def _write_index(self):
        # snapshots/index.json lets read-only URL repositories (no directory
        # listing over http) enumerate snapshots
        with open(os.path.join(self.location, "snapshots", "index.json"), "w") as fh:
            json.dump(self.list_snapshots(), fh)

    def read_snapshot(self, snapshot: str) -> dict:
        p = self.snapshot_meta_path(snapshot)
        if not os.path.exists(p):
            raise SnapshotMissingError(f"[{self.name}:{snapshot}] missing")
        with open(p) as fh:
            return json.load(fh)

    def list_snapshots(self) -> list[str]:
        return sorted(
            n[:-5] for n in os.listdir(os.path.join(self.location, "snapshots"))
            if n.endswith(".json") and n != "index.json"
        )

    def delete_snapshot(self, snapshot: str):
        p = self.snapshot_meta_path(snapshot)
        if os.path.exists(p):
            os.unlink(p)
        self._write_index()
        # blobs referenced by other snapshots survive; orphan cleanup:
        referenced: set[str] = set()
        for s in self.list_snapshots():
            meta = self.read_snapshot(s)
            for idx in meta.get("indices", {}).values():
                for shard in idx.get("shards", {}).values():
                    referenced.update(shard.get("files", {}).values())
        blob_dir = os.path.join(self.location, "blobs")
        for blob in os.listdir(blob_dir):
            if blob not in referenced:
                os.unlink(os.path.join(blob_dir, blob))


class UrlRepository:
    """Read-only repository addressed by URL (ref: repositories/uri/URLRepository.java
    + common/blobstore/url/URLBlobStore.java — read-only restore source).

    `file://` URLs resolve to a local directory; `http(s)://` URLs are fetched
    with urllib (restore from a snapshot server). All mutations raise.
    """

    type = "url"
    readonly = True

    def __init__(self, name: str, url: str):
        from urllib.parse import urlparse

        self.name = name
        self.url = url.rstrip("/")
        parsed = urlparse(url)
        if parsed.scheme in ("", "file"):
            self._local = parsed.path if parsed.scheme == "file" else url
            if not os.path.isdir(self._local):
                raise SnapshotError(
                    f"url repository [{name}]: directory [{self._local}] not found")
        elif parsed.scheme in ("http", "https"):
            self._local = None
        else:
            raise SnapshotError(
                f"url repository [{name}]: unsupported scheme [{parsed.scheme}]")
        self.location = self._local or self.url  # for wire requests / display

    # read side ---------------------------------------------------------------
    def _fetch(self, relpath: str) -> bytes:
        if self._local is not None:
            p = os.path.join(self._local, relpath)
            if not os.path.exists(p):
                raise SnapshotMissingError(f"[{self.name}] blob [{relpath}] missing")
            with open(p, "rb") as fh:
                return fh.read()
        import urllib.error
        import urllib.request

        try:
            with urllib.request.urlopen(f"{self.url}/{relpath}", timeout=30) as resp:
                return resp.read()
        except urllib.error.HTTPError as e:
            if e.code == 404:
                raise SnapshotMissingError(
                    f"[{self.name}] blob [{relpath}] missing") from e
            raise SnapshotError(f"[{self.name}] fetch [{relpath}]: {e}") from e
        except urllib.error.URLError as e:
            raise SnapshotError(f"[{self.name}] unreachable: {e}") from e

    def get_file(self, blob_name: str, dst_path: str):
        with open(dst_path, "wb") as fh:
            fh.write(self._fetch(f"blobs/{blob_name}"))

    def read_snapshot(self, snapshot: str) -> dict:
        return json.loads(self._fetch(f"snapshots/{snapshot}.json"))

    def list_snapshots(self) -> list[str]:
        if self._local is not None:
            snap_dir = os.path.join(self._local, "snapshots")
            if not os.path.isdir(snap_dir):
                return []
            return sorted(n[:-5] for n in os.listdir(snap_dir)
                          if n.endswith(".json") and n != "index.json")
        # http: directory listing isn't part of the protocol — the writer
        # maintains snapshots/index.json for exactly this
        try:
            return sorted(json.loads(self._fetch("snapshots/index.json")))
        except SnapshotMissingError:
            return []

    def verify_readable(self):
        self.list_snapshots()

    # write side: always refused ---------------------------------------------
    def _ro(self):
        raise SnapshotError(f"repository [{self.name}] is readonly (url)")

    def put_file(self, *a, **k):
        self._ro()

    def write_snapshot(self, *a, **k):
        self._ro()

    def delete_snapshot(self, *a, **k):
        self._ro()


class SnapshotsService:
    """Master-side coordinator + shard-level handlers (registered on every node)."""

    def __init__(self, node):
        self.node = node
        self.repositories: dict[str, FsRepository] = {}
        self.logger = get_logger("snapshots", node=node.name)
        node.transport.register_handler(A_SNAPSHOT_SHARD, self._handle_snapshot_shard)
        node.transport.register_handler(A_RESTORE_SHARD, self._handle_restore_shard)
        self._repo_file = os.path.join(node.data_path, "_state", "repositories.json")
        self._load_repos()

    # repositories ------------------------------------------------------------
    def put_repository(self, name: str, body: dict) -> dict:
        rtype = body.get("type", "fs")
        settings = body.get("settings", {})
        if rtype == "fs":
            location = settings.get("location")
            if not location:
                raise SnapshotError("fs repository requires settings.location")
            self.repositories[name] = FsRepository(name, location)
        elif rtype == "url":
            url = settings.get("url")
            if not url:
                raise SnapshotError("url repository requires settings.url")
            self.repositories[name] = UrlRepository(name, url)
        else:
            raise SnapshotError(f"unknown repository type [{rtype}]")
        self._save_repos(body, name)
        return {"acknowledged": True}

    def get_repository(self, name: str | None = None) -> dict:
        def spec(r):
            if r.type == "url":
                return {"type": "url", "settings": {"url": r.url}}
            return {"type": "fs", "settings": {"location": r.location}}

        if name:
            return {name: spec(self._repo(name))}
        return {n: spec(r) for n, r in self.repositories.items()}

    def delete_repository(self, name: str) -> dict:
        if name not in self.repositories:
            raise RepositoryMissingError(f"[{name}] missing")
        del self.repositories[name]
        self._save_repos(None, name, delete=True)
        return {"acknowledged": True}

    def verify_repository(self, name: str) -> dict:
        repo = self._repo(name)
        if getattr(repo, "readonly", False):
            # read-only repos are verified by a read, not a probe write
            if isinstance(repo, UrlRepository):
                repo.verify_readable()
            else:
                repo.list_snapshots()
        else:
            probe = os.path.join(repo.location, ".verify")
            with open(probe, "w") as fh:
                fh.write("ok")
            os.unlink(probe)
        return {"nodes": {self.node.node_id: {"name": self.node.name}}}

    def _repo(self, name: str) -> FsRepository:
        repo = self.repositories.get(name)
        if repo is None:
            raise RepositoryMissingError(f"[{name}] missing")
        return repo

    def _load_repos(self):
        if os.path.exists(self._repo_file):
            with open(self._repo_file) as fh:
                for name, body in json.load(fh).items():
                    try:
                        self.put_repository(name, body)
                    except SnapshotError:
                        pass

    def _save_repos(self, body, name, delete=False):
        data = {}
        if os.path.exists(self._repo_file):
            with open(self._repo_file) as fh:
                data = json.load(fh)
        if delete:
            data.pop(name, None)
        elif body is not None:
            data[name] = body
        os.makedirs(os.path.dirname(self._repo_file), exist_ok=True)
        with open(self._repo_file, "w") as fh:
            json.dump(data, fh)

    # snapshot ----------------------------------------------------------------
    def create_snapshot(self, repo_name: str, snapshot: str, body: dict | None = None) -> dict:
        repo = self._repo(repo_name)
        if getattr(repo, "readonly", False):
            # guard BEFORE the shard fan-out — data nodes write blobs directly,
            # which would bypass the final write_snapshot readonly check
            raise SnapshotError(f"repository [{repo_name}] is readonly")
        state = self.node.cluster_service.state
        body = body or {}
        indices = state.metadata.resolve_indices(body.get("indices", "_all"))
        t0 = time.time()
        meta: dict = {
            "snapshot": snapshot, "state": "IN_PROGRESS",
            "start_time_ms": int(t0 * 1000), "indices": {},
        }
        failures = []
        # gate rebalancing of these indices while their primaries stream out
        # (SnapshotInProgressAllocationDecider reads this set)
        alloc = getattr(self.node, "allocation", None)
        if alloc is not None:
            alloc.snapshotting_indices.update(indices)
        try:
            failures = self._snapshot_indices(state, indices, repo, meta)
        finally:
            if alloc is not None:
                alloc.snapshotting_indices.difference_update(indices)
        meta["state"] = "SUCCESS" if not failures else "PARTIAL"
        meta["failures"] = failures
        meta["end_time_ms"] = int(time.time() * 1000)
        repo.write_snapshot(snapshot, meta)
        return {"snapshot": {"snapshot": snapshot, "state": meta["state"],
                             "indices": list(meta["indices"]),
                             "failures": failures,
                             "duration_in_millis": meta["end_time_ms"] - meta["start_time_ms"]}}

    def _snapshot_indices(self, state, indices, repo, meta) -> list:
        failures = []
        for index in indices:
            imeta = state.metadata.index(index)
            table = state.routing_table.index(index)
            entry = {"metadata": imeta.to_dict(), "shards": {}}
            for grp in table.shards:
                primary = grp.primary
                if primary is None or not primary.active:
                    failures.append(f"[{index}][{grp.shards[0].shard_id}] primary inactive")
                    continue
                node = state.nodes.get(primary.node_id)
                try:
                    r = self.node.transport.submit_request(node, A_SNAPSHOT_SHARD, {
                        "index": index, "shard": primary.shard_id,
                        "repo_location": repo.location}, timeout=120.0)
                    entry["shards"][str(primary.shard_id)] = {"files": r["files"]}
                except SearchEngineError as e:
                    failures.append(f"[{index}][{primary.shard_id}] {e}")
            meta["indices"][index] = entry
        return failures

    def _handle_snapshot_shard(self, request, channel):
        """Data-node side: flush + copy this shard's files into the repo (incremental)."""
        shard = self.node.indices.index_service(request["index"]).shard(request["shard"])
        shard.engine.flush(force=True)
        repo = FsRepository("_inline", request["repo_location"])
        files = {}
        store = shard.engine.store
        for name, info in store.list_files().items():
            blob = repo.put_file(os.path.join(store.dir, name), name, info["checksum"])
            files[name] = blob
        return {"files": files}

    def get_snapshots(self, repo_name: str, snapshot: str | None = None) -> dict:
        repo = self._repo(repo_name)
        names = [snapshot] if snapshot and snapshot != "_all" else repo.list_snapshots()
        out = []
        for n in names:
            meta = repo.read_snapshot(n)
            out.append({"snapshot": n, "state": meta["state"],
                        "indices": list(meta.get("indices", {})),
                        "start_time_in_millis": meta.get("start_time_ms"),
                        "end_time_in_millis": meta.get("end_time_ms")})
        return {"snapshots": out}

    def snapshot_status(self, repo_name: str, snapshot: str) -> dict:
        meta = self._repo(repo_name).read_snapshot(snapshot)
        return {"snapshots": [{"snapshot": snapshot, "state": meta["state"],
                               "shards_stats": {
                                   "done": sum(len(i["shards"]) for i in
                                               meta["indices"].values()),
                                   "failed": len(meta.get("failures", []))}}]}

    def delete_snapshot(self, repo_name: str, snapshot: str) -> dict:
        self._repo(repo_name).delete_snapshot(snapshot)
        return {"acknowledged": True}

    # restore -----------------------------------------------------------------
    def restore_snapshot(self, repo_name: str, snapshot: str, body: dict | None = None) -> dict:
        repo = self._repo(repo_name)
        meta = repo.read_snapshot(snapshot)
        body = body or {}
        wanted = body.get("indices")
        rename_pattern = body.get("rename_pattern")
        rename_replacement = body.get("rename_replacement", "")
        client = self.node.client()
        restored = []
        for index, entry in meta["indices"].items():
            if wanted and index not in ([wanted] if isinstance(wanted, str) else wanted):
                continue
            target = index
            if rename_pattern:
                import re as _re

                target = _re.sub(rename_pattern, rename_replacement, index)
            imeta = entry["metadata"]
            if self.node.cluster_service.state.metadata.has_index(target):
                raise SnapshotError(f"index [{target}] already exists — close/delete first")
            settings = dict(imeta.get("settings", {}))
            client.create_index(target, {
                "settings": {k: v for k, v in settings.items()},
                "mappings": {t: json.loads(m) if isinstance(m, str) else m
                             for t, m in imeta.get("mappings", {}).items()},
            })
            client.cluster_health(wait_for_status="yellow", timeout=10)
            state = self.node.cluster_service.state
            table = state.routing_table.index(target)
            for grp in table.shards:
                primary = grp.primary
                sid = str(grp.shards[0].shard_id)
                shard_files = entry["shards"].get(sid, {}).get("files", {})
                node = state.nodes.get(primary.node_id)
                self.node.transport.submit_request(node, A_RESTORE_SHARD, {
                    "index": target, "shard": int(sid),
                    "repo_type": repo.type,
                    "repo_location": repo.url if repo.type == "url" else repo.location,
                    "files": shard_files,
                }, timeout=120.0)
            restored.append(target)
        return {"snapshot": {"snapshot": snapshot, "indices": restored,
                             "shards": {"failed": 0}}}

    def _handle_restore_shard(self, request, channel):
        svc = self.node.indices.index_service(request["index"])
        shard = svc.shard(request["shard"])
        if request.get("repo_type") == "url":
            repo = UrlRepository("_inline", request["repo_location"])
        else:
            repo = FsRepository("_inline", request["repo_location"], readonly=True)
        store_dir = shard.engine.store.dir
        translog_dir = shard.engine.translog.dir
        # close the live engine FIRST, then wipe store + translog (a stale translog
        # generation would replay foreign ops over the restored commit)
        svc.remove_shard(request["shard"])
        for d in (store_dir, translog_dir):
            for name in list(os.listdir(d)):
                os.unlink(os.path.join(d, name))
        for name, blob in request["files"].items():
            repo.get_file(blob, os.path.join(store_dir, name))
        new_shard = svc.create_shard(request["shard"], primary=True)
        new_shard.engine.recover_from_store()
        new_shard.engine.refresh()
        new_shard.state = "STARTED"
        return {"ok": True}
