"""Scripting: a sandboxed expression language.

Analogue of script/ScriptService.java (SURVEY.md §2.9 sidebars — mvel default in the
reference). Instead of embedding a JVM expression language, scripts are a restricted
Python-expression subset compiled through the `ast` module with a strict whitelist:
names, numeric literals, arithmetic, comparisons, boolean ops, ternaries, math functions,
`doc['field'].value` access, `_score`, and script params. No attribute access beyond the
whitelist, no calls except whitelisted functions, no subscripts except on `doc`/params —
so user scripts cannot escape (same spirit as the reference's sandboxed mvel).

SURVEY.md §7 notes the design goal of lowering a compiled expression subset to XLA for
device-side scoring; this module keeps the AST around (`CompiledScript.tree`) so a later
round can lower simple arithmetic scripts to jnp column expressions.
"""

from __future__ import annotations

import ast
import math

import numpy as np

from ..common.errors import ScriptError

_ALLOWED_FUNCS = {
    "abs": abs, "min": min, "max": max, "round": round,
    "sqrt": math.sqrt, "log": math.log, "log10": math.log10, "exp": math.exp,
    "pow": pow, "floor": math.floor, "ceil": math.ceil,
    "sin": math.sin, "cos": math.cos, "tan": math.tan,
}

_ALLOWED_NODES = (
    ast.Expression, ast.BinOp, ast.UnaryOp, ast.BoolOp, ast.Compare, ast.IfExp,
    ast.Name, ast.Load, ast.Constant, ast.Subscript, ast.Attribute, ast.Call,
    ast.Add, ast.Sub, ast.Mult, ast.Div, ast.FloorDiv, ast.Mod, ast.Pow,
    ast.USub, ast.UAdd, ast.Not, ast.And, ast.Or,
    ast.Eq, ast.NotEq, ast.Lt, ast.LtE, ast.Gt, ast.GtE, ast.In, ast.NotIn,
)

_ALLOWED_ATTRS = {"value", "values", "empty"}


class CompiledScript:
    def __init__(self, source: str, params: dict):
        self.source = source
        self.params = dict(params or {})
        try:
            self.tree = ast.parse(source, mode="eval")
        except SyntaxError as e:
            raise ScriptError(f"script compile error: {e}") from None
        self._validate(self.tree)
        self._code = compile(self.tree, "<script>", "eval")

    def _validate(self, tree: ast.AST):
        for node in ast.walk(tree):
            if not isinstance(node, _ALLOWED_NODES):
                raise ScriptError(
                    f"disallowed construct [{type(node).__name__}] in script [{self.source}]"
                )
            if isinstance(node, ast.Attribute) and node.attr not in _ALLOWED_ATTRS:
                raise ScriptError(f"disallowed attribute [{node.attr}]")
            if isinstance(node, ast.Call):
                if not isinstance(node.func, ast.Name) or node.func.id not in _ALLOWED_FUNCS:
                    raise ScriptError("only whitelisted functions may be called")

    def __call__(self, doc, _score: float = 0.0, **extra):
        env = {"doc": doc, "_score": _score, **_ALLOWED_FUNCS, **self.params, **extra}
        try:
            return eval(self._code, {"__builtins__": {}}, env)  # noqa: S307 — sandboxed AST
        except ScriptError:
            raise
        except Exception as e:  # noqa: BLE001
            raise ScriptError(f"script runtime error: {e}") from None


class ColumnVectorizer:
    """Lower a sandboxed expression to COLUMN math — the whole segment in a few
    numpy ops instead of one Python eval per doc (SURVEY §7 hard-parts: "a compiled
    expression subset that lowers to XLA"; numpy is the host tier of that design,
    the arrays are ready to jnp-lift).

    Supported subset: arithmetic/comparison/boolean ops, IfExp, whitelisted calls,
    params, _score, and doc['field'].value / .empty over numeric columns. Returns
    None from vectorize() when the tree goes outside the subset — callers fall back
    to the per-doc path, so behavior never changes, only speed."""

    _FUNCS = {
        "abs": np.abs, "sqrt": np.sqrt, "log": np.log, "log10": np.log10,
        "exp": np.exp, "floor": np.floor, "ceil": np.ceil,
        "sin": np.sin, "cos": np.cos, "tan": np.tan, "round": np.round,
        "pow": np.power, "min": np.minimum, "max": np.maximum,
    }
    _BINOPS = {
        ast.Add: np.add, ast.Sub: np.subtract, ast.Mult: np.multiply,
        ast.Div: np.divide, ast.FloorDiv: np.floor_divide, ast.Mod: np.mod,
        ast.Pow: np.power,
    }
    _CMPOPS = {
        ast.Eq: np.equal, ast.NotEq: np.not_equal, ast.Lt: np.less,
        ast.LtE: np.less_equal, ast.Gt: np.greater, ast.GtE: np.greater_equal,
    }
    # array primitives as class attrs so JaxVectorizer can swap in jnp and reuse
    # the exact same AST walk (one lowering, two backends)
    _where = staticmethod(np.where)
    _isnan = staticmethod(np.isnan)
    _negative = staticmethod(np.negative)
    _logical_not = staticmethod(np.logical_not)

    def __init__(self, script: "CompiledScript", columns, scores):
        """columns: field name -> float64[D] (NaN = missing); scores: float[D]."""
        self.script = script
        self.columns = columns
        self.scores = scores
        self.used_fields: set[str] = set()

    def vectorize(self):
        try:
            with np.errstate(all="ignore"):  # domain errors surface as NaN/inf,
                # which the caller routes to the per-doc path (where they raise
                # ScriptError exactly as before)
                return self._visit(self.script.tree.body)
        except Exception:  # noqa: BLE001 — ANY lowering trouble (numpy arity
            # mismatches, unexpected dtypes, subset gaps) means per-doc fallback,
            # never a changed or crashed search
            return None

    def _visit(self, node):
        if isinstance(node, ast.Constant) and isinstance(node.value, (int, float,
                                                                      bool)):
            return node.value
        if isinstance(node, ast.Name):
            # params FIRST — the per-doc env is {doc, _score, **funcs, **params},
            # so params shadow _score and the builtins; mirror that
            if node.id in self.script.params:
                v = self.script.params[node.id]
                if isinstance(v, (int, float, bool)):
                    return v
                raise _NotVectorizable
            if node.id == "_score":
                return self.scores
            raise _NotVectorizable
        if isinstance(node, ast.BinOp) and type(node.op) in self._BINOPS:
            return self._BINOPS[type(node.op)](self._visit(node.left),
                                               self._visit(node.right))
        if isinstance(node, ast.UnaryOp):
            v = self._visit(node.operand)
            if isinstance(node.op, ast.USub):
                return self._negative(v)
            if isinstance(node.op, ast.UAdd):
                return v
            if isinstance(node.op, ast.Not):
                return self._logical_not(v)
            raise _NotVectorizable
        if isinstance(node, ast.Compare) and len(node.ops) == 1 \
                and type(node.ops[0]) in self._CMPOPS:
            return self._CMPOPS[type(node.ops[0])](self._visit(node.left),
                                                   self._visit(node.comparators[0]))
        if isinstance(node, ast.BoolOp):
            # Python and/or return VALUES, not booleans: a and b == b if a else a
            vals = [self._visit(v) for v in node.values]
            out = vals[0]
            for v in vals[1:]:
                truthy = out != 0
                out = self._where(truthy, v, out) if isinstance(node.op, ast.And) \
                    else self._where(truthy, out, v)
            return out
        if isinstance(node, ast.IfExp):
            return self._where(self._visit(node.test), self._visit(node.body),
                               self._visit(node.orelse))
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                and node.func.id in self._FUNCS and not node.keywords \
                and node.func.id not in self.script.params:  # params shadow funcs
            args = [self._visit(a) for a in node.args]
            fn = self._FUNCS[node.func.id]
            if node.func.id in ("min", "max"):
                out = args[0]
                for a in args[1:]:
                    out = fn(out, a)
                return out
            return fn(*args)
        if isinstance(node, ast.Attribute) and node.attr in ("value", "empty") \
                and isinstance(node.value, ast.Subscript):
            sub = node.value
            if isinstance(sub.value, ast.Name) and sub.value.id == "doc" \
                    and isinstance(sub.slice, ast.Constant):
                col = self.columns(str(sub.slice.value))
                if col is None:
                    raise _NotVectorizable
                self.used_fields.add(str(sub.slice.value))
                return self._isnan(col) if node.attr == "empty" else col
        raise _NotVectorizable


class _NotVectorizable(Exception):
    pass


_jax_vectorizer_cls = None


def jax_vectorizer_cls():
    """The jnp twin of ColumnVectorizer — same AST walk, jax.numpy primitives.

    Used under `jit` tracing: the walk runs once at trace time and emits the
    script as fused XLA ops with `_score` bound to the dense device score array
    and doc columns bound to device-resident rows. This is SURVEY §7's "compiled
    expression subset that lowers to XLA" (the device tier; ColumnVectorizer is
    the host tier)."""
    global _jax_vectorizer_cls
    if _jax_vectorizer_cls is None:
        import jax.numpy as jnp

        class JaxVectorizer(ColumnVectorizer):
            _FUNCS = {
                "abs": jnp.abs, "sqrt": jnp.sqrt, "log": jnp.log,
                "log10": jnp.log10, "exp": jnp.exp, "floor": jnp.floor,
                "ceil": jnp.ceil, "sin": jnp.sin, "cos": jnp.cos,
                "tan": jnp.tan, "round": jnp.round, "pow": jnp.power,
                "min": jnp.minimum, "max": jnp.maximum,
            }
            _BINOPS = {
                ast.Add: jnp.add, ast.Sub: jnp.subtract, ast.Mult: jnp.multiply,
                ast.Div: jnp.divide, ast.FloorDiv: jnp.floor_divide,
                ast.Mod: jnp.mod, ast.Pow: jnp.power,
            }
            _CMPOPS = {
                ast.Eq: jnp.equal, ast.NotEq: jnp.not_equal, ast.Lt: jnp.less,
                ast.LtE: jnp.less_equal, ast.Gt: jnp.greater,
                ast.GtE: jnp.greater_equal,
            }
            _where = staticmethod(jnp.where)
            _isnan = staticmethod(jnp.isnan)
            _negative = staticmethod(jnp.negative)
            _logical_not = staticmethod(jnp.logical_not)

            def vectorize(self):
                # no errstate / no exception swallowing: under jit tracing a
                # failure must propagate so the caller can fall back BEFORE
                # compiling a wrong program
                return self._visit(self.script.tree.body)

        _jax_vectorizer_cls = JaxVectorizer
    return _jax_vectorizer_cls


def script_uses_score(script: "CompiledScript") -> bool:
    """True if the script reads `_score` (params shadow it, mirroring the eval
    env construction in CompiledScript.__call__)."""
    if "_score" in script.params:
        return False
    return any(isinstance(n, ast.Name) and n.id == "_score"
               for n in ast.walk(script.tree))


def script_vector_info(script: "CompiledScript") -> tuple[bool, tuple]:
    """(vectorizable, used_fields) — probed once with dummy 2-element columns and
    cached on the CompiledScript (compile_script caches those, so classification
    at lower time and execution share one probe). The subsets of ColumnVectorizer
    and JaxVectorizer are identical by construction (same walk, parallel op
    tables)."""
    info = getattr(script, "_vector_info", None)
    if info is None:
        probe = ColumnVectorizer(script, lambda f: np.zeros(2), np.zeros(2))
        ok = probe.vectorize() is not None
        info = (ok, tuple(sorted(probe.used_fields)))
        script._vector_info = info
    return info


def script_vectorizable(script: "CompiledScript") -> bool:
    return script_vector_info(script)[0]


SUPPORTED_LANGS = {None, "mvel", "expression", "native", "python"}


def check_lang(lang):
    """ref: ScriptService — unknown `lang` rejects the request."""
    if lang not in SUPPORTED_LANGS:
        raise ScriptError(f"script_lang not supported [{lang}]")


class _AttrDict:
    """Attribute-style access over a plain dict, so mvel-shaped update scripts
    (`ctx._source.foo = ...`) run unmodified (the reference's default lang is mvel —
    script/ScriptService.java:77)."""

    __slots__ = ("_d",)

    def __init__(self, d: dict):
        object.__setattr__(self, "_d", d)

    def __getattr__(self, k):
        try:
            v = self._d[k]
        except KeyError:
            raise AttributeError(k) from None
        return _AttrDict(v) if isinstance(v, dict) else v

    def __setattr__(self, k, v):
        self._d[k] = v

    def __getitem__(self, k):
        v = self._d[k]
        return _AttrDict(v) if isinstance(v, dict) else v

    def __setitem__(self, k, v):
        self._d[k] = v

    def __contains__(self, k):
        return k in self._d


_STMT_NODES = _ALLOWED_NODES + (
    ast.Module, ast.Assign, ast.AugAssign, ast.Expr, ast.If, ast.Store,
    ast.List, ast.Dict, ast.Tuple,
)


class UpdateScript:
    """Statement-mode script over a mutable `ctx` (ref: update scripts mutate
    ctx._source / ctx.op / ctx._ttl — TransportUpdateAction.java:212-270)."""

    def __init__(self, source: str, params: dict):
        self.source = source
        self.params = dict(params or {})
        try:
            self.tree = ast.parse(source, mode="exec")
        except SyntaxError as e:
            raise ScriptError(f"script compile error: {e}") from None
        for node in ast.walk(self.tree):
            if not isinstance(node, _STMT_NODES):
                raise ScriptError(
                    f"disallowed construct [{type(node).__name__}] in script "
                    f"[{self.source}]")
            if isinstance(node, ast.Attribute):
                # attribute chains must be rooted at `ctx` (mediated by _AttrDict)
                # and never reach dunders — blocks `().__class__...` escapes
                if node.attr.startswith("__"):
                    raise ScriptError(f"disallowed attribute [{node.attr}]")
                base = node
                while isinstance(base, (ast.Attribute, ast.Subscript)):
                    base = base.value
                if not (isinstance(base, ast.Name) and base.id == "ctx"):
                    raise ScriptError(
                        "attribute access is only allowed on ctx.*")
            if isinstance(node, ast.Call):
                if not isinstance(node.func, ast.Name) or \
                        node.func.id not in _ALLOWED_FUNCS:
                    raise ScriptError("only whitelisted functions may be called")
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) else \
                    [node.target]
                for t in targets:
                    if isinstance(t, ast.Name) and t.id in (
                            *_ALLOWED_FUNCS, "ctx"):
                        raise ScriptError(
                            f"cannot rebind builtin name [{t.id}]")
        self._code = compile(self.tree, "<update-script>", "exec")

    def run(self, ctx: dict, **extra):
        env = {"ctx": _AttrDict(ctx), **_ALLOWED_FUNCS, **self.params, **extra}
        try:
            exec(self._code, {"__builtins__": {}}, env)  # noqa: S102 — sandboxed AST
        except ScriptError:
            raise
        except Exception as e:  # noqa: BLE001
            raise ScriptError(f"script runtime error: {e}") from None
        return ctx


def compile_update_script(source: str, params: dict | None = None,
                          lang=None) -> UpdateScript:
    check_lang(lang)
    return UpdateScript(source, params or {})


_cache: dict[tuple, CompiledScript] = {}

# named scripts (stored via API or loaded from config/scripts by the resource
# watcher). The registry is process-wide because every compile site resolves names
# through module-level compile_script; entries are OWNER-scoped (one sub-entry per
# ScriptService) so one in-process node deleting its file never clobbers another
# node's same-named script — resolution takes the newest owner's source.
_named: dict[str, dict[int, str]] = {}


def _resolve_named(name: str) -> str | None:
    owners = _named.get(name)
    if owners:
        return next(reversed(owners.values()))
    return None


def compile_script(source: str, params: dict | None = None,
                   lang=None) -> CompiledScript:
    check_lang(lang)
    source = _resolve_named(source) or source
    key = (source, tuple(sorted((params or {}).items())))
    try:
        cs = _cache.get(key)
    except TypeError:  # unhashable params
        return CompiledScript(source, params or {})
    if cs is None:
        cs = CompiledScript(source, params or {})
        _cache[key] = cs
    return cs


class ScriptService:
    """Named/stored script registry + language dispatch (parity shell: the single
    supported language is the sandboxed expression subset, like the reference's
    default-language mvel registry). File scripts arrive via
    watcher.ScriptDirectoryListener."""

    def __init__(self, settings=None):
        self._sid = id(self)

    def put(self, name: str, source: str):
        owners = _named.setdefault(name, {})
        owners.pop(self._sid, None)  # re-put moves this owner to newest
        owners[self._sid] = source

    def remove(self, name: str):
        owners = _named.get(name)
        if owners is not None:
            owners.pop(self._sid, None)
            if not owners:
                _named.pop(name, None)

    def compile(self, source_or_name: str, params: dict | None = None) -> CompiledScript:
        return compile_script(source_or_name, params)
