"""CLI launcher: `python -m elasticsearch_tpu [options]`.

Analogue of bin/elasticsearch → bootstrap/Bootstrap.java:143 (SURVEY.md §3.1): prepare
settings (yaml config + -D overrides), build a Node, start transport/discovery/HTTP,
then block until SIGINT/SIGTERM.

Options mirror the reference launcher's surface:
  -Dkey=value          setting override (repeatable; e.g. -Dnode.name=n1)
  --config PATH        elasticsearch.yml-style settings file
  --data PATH          data directory (path.data)
  --http-port N        REST port (default 9200; 0 = ephemeral)
  --transport tcp|local
  --seeds host:port,…  unicast discovery seeds
"""

from __future__ import annotations

import argparse
import signal
import sys
import threading


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="estpu", description=__doc__,
                                 formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("-D", action="append", default=[], metavar="key=value",
                    dest="defines")
    ap.add_argument("--config", default=None)
    ap.add_argument("--data", default=None)
    ap.add_argument("--http-port", type=int, default=9200)
    ap.add_argument("--transport", choices=("tcp", "local"), default="tcp")
    ap.add_argument("--seeds", default=None)
    args = ap.parse_args(argv)

    settings: dict = {}
    if args.config:
        import yaml

        with open(args.config) as f:
            settings.update(yaml.safe_load(f) or {})
    for d in args.defines:
        key, _, value = d.partition("=")
        settings[key] = value
    settings.setdefault("transport.type", args.transport)
    settings.setdefault("http.enabled", True)
    settings.setdefault("http.port", args.http_port)
    if args.seeds:
        settings.setdefault("discovery.zen.ping.unicast.hosts",
                            [s.strip() for s in args.seeds.split(",") if s.strip()])

    from .node import Node

    node = Node(settings=settings, data_path=args.data)
    seeds = settings.get("discovery.zen.ping.unicast.hosts")
    node.start(seeds=list(seeds) if seeds else [])

    stop = threading.Event()

    def shutdown(_sig, _frm):
        stop.set()

    signal.signal(signal.SIGINT, shutdown)
    signal.signal(signal.SIGTERM, shutdown)
    addr = node.local_node.transport_address
    port = node.http.port if node.http else None
    print(f"[estpu] node [{node.name}] started — transport {addr}, http port {port}",
          flush=True)
    stop.wait()
    print("[estpu] shutting down", flush=True)
    node.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
