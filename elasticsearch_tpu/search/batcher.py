"""Cross-request device micro-batching — the serving-path throughput lever.

The bench proves the device path is batch-hungry (BENCH_r05: 128 queries score
in one ~17 ms pipelined launch) yet live serving dispatched ONE request per
device launch, paying a full launch + host merge per query under concurrent
load. DeviceBatcher coalesces concurrent `execute_query_phase` calls into one
bucketed `execute_flat_batch` launch — the same continuous/micro-batching
lever inference servers use (Orca-style iteration batching; the shape of the
reference's per-shard search pooling):

    search pool threads                drainer ("search_batcher" pool)
    ───────────────────                ─────────────────────────────────
    enqueue(plan, key)──►[bounded coalescing queue]
    wait(future)                          │ collect same-key items
         ▲                                ▼
         │                        dispatch batch N+1 ──► device
         └────── fan-out ◄─────── merge batch N     ◄── device

Items coalesce only under an identical key: same segment point-in-time view +
mapper/similarity services + k bucket (k rounds up to a power of two so mixed
page sizes share executables — the kernel runs at the bucket, fan-out trims).
DFS-stats requests bypass the queue entirely (their per-request global stats
would poison the batch's shared weights).

Flush policy — whichever fires first:
  * batch-full  : `search.batch.max_batch` same-key plans are waiting
  * linger      : the oldest item has waited `linger_eff`, where
                  linger_eff = linger_ms * (1 - queued/max_batch), floored at
                  `search.batch.min_linger_ms` — a hot queue shrinks the
                  linger toward zero because latency is only spent when it
                  buys occupancy; a lone request pays at most linger_ms
  * deadline    : now >= tightest enqueued Deadline - EWMA(batch service
                  time) — flushing early leaves budget for the device launch
                  AND the host merge, so PR-3 timeout semantics survive
                  coalescing
  * pending     : a dispatched batch is waiting to be merged — lingering
                  would hold its answered futures hostage to the NEXT batch's
                  linger window; with the device already busy, waiting buys
                  no occupancy, so the queue flushes immediately

Double buffering: the drainer dispatches batch N+1 BEFORE merging batch N, so
batch N's host merge overlaps batch N+1's device compute. The dispatch half
never calls jax.device_get; the merge half performs the batch's single batched
pull (execute._merge_flat_plain) — the tpulint TPU001 baseline stays empty.

Breaker rule: sparse staging buffers and merge canvases are reserved per
BATCH on the request breaker (the coalesced launch is the allocation, not the
per-request share — ops/scoring.launch_flat_sparse). When a coalesced launch
trips a breaker (or fails any other way), the drainer replays each item
individually so only the request that is actually oversized fails with the
429; its neighbors keep their answers.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from concurrent.futures import Future

from ..common import insights as _insights
from ..common import tracing
from ..common.deadline import NO_DEADLINE, Deadline
from ..common.errors import RejectedExecutionError
from ..common.logging import get_logger
from ..common.metrics import HistogramMetric
from ..ops.device_index import _ladder_bucket

_K_MIN = 16  # smallest k bucket (top-10 pages and top-16 share executables)


def _k_bucket(k: int) -> int:
    # autotuned ladder (compilecache "k" dimension) with pow-2-from-16
    # fallback while cold — one executable per k RUNG, not per distinct k
    return _ladder_bucket("k", k, _K_MIN)


class _Item:
    __slots__ = ("family", "key", "payload", "k", "kb", "deadline", "future",
                 "t_enq", "span", "obs")

    def __init__(self, family, key, payload, k: int, kb: int,
                 deadline: Deadline):
        self.family = family
        self.key = key
        self.payload = payload
        self.k = k  # the request's own k (fan-out trims to it)
        self.kb = kb  # the bucketed launch k
        self.deadline = deadline
        self.future: Future = Future()
        self.t_enq = time.monotonic()
        # the enqueuing request's active span (None when untraced): the
        # drainer attributes the shared batch's queue/dispatch/merge/pull
        # timings back to EVERY member's trace through this handle
        self.span = tracing.current_span()
        # the request's always-on insights observation (common/insights.py;
        # None when insights are off): the drainer writes the batch's queue
        # wait + the existing pull window into it with clocks it already
        # reads — the item's Future resolution is the happens-before edge
        # back to the reader
        self.obs = _insights.current()


class _FlatFamily:
    """Coalesces single-shard FlatPlans into execute_flat_batch launches.
    payload = (plan, ShardContext); the batch runs with the LEADER item's
    context — the key guarantees every member sees the identical segment
    view and stats sources, so per-plan weights are identical either way."""

    name = "flat"

    @staticmethod
    def key(ctx, kb: int):
        s = ctx.searcher
        return ("flat", id(ctx.mapper_service), id(ctx.similarity_service),
                tuple(id(seg) for seg in s.segments), kb)

    @staticmethod
    def dispatch(items, kb: int):
        from .execute import dispatch_flat_batch

        ctx = items[0].payload[1]
        return dispatch_flat_batch([it.payload[0] for it in items], ctx, kb)

    @staticmethod
    def fan_out(handle, items):
        from .execute import TopDocs

        merged = handle.merge()
        return [TopDocs(total=td.total, hits=td.hits[: it.k],
                        max_score=td.max_score, timed_out=td.timed_out)
                for it, td in zip(items, merged)]

    @staticmethod
    def execute_single(item):
        from .execute import execute_flat_batch

        plan, ctx = item.payload
        return execute_flat_batch([plan], ctx, item.k)[0]


class _MeshFamily:
    """Coalesces plain mesh searches into one SPMD program launch.
    payload = (plan, MeshSearchExecutor); results fan out as per-query host
    row tuples (shard_row, score_row, doc_row, shard_totals_col, qmax_col) —
    exactly what mesh_serving's assembly consumes. The plan list pads to the
    "q" bucket ladder with zero-clause plans (msm=1 matches nothing) so batch
    sizes share compiled programs."""

    name = "mesh"

    @staticmethod
    def key(executor, kb: int):
        return ("mesh", id(executor), kb)

    @staticmethod
    def dispatch(items, kb: int):
        from .execute import FlatPlan

        executor = items[0].payload[1]
        plans = [it.payload[0] for it in items]
        # the k bucket may round past the program's doc space (the request's
        # own k was validated against doc_pad by mesh_serving) — clamp it
        kb = min(kb, executor.index.doc_pad)
        qb = _ladder_bucket("q", len(plans), 1)
        plans += [FlatPlan([], msm=1, n_must=0, coord_enabled=False, boost=1.0)
                  for _ in range(qb - len(plans))]
        # executor.search pulls its program output itself (one device_get for
        # the whole result pytree) — the mesh family merges at dispatch time
        from ..common.jaxenv import compile_tag

        with compile_tag("mesh"):
            return executor.search(plans, kb)

    @staticmethod
    def fan_out(out, items):
        results = []
        for qi, it in enumerate(items):
            results.append((out.shard[qi].tolist(), out.scores[qi].tolist(),
                            out.doc[qi].tolist(),
                            out.shard_totals[:, qi].tolist(),
                            out.qmax[:, qi].tolist()))
        return results

    @staticmethod
    def execute_single(item):
        plan, executor = item.payload
        out = executor.search([plan], min(item.kb, executor.index.doc_pad))
        return (out.shard[0].tolist(), out.scores[0].tolist(),
                out.doc[0].tolist(), out.shard_totals[:, 0].tolist(),
                out.qmax[:, 0].tolist())


class DeviceBatcher:
    """Per-node coalescing queue + drainer for cross-request device batching.

    Grouping is per coalesce key — which embeds the shard's point-in-time
    segment view — so this IS per-shard batching; one node-level queue simply
    lets a single drainer double-buffer across shards too."""

    def __init__(self, settings=None, threadpool=None, node_name: str = "node"):
        from ..common.settings import Settings

        settings = settings or Settings.EMPTY
        self.enabled = bool(settings.get_bool("search.batch.enabled", True))
        self.max_batch = max(1, settings.get_int("search.batch.max_batch", 64))
        self.linger_s = max(
            0.0, settings.get_float("search.batch.linger_ms", 1.5)) / 1000.0
        self.min_linger_s = max(
            0.0, settings.get_float("search.batch.min_linger_ms", 0.1)) / 1000.0
        self.queue_cap = max(1, settings.get_int("search.batch.queue_size", 1024))
        self.logger = get_logger("search.batcher", node=node_name)
        self._threadpool = threadpool
        self._queue: deque[_Item] = deque()
        self._cv = threading.Condition()
        self._shutdown = False
        self._drainer_started = False
        self._drainer_dead = False
        # EWMA of batch service time (dispatch start -> fan-out done): what the
        # deadline flush subtracts so launch + merge still fit in the budget
        self._ewma_cost = 0.004
        self._stats_lock = threading.Lock()
        self._launches = 0
        self._items_launched = 0  # total items served via coalesced launches
        self._full_flushes = 0
        self._linger_flushes = 0
        self._deadline_flushes = 0
        self._pending_flushes = 0  # flushed early because a merge was waiting
        self._bypassed = 0  # queue full / disabled / drainer dead -> inline
        # profiled requests bypass BEFORE enqueueing (service._execute_flat_
        # single: their per-request sync must not serialize a shared batch) —
        # counted separately so occupancy regressions aren't blamed on load
        self._profile_bypassed = 0
        self._splits = 0  # coalesced launch failed -> per-item replay
        self._device_splits = 0  # splits whose trigger classified as a
        # device fault (common/devicehealth taxonomy) — the containment
        # counter: one poisoned plan replayed away from its neighbors
        # batch service-time tail (dispatch start -> fan-out done): percentile
        # twin of _ewma_cost, exported in /_nodes/stats + Prometheus
        self.service_hist = HistogramMetric()
        self._batch_ids = itertools.count(1)  # trace tag joining members
        # in-flight (dispatching-or-unmerged) batches, OLDEST FIRST, written
        # ONLY by the drainer and read unlocked by the stall watchdog:
        # (batch_id, t_dispatch, family name, occupancy, shard label).
        # Appended BEFORE family.dispatch so a hang INSIDE dispatch (the
        # mesh family executes + pulls there) is visible too; the head is
        # the oldest unresolved batch, so double-buffering (N merging while
        # N+1 is dispatched) still ages N, not N+1. Deque ops under the GIL;
        # a torn watchdog read is at worst one batch stale.
        self._inflight_q: deque[tuple] = deque()
        self._flat = _FlatFamily()
        self._mesh = _MeshFamily()

    # -- public entry points -------------------------------------------------
    def execute(self, plan, ctx, k: int, deadline: Deadline = NO_DEADLINE):
        """Coalesce one shard-local FlatPlan with concurrent callers; blocks
        until the batch lands and returns this plan's TopDocs (hits trimmed
        to k). Falls back to a direct single-plan launch when batching is
        disabled, the queue is saturated, or the drainer has died."""
        k = max(k, 1)
        kb = _k_bucket(k)
        item = _Item(self._flat, self._flat.key(ctx, kb), (plan, ctx), k, kb,
                     deadline or NO_DEADLINE)
        return self._submit(item)

    def execute_mesh(self, plan, executor, k: int,
                     deadline: Deadline = NO_DEADLINE):
        """Coalesce one plain mesh search; returns the per-query host rows
        (shard, score, doc, shard_totals, qmax) mesh_serving assembles from."""
        k = max(k, 1)
        kb = _k_bucket(k)
        item = _Item(self._mesh, self._mesh.key(executor, kb),
                     (plan, executor), k, kb, deadline or NO_DEADLINE)
        return self._submit(item)

    def _submit(self, item: _Item):
        if not self.enabled:
            with self._stats_lock:
                self._bypassed += 1
            return item.family.execute_single(item)
        with self._cv:
            # _drainer_dead is re-checked HERE, under the condition: the death
            # path flips it and drains the queue under the same lock, so an
            # item can never land in a queue nobody will ever service
            if (self._shutdown or self._drainer_dead
                    or len(self._queue) >= self.queue_cap):
                inline = True
            else:
                self._queue.append(item)
                self._cv.notify_all()
                inline = False
        if inline:
            # a saturated coalescing queue must not become a second rejection
            # layer on top of the search pool's — serve directly instead
            with self._stats_lock:
                self._bypassed += 1
            return item.family.execute_single(item)
        self._ensure_drainer()
        remaining = item.deadline.remaining()
        # generous slack past the deadline: the flush logic targets the
        # deadline itself, this wait only guards against a wedged drainer
        timeout = None if remaining is None else remaining + 30.0
        return item.future.result(timeout=timeout)

    # -- drainer -------------------------------------------------------------
    def _ensure_drainer(self):
        if self._drainer_started:
            return
        with self._cv:
            if self._drainer_started or self._shutdown:
                return
            self._drainer_started = True
        if self._threadpool is not None:
            try:
                # a named pool so the drainer shows in /_nodes/stats thread_pool
                self._threadpool.submit("search_batcher", self._drain_loop)
                return
            except Exception:  # noqa: BLE001 — pool missing/closed: plain thread
                pass
        threading.Thread(target=self._drain_loop, daemon=True,
                         name="estpu[search_batcher]").start()

    def _drain_loop(self):
        try:
            self._drain()
        except BaseException as e:  # noqa: BLE001 — a dead drainer must not
            # strand waiters: flag it (under the condition, so no _submit can
            # slip an item into the queue after the drain below) and fail
            # anything already queued; later submits bypass to direct execution
            with self._cv:
                self._drainer_dead = True
            self.logger.warning(f"batcher drainer died ({type(e).__name__}: "
                                f"{e}); serving falls back to direct launches")
            self._fail_queued(e)

    def _drain(self):
        pending = None  # (family, items, handle, t0) — dispatched, not merged
        while True:
            batch = None
            with self._cv:
                while not self._queue and not self._shutdown:
                    if pending is not None:
                        break  # merge the in-flight batch instead of idling
                    self._cv.wait(0.1)
                if self._queue and not self._shutdown:
                    batch = self._collect_locked(urgent=pending is not None)
            if batch is None:
                if pending is not None:
                    self._finish(*pending)
                    pending = None
                    continue
                if self._shutdown:
                    break
                continue
            items, reason = batch
            batch_id = next(self._batch_ids)
            traced = [it for it in items if it.span]
            t0 = time.monotonic()
            # enqueue-wait: t_enq -> the drainer taking the batch (span
            # recording happens OUTSIDE the condition/stats locks — trace
            # locks are leaves, and record() never blocks or dispatches)
            for it in traced:
                it.span.record("batcher.queue", it.t_enq, t0, batch=batch_id,
                               reason=reason, occupancy=len(items))
            # always-on insights: the coalescing-queue wait, from the SAME
            # t_enq/t0 clock pair the trace spans above use (plain attribute
            # writes; the item futures resolve after these, so readers see
            # them without locks)
            for it in items:
                if it.obs is not None:
                    it.obs.queue_s = t0 - it.t_enq
            family = items[0].family
            # publish the in-flight marker BEFORE dispatching: a hang inside
            # dispatch itself (the mesh family's whole execution + pull live
            # there) must age for the watchdog exactly like a wedged merge.
            # Label extraction must never throw — a drainer death strands
            # every queued future (payload shape is per-family: (plan, ctx)
            # for flat, (plan, executor) for mesh, opaque in unit fakes)
            payload = items[0].payload
            ctx0 = payload[1] if isinstance(payload, tuple) \
                and len(payload) > 1 else None
            self._inflight_q.append(
                (batch_id, t0, family.name, len(items),
                 getattr(ctx0, "index_name", None) or family.name))
            try:
                # dispatch-then-merge double buffering: batch N+1's device
                # work is enqueued BEFORE batch N's host merge runs, so the
                # merge overlaps device compute (no device_get in this half)
                handle = family.dispatch(items, items[0].kb)
            except Exception as e:  # noqa: BLE001 — replay decides per item
                self._retire_inflight(batch_id)
                self._split(family, items, e)
                continue
            if traced and tracing.sync_armed():
                # ESTPU_TRACE_SYNC=1 precise mode (bench/debug ONLY): wait for
                # the dispatched launches so the dispatch span measures true
                # device time — this deliberately forfeits the double-buffer
                # overlap, which is why it is never the default
                sync = getattr(handle, "sync", None)
                if sync is not None:
                    sync()
            t_disp = time.monotonic()
            for it in traced:
                it.span.record("batcher.dispatch", t0, t_disp, batch=batch_id,
                               occupancy=len(items), family=family.name)
            self._note_flush(reason)
            if pending is not None:
                self._finish(*pending)
            pending = (family, items, handle, t0, batch_id)
            with self._cv:
                queue_empty = not self._queue
            if queue_empty:
                self._finish(*pending)
                pending = None
        if pending is not None:
            self._finish(*pending)
        self._fail_queued(RejectedExecutionError(
            "search batcher is shut down"))

    def _collect_locked(self, urgent: bool = False):
        """Pick the oldest item's key and wait (under the condition) until a
        flush trigger fires; pops and returns (items, reason). Called with
        the condition held; may release it while waiting.

        `urgent` means a dispatched batch is waiting to be MERGED: lingering
        here would hold batch N's answered futures hostage to batch N+1's
        linger window (the drainer's merge-delay bug, PR 6). Take whatever is
        queued immediately — the device is busy anyway, so the linger's
        latency-for-occupancy trade buys nothing."""
        head = self._queue[0]
        key = head.key
        while True:
            same = [it for it in self._queue if it.key == key]
            n = len(same)
            if n >= self.max_batch:
                reason = "full"
                break
            if urgent:
                reason = "pending"
                break
            now = time.monotonic()
            # adaptive linger: shrinks linearly as the queue fills — waiting
            # longer only pays when it buys occupancy
            linger_eff = max(self.min_linger_s,
                             self.linger_s * (1.0 - n / float(self.max_batch)))
            flush_at = head.t_enq + linger_eff
            reason = "linger"
            for it in same:
                rem = it.deadline.remaining()
                if rem is None:
                    continue
                # leave one expected batch service time (launch + merge) of
                # budget so the flushed batch can still answer in time
                dl_at = now + rem - self._ewma_cost
                if dl_at < flush_at:
                    flush_at = dl_at
                    reason = "deadline"
            if now >= flush_at or self._shutdown:
                break
            self._cv.wait(min(flush_at - now, 0.05))
        taken: list[_Item] = []
        rest: deque[_Item] = deque()
        for it in self._queue:
            if it.key == key and len(taken) < self.max_batch:
                taken.append(it)
            else:
                rest.append(it)
        self._queue.clear()
        self._queue.extend(rest)
        return taken, reason

    def _finish(self, family, items, handle, t0: float, batch_id: int = 0):
        """Merge a dispatched batch and fan results out to the item futures."""
        t_m0 = time.monotonic()
        try:
            results = family.fan_out(handle, items)
        except Exception as e:  # noqa: BLE001 — replay decides per item
            self._retire_inflight(batch_id)
            self._split(family, items, e)
            return
        t_m1 = time.monotonic()
        self._retire_inflight(batch_id)  # merged: the stall marker retires
        dt = t_m1 - t0
        # merge span + the batch's ONE device pull, attributed to EVERY
        # coalesced member (the pull timestamps were stamped by
        # execute._merge_flat_plain on the pending handle — span end-times
        # ride the existing batched device_get, no extra sync)
        pull_t0 = getattr(handle, "pull_t0", None)
        pull_t1 = getattr(handle, "pull_t1", None)
        for it in items:
            if it.obs is not None:
                # device time rides the batch's existing single pull window
                # (zero added clocks/syncs — the insights contract)
                if pull_t0 is not None and pull_t1 is not None:
                    it.obs.device_s = pull_t1 - pull_t0
                it.obs.occupancy = len(items)
            if not it.span:
                continue
            merge_span = it.span.record("batcher.merge", t_m0, t_m1,
                                        batch=batch_id)
            if pull_t0 is not None and pull_t1 is not None:
                merge_span.record("device_pull", pull_t0, pull_t1,
                                  batch=batch_id)
        self.service_hist.observe(dt)  # own stripe locks — outside _stats_lock
        with self._stats_lock:
            self._ewma_cost = 0.2 * dt + 0.8 * self._ewma_cost
            self._launches += 1
            self._items_launched += len(items)
        for it, res in zip(items, results):
            it.future.set_result(res)

    def _split(self, family, items, err):
        """A coalesced launch failed (breaker trip, device error): replay every
        item individually so only the request that actually trips carries the
        error — its neighbors must not inherit a 429 sized for the batch.

        Device containment (common/devicehealth) rides this same path: a
        classified XLA error inside a shared launch replays each member, so
        one poisoned plan degrades ITS request to the host scorer while the
        N-1 neighbors re-launch and serve from the device. Per-item verdicts
        reach the circuit tracker through the members' own futures
        (service._device_failed classifies the tagged exception); the batch-
        level error is NOT recorded — the replay re-derives who is actually
        poisoned, and neighbors' collateral must never advance a circuit."""
        from ..common.devicehealth import classify_device_error

        if len(items) == 1:
            items[0].future.set_exception(err)
            return
        with self._stats_lock:
            self._splits += 1
            if classify_device_error(err) is not None:
                self._device_splits += 1
        for it in items:
            try:
                res = family.execute_single(it)
            except Exception as e:  # noqa: BLE001 — per-item verdict
                it.future.set_exception(e)
            else:
                it.future.set_result(res)

    def _note_flush(self, reason: str):
        with self._stats_lock:
            if reason == "full":
                self._full_flushes += 1
            elif reason == "deadline":
                self._deadline_flushes += 1
            elif reason == "pending":
                self._pending_flushes += 1
            else:
                self._linger_flushes += 1

    def _fail_queued(self, err):
        with self._cv:
            items, self._queue = list(self._queue), deque()
        for it in items:
            if not it.future.done():
                it.future.set_exception(err)

    def _retire_inflight(self, batch_id: int):
        """Drop one batch's in-flight marker (drainer thread only). The
        retiring batch is almost always the head; the fallback filter covers
        the dispatch-failed-while-older-batch-pending interleaving."""
        q = self._inflight_q
        try:
            if q and q[0][0] == batch_id:
                q.popleft()
                return
        except IndexError:
            return
        for entry in list(q):
            if entry[0] == batch_id:
                try:
                    q.remove(entry)
                except ValueError:
                    pass
                return

    def inflight(self) -> dict | None:
        """The OLDEST in-flight (dispatching-or-unmerged) batch as the stall
        watchdog sees it: {batch, age_s, family, occupancy, shard}, or None.
        One unlocked deque head read of drainer-written state — the
        watchdog's clock, never a serving thread's."""
        try:
            batch_id, t0, family, occupancy, label = self._inflight_q[0]
        except IndexError:
            return None
        return {"batch": batch_id, "age_s": time.monotonic() - t0,
                "family": family, "occupancy": occupancy, "shard": label}

    def note_profile_bypass(self):
        """A profiled request served itself directly instead of coalescing
        (search/service._execute_flat_single — the `reason: profile` bypass)."""
        with self._stats_lock:
            self._profile_bypassed += 1

    # -- lifecycle / observability -------------------------------------------
    def shutdown(self):
        with self._cv:
            self._shutdown = True
            self._cv.notify_all()

    def stats(self) -> dict:
        with self._stats_lock:
            launches = self._launches
            items = self._items_launched
            out = {
                "launches": launches,
                "coalesced": items,
                "occupancy_mean": round(items / launches, 3) if launches else 0.0,
                "full_flushes": self._full_flushes,
                "linger_flushes": self._linger_flushes,
                "deadline_flushes": self._deadline_flushes,
                "pending_flushes": self._pending_flushes,
                "bypassed": self._bypassed,
                "profile_bypassed": self._profile_bypassed,
                "splits": self._splits,
                "device_splits": self._device_splits,
                "queue": len(self._queue),
                "ewma_batch_ms": round(self._ewma_cost * 1000.0, 3),
            }
        # batch service-time percentiles (HistogramMetric — the tail the EWMA
        # can't show); stripe locks are leaves, summed outside _stats_lock
        out["batch"] = self.service_hist.stats()
        return out
