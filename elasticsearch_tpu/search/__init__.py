from .queries import Query, parse_query, parse_filter  # noqa: F401
from .execute import (  # noqa: F401
    ShardContext,
    TopDocs,
    search_shard,
    search_shard_batch,
    count_shard,
)
from .similarity import SimilarityService, BM25Similarity, TFIDFSimilarity  # noqa: F401
