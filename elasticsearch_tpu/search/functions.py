"""function_score score functions.

Analogue of index/query/functionscore/ (22 files — SURVEY.md §2.3): decay functions
(gauss/exp/linear over numeric/date/geo fields), script_score, field_value_factor,
random_score, boost_factor, with filters, weights, score_mode/boost_mode combination and
max_boost capping (FunctionScoreQueryParser.java semantics).

Decay math follows the reference docs: for value v, origin o, scale s, offset f, decay d:
  dist = max(0, |v - o| - f)
  gauss : exp(-dist² / (2σ²)),  σ² = -s²/(2·ln d)
  exp   : exp(λ·dist),          λ = ln(d)/s
  linear: max(0, (l - dist)/l), l = s/(1 - d)

Vectorized over the segment's columnar doc values — on-device for single-valued numeric
columns via PackedSegment.dv_single when the executor runs the dense device path.
"""

from __future__ import annotations

import math
import zlib

import numpy as np

from ..common.errors import QueryParsingError
from ..mapper.core import parse_date_math
from .filters import haversine_m, parse_distance, segment_mask


def vectorized_script_eval(fn, seg, scores: np.ndarray):
    """Column-lowered script evaluation over a whole segment.

    Returns (values float64[D], ok bool[D]) or None when the script is outside the
    vectorizable subset. `ok` excludes exactly the docs whose per-doc evaluation
    may diverge or raise — referenced fields missing (per-doc sees value=None) and
    non-finite vectorized results (per-doc raises ScriptError on the same domain
    error, e.g. log(0)) — so callers run the per-doc path for ~ok docs and
    semantics, including errors, are unchanged. Shared by script_score and
    _script sorts; keep the masking rules HERE so both stay in lockstep."""
    from ..script import ColumnVectorizer

    col_cache: dict[str, np.ndarray] = {}

    def col(f):
        if f not in col_cache:
            col_cache[f] = _column_first_value(seg, f)
        return col_cache[f]

    vec = ColumnVectorizer(fn, col, scores)
    result = vec.vectorize()
    if result is None:
        return None
    vals = np.broadcast_to(np.asarray(result, dtype=np.float64),
                           (seg.doc_count,))
    ok = seg.parent_mask & np.isfinite(vals)
    for f in vec.used_fields:
        ok &= ~np.isnan(col(f))
    return vals, ok


def _column_first_value(seg, field: str) -> np.ndarray:
    """First numeric value per doc (NaN = missing)."""
    col = seg.dv_num.get(field)
    out = np.full(seg.doc_count, np.nan)
    if col is None:
        return out
    off, vals = col
    has = np.diff(off) > 0
    first_idx = off[:-1][has]
    out[has] = vals[first_idx]
    return out


def _parse_scale(sf, ft) -> float:
    scale = sf.scale
    if ft is not None and ft.type == "date":
        from ..common.units import parse_time

        return parse_time(scale) * 1000.0
    if ft is not None and ft.type == "geo_point":
        return parse_distance(scale)
    return float(scale)


def _parse_origin(sf, ft):
    if ft is not None and ft.type == "date":
        if sf.origin is None:
            import time

            return time.time() * 1000.0
        return float(parse_date_math(str(sf.origin)))
    if ft is not None and ft.type == "geo_point":
        o = sf.origin
        if isinstance(o, dict):
            return (float(o["lat"]), float(o["lon"]))
        if isinstance(o, str):
            lat, lon = o.split(",")
            return (float(lat), float(lon))
        return (float(o[1]), float(o[0]))
    return float(sf.origin)


def _parse_offset(sf, ft) -> float:
    if not sf.offset:
        return 0.0
    if ft is not None and ft.type == "date":
        from ..common.units import parse_time

        return parse_time(sf.offset) * 1000.0
    if ft is not None and ft.type == "geo_point":
        return parse_distance(sf.offset)
    return float(sf.offset)


def evaluate_function(sf, seg, ctx, sub_scores: np.ndarray) -> np.ndarray:
    """One function's value per doc (before filter/weight)."""
    D = seg.doc_count
    if sf.kind == "boost_factor":
        return np.full(D, np.float32(sf.factor), dtype=np.float32)

    if sf.kind == "random_score":
        seed = sf.seed if sf.seed is not None else 42
        ids = np.asarray([zlib.crc32(f"{seed}:{i}".encode()) for i in seg.ids or []],
                         dtype=np.float64)
        return ((ids % 10_000) / 10_000.0).astype(np.float32)

    if sf.kind == "field_value_factor":
        vals = _column_first_value(seg, sf.field)
        missing = 1.0 if sf.missing is None else float(sf.missing)
        vals = np.where(np.isnan(vals), missing, vals) * sf.factor
        mod = sf.modifier
        with np.errstate(divide="ignore", invalid="ignore"):
            if mod in ("none", None):
                out = vals
            elif mod == "log":
                out = np.log10(vals)
            elif mod == "log1p":
                out = np.log10(vals + 1)
            elif mod == "log2p":
                out = np.log10(vals + 2)
            elif mod == "ln":
                out = np.log(vals)
            elif mod == "ln1p":
                out = np.log1p(vals)
            elif mod == "ln2p":
                out = np.log(vals + 2)
            elif mod == "square":
                out = vals * vals
            elif mod == "sqrt":
                out = np.sqrt(vals)
            elif mod == "reciprocal":
                out = 1.0 / vals
            else:
                raise QueryParsingError(f"unknown field_value_factor modifier [{mod}]")
        return np.nan_to_num(out, nan=0.0, posinf=0.0, neginf=0.0).astype(np.float32)

    if sf.kind == "script_score":
        from ..script import compile_script
        from .filters import DocAccess

        fn = compile_script(sf.script, sf.params)
        vec = vectorized_script_eval(fn, seg, sub_scores.astype(np.float64))
        if vec is not None:
            vals, ok = vec
            out = np.where(ok, vals, 0.0).astype(np.float32)
            for local in np.nonzero(seg.parent_mask & ~ok)[0]:
                out[local] = float(fn(DocAccess(seg, int(local)),
                                      _score=float(sub_scores[local])))
            return out
        out = np.zeros(D, dtype=np.float32)
        for local in range(D):
            if seg.parent_mask[local]:
                out[local] = float(fn(DocAccess(seg, local), _score=float(sub_scores[local])))
        return out

    if sf.kind in ("gauss", "exp", "linear"):
        ft = ctx.field_type(sf.field)
        scale = _parse_scale(sf, ft)
        offset = _parse_offset(sf, ft)
        decay = sf.decay
        if ft is not None and ft.type == "geo_point":
            lat0, lon0 = _parse_origin(sf, ft)
            lats = _column_first_value(seg, f"{sf.field}.lat")
            lons = _column_first_value(seg, f"{sf.field}.lon")
            dist = haversine_m(lat0, lon0, lats, lons)
        else:
            origin = _parse_origin(sf, ft)
            vals = _column_first_value(seg, sf.field)
            dist = np.abs(vals - origin)
        dist = np.maximum(0.0, dist - offset)
        if sf.kind == "gauss":
            sigma2 = -(scale * scale) / (2.0 * math.log(decay))
            out = np.exp(-(dist * dist) / (2.0 * sigma2))
        elif sf.kind == "exp":
            lam = math.log(decay) / scale
            out = np.exp(lam * dist)
        else:
            l = scale / (1.0 - decay)
            out = np.maximum(0.0, (l - dist) / l)
        return np.where(np.isnan(out), 1.0, out).astype(np.float32)  # missing → neutral

    raise QueryParsingError(f"unknown score function [{sf.kind}]")


def combined_doc_rows(q, sub_scores: np.ndarray, seg, ctx):
    """score_mode-combined function values + applies mask: (float32[D], bool[D]).

    The per-doc part of function_score — everything up to (but excluding) the
    no-function default, max_boost cap and boost_mode. Shared by the host tail
    (apply_functions) and the device factor-row builder
    (execute._execute_flat_fs): all math is float32 so the two paths are
    bit-identical."""
    D = seg.doc_count
    vals: list[np.ndarray] = []
    masks: list[np.ndarray] = []
    for sf in q.functions:
        v = evaluate_function(sf, seg, ctx, sub_scores).astype(np.float32)
        if sf.weight is not None:
            v = v * np.float32(sf.weight)
        fmask = segment_mask(seg, sf.filter, ctx) if sf.filter is not None else None
        vals.append(v)
        masks.append(fmask if fmask is not None else np.ones(D, dtype=bool))
    stacked = np.stack(vals)
    mstack = np.stack(masks)
    any_applies = mstack.any(axis=0)
    one = np.float32(1.0)
    if q.score_mode == "multiply":
        combined = np.where(mstack, stacked, one).prod(axis=0, dtype=np.float32)
    elif q.score_mode == "sum":
        combined = np.where(mstack, stacked, np.float32(0.0)).sum(
            axis=0, dtype=np.float32)
    elif q.score_mode == "avg":
        cnt = mstack.sum(axis=0)
        s = np.where(mstack, stacked, np.float32(0.0)).sum(axis=0, dtype=np.float32)
        combined = np.where(cnt > 0, s / np.maximum(cnt, 1).astype(np.float32), one)
    elif q.score_mode == "max":
        combined = np.where(mstack, stacked, np.float32(-np.inf)).max(axis=0)
        combined = np.where(np.isfinite(combined), combined, one)
    elif q.score_mode == "min":
        combined = np.where(mstack, stacked, np.float32(np.inf)).min(axis=0)
        combined = np.where(np.isfinite(combined), combined, one)
    elif q.score_mode == "first":
        combined = np.ones(D, dtype=np.float32)
        chosen = np.zeros(D, dtype=bool)
        for v, m in zip(vals, masks):
            take = m & ~chosen
            combined = np.where(take, v, combined)
            chosen |= m
    else:
        raise QueryParsingError(f"unknown score_mode [{q.score_mode}]")
    return combined.astype(np.float32), any_applies


def apply_functions(q, sub_scores: np.ndarray, match: np.ndarray, seg, ctx) -> np.ndarray:
    """Combine function values with the subquery score (score_mode × boost_mode).
    Float32 throughout — in bit-lockstep with the device kernel's fs tail
    (ops/scoring._fs_tail)."""
    if not q.functions:
        return sub_scores.astype(np.float32)
    combined, any_applies = combined_doc_rows(q, sub_scores, seg, ctx)
    sub_scores = sub_scores.astype(np.float32)
    combined = np.where(any_applies, combined, np.float32(1.0))
    if math.isfinite(q.max_boost):
        combined = np.minimum(combined, np.float32(q.max_boost))
    bm = q.boost_mode
    if bm == "multiply":
        out = sub_scores * combined
    elif bm == "replace":
        out = np.where(any_applies, combined, sub_scores)
    elif bm == "sum":
        out = sub_scores + combined
    elif bm == "avg":
        out = (sub_scores + combined) / np.float32(2.0)
    elif bm == "max":
        out = np.maximum(sub_scores, combined)
    elif bm == "min":
        out = np.minimum(sub_scores, combined)
    else:
        raise QueryParsingError(f"unknown boost_mode [{bm}]")
    return out.astype(np.float32)
