"""Sort: field / score / geo-distance / script sort keys over fielddata columns.

Analogue of search/sort/ (SURVEY.md §2.5): sort builders → per-doc comparators over
fielddata. Here: per-segment vectorized key extraction → np.lexsort, with the standard
multi-valued `mode` reductions (min/max/avg/sum) and `missing` handling (_last/_first
or a constant). Sort tuples travel with hits so the multi-shard merge can re-compare
them (SearchPhaseController.sortDocs field-sort variant).
"""

from __future__ import annotations

from typing import Any

import numpy as np

from ..common.errors import QueryParsingError
from .filters import haversine_m, parse_distance


class SortSpec:
    __slots__ = ("field", "order", "mode", "missing", "kind", "lat", "lon", "unit",
                 "script", "params")

    def __init__(self, field: str, order: str = "asc", mode: str | None = None,
                 missing: Any = "_last", kind: str = "field", lat=0.0, lon=0.0,
                 unit=1.0, script=None, params=None):
        self.field = field
        self.order = order
        self.mode = mode
        self.missing = missing
        self.kind = kind
        self.lat = lat
        self.lon = lon
        self.unit = unit
        self.script = script
        self.params = params or {}

    @property
    def reverse(self) -> bool:
        return self.order == "desc"


def parse_sort(spec) -> list[SortSpec]:
    """"sort": ["_score", {"price": "desc"}, {"_geo_distance": {...}}, "field"]"""
    if spec is None:
        return []
    if not isinstance(spec, list):
        spec = [spec]
    out: list[SortSpec] = []
    for item in spec:
        if isinstance(item, str):
            if item == "_score":
                out.append(SortSpec("_score", "desc", kind="score"))
            else:
                out.append(SortSpec(item, "asc"))
            continue
        if not isinstance(item, dict) or len(item) != 1:
            raise QueryParsingError(f"invalid sort spec {item!r}")
        (field, opts), = item.items()
        if field == "_score":
            order = opts if isinstance(opts, str) else opts.get("order", "desc")
            out.append(SortSpec("_score", order, kind="score"))
        elif field == "_geo_distance":
            opts = dict(opts)
            order = opts.pop("order", "asc")
            unit = parse_distance("1" + opts.pop("unit", "km"))
            mode = opts.pop("mode", None)
            (gfield, point), = opts.items()
            if isinstance(point, dict):
                lat, lon = float(point["lat"]), float(point["lon"])
            elif isinstance(point, str):
                lat, lon = (float(x) for x in point.split(","))
            else:
                lon, lat = float(point[0]), float(point[1])
            out.append(SortSpec(gfield, order, mode, kind="geo", lat=lat, lon=lon, unit=unit))
        elif field == "_script":
            out.append(SortSpec("_script", opts.get("order", "asc"), kind="script",
                                script=opts.get("script"), params=opts.get("params")))
        else:
            if isinstance(opts, str):
                out.append(SortSpec(field, opts))
            else:
                out.append(SortSpec(field, opts.get("order", "asc"), opts.get("mode"),
                                    opts.get("missing", "_last")))
    return out


def _reduce_multi(off: np.ndarray, vals: np.ndarray, D: int, mode: str) -> np.ndarray:
    out = np.full(D, np.nan)
    counts = np.diff(off)
    has = counts > 0
    if not has.any():
        return out
    if mode in (None, "min"):
        red = np.minimum.reduceat(vals, off[:-1][has])
    elif mode == "max":
        red = np.maximum.reduceat(vals, off[:-1][has])
    elif mode in ("sum", "avg"):
        red = np.add.reduceat(vals, off[:-1][has])
        if mode == "avg":
            red = red / counts[has]
    else:
        raise QueryParsingError(f"unknown sort mode [{mode}]")
    out[has] = red
    return out


def sort_key_column(spec: SortSpec, seg, ctx, scores: np.ndarray | None) -> np.ndarray:
    """One float64 key per doc; NaN = missing. Ascending semantics (caller negates for
    desc through lexsort ordering)."""
    D = seg.doc_count
    if spec.kind == "score":
        return (scores if scores is not None else np.zeros(D)).astype(np.float64)
    if spec.kind == "geo":
        lat_col = seg.dv_num.get(f"{spec.field}.lat")
        lon_col = seg.dv_num.get(f"{spec.field}.lon")
        if lat_col is None or lon_col is None:
            return np.full(D, np.nan)
        off, lats = lat_col
        _, lons = lon_col
        d = haversine_m(spec.lat, spec.lon, lats, lons) / spec.unit
        mode = spec.mode or "min"
        return _reduce_multi(off, d, D, mode if mode in ("min", "max", "avg", "sum") else "min")
    if spec.kind == "script":
        from ..script import compile_script
        from .filters import DocAccess
        from .functions import vectorized_script_eval

        fn = compile_script(spec.script or "0", spec.params)
        # _script sorts expose the document's _score (reference semantics)
        score_arr = (scores if scores is not None
                     else np.zeros(D)).astype(np.float64)
        out = np.full(D, np.nan)
        # column-lowered fast path (shared contract with script_score: identical
        # or fall back per doc — here, per-doc errors become NaN keys)
        vec = vectorized_script_eval(fn, seg, score_arr)
        if vec is not None:
            vals, ok = vec
            out[ok] = vals[ok]
            rest = np.nonzero(seg.parent_mask & ~ok)[0]
        else:
            rest = np.nonzero(seg.parent_mask)[0]
        for local in rest:
            try:
                out[local] = float(fn(DocAccess(seg, int(local)),
                                      _score=float(score_arr[local])))
            except Exception:  # noqa: BLE001 — missing fields etc. → NaN key
                pass
        return out
    col = seg.dv_num.get(spec.field)
    if col is not None:
        off, vals = col
        mode = spec.mode or ("min" if spec.order == "asc" else "max")
        return _reduce_multi(off, vals, D, mode)
    scol = seg.dv_str.get(spec.field)
    if scol is not None:
        # string sort via GLOBAL ordinals would not merge across segments/shards;
        # hits carry the raw string (see sort_values_for_docs) — here we return the
        # segment-local ordinal as a float key for segment-local top-k only
        uniq, off, ords = scol
        counts = np.diff(off)
        out = np.full(D, np.nan)
        has = counts > 0
        if has.any():
            red = np.minimum.reduceat(ords.astype(np.float64), off[:-1][has])
            out[has] = red
        return out
    return np.full(D, np.nan)


_F32_MAX = float(np.finfo(np.float32).max)


def device_sort_key_row(spec: SortSpec, seg, doc_pad: int) -> np.ndarray | None:
    """float32 [doc_pad] ascending-semantics key row for the device sort kernel,
    or None when the spec/column needs the host path.

    Sort order is deterministic user-visible state, so only columns whose values
    are EXACTLY float32-representable ride the kernel (fractional f64 rounding
    could swap strict orderings); avg/sum modes divide/accumulate in f64 on the
    host and stay there. Missing docs take ±FLT_MAX (not ±inf) so the kernel can
    rank them after real keys but before its ±inf padding; custom numeric
    missing fills must be f32-exact too."""
    if spec.kind != "field" or spec.mode in ("avg", "sum"):
        return None
    if spec.field in seg.dv_str and spec.field not in seg.dv_num:
        return None
    mode = spec.mode or ("min" if spec.order == "asc" else "max")
    # the exactness check + per-doc fold are pure functions of the immutable
    # (segment column, mode) — cache them so hot sorted queries don't re-scan
    # the column (missing/order handling below is per-spec and cheap)
    ckey = ("sort_keys", spec.field, mode)
    keys = seg._device_cache.get(ckey)
    if keys is None:
        col = seg.dv_num.get(spec.field)
        if col is None:
            keys = np.full(seg.doc_count, np.nan)
        else:
            off, vals = col
            if len(vals) and (
                    not np.array_equal(
                        vals.astype(np.float32).astype(np.float64), vals)
                    or np.abs(vals).max() >= _F32_MAX / 2):
                keys = "inexact"
            else:
                keys = _reduce_multi(off, vals, seg.doc_count, mode)
        seg._device_cache[ckey] = keys
    if isinstance(keys, str):
        return None
    if spec.missing == "_last":
        fill = _F32_MAX if not spec.reverse else -_F32_MAX
    elif spec.missing == "_first":
        fill = -_F32_MAX if not spec.reverse else _F32_MAX
    else:
        try:
            fill = float(spec.missing)
        except (TypeError, ValueError):
            fill = _F32_MAX
        if float(np.float32(fill)) != fill:
            return None
    keys = np.where(np.isnan(keys), fill, keys)
    row = np.full(doc_pad, _F32_MAX if not spec.reverse else -_F32_MAX,
                  dtype=np.float32)
    row[: seg.doc_count] = keys.astype(np.float32)
    return row


def apply_missing(keys: np.ndarray, spec: SortSpec) -> np.ndarray:
    missing = spec.missing
    if missing == "_last":
        fill = np.inf if not spec.reverse else -np.inf
    elif missing == "_first":
        fill = -np.inf if not spec.reverse else np.inf
    else:
        try:
            fill = float(missing)
        except (TypeError, ValueError):
            fill = np.inf
    return np.where(np.isnan(keys), fill, keys)


def sort_values_for_docs(specs: list[SortSpec], seg, ctx, locals_: np.ndarray,
                         scores: np.ndarray | None):
    """Per-hit sort VALUE tuples (travel with hits for cross-shard merge + response
    "sort" arrays). Strings stay strings so merges compare lexicographically."""
    out: list[list] = [[] for _ in range(len(locals_))]
    for spec in specs:
        if spec.kind == "field" and spec.field in seg.dv_str and spec.field not in seg.dv_num:
            for i, local in enumerate(locals_):
                vals = seg.str_values(spec.field, int(local))
                out[i].append(min(vals) if vals else None)
        else:
            col = sort_key_column(spec, seg, ctx, scores)
            for i, local in enumerate(locals_):
                v = col[int(local)]
                out[i].append(None if np.isnan(v) else float(v))
    return out


def compare_sort_values(a: list, b: list, specs: list[SortSpec]) -> int:
    """Cross-shard comparator over sort-value tuples (None = missing)."""
    for av, bv, spec in zip(a, b, specs):
        if av == bv:
            continue
        if av is None:
            return 1 if spec.missing == "_last" else -1
        if bv is None:
            return -1 if spec.missing == "_last" else 1
        lt = av < bv
        if spec.reverse:
            return 1 if lt else -1
        return -1 if lt else 1
    return 0
