"""SearchPhaseController: the coordinating-node reduce.

Analogue of search/controller/SearchPhaseController.java (SURVEY.md §2.5):
- sortDocs: merge per-shard top-k into the global top-k (score order or field-sort
  order, ties broken by shard index then doc — SearchPhaseController.java:137-214)
- aggregateDfs: sum per-shard term/field statistics for exact global IDF
  (SearchPhaseController.java:83-135) — the host-side form; the mesh executor does the
  same reduction as a psum over the shards axis (parallel/mesh_search.py)
- merge: reduce aggregations/facets/suggest partials and assemble the final response

Pure functions over shard results — unit-testable exactly like the reference's
controller, and identical whether results came from local shards, remote nodes, or the
device mesh.
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field

import numpy as np

from ..index.segment import FieldStats
from .aggregations import facet_response, reduce_aggs
from .service import ParsedSearchRequest, ShardQueryResult
from .sorting import compare_sort_values


@dataclass
class DfsResult:
    """Per-shard statistics collected by the DFS phase (ref: search/dfs/DfsPhase.java:
    term stats + collection stats per queried field)."""

    shard_id: int
    max_doc: int
    term_df: dict  # (field, term) -> df
    field_stats: dict  # field -> FieldStats


def aggregate_dfs(results: list[DfsResult]) -> dict:
    """Sum per-shard stats into the global view handed back to every shard's query
    phase (ShardContext.global_stats) — ref: SearchPhaseController.aggregateDfs."""
    df: dict = {}
    fstats: dict[str, FieldStats] = {}
    max_doc = 0
    for r in results:
        max_doc += r.max_doc
        for key, v in r.term_df.items():
            df[key] = df.get(key, 0) + v
        for f, s in r.field_stats.items():
            cur = fstats.get(f)
            fstats[f] = s if cur is None else cur.merged(s)
    return {"df": df, "max_doc": max_doc, "field_stats": fstats}


def collect_dfs(ctx, query, shard_id: int = 0) -> DfsResult:
    """DFS phase on one shard: df for every term the query will score + field stats."""
    from .execute import FlatPlan, lower_flat

    term_df = {}
    fields = set()
    plan = lower_flat(query, ctx)
    if plan is not None:
        for c in plan.clauses:
            term_df[(c.field, c.term)] = ctx.searcher.doc_freq(c.field, c.term)
            fields.add(c.field)
    else:
        _walk_terms(query, ctx, term_df, fields)
    return DfsResult(
        shard_id=shard_id,
        max_doc=ctx.searcher.max_doc,
        term_df=term_df,
        field_stats={f: ctx.searcher.field_stats(f) for f in fields},
    )


def _walk_terms(query, ctx, term_df: dict, fields: set):
    from .queries import (
        BoolQuery, DisMaxQuery, FilteredQuery, FunctionScoreQuery, MatchQuery,
        MultiMatchQuery, NestedQuery, PhraseQuery, TermQuery,
    )

    if isinstance(query, TermQuery):
        term_df[(query.field, str(query.value))] = ctx.searcher.doc_freq(
            query.field, str(query.value))
        fields.add(query.field)
    elif isinstance(query, (MatchQuery, PhraseQuery)):
        for t in ctx.analyze(query.field, query.text):
            term_df[(query.field, t)] = ctx.searcher.doc_freq(query.field, t)
        fields.add(query.field)
    elif isinstance(query, MultiMatchQuery):
        for fspec in query.fields:
            f = fspec.split("^")[0]
            for t in ctx.analyze(f, query.text):
                term_df[(f, t)] = ctx.searcher.doc_freq(f, t)
            fields.add(f)
    elif isinstance(query, BoolQuery):
        for sub in query.must + query.should + query.must_not:
            _walk_terms(sub, ctx, term_df, fields)
    elif isinstance(query, DisMaxQuery):
        for sub in query.queries:
            _walk_terms(sub, ctx, term_df, fields)
    elif isinstance(query, (FilteredQuery, FunctionScoreQuery, NestedQuery)):
        inner = getattr(query, "query", None)
        if inner is not None and not callable(getattr(inner, "evaluate", None)):
            _walk_terms(inner, ctx, term_df, fields)


@dataclass
class MergedTopDocs:
    total: int
    max_score: float
    # [(score, shard_id, global_doc, sort_values)]
    hits: list
    timed_out: bool = False


def sort_docs(req: ParsedSearchRequest, shard_results: list[ShardQueryResult]) -> MergedTopDocs:
    """Global top-(from+size) merge across shards. Score order: (score desc, shard asc,
    doc asc). Field order: sort-value tuples via the shared comparator. A single
    shard-level partial (deadline expired mid-collection) marks the whole merged
    result timed_out — totals and aggregations cover only the scored segments."""
    total = sum(r.total for r in shard_results)
    max_score = float("nan")
    for r in shard_results:
        if r.max_score == r.max_score:
            max_score = r.max_score if max_score != max_score else max(max_score, r.max_score)
    entries = []
    for r in shard_results:
        for (score, doc, sort_values) in r.docs:
            entries.append((score, r.shard_id, doc, sort_values))
    if req.sort:
        import functools

        entries.sort(key=functools.cmp_to_key(
            lambda a, b: (compare_sort_values(a[3], b[3], req.sort)
                          or (a[1] - b[1]) or (a[2] - b[2]))
        ))
    else:
        entries.sort(key=lambda e: (-e[0] if e[0] == e[0] else float("inf"), e[1], e[2]))
    k = req.from_ + req.size
    return MergedTopDocs(total=total, max_score=max_score, hits=entries[:k],
                         timed_out=any(r.timed_out for r in shard_results))


def merge_responses(req: ParsedSearchRequest, merged: MergedTopDocs,
                    shard_results: list[ShardQueryResult],
                    fetched_hits: list[dict], took_ms: int,
                    total_shards: int, successful: int, failures: list | None = None) -> dict:
    """Final response assembly (ref: SearchPhaseController.merge:308-380)."""
    resp: dict = {
        "took": took_ms,
        "timed_out": merged.timed_out,
        "_shards": {
            "total": total_shards,
            "successful": successful,
            # shards that answered (counted successful — same bitwise hits)
            # but via the host path because a device fault domain was open
            # (common/devicehealth): the response stays honest about serving
            # health without failing anything, like the reference's
            # timed_out-but-partial contract
            "degraded": sum(1 for r in shard_results
                            if getattr(r, "degraded", False)),
            "failed": total_shards - successful,
        },
        "hits": {
            "total": merged.total,
            "max_score": None if merged.max_score != merged.max_score else merged.max_score,
            "hits": fetched_hits,
        },
    }
    if failures:
        resp["_shards"]["failures"] = failures
    if req.profile:
        # per-shard white-box execution profiles merged next to _shards —
        # the reference's `profile` section shape: one entry per shard copy
        # that answered, ordered by shard id (common/profile.py; shards that
        # failed contribute no profile, exactly like their hits)
        shard_profiles = [r.profile for r in shard_results
                          if r.profile is not None]
        shard_profiles.sort(key=lambda p: (str(p.get("index", "")),
                                           int(p.get("shard", 0))))
        resp["profile"] = {"shards": shard_profiles}
    if req.aggs:
        partials = [p for r in shard_results for p in r.agg_partials]
        resp["aggregations"] = reduce_aggs(req.aggs, partials)
    if req.facets:
        facets = {}
        for name, (agg, kind) in req.facets.items():
            partials = [p[name] for r in shard_results for p in r.facet_partials]
            facets[name] = facet_response(agg, kind, agg.finalize(agg.merge(partials)))
        resp["facets"] = facets
    suggest_merged = _merge_suggest(shard_results)
    if suggest_merged is not None:
        resp["suggest"] = suggest_merged
    return resp


def _merge_suggest(shard_results: list[ShardQueryResult]):
    """Merge per-shard suggester entries: options unioned, re-ranked, deduped."""
    any_suggest = [r.suggest for r in shard_results if r.suggest is not None]
    if not any_suggest:
        return None
    out: dict = {}
    for s in any_suggest:
        for name, entries in s.items():
            if name not in out:
                out[name] = [dict(e, options=list(e["options"])) for e in entries]
            else:
                for mine, theirs in zip(out[name], entries):
                    mine["options"].extend(theirs["options"])
    for entries in out.values():
        for e in entries:
            seen = {}
            for o in e["options"]:
                key = o["text"]
                if key not in seen or o.get("score", 0) > seen[key].get("score", 0):
                    seen[key] = o
            e["options"] = sorted(
                seen.values(),
                key=lambda o: (-o.get("score", 0), -o.get("freq", 0), o["text"]),
            )[:5]
    return out
