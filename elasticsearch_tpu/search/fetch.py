"""Fetch phase: hydrate winning doc ids into hits.

Analogue of search/fetch/ (SURVEY.md §2.5): _source loading + filtering (includes/
excludes/partial), stored fields, script_fields, fielddata_fields, version, highlight,
matched_queries, explain. Runs host-side — the fetch phase is IO/format work, not
compute, so it stays off the device exactly as the reference keeps it out of the
scoring loop.
"""

from __future__ import annotations

import fnmatch
import re
from typing import Any

import numpy as np

from .queries import (
    BoolQuery,
    FilteredQuery,
    MatchQuery,
    MultiMatchQuery,
    PhraseQuery,
    Query,
    QueryStringQuery,
    TermQuery,
)


def filter_source(source: dict, includes, excludes) -> dict:
    if not includes and not excludes:
        return source

    def walk(obj, path=""):
        if not isinstance(obj, dict):
            return obj
        out = {}
        for k, v in obj.items():
            p = f"{path}{k}"
            if isinstance(v, dict):
                sub = walk(v, p + ".")
                if sub or _included(p, includes, excludes):
                    if not _excluded(p, excludes):
                        out[k] = sub if isinstance(v, dict) else v
            else:
                if _included(p, includes, excludes) and not _excluded(p, excludes):
                    out[k] = v
        return out

    return walk(source)


def _included(path: str, includes, excludes) -> bool:
    if not includes:
        return True
    # a pattern naming an ancestor keeps the whole subtree; one naming a descendant
    # keeps walking through this node
    return any(
        fnmatch.fnmatch(path, pat) or pat.startswith(path + ".")
        or path.startswith(pat + ".")
        for pat in includes
    )


def _excluded(path: str, excludes) -> bool:
    return any(fnmatch.fnmatch(path, pat) for pat in (excludes or []))


def source_spec(body: dict):
    """Parse the _source directive: bool / str / list / {includes, excludes}."""
    spec = body.get("_source")
    if spec is None:
        return True, [], []
    if spec is False:
        return False, [], []
    if spec is True:
        return True, [], []
    if isinstance(spec, str):
        return True, [spec], []
    if isinstance(spec, list):
        return True, spec, []
    def as_list(v):
        if v is None:
            return []
        return [v] if isinstance(v, str) else list(v)

    return True, as_list(spec.get("includes") or spec.get("include")), \
        as_list(spec.get("excludes") or spec.get("exclude"))


def extract_field(source: dict, path: str) -> list:
    """Dotted-path field extraction from _source (for "fields": [...])."""
    node: Any = source
    for part in path.split("."):
        if isinstance(node, list):
            node = [n.get(part) for n in node if isinstance(n, dict)]
        elif isinstance(node, dict):
            node = node.get(part)
        else:
            return []
        if node is None:
            return []
    if isinstance(node, list):
        return [n for n in node if n is not None]
    return [node]


# ---------------------------------------------------------------------------
# highlight (plain highlighter — ref: search/highlight/PlainHighlighter)
# ---------------------------------------------------------------------------


def query_terms_for_field(query: Query, field: str, ctx) -> set[str]:
    out: set[str] = set()

    def walk(q):
        if isinstance(q, TermQuery) and q.field in (field, "_all"):
            out.add(str(q.value).lower())
        elif isinstance(q, MatchQuery) and q.field in (field, "_all"):
            out.update(ctx.analyze(field, q.text))
        elif isinstance(q, PhraseQuery) and q.field in (field, "_all"):
            out.update(ctx.analyze(field, q.text))
        elif isinstance(q, MultiMatchQuery):
            for fspec in q.fields:
                fname = fspec.split("^")[0]
                if fname in (field, "_all"):
                    out.update(ctx.analyze(field, q.text))
        elif isinstance(q, BoolQuery):
            for sub in q.must + q.should:
                walk(sub)
        elif isinstance(q, FilteredQuery):
            walk(q.query)
        elif isinstance(q, QueryStringQuery):
            from .execute import parse_query_string

            walk(parse_query_string(q, ctx))
        elif hasattr(q, "query") and isinstance(getattr(q, "query"), Query):
            walk(q.query)
        elif hasattr(q, "queries"):
            for sub in q.queries:
                walk(sub)

    walk(query)
    return out


def highlight_field(text: str, terms: set[str], ctx, field: str,
                    fragment_size: int = 100, number_of_fragments: int = 5,
                    pre_tag: str = "<em>", post_tag: str = "</em>") -> list[str]:
    if not text or not terms:
        return []
    analyzer = ctx.mapper_service.search_analyzer_for(field)
    tokens = analyzer.analyze(text)
    spans = [(t.start, t.end) for t in tokens if t.term.lower() in terms]
    if not spans:
        return []
    if number_of_fragments == 0:
        # highlight whole field
        return [_mark(text, spans, pre_tag, post_tag)]
    fragments: list[tuple[int, int, list[tuple[int, int]]]] = []
    for start, end in spans:
        placed = False
        for i, (fs, fe, fspans) in enumerate(fragments):
            if start < fe:
                fragments[i] = (fs, max(fe, min(len(text), start + fragment_size)), fspans + [(start, end)])
                placed = True
                break
        if not placed:
            fs = max(0, start - fragment_size // 4)
            fe = min(len(text), fs + fragment_size)
            fragments.append((fs, fe, [(start, end)]))
    out = []
    fragments.sort(key=lambda f: -len(f[2]))  # most matches first (Lucene frag scoring)
    for fs, fe, fspans in fragments[:number_of_fragments]:
        frag = text[fs:fe]
        rel = [(s - fs, e - fs) for s, e in fspans if s >= fs and e <= fe]
        out.append(_mark(frag, rel, pre_tag, post_tag))
    return out


def _mark(text: str, spans: list[tuple[int, int]], pre: str, post: str) -> str:
    out = []
    last = 0
    for s, e in sorted(set(spans)):
        if s < last:
            continue
        out.append(text[last:s])
        out.append(pre)
        out.append(text[s:e])
        out.append(post)
        last = e
    out.append(text[last:])
    return "".join(out)


def query_phrases_for_field(query: Query, field: str, ctx) -> list[list[str]]:
    """Phrase term sequences targeting this field (for phrase-unit highlighting)."""
    out: list[list[str]] = []

    def walk(q):
        if isinstance(q, PhraseQuery) and q.field in (field, "_all"):
            terms = ctx.analyze(field, q.text)
            if len(terms) > 1:
                out.append(terms)
        elif isinstance(q, BoolQuery):
            for sub in q.must + q.should:
                walk(sub)
        elif isinstance(q, FilteredQuery):
            walk(q.query)
        elif hasattr(q, "query") and isinstance(getattr(q, "query"), Query):
            walk(q.query)
        elif hasattr(q, "queries"):
            for sub in q.queries:
                walk(sub)

    walk(query)
    return out


_BOUNDARY_CHARS = set(".,!? \t\n")


def fvh_highlight_field(text: str, terms: set[str], phrases: list[list[str]],
                        ctx, field: str, fragment_size: int = 100,
                        number_of_fragments: int = 5, pre_tag: str = "<em>",
                        post_tag: str = "</em>", boundary_max_scan: int = 20) -> list[str]:
    """Fast-vector-highlighter semantics (ref: search/highlight/ FVH wiring over
    Lucene's vectorhighlight): phrase matches highlight as ONE unit (not per word),
    fragments are scored by total match weight (phrases weigh their length), and
    fragment edges snap to boundary characters within boundary_max_scan."""
    if not text or (not terms and not phrases):
        return []
    analyzer = ctx.mapper_service.search_analyzer_for(field)
    tokens = analyzer.analyze(text)
    if not tokens:
        return []
    # phrase spans: consecutive-position runs matching the phrase in order
    spans: list[tuple[int, int, float]] = []  # (start_off, end_off, weight)
    phrase_positions: set[int] = set()
    by_pos = {t.position: t for t in tokens}
    for phrase in phrases:
        n = len(phrase)
        for t in tokens:
            if t.term.lower() != phrase[0]:
                continue
            run = [t]
            for j in range(1, n):
                nxt = by_pos.get(t.position + j)
                if nxt is None or nxt.term.lower() != phrase[j]:
                    run = None
                    break
                run.append(nxt)
            if run:
                spans.append((run[0].start, run[-1].end, float(n)))
                phrase_positions.update(x.position for x in run)
    for t in tokens:
        if t.term.lower() in terms and t.position not in phrase_positions:
            spans.append((t.start, t.end, 1.0))
    if not spans:
        return []
    spans.sort()
    if number_of_fragments == 0:
        return [_mark(text, [(s, e) for s, e, _ in spans], pre_tag, post_tag)]

    def snap(pos: int, forward: bool) -> int:
        """Move a fragment edge to the nearest boundary char within the scan window."""
        if forward:
            for i in range(pos, min(len(text), pos + boundary_max_scan)):
                if text[i] in _BOUNDARY_CHARS:
                    return i + (1 if text[i] != " " else 0)
            return pos
        for i in range(pos, max(0, pos - boundary_max_scan), -1):
            if text[i - 1] in _BOUNDARY_CHARS:
                return i
        return pos

    # greedy fragment packing: group spans into windows of fragment_size
    frags: list[tuple[float, int, int, list[tuple[int, int]]]] = []
    i = 0
    while i < len(spans):
        fs = snap(max(0, spans[i][0] - fragment_size // 4), forward=False)
        fe_limit = fs + fragment_size
        window: list[tuple[int, int]] = []
        weight = 0.0
        j = i
        while j < len(spans) and spans[j][1] <= fe_limit:
            window.append((spans[j][0], spans[j][1]))
            weight += spans[j][2]
            j += 1
        if not window:  # single span longer than the fragment
            window = [(spans[i][0], spans[i][1])]
            weight = spans[i][2]
            j = i + 1
        fe = snap(min(len(text), max(e for _, e in window) + fragment_size // 4),
                  forward=True)
        frags.append((weight, fs, max(fe, max(e for _, e in window)), window))
        i = j
    frags.sort(key=lambda f: -f[0])  # highest total match weight first
    out = []
    for _w, fs, fe, window in frags[:number_of_fragments]:
        rel = [(s - fs, e - fs) for s, e in window]
        out.append(_mark(text[fs:fe], rel, pre_tag, post_tag))
    return out


def build_highlights(query: Query, hl_spec: dict, seg, local: int, ctx) -> dict:
    source = seg.stored[local] or {}
    out = {}
    global_pre = (hl_spec.get("pre_tags") or ["<em>"])[0]
    global_post = (hl_spec.get("post_tags") or ["</em>"])[0]
    for field, fopts in (hl_spec.get("fields") or {}).items():
        fopts = fopts or {}
        terms = query_terms_for_field(query, field, ctx)
        vals = extract_field(source, field)
        hl_type = fopts.get("type", hl_spec.get("type", "plain"))
        kwargs = dict(
            fragment_size=int(fopts.get("fragment_size", hl_spec.get("fragment_size", 100))),
            number_of_fragments=int(fopts.get("number_of_fragments",
                                              hl_spec.get("number_of_fragments", 5))),
            pre_tag=(fopts.get("pre_tags") or [global_pre])[0],
            post_tag=(fopts.get("post_tags") or [global_post])[0],
        )
        frags: list[str] = []
        for v in vals:
            if hl_type in ("fvh", "fast-vector-highlighter", "postings"):
                # postings highlighter shares the offsets-based path here — both
                # highlight from positions+offsets rather than re-scanning
                phrases = query_phrases_for_field(query, field, ctx)
                frags.extend(fvh_highlight_field(
                    str(v), terms, phrases, ctx, field,
                    boundary_max_scan=int(fopts.get("boundary_max_scan",
                                                    hl_spec.get("boundary_max_scan", 20))),
                    **kwargs))
            else:
                frags.extend(highlight_field(str(v), terms, ctx, field, **kwargs))
        if frags:
            out[field] = frags
    return out


# ---------------------------------------------------------------------------
# hit assembly
# ---------------------------------------------------------------------------


def build_hit(seg, local: int, score: float, body: dict, query: Query, ctx,
              index_name: str = "index", sort_values: list | None = None,
              shard_id: int | None = None) -> dict:
    hit: dict[str, Any] = {
        "_index": index_name,
        "_type": seg.types[local],
        "_id": seg.ids[local],
        "_score": None if score != score else score,  # NaN → null (sorted results)
    }
    if shard_id is not None:
        hit["_shard"] = shard_id
    enabled, includes, excludes = source_spec(body)
    fields_directive = body.get("fields") or body.get("stored_fields")
    if fields_directive and body.get("_source") is None:
        # a fields list suppresses _source unless it names "_source" itself
        # (ref: fetch/FieldsParseElement source handling)
        listed = [fields_directive] if isinstance(fields_directive, str) \
            else list(fields_directive)
        enabled = "_source" in listed
    if enabled and seg.stored[local] is not None:
        hit["_source"] = filter_source(seg.stored[local], includes, excludes)
    if body.get("version"):
        hit["_version"] = int(seg.versions[local])
    fields_spec = body.get("fields") or body.get("stored_fields")
    if fields_spec:
        if isinstance(fields_spec, str):
            fields_spec = [fields_spec]
        fields_out = {}
        for f in fields_spec:
            if f == "_source":
                continue
            vals = extract_field(seg.stored[local] or {}, f)
            if vals:
                fields_out[f] = vals
        if fields_out:
            hit["fields"] = fields_out
    script_fields = body.get("script_fields")
    if script_fields:
        from ..script import compile_script
        from .filters import DocAccess

        sf_out = hit.setdefault("fields", {})
        for name, sspec in script_fields.items():
            fn = compile_script(sspec.get("script", ""), sspec.get("params", {}))
            try:
                sf_out[name] = [fn(DocAccess(seg, local), _score=score if score == score else 0.0)]
            except Exception:  # noqa: BLE001
                sf_out[name] = [None]
    if body.get("highlight"):
        hl = build_highlights(query, body["highlight"], seg, local, ctx)
        if hl:
            hit["highlight"] = hl
    if sort_values is not None:
        hit["sort"] = sort_values
    return hit
