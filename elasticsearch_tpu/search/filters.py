"""Filter tree: non-scoring matchers evaluated per segment as boolean doc masks.

Analogue of the reference's 29 filter parsers (index/query/*FilterParser.java —
SURVEY.md §2.3) and its per-index weighted-LRU filter cache (index/cache/filter/).
A filter evaluates to bool[doc_count] per segment; masks combine with numpy logical ops
and feed the device scorer as a score mask (filters never contribute to scores, matching
FilteredQuery/BooleanFilter semantics).

Evaluation is host-side numpy over the segment's CSR postings / columnar doc values —
cheap, and the per-(segment, filter-key) result is cached exactly like the reference's
filter cache. Range/term filters over single-valued numeric columns additionally have a
device fast path via PackedSegment.dv_single (used by function_score and sort).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field as dc_field
from typing import Any

import numpy as np

from ..common.errors import QueryParsingError
from ..index.segment import FrozenSegment
from ..mapper.core import parse_date_math


class Filter:
    def key(self) -> str:
        raise NotImplementedError

    def evaluate(self, seg: FrozenSegment, ctx) -> np.ndarray:
        raise NotImplementedError

    def cacheable(self) -> bool:
        """False for masks that depend on state OUTSIDE the segment (e.g. the
        parent/child join spans the whole shard): the per-segment filter cache
        would serve stale results after other segments change. Composites
        propagate from their children."""
        return True


def segment_mask(seg: FrozenSegment, f: Filter, ctx) -> np.ndarray:
    """Cached evaluation (the filter cache). ctx carries the mapper service."""
    if not f.cacheable():
        return f.evaluate(seg, ctx)
    cache = seg._device_cache.setdefault("filters", {})
    k = f.key()
    m = cache.get(k)
    if m is None:
        m = f.evaluate(seg, ctx)
        cache[k] = m
    return m


def _postings_mask(seg: FrozenSegment, field: str, term: str) -> np.ndarray:
    mask = np.zeros(seg.doc_count, dtype=bool)
    docs, _ = seg.postings(field, str(term))
    mask[docs] = True
    return mask


def _num_column_mask(seg: FrozenSegment, field: str, pred) -> np.ndarray:
    col = seg.dv_num.get(field)
    mask = np.zeros(seg.doc_count, dtype=bool)
    if col is None:
        return mask
    off, vals = col
    if len(vals) == 0:
        return mask
    hit = pred(vals)
    counts = np.diff(off)
    doc_of_val = np.repeat(np.arange(seg.doc_count), counts)
    np.logical_or.at(mask, doc_of_val, hit)
    return mask


@dataclass
class TermFilter(Filter):
    field: str
    value: Any

    def key(self):
        return f"term:{self.field}:{self.value}"

    def evaluate(self, seg, ctx):
        ft = ctx.field_type(self.field)
        if ft is not None and ft.is_numeric:
            coerced = ft.coerce(self.value)
            return _num_column_mask(seg, self.field, lambda v: v == float(coerced))
        return _postings_mask(seg, self.field, _index_term(ctx, self.field, self.value))


@dataclass
class TermsFilter(Filter):
    field: str
    values: list

    def key(self):
        return f"terms:{self.field}:{sorted(map(str, self.values))!r}"

    def evaluate(self, seg, ctx):
        ft = ctx.field_type(self.field)
        mask = np.zeros(seg.doc_count, dtype=bool)
        if ft is not None and ft.is_numeric:
            coerced = {float(ft.coerce(v)) for v in self.values}
            arr = np.asarray(sorted(coerced))
            return _num_column_mask(seg, self.field, lambda v: np.isin(v, arr))
        for v in self.values:
            mask |= _postings_mask(seg, self.field, _index_term(ctx, self.field, v))
        return mask


@dataclass
class RangeFilter(Filter):
    field: str
    gte: Any = None
    gt: Any = None
    lte: Any = None
    lt: Any = None

    def key(self):
        return f"range:{self.field}:{self.gte}:{self.gt}:{self.lte}:{self.lt}"

    def _bounds_numeric(self, ft) -> tuple[float, float, bool, bool]:
        def conv(v):
            if ft is not None and ft.type == "date" and isinstance(v, str):
                return float(parse_date_math(v))
            return float(ft.coerce(v)) if ft is not None and ft.is_numeric else float(v)

        lo, lo_inc = -np.inf, True
        hi, hi_inc = np.inf, True
        if self.gte is not None:
            lo = conv(self.gte)
        if self.gt is not None:
            lo, lo_inc = conv(self.gt), False
        if self.lte is not None:
            hi = conv(self.lte)
        if self.lt is not None:
            hi, hi_inc = conv(self.lt), False
        return lo, hi, lo_inc, hi_inc

    def evaluate(self, seg, ctx):
        ft = ctx.field_type(self.field)
        if ft is None or ft.is_numeric:
            lo, hi, lo_inc, hi_inc = self._bounds_numeric(ft)

            def pred(v):
                lower = v >= lo if lo_inc else v > lo
                upper = v <= hi if hi_inc else v < hi
                return lower & upper

            return _num_column_mask(seg, self.field, pred)
        # lexicographic range over the sorted term dictionary (keyword fields)
        mask = np.zeros(seg.doc_count, dtype=bool)
        for term in seg.terms_for_field(self.field):
            if self.gte is not None and term < str(self.gte):
                continue
            if self.gt is not None and term <= str(self.gt):
                continue
            if self.lte is not None and term > str(self.lte):
                break
            if self.lt is not None and term >= str(self.lt):
                break
            mask |= _postings_mask(seg, self.field, term)
        return mask


@dataclass
class PrefixFilter(Filter):
    field: str
    prefix: str

    def key(self):
        return f"prefix:{self.field}:{self.prefix}"

    def evaluate(self, seg, ctx):
        mask = np.zeros(seg.doc_count, dtype=bool)
        for term in seg.terms_for_field(self.field):
            if term.startswith(self.prefix):
                mask |= _postings_mask(seg, self.field, term)
            elif term > self.prefix and not term.startswith(self.prefix):
                break
        return mask


@dataclass
class ExistsFilter(Filter):
    field: str

    def key(self):
        return f"exists:{self.field}"

    def evaluate(self, seg, ctx):
        mask = np.zeros(seg.doc_count, dtype=bool)
        td = seg.term_dict.get(self.field)
        if td:
            for tid in td.values():
                s, e = seg.post_offsets[tid], seg.post_offsets[tid + 1]
                mask[seg.post_docs[s:e]] = True
        col = seg.dv_num.get(self.field)
        if col is not None:
            off, _ = col
            mask |= np.diff(off) > 0
        scol = seg.dv_str.get(self.field)
        if scol is not None:
            _, off, _ = scol
            mask |= np.diff(off) > 0
        return mask


@dataclass
class MissingFilter(Filter):
    field: str

    def key(self):
        return f"missing:{self.field}"

    def evaluate(self, seg, ctx):
        return ~ExistsFilter(self.field).evaluate(seg, ctx)


@dataclass
class IdsFilter(Filter):
    ids: list
    types: list = dc_field(default_factory=list)

    def key(self):
        return f"ids:{sorted(self.types)}:{sorted(map(str, self.ids))!r}"

    def evaluate(self, seg, ctx):
        mask = np.zeros(seg.doc_count, dtype=bool)
        idset = set(map(str, self.ids))
        for local in range(seg.doc_count):
            if seg.parent_mask[local] and seg.ids[local] in idset:
                if not self.types or seg.types[local] in self.types:
                    mask[local] = True
        return mask


@dataclass
class TypeFilter(Filter):
    type: str

    def key(self):
        return f"type:{self.type}"

    def evaluate(self, seg, ctx):
        return np.asarray([t == self.type for t in seg.types], dtype=bool)


@dataclass
class MatchAllFilter(Filter):
    def key(self):
        return "match_all"

    def evaluate(self, seg, ctx):
        return np.ones(seg.doc_count, dtype=bool)


@dataclass
class BoolFilter(Filter):
    must: list = dc_field(default_factory=list)
    should: list = dc_field(default_factory=list)
    must_not: list = dc_field(default_factory=list)

    def key(self):
        return (
            "bool:" + "&".join(f.key() for f in self.must)
            + "|" + ";".join(f.key() for f in self.should)
            + "!" + ";".join(f.key() for f in self.must_not)
        )

    def evaluate(self, seg, ctx):
        mask = np.ones(seg.doc_count, dtype=bool)
        for f in self.must:
            mask &= segment_mask(seg, f, ctx)
        if self.should:
            smask = np.zeros(seg.doc_count, dtype=bool)
            for f in self.should:
                smask |= segment_mask(seg, f, ctx)
            mask &= smask
        for f in self.must_not:
            mask &= ~segment_mask(seg, f, ctx)
        return mask

    def cacheable(self):
        return all(f.cacheable()
                   for f in (*self.must, *self.should, *self.must_not))


@dataclass
class NotFilter(Filter):
    inner: Filter

    def key(self):
        return f"not:{self.inner.key()}"

    def evaluate(self, seg, ctx):
        return ~segment_mask(seg, self.inner, ctx)

    def cacheable(self):
        return self.inner.cacheable()


@dataclass
class QueryWrapperFilter(Filter):
    """Wraps a scoring query as a filter (ref: FQueryFilterParser / query filter)."""

    query: Any  # Query — evaluated via the host scorer for its match mask

    def key(self):
        return f"query:{self.query!r}"

    def evaluate(self, seg, ctx):
        from .execute import host_match_mask

        return host_match_mask(self.query, seg, ctx)


@dataclass
class NestedFilter(Filter):
    path: str
    inner: Any  # Query or Filter on child docs

    def key(self):
        return f"nested:{self.path}:{getattr(self.inner, 'key', lambda: repr(self.inner))()}"

    def evaluate(self, seg, ctx):
        from .execute import child_match_to_parents

        return child_match_to_parents(seg, ctx, self.path, self.inner)[0]


@dataclass
class GeoDistanceFilter(Filter):
    field: str
    lat: float
    lon: float
    distance_m: float

    def key(self):
        return f"geodist:{self.field}:{self.lat}:{self.lon}:{self.distance_m}"

    def evaluate(self, seg, ctx):
        return _geo_points_mask(
            seg, self.field,
            lambda lats, lons: haversine_m(self.lat, self.lon, lats, lons)
            <= self.distance_m)


def _geo_points_mask(seg, field: str, hit_fn) -> np.ndarray:
    """Doc mask from the multi-valued point columns: hit_fn(lats, lons) -> bool[V]
    per value, OR-scattered to docs — shared by every point-based geo filter."""
    lat_col = seg.dv_num.get(f"{field}.lat")
    lon_col = seg.dv_num.get(f"{field}.lon")
    mask = np.zeros(seg.doc_count, dtype=bool)
    if lat_col is None or lon_col is None:
        return mask
    off, lats = lat_col
    _, lons = lon_col
    hit = hit_fn(lats, lons)
    counts = np.diff(off)
    doc_of_val = np.repeat(np.arange(seg.doc_count), counts)
    np.logical_or.at(mask, doc_of_val, hit)
    return mask


@dataclass
class GeoShapeFilter(Filter):
    """Docs whose stored shape relates to the query shape.

    ref: GeoShapeFilter/GeoShapeQueryParser.java:1 — the reference tests prefix-tree
    cell terms; here the shape column is decoded once per segment (cached) and the
    relation computed exactly (common/geo.py)."""

    field: str
    shape: tuple  # normalized (kind, data)
    relation: str = "intersects"  # intersects | within | disjoint

    def key(self):
        import json

        return f"geoshape:{self.field}:{self.relation}:" \
               f"{json.dumps(self.shape, sort_keys=True)}"

    def _doc_shapes(self, seg):
        """Parsed per-doc shape lists, cached on the segment."""
        import json

        cache = seg._device_cache.setdefault("geo_shapes", {})
        parsed = cache.get(self.field)
        if parsed is None:
            parsed = [None] * seg.doc_count
            for d in range(seg.doc_count):
                vals = seg.str_values(self.field, d)
                if vals:
                    parsed[d] = [tuple(json.loads(v)) for v in vals]
            cache[self.field] = parsed
        return parsed

    def evaluate(self, seg, ctx):
        from ..common.geo import shape_within, shapes_intersect

        mask = np.zeros(seg.doc_count, dtype=bool)
        q = self.shape
        for d, shapes in enumerate(self._doc_shapes(seg)):
            if not shapes:
                continue
            if self.relation == "within":
                mask[d] = any(shape_within(s, q) for s in shapes)
            elif self.relation == "disjoint":
                mask[d] = not any(shapes_intersect(s, q) for s in shapes)
            else:
                mask[d] = any(shapes_intersect(s, q) for s in shapes)
        return mask


@dataclass
class GeohashCellFilter(Filter):
    """Docs whose geo_point falls in the given geohash cell (optionally + the 8
    neighbors). ref: index/query/GeohashCellFilter.java:1 — the reference matches
    indexed geohash prefix terms; here the cell is a bbox test over the point
    columns (identical semantics: a point is in the cell iff the cell geohash
    prefixes the point's geohash)."""

    field: str
    geohash: str
    neighbors: bool = False

    def key(self):
        return f"geohashcell:{self.field}:{self.geohash}:{self.neighbors}"

    def evaluate(self, seg, ctx):
        from ..common.geo import geohash_bbox, geohash_neighbors

        cells = [self.geohash] + (geohash_neighbors(self.geohash)
                                  if self.neighbors else [])

        def hit(lats, lons):
            h = np.zeros(len(lats), dtype=bool)
            for cell in cells:
                lat_lo, lat_hi, lon_lo, lon_hi = geohash_bbox(cell)
                h |= ((lats >= lat_lo) & (lats < lat_hi)
                      & (lons >= lon_lo) & (lons < lon_hi))
            return h

        return _geo_points_mask(seg, self.field, hit)


@dataclass
class GeoBoundingBoxFilter(Filter):
    field: str
    top: float
    left: float
    bottom: float
    right: float

    def key(self):
        return f"geobb:{self.field}:{self.top}:{self.left}:{self.bottom}:{self.right}"

    def evaluate(self, seg, ctx):
        def hit(lats, lons):
            h = (lats <= self.top) & (lats >= self.bottom)
            if self.left <= self.right:
                return h & (lons >= self.left) & (lons <= self.right)
            return h & ((lons >= self.left) | (lons <= self.right))  # dateline

        return _geo_points_mask(seg, self.field, hit)


@dataclass
class ScriptFilter(Filter):
    script: str
    params: dict = dc_field(default_factory=dict)

    def key(self):
        return f"script:{self.script}:{sorted(self.params.items())!r}"

    def evaluate(self, seg, ctx):
        from ..script import compile_script

        fn = compile_script(self.script, self.params)
        mask = np.zeros(seg.doc_count, dtype=bool)
        for local in range(seg.doc_count):
            if seg.parent_mask[local]:
                mask[local] = bool(fn(DocAccess(seg, local)))
        return mask


@dataclass
class RegexpFilter(Filter):
    field: str
    pattern: str

    def key(self):
        return f"regexp:{self.field}:{self.pattern}"

    def evaluate(self, seg, ctx):
        rex = re.compile(self.pattern)
        mask = np.zeros(seg.doc_count, dtype=bool)
        for term in seg.terms_for_field(self.field):
            if rex.fullmatch(term):
                mask |= _postings_mask(seg, self.field, term)
        return mask


EARTH_RADIUS_M = 6371008.7714


@dataclass
class HasChildFilter(Filter):
    """Parent docs with a matching child — the non-scoring filter form
    (ref: index/query/HasChildFilterParser.java:1). Wraps the query-form's
    cross-segment join (execute._shard_join) because parent/child links span
    segments; the per-segment mask slices out of that shard-level join."""

    query: Any  # HasChildQuery or HasParentQuery with score_mode "none"

    def key(self):
        q = self.query
        inner_key = repr(q.query)
        return f"haschildf:{type(q).__name__}:{getattr(q, 'child_type', getattr(q, 'parent_type', None))}:{inner_key}"

    def cacheable(self):
        # the join spans the whole shard: a per-segment cached mask would go
        # stale when a child lands in (or leaves) ANOTHER segment
        return False

    def evaluate(self, seg, ctx):
        from .execute import _shard_join

        # one join per (searcher, filter): the searcher's segment set is
        # immutable for its lifetime, so caching there is both correct and
        # avoids recomputing the shard-wide join once per segment
        cache = getattr(ctx.searcher, "_join_cache", None)
        if cache is None:
            cache = ctx.searcher._join_cache = {}
        join = cache.get(self.key())
        if join is None:
            join = cache[self.key()] = _shard_join(ctx, self.query)
        for si, s in enumerate(ctx.searcher.segments):
            if s is seg:
                return join[si][1]
        return np.zeros(seg.doc_count, dtype=bool)


@dataclass
class GeoPolygonFilter(Filter):
    """Docs with a point inside the polygon (ray casting over the value columns).

    ref: index/query/GeoPolygonFilterParser.java:1 + GeoPolygonFilter.java —
    the reference walks polygon edges per point (pointInPolygon); here the
    crossing test vectorizes over every stored point at once."""

    field: str
    points: tuple  # ((lat, lon), ...) — closed or open ring, either works

    def key(self):
        return f"geopoly:{self.field}:{self.points}"

    def evaluate(self, seg, ctx):
        pts = [p for p in self.points]
        if len(pts) > 1 and pts[0] == pts[-1]:
            pts = pts[:-1]  # drop the explicit closing point
        lat_v = np.asarray([p[0] for p in pts])
        lon_v = np.asarray([p[1] for p in pts])

        def inside(lats, lons):
            hit = np.zeros(len(lats), dtype=bool)
            n = len(lat_v)
            for i in range(n):
                j = (i - 1) % n
                crosses = ((lat_v[i] > lats) != (lat_v[j] > lats)) & (
                    lons < (lon_v[j] - lon_v[i]) * (lats - lat_v[i])
                    / (lat_v[j] - lat_v[i] + 1e-300) + lon_v[i])
                hit ^= crosses
            return hit

        return _geo_points_mask(seg, self.field, inside)


@dataclass
class GeoDistanceRangeFilter(Filter):
    """Docs whose point distance from the origin falls in [from, to).

    ref: index/query/GeoDistanceRangeFilterParser.java:1 — the ring/doughnut
    variant of geo_distance; bounds honor include_lower/include_upper."""

    field: str
    lat: float
    lon: float
    from_m: float | None = None
    to_m: float | None = None
    include_lower: bool = True
    include_upper: bool = True

    def key(self):
        return (f"geodistrange:{self.field}:{self.lat}:{self.lon}:"
                f"{self.from_m}:{self.to_m}:{self.include_lower}:{self.include_upper}")

    def evaluate(self, seg, ctx):
        def hit(lats, lons):
            d = haversine_m(self.lat, self.lon, lats, lons)
            ok = np.ones(len(d), dtype=bool)
            if self.from_m is not None:
                ok &= (d >= self.from_m) if self.include_lower else (d > self.from_m)
            if self.to_m is not None:
                ok &= (d <= self.to_m) if self.include_upper else (d < self.to_m)
            return ok

        return _geo_points_mask(seg, self.field, hit)


@dataclass
class IndicesFilter(Filter):
    """Filter that applies only when searching the named indices; other indices
    see no_match_filter (default all — ref: IndicesFilterParser.java:1).
    Needs the shard's index name: ShardContext.index_name (None = assume match,
    the single-index embedded case)."""

    indices: tuple
    filter: Any = None
    no_match_filter: Any = None  # None = match_all
    no_match_none: bool = False

    def key(self):
        inner_key = getattr(self.filter, "key", lambda: repr(self.filter))()
        nm_key = (getattr(self.no_match_filter, "key",
                          lambda: repr(self.no_match_filter))()
                  if self.no_match_filter is not None else "all")
        return f"indices:{self.indices}:{inner_key}:{nm_key}:{self.no_match_none}"

    def cacheable(self):
        return (self.filter is None or self.filter.cacheable()) and (
            self.no_match_filter is None or self.no_match_filter.cacheable())

    def _matches_index(self, ctx) -> bool:
        name = getattr(ctx, "index_name", None)
        if name is None:
            return True
        import fnmatch

        return any(fnmatch.fnmatch(name, pat) for pat in self.indices)

    def evaluate(self, seg, ctx):
        if self._matches_index(ctx):
            return segment_mask(seg, self.filter, ctx)
        if self.no_match_none:
            return np.zeros(seg.doc_count, dtype=bool)
        if self.no_match_filter is None:
            return np.ones(seg.doc_count, dtype=bool)
        return segment_mask(seg, self.no_match_filter, ctx)


def haversine_m(lat1, lon1, lat2, lon2):
    la1, lo1 = np.radians(lat1), np.radians(lon1)
    la2, lo2 = np.radians(lat2), np.radians(lon2)
    a = np.sin((la2 - la1) / 2) ** 2 + np.cos(la1) * np.cos(la2) * np.sin((lo2 - lo1) / 2) ** 2
    return 2 * EARTH_RADIUS_M * np.arcsin(np.sqrt(np.clip(a, 0, 1)))


_DIST_RE = re.compile(r"^\s*([\d.]+)\s*([a-zA-Z]*)\s*$")
_DIST_UNITS = {
    "m": 1.0, "meters": 1.0, "km": 1000.0, "kilometers": 1000.0,
    "mi": 1609.344, "miles": 1609.344, "yd": 0.9144, "ft": 0.3048,
    "in": 0.0254, "cm": 0.01, "mm": 0.001, "nmi": 1852.0, "": 1.0,
}


def parse_distance(s) -> float:
    if isinstance(s, (int, float)):
        return float(s)
    m = _DIST_RE.match(str(s))
    if not m:
        raise QueryParsingError(f"failed to parse distance [{s}]")
    return float(m.group(1)) * _DIST_UNITS.get(m.group(2).lower(), 1.0)


class DocAccess:
    """Per-doc field access for scripts: doc['field'].value style."""

    def __init__(self, seg: FrozenSegment, local: int):
        self.seg = seg
        self.local = local

    def __getitem__(self, field: str):
        nums = self.seg.num_values(field, self.local)
        if len(nums):
            return FieldVal(list(nums))
        return FieldVal(self.seg.str_values(field, self.local))


class FieldVal:
    def __init__(self, values: list):
        self.values = values

    @property
    def value(self):
        return self.values[0] if self.values else None

    @property
    def empty(self):
        return not self.values


def _index_term(ctx, field: str, value) -> str:
    """How a term/terms filter value maps to an indexed token: not_analyzed fields keep
    the raw value; analyzed fields take the single analyzed token (ES term filter
    semantics: no analysis — we mirror that by using the raw value lowercased only when
    the target field is analyzed with a lowercasing chain is NOT applied — raw match)."""
    return str(value)
