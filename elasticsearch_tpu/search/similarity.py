"""Similarity (scoring models): Lucene-exact BM25 and classic TF-IDF.

Analogue of index/similarity/ (SURVEY.md §2.3 — "the north-star intercept point"):
per-field pluggable similarity configured via index settings/mappings, default TF-IDF,
BM25 opt-in — matching the reference's SimilarityModule (BM25SimilarityProvider.java,
DefaultSimilarityProvider.java).

Exactness notes (hit-ordering parity, SURVEY.md §7 hard parts):
- Norms are the byte315-quantized 1/sqrt(fieldLength) — common/smallfloat.py.
- TF-IDF practical scoring (Lucene TFIDFSimilarity):
    score(q,d) = coord(q,d) · Σ_t [ tf(freq) · idf(t)² · queryNorm · boost_t · norm(d) ]
    tf = sqrt(freq); idf = 1 + ln(maxDocs/(docFreq+1));
    queryNorm = 1/sqrt(Σ (idf·boost)²)  [rank-neutral but computed for score parity]
    coord = overlap/maxOverlap for bool queries.
- BM25 (Lucene 4.7 BM25Similarity, k1=1.2 b=0.75):
    idf = ln(1 + (N - df + 0.5)/(df + 0.5))     [N = maxDoc]
    tfNorm = freq·(k1+1) / (freq + k1·(1 - b + b·dl/avgdl))
    avgdl = sumTotalTermFreq/maxDoc;  dl decoded from the 1-byte norm
    score = Σ_t boost_t · idf_t · tfNorm   (no coord, no queryNorm)
- All arithmetic float32, matching Lucene's float math.

The similarity exposes two device-friendly artifacts per (field, query): a scalar
per-term weight and a 256-entry norm-decode table, so the scoring kernel is pure
gather/FMA — see ops/scoring.py.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import numpy as np

from ..common.smallfloat import NORM_TABLE, decode_norm_doclen


@dataclass
class TermStats:
    doc_freq: int
    total_term_freq: int = 0


class Similarity:
    name = "base"

    def term_weight(self, boost: float, df: int, max_docs: int) -> float:
        raise NotImplementedError

    def norm_cache(self, field_stats, max_docs: int) -> np.ndarray:
        """256-entry table indexed by the norm byte; meaning is similarity-specific."""
        raise NotImplementedError

    def needs_coord(self) -> bool:
        return False


class TFIDFSimilarity(Similarity):
    """Lucene DefaultSimilarity. term weight folds idf² (queryNorm applied separately
    per query since it spans all terms)."""

    name = "default"

    @staticmethod
    def idf(df: int, max_docs: int) -> float:
        return np.float32(1.0 + math.log(max_docs / (df + 1.0)))

    @staticmethod
    def tf(freq: np.ndarray) -> np.ndarray:
        return np.sqrt(freq, dtype=np.float32)

    def term_weight(self, boost: float, df: int, max_docs: int) -> float:
        # idf * boost = query-time weight; squared via the separate queryNorm pipeline:
        # scorer value = queryWeight * idf = idf² * boost * queryNorm
        return float(self.idf(df, max_docs) * boost)

    def norm_cache(self, field_stats, max_docs: int) -> np.ndarray:
        # TF-IDF: decoded norm multiplies the score directly
        return NORM_TABLE.astype(np.float32)

    def needs_coord(self) -> bool:
        return True

    @staticmethod
    def query_norm(sum_sq_weights: float) -> float:
        if sum_sq_weights <= 0:
            return 1.0
        return np.float32(1.0 / math.sqrt(sum_sq_weights))


class BM25Similarity(Similarity):
    name = "BM25"

    def __init__(self, k1: float = 1.2, b: float = 0.75):
        self.k1 = float(k1)
        self.b = float(b)

    @staticmethod
    def idf(df: int, max_docs: int) -> float:
        return np.float32(math.log(1.0 + (max_docs - df + 0.5) / (df + 0.5)))

    def term_weight(self, boost: float, df: int, max_docs: int) -> float:
        return float(self.idf(df, max_docs) * boost)

    def norm_cache(self, field_stats, max_docs: int) -> np.ndarray:
        """cache[b] = k1 * (1 - b + b * dl(byte)/avgdl) — the denominator addend, exactly
        Lucene BM25Similarity's per-weight norm cache."""
        sum_ttf = getattr(field_stats, "sum_ttf", 0) if field_stats else 0
        avgdl = np.float32(1.0) if sum_ttf <= 0 or max_docs <= 0 else np.float32(sum_ttf / max_docs)
        dl = decode_norm_doclen(np.arange(256, dtype=np.uint8))
        return (self.k1 * (1.0 - self.b + self.b * dl / avgdl)).astype(np.float32)


class FreqNormSimilarity(Similarity):
    """Base for similarities scored as f(freq, doc_len, corpus stats) — the shape of
    Lucene's SimilarityBase, which the reference's DFR/IB providers build on
    (index/similarity/DFRSimilarityProvider.java, IBSimilarityProvider.java).

    These run on the host scorer path (the device kernel keeps its two fused
    fast-path modes, BM25/TF-IDF; queries over DFR/IB fields lower to host)."""

    def term_weight(self, boost: float, df: int, max_docs: int) -> float:
        return float(boost)

    def norm_cache(self, field_stats, max_docs: int) -> np.ndarray:
        return NORM_TABLE.astype(np.float32)

    def score_freqs(self, freqs: np.ndarray, doc_len: np.ndarray, df: int,
                    ttf: int, field_stats, max_docs: int,
                    boost: float) -> np.ndarray:
        """Vectorized over a term's postings: freqs[i] occurrences in a doc of
        doc_len[i] tokens → per-doc contribution."""
        raise NotImplementedError

    @staticmethod
    def _avgdl(field_stats, max_docs: int) -> float:
        sum_ttf = getattr(field_stats, "sum_ttf", 0) if field_stats else 0
        docs = getattr(field_stats, "doc_count", 0) or max_docs
        return float(sum_ttf) / docs if sum_ttf > 0 and docs > 0 else 1.0


_LOG2 = math.log(2.0)


def _log2(x):
    return np.log(np.maximum(x, 1e-12)) / _LOG2


class DFRSimilarity(FreqNormSimilarity):
    """Divergence-from-randomness framework (Amati & van Rijsbergen): score =
    boost · basic_model(tfn) · after_effect(tfn), tfn = length-normalized tf.
    Models/effects/normalizations match the reference's option set
    (DFRSimilarityProvider.java: be/d/g/if/in/ine × no/b/l × no/h1/h2/h3/z)."""

    name = "DFR"

    def __init__(self, basic_model: str = "g", after_effect: str = "l",
                 normalization: str = "h2", c: float = 1.0, mu: float = 800.0,
                 z: float = 0.3):
        self.basic_model = basic_model.lower()
        self.after_effect = after_effect.lower()
        self.normalization = normalization.lower()
        self.c, self.mu, self.z = float(c), float(mu), float(z)

    def _tfn(self, freqs, doc_len, field_stats, max_docs, ttf):
        avgdl = self._avgdl(field_stats, max_docs)
        dl = np.maximum(doc_len.astype(np.float64), 1.0)
        f = freqs.astype(np.float64)
        if self.normalization in ("no", "none"):
            return f
        if self.normalization == "h1":
            return f * (avgdl / dl)
        if self.normalization == "h2":
            return f * _log2(1.0 + self.c * avgdl / dl)
        if self.normalization == "h3":
            sum_ttf = (getattr(field_stats, "sum_ttf", 0) if field_stats else 0) or 1
            p = (ttf + 1.0) / (sum_ttf + 1.0)
            return (f + self.mu * p) / (dl + self.mu) * self.mu
        if self.normalization == "z":
            return f * (avgdl / dl) ** self.z
        return f * _log2(1.0 + self.c * avgdl / dl)

    def score_freqs(self, freqs, doc_len, df, ttf, field_stats, max_docs, boost):
        N = max(max_docs, 1)
        n = max(df, 1)
        F = max(ttf, n)
        tfn = np.maximum(self._tfn(freqs, doc_len, field_stats, max_docs, ttf), 1e-9)
        lam = F / float(N)
        m = self.basic_model
        if m == "be":
            # Bose-Einstein (Bernoulli approximation)
            score = -_log2(1.0 / (1.0 + lam)) - tfn * _log2(lam / (1.0 + lam))
        elif m == "g":
            lg = F / float(N + F)
            score = -_log2(1.0 / (1.0 + lg)) - tfn * _log2(lg / (1.0 + lg))
        elif m == "p":
            # Poisson approximation via Stirling
            score = tfn * _log2(tfn / lam) + (lam - tfn) / _LOG2 + \
                0.5 * _log2(2.0 * math.pi * tfn)
        elif m == "d":
            phi = tfn / (tfn + 1.0)
            score = tfn * _log2(tfn / lam) + (lam + 1.0 / 12.0 / tfn - tfn) / _LOG2 + \
                0.5 * _log2(2.0 * math.pi * tfn) * phi
        elif m == "in":
            score = tfn * _log2((N + 1.0) / (n + 0.5))
        elif m == "ine":
            ne = N * (1.0 - ((N - 1.0) / N) ** F)
            score = tfn * _log2((N + 1.0) / (ne + 0.5))
        else:  # "if" — inverse term frequency
            score = tfn * _log2((N + 1.0) / (F + 0.5))
        ae = self.after_effect
        if ae == "b":
            gain = (F + 1.0) / (n * (tfn + 1.0))
        elif ae in ("no", "none"):
            gain = 1.0
        else:  # "l" — Laplace
            gain = 1.0 / (tfn + 1.0)
        return np.maximum(boost * gain * score, 0.0).astype(np.float32)


class IBSimilarity(FreqNormSimilarity):
    """Information-based framework (Clinchant & Gaussier): score =
    boost · distribution(tfn, λ) with λ from df or ttf
    (ref: IBSimilarityProvider.java — distribution ll/spl, lambda df/ttf,
    normalization shared with DFR)."""

    name = "IB"

    def __init__(self, distribution: str = "ll", lambda_: str = "df",
                 normalization: str = "h2", c: float = 1.0):
        self.distribution = distribution.lower()
        self.lambda_ = lambda_.lower()
        self._norm = DFRSimilarity(normalization=normalization, c=c)

    def score_freqs(self, freqs, doc_len, df, ttf, field_stats, max_docs, boost):
        N = max(max_docs, 1)
        tfn = np.maximum(
            self._norm._tfn(freqs, doc_len, field_stats, max_docs, ttf), 1e-9)
        if self.lambda_ == "ttf":
            lam = (max(ttf, 1) + 1.0) / (N + 1.0)
        else:
            lam = (max(df, 1) + 1.0) / (N + 1.0)
        if self.distribution == "spl":
            score = -_log2((np.power(lam, tfn / (tfn + 1.0)) - lam) /
                           np.maximum(1.0 - lam, 1e-12))
        else:  # "ll" — log-logistic
            score = _log2((tfn + lam) / lam)
        return np.maximum(boost * score, 0.0).astype(np.float32)


class LMDirichletSimilarity(FreqNormSimilarity):
    """LM with Dirichlet smoothing (Lucene LMDirichletSimilarity shape)."""

    name = "LMDirichlet"

    def __init__(self, mu: float = 2000.0):
        self.mu = float(mu)

    def score_freqs(self, freqs, doc_len, df, ttf, field_stats, max_docs, boost):
        sum_ttf = (getattr(field_stats, "sum_ttf", 0) if field_stats else 0) or 1
        p = (max(ttf, 1) + 1.0) / (sum_ttf + 1.0)
        dl = np.maximum(doc_len.astype(np.float64), 0.0)
        score = np.log(1.0 + freqs / (self.mu * p)) + np.log(self.mu / (dl + self.mu))
        return np.maximum(boost * score, 0.0).astype(np.float32)


class LMJelinekMercerSimilarity(FreqNormSimilarity):
    """LM with Jelinek-Mercer smoothing (Lucene LMJelinekMercerSimilarity shape)."""

    name = "LMJelinekMercer"

    def __init__(self, lambda_: float = 0.1):
        self.lambda_ = float(lambda_)

    def score_freqs(self, freqs, doc_len, df, ttf, field_stats, max_docs, boost):
        sum_ttf = (getattr(field_stats, "sum_ttf", 0) if field_stats else 0) or 1
        p = (max(ttf, 1) + 1.0) / (sum_ttf + 1.0)
        dl = np.maximum(doc_len.astype(np.float64), 1.0)
        score = np.log(1.0 + ((1.0 - self.lambda_) * freqs / dl) / (self.lambda_ * p))
        return np.maximum(boost * score, 0.0).astype(np.float32)


_REGISTRY = {
    "default": TFIDFSimilarity,
    "tfidf": TFIDFSimilarity,
    "BM25": BM25Similarity,
    "bm25": BM25Similarity,
    "DFR": DFRSimilarity,
    "dfr": DFRSimilarity,
    "IB": IBSimilarity,
    "ib": IBSimilarity,
    "LMDirichlet": LMDirichletSimilarity,
    "LMJelinekMercer": LMJelinekMercerSimilarity,
}


class SimilarityService:
    """Per-index similarity resolution (ref: index/similarity/SimilarityService.java):
    named configs from `index.similarity.<name>.*` settings, per-field override via the
    mapping's `similarity` key, default from `index.similarity.default.type`."""

    def __init__(self, index_settings=None, mapper_service=None):
        from ..common.settings import Settings

        settings = index_settings or Settings.EMPTY
        self.mapper_service = mapper_service
        self._named: dict[str, Similarity] = {}
        for name, conf in settings.groups("index.similarity.").items():
            stype = conf.get_str("type", name)
            self._named[name] = self._build(stype, conf)
        self.default: Similarity = self._named.get("default", TFIDFSimilarity())

    @staticmethod
    def _build(stype: str, conf) -> Similarity:
        cls = _REGISTRY.get(stype)
        if cls is None:
            from ..common.errors import IllegalArgumentError

            raise IllegalArgumentError(f"unknown similarity type [{stype}]")
        if cls is BM25Similarity:
            return BM25Similarity(conf.get_float("k1", 1.2), conf.get_float("b", 0.75))
        if cls is DFRSimilarity:
            return DFRSimilarity(
                basic_model=conf.get_str("basic_model", "g"),
                after_effect=conf.get_str("after_effect", "l"),
                normalization=conf.get_str("normalization", "h2"),
                c=conf.get_float("normalization.h2.c", conf.get_float("c", 1.0)),
                mu=conf.get_float("normalization.h3.mu", 800.0),
                z=conf.get_float("normalization.z.z", 0.3))
        if cls is IBSimilarity:
            return IBSimilarity(
                distribution=conf.get_str("distribution", "ll"),
                lambda_=conf.get_str("lambda", "df"),
                normalization=conf.get_str("normalization", "h2"),
                c=conf.get_float("normalization.h2.c", conf.get_float("c", 1.0)))
        if cls is LMDirichletSimilarity:
            return LMDirichletSimilarity(mu=conf.get_float("mu", 2000.0))
        if cls is LMJelinekMercerSimilarity:
            return LMJelinekMercerSimilarity(lambda_=conf.get_float("lambda", 0.1))
        return cls()

    def for_field(self, field: str) -> Similarity:
        if self.mapper_service is not None:
            ft = self.mapper_service.field_type(field)
            sim_name = getattr(ft, "similarity", None) if ft else None
            if sim_name and sim_name in self._named:
                return self._named[sim_name]
        return self.default
