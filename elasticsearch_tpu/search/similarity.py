"""Similarity (scoring models): Lucene-exact BM25 and classic TF-IDF.

Analogue of index/similarity/ (SURVEY.md §2.3 — "the north-star intercept point"):
per-field pluggable similarity configured via index settings/mappings, default TF-IDF,
BM25 opt-in — matching the reference's SimilarityModule (BM25SimilarityProvider.java,
DefaultSimilarityProvider.java).

Exactness notes (hit-ordering parity, SURVEY.md §7 hard parts):
- Norms are the byte315-quantized 1/sqrt(fieldLength) — common/smallfloat.py.
- TF-IDF practical scoring (Lucene TFIDFSimilarity):
    score(q,d) = coord(q,d) · Σ_t [ tf(freq) · idf(t)² · queryNorm · boost_t · norm(d) ]
    tf = sqrt(freq); idf = 1 + ln(maxDocs/(docFreq+1));
    queryNorm = 1/sqrt(Σ (idf·boost)²)  [rank-neutral but computed for score parity]
    coord = overlap/maxOverlap for bool queries.
- BM25 (Lucene 4.7 BM25Similarity, k1=1.2 b=0.75):
    idf = ln(1 + (N - df + 0.5)/(df + 0.5))     [N = maxDoc]
    tfNorm = freq·(k1+1) / (freq + k1·(1 - b + b·dl/avgdl))
    avgdl = sumTotalTermFreq/maxDoc;  dl decoded from the 1-byte norm
    score = Σ_t boost_t · idf_t · tfNorm   (no coord, no queryNorm)
- All arithmetic float32, matching Lucene's float math.

The similarity exposes two device-friendly artifacts per (field, query): a scalar
per-term weight and a 256-entry norm-decode table, so the scoring kernel is pure
gather/FMA — see ops/scoring.py.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import numpy as np

from ..common.smallfloat import NORM_TABLE, decode_norm_doclen


@dataclass
class TermStats:
    doc_freq: int
    total_term_freq: int = 0


class Similarity:
    name = "base"

    def term_weight(self, boost: float, df: int, max_docs: int) -> float:
        raise NotImplementedError

    def norm_cache(self, field_stats, max_docs: int) -> np.ndarray:
        """256-entry table indexed by the norm byte; meaning is similarity-specific."""
        raise NotImplementedError

    def needs_coord(self) -> bool:
        return False


class TFIDFSimilarity(Similarity):
    """Lucene DefaultSimilarity. term weight folds idf² (queryNorm applied separately
    per query since it spans all terms)."""

    name = "default"

    @staticmethod
    def idf(df: int, max_docs: int) -> float:
        return np.float32(1.0 + math.log(max_docs / (df + 1.0)))

    @staticmethod
    def tf(freq: np.ndarray) -> np.ndarray:
        return np.sqrt(freq, dtype=np.float32)

    def term_weight(self, boost: float, df: int, max_docs: int) -> float:
        # idf * boost = query-time weight; squared via the separate queryNorm pipeline:
        # scorer value = queryWeight * idf = idf² * boost * queryNorm
        return float(self.idf(df, max_docs) * boost)

    def norm_cache(self, field_stats, max_docs: int) -> np.ndarray:
        # TF-IDF: decoded norm multiplies the score directly
        return NORM_TABLE.astype(np.float32)

    def needs_coord(self) -> bool:
        return True

    @staticmethod
    def query_norm(sum_sq_weights: float) -> float:
        if sum_sq_weights <= 0:
            return 1.0
        return np.float32(1.0 / math.sqrt(sum_sq_weights))


class BM25Similarity(Similarity):
    name = "BM25"

    def __init__(self, k1: float = 1.2, b: float = 0.75):
        self.k1 = float(k1)
        self.b = float(b)

    @staticmethod
    def idf(df: int, max_docs: int) -> float:
        return np.float32(math.log(1.0 + (max_docs - df + 0.5) / (df + 0.5)))

    def term_weight(self, boost: float, df: int, max_docs: int) -> float:
        return float(self.idf(df, max_docs) * boost)

    def norm_cache(self, field_stats, max_docs: int) -> np.ndarray:
        """cache[b] = k1 * (1 - b + b * dl(byte)/avgdl) — the denominator addend, exactly
        Lucene BM25Similarity's per-weight norm cache."""
        sum_ttf = getattr(field_stats, "sum_ttf", 0) if field_stats else 0
        avgdl = np.float32(1.0) if sum_ttf <= 0 or max_docs <= 0 else np.float32(sum_ttf / max_docs)
        dl = decode_norm_doclen(np.arange(256, dtype=np.uint8))
        return (self.k1 * (1.0 - self.b + self.b * dl / avgdl)).astype(np.float32)


_REGISTRY = {
    "default": TFIDFSimilarity,
    "tfidf": TFIDFSimilarity,
    "BM25": BM25Similarity,
    "bm25": BM25Similarity,
}


class SimilarityService:
    """Per-index similarity resolution (ref: index/similarity/SimilarityService.java):
    named configs from `index.similarity.<name>.*` settings, per-field override via the
    mapping's `similarity` key, default from `index.similarity.default.type`."""

    def __init__(self, index_settings=None, mapper_service=None):
        from ..common.settings import Settings

        settings = index_settings or Settings.EMPTY
        self.mapper_service = mapper_service
        self._named: dict[str, Similarity] = {}
        for name, conf in settings.groups("index.similarity.").items():
            stype = conf.get_str("type", name)
            self._named[name] = self._build(stype, conf)
        self.default: Similarity = self._named.get("default", TFIDFSimilarity())

    @staticmethod
    def _build(stype: str, conf) -> Similarity:
        cls = _REGISTRY.get(stype)
        if cls is None:
            from ..common.errors import IllegalArgumentError

            raise IllegalArgumentError(f"unknown similarity type [{stype}]")
        if cls is BM25Similarity:
            return BM25Similarity(conf.get_float("k1", 1.2), conf.get_float("b", 0.75))
        return cls()

    def for_field(self, field: str) -> Similarity:
        if self.mapper_service is not None:
            ft = self.mapper_service.field_type(field)
            sim_name = getattr(ft, "similarity", None) if ft else None
            if sim_name and sim_name in self._named:
                return self._named[sim_name]
        return self.default
