"""Aggregations: collector-tree framework + metrics/bucket implementations.

Analogue of search/aggregations/ (17k LoC — SURVEY.md §2.5): every aggregation defines a
map-side collect over one segment's matching docs and a reduce-side merge of partial
results — exactly the shape the reference uses (Aggregator / InternalAggregation) and
exactly what distributes over shards as a collective reduce (SURVEY.md §5.7 "shard-level
parallel reduce of aggregations").

Implemented (registered like AggregationModule.java:54-73):
  metrics : avg, sum, min, max, stats, extended_stats, value_count, cardinality,
            percentiles, top_hits (single-shard), geo_bounds
  buckets : terms, range, date_range, ip_range, histogram, date_histogram, filter,
            filters, global, missing, nested, significant_terms (simplified scoring),
            geo_distance
Sub-aggregations nest arbitrarily (bucket → mask → child collect).

Collect is vectorized numpy over columnar doc values (the fielddata analogue); the
hot single-valued numeric cases (sum/avg/min/max/histogram) read the same columns the
device keeps in PackedSegment.dv_single, so a later round can lower whole agg trees to
segment_sum on device without changing this API.
"""

from __future__ import annotations

import math
from typing import Any

import numpy as np

from ..common.breaker import reserve
from ..common.errors import QueryParsingError
from ..mapper.core import parse_date_math
from .filters import haversine_m, parse_distance, segment_mask
from .queries import parse_filter, parse_query

# ---------------------------------------------------------------------------
# framework
# ---------------------------------------------------------------------------


class Agg:
    """One aggregation node: collect(seg, ctx, mask) -> partial; merge(partials) ->
    reduced; finalize(reduced) -> response dict."""

    def __init__(self, name: str, spec: dict, subs: "dict[str, Agg] | None" = None):
        self.name = name
        self.spec = spec
        self.subs = subs or {}

    def collect(self, seg, ctx, mask: np.ndarray, scores: np.ndarray | None = None):
        raise NotImplementedError

    def merge(self, partials: list):
        raise NotImplementedError

    def finalize(self, merged) -> dict:
        raise NotImplementedError

    # helpers ---------------------------------------------------------------
    def _collect_subs(self, seg, ctx, mask, scores=None) -> dict:
        return {n: a.collect(seg, ctx, mask, scores) for n, a in self.subs.items()}

    def _merge_subs(self, partial_list: list[dict]) -> dict:
        return {
            n: a.merge([p[n] for p in partial_list]) for n, a in self.subs.items()
        }

    def _finalize_subs(self, merged: dict) -> dict:
        return {n: a.finalize(merged[n]) for n, a in self.subs.items()}


def parse_aggs(spec: dict) -> dict[str, Agg]:
    out: dict[str, Agg] = {}
    for name, body in (spec or {}).items():
        subs_spec = body.get("aggs") or body.get("aggregations") or {}
        subs = parse_aggs(subs_spec)
        kinds = [k for k in body if k not in ("aggs", "aggregations", "meta")]
        if len(kinds) != 1:
            raise QueryParsingError(f"aggregation [{name}] must have exactly one type")
        kind = kinds[0]
        cls = _AGG_REGISTRY.get(kind)
        if cls is None:
            raise QueryParsingError(f"unknown aggregation type [{kind}]")
        out[name] = cls(name, body[kind], subs)
    return out


def run_aggs(aggs: dict[str, Agg], seg_masks: list, ctx) -> list[dict]:
    """Collect partials per segment: seg_masks = [(seg, mask, scores)]."""
    partials = []
    for seg, mask, scores in seg_masks:
        partials.append({n: a.collect(seg, ctx, mask, scores) for n, a in aggs.items()})
    return partials


def reduce_aggs(aggs: dict[str, Agg], partial_list: list[dict]) -> dict:
    """Merge partials (across segments AND shards — same operation) + finalize."""
    return {
        n: a.finalize(a.merge([p[n] for p in partial_list])) for n, a in aggs.items()
    }


def _field_values(seg, field: str, mask: np.ndarray):
    """(doc_idx_per_value, values) for numeric columns restricted to mask."""
    col = seg.dv_num.get(field)
    if col is None:
        return np.zeros(0, np.int64), np.zeros(0)
    off, vals = col
    counts = np.diff(off)
    doc_of_val = np.repeat(np.arange(seg.doc_count), counts)
    sel = mask[doc_of_val]
    return doc_of_val[sel], vals[sel]


def _str_values(seg, field: str, mask: np.ndarray):
    col = seg.dv_str.get(field)
    if col is None:
        return np.zeros(0, np.int64), []
    uniq, off, ords = col
    counts = np.diff(off)
    doc_of_val = np.repeat(np.arange(seg.doc_count), counts)
    sel = mask[doc_of_val]
    return doc_of_val[sel], [uniq[o] for o in ords[sel]]


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------


class _NumericAgg(Agg):
    def _values(self, seg, ctx, mask):
        field = self.spec.get("field")
        vals: np.ndarray
        if field:
            _, vals = _field_values(seg, field, mask)
        else:
            script = self.spec.get("script")
            if not script:
                raise QueryParsingError(f"agg [{self.name}] requires field or script")
            from ..script import compile_script
            from .filters import DocAccess

            fn = compile_script(script, self.spec.get("params", {}))
            vals = np.asarray([
                float(fn(DocAccess(seg, int(d)))) for d in np.nonzero(mask)[0]
            ])
        return vals


class SumAgg(_NumericAgg):
    def collect(self, seg, ctx, mask, scores=None):
        return float(self._values(seg, ctx, mask).sum())

    def merge(self, partials):
        return float(sum(partials))

    def finalize(self, merged):
        return {"value": merged}


class AvgAgg(_NumericAgg):
    def collect(self, seg, ctx, mask, scores=None):
        v = self._values(seg, ctx, mask)
        return (float(v.sum()), int(len(v)))

    def merge(self, partials):
        return (sum(p[0] for p in partials), sum(p[1] for p in partials))

    def finalize(self, merged):
        s, c = merged
        return {"value": (s / c) if c else None}


class MinAgg(_NumericAgg):
    def collect(self, seg, ctx, mask, scores=None):
        v = self._values(seg, ctx, mask)
        return float(v.min()) if len(v) else None

    def merge(self, partials):
        vals = [p for p in partials if p is not None]
        return min(vals) if vals else None

    def finalize(self, merged):
        return {"value": merged}


class MaxAgg(_NumericAgg):
    def collect(self, seg, ctx, mask, scores=None):
        v = self._values(seg, ctx, mask)
        return float(v.max()) if len(v) else None

    def merge(self, partials):
        vals = [p for p in partials if p is not None]
        return max(vals) if vals else None

    def finalize(self, merged):
        return {"value": merged}


class ValueCountAgg(_NumericAgg):
    def collect(self, seg, ctx, mask, scores=None):
        field = self.spec.get("field")
        if field and field in seg.dv_str:
            _, vals = _str_values(seg, field, mask)
            return len(vals)
        return int(len(self._values(seg, ctx, mask)))

    def merge(self, partials):
        return int(sum(partials))

    def finalize(self, merged):
        return {"value": merged}


class StatsAgg(_NumericAgg):
    def collect(self, seg, ctx, mask, scores=None):
        v = self._values(seg, ctx, mask)
        if not len(v):
            return (0, 0.0, None, None, 0.0)
        return (int(len(v)), float(v.sum()), float(v.min()), float(v.max()),
                float((v * v).sum()))

    def merge(self, partials):
        count = sum(p[0] for p in partials)
        total = sum(p[1] for p in partials)
        mins = [p[2] for p in partials if p[2] is not None]
        maxs = [p[3] for p in partials if p[3] is not None]
        sq = sum(p[4] for p in partials)
        return (count, total, min(mins) if mins else None, max(maxs) if maxs else None, sq)

    def finalize(self, merged):
        count, total, mn, mx, _sq = merged
        return {
            "count": count, "sum": total, "min": mn, "max": mx,
            "avg": (total / count) if count else None,
        }


class ExtendedStatsAgg(StatsAgg):
    def finalize(self, merged):
        count, total, mn, mx, sq = merged
        out = {
            "count": count, "sum": total, "min": mn, "max": mx,
            "avg": (total / count) if count else None,
            "sum_of_squares": sq,
        }
        if count:
            variance = sq / count - (total / count) ** 2
            out["variance"] = variance
            out["std_deviation"] = math.sqrt(max(variance, 0.0))
        else:
            out["variance"] = None
            out["std_deviation"] = None
        return out


# ---------------------------------------------------------------------------
# device metric-agg bridge (ops/scoring.score_agg_batch)
# ---------------------------------------------------------------------------

_DEVICE_METRIC_CLASSES = (SumAgg, AvgAgg, MinAgg, MaxAgg, ValueCountAgg, StatsAgg)


def device_agg_field(agg: Agg, ctx) -> str | None:
    """The numeric column this agg can reduce on-device, else None (host path).
    extended_stats stays host-side: its variance finalization subtracts nearly
    equal sums, which float32 kernel accumulation would amplify."""
    if type(agg) is ExtendedStatsAgg or not isinstance(agg, _DEVICE_METRIC_CLASSES):
        return None
    if agg.subs:
        return None
    field = agg.spec.get("field")
    if not field or agg.spec.get("script"):
        return None
    ft = ctx.field_type(field)
    if ft is None or not getattr(ft, "is_numeric", False):
        return None
    return field


def device_bucket_subs(agg: Agg, ctx) -> dict | None:
    """name -> numeric column for every metric sub-agg of a bucket agg, or None
    when any sub can't ride the kernel (deeper nesting, scripts, bucket subs)."""
    out = {}
    for name, sub in agg.subs.items():
        f = device_agg_field(sub, ctx)
        if f is None:
            return None
        out[name] = f
    return out


def device_agg_fields(aggs: dict, ctx) -> dict | None:
    """name -> numeric column for EVERY agg in the request, or None when any agg
    needs the host path — the single eligibility gate shared by the single-shard
    serving branch (service._try_device_aggs) and the mesh path (mesh_serving)."""
    out = {}
    for name, agg in aggs.items():
        f = device_agg_field(agg, ctx)
        if f is None:
            return None
        out[name] = f
    return out


def device_partial(agg: Agg, count, st):
    """One kernel result (count int, st = (sum, min, max, sumsq) f32) → the SAME
    partial shape Agg.collect produces, so merge/finalize stay shared between
    paths. Counts arrive from an exact int32 device reduction."""
    count = int(count)
    total = float(st[0])
    mn = float(st[1]) if count and np.isfinite(st[1]) else None
    mx = float(st[2]) if count and np.isfinite(st[2]) else None
    if isinstance(agg, AvgAgg):
        return (total, count)
    if isinstance(agg, SumAgg):
        return total
    if isinstance(agg, MinAgg):
        return mn
    if isinstance(agg, MaxAgg):
        return mx
    if isinstance(agg, ValueCountAgg):
        return count
    if isinstance(agg, StatsAgg):
        return (count, total, mn, mx, float(st[3])) if count \
            else (0, 0.0, None, None, 0.0)
    raise QueryParsingError(f"not a device agg [{type(agg).__name__}]")


def device_bucket_eligible(agg: Agg) -> bool:
    """Bucket aggs the device path serves: terms / significant_terms /
    histogram / date_histogram / range family / geo buckets on a plain field,
    plus the mask-shaped buckets (filter / filters / missing — their masks are
    host-evaluated per segment like FilteredQuery). Bucket KEYS are computed
    host-side (exact — calendar bucketing and range bound conversion included);
    only the per-bucket doc counts ride the kernel (exact int32 scatter-add
    under the match mask). Specs containing relative date math ("now…") refuse:
    they re-resolve per query on the host while the device pair cache lives per
    segment generation.

    Metric SUB-aggs are separately eligible (device_bucket_subs): their per-doc
    folds scatter along the same (doc, bucket) pairs — callers must check."""
    if type(agg) in (FilterAgg, FiltersAgg, MissingAgg):
        return "now" not in repr(agg.spec)
    if not agg.spec.get("field") or agg.spec.get("script"):
        return False
    if type(agg) in (RangeAgg, DateRangeAgg, IpRangeAgg):
        return not any("now" in str(b)
                       for r in agg.spec.get("ranges", [])
                       for b in (r.get("from"), r.get("to")) if b is not None)
    return type(agg) in (TermsAgg, SignificantTermsAgg, HistogramAgg,
                         DateHistogramAgg, GeoDistanceAgg, GeohashGridAgg)


_BUCKET_CACHE_MAX = 8  # distinct bucket-agg shapes cached per segment


def bucket_cache_key(agg: Agg) -> tuple:
    """The ONE cache-key constructor for a bucket agg's per-segment columns —
    shared by the host cache here and the device-array cache on PackedSegment
    (execute.execute_flat_aggs) so the two can never drift. Every spec param
    that changes the (pairs, keys) layout MUST appear here."""
    # finalize-only params don't change the (pairs, keys) layout — excluding
    # them keeps e.g. size:10 / size:50 variants of one terms agg on one cache
    # entry instead of fragmenting the FIFO
    layout_irrelevant = ("size", "shard_size", "order", "min_doc_count",
                         "extended_bounds")
    return ("bucket_cols", type(agg).__name__,
            repr(sorted(((k, v) for k, v in agg.spec.items()
                         if k not in layout_irrelevant), key=lambda kv: kv[0])))


def _bucket_cache_put(cache: dict, ckey: tuple, value):
    """FIFO-bound the bucket entries (user-controlled intervals must not grow
    memory unboundedly); non-bucket entries in the same dict are untouched."""
    bucket_keys = [k for k in cache
                   if isinstance(k, tuple) and k and k[0] == "bucket_cols"]
    while len(bucket_keys) >= _BUCKET_CACHE_MAX:
        cache.pop(bucket_keys.pop(0), None)
    cache[ckey] = value
    return value


def bucket_cols_for(agg: Agg, seg, ctx=None) -> tuple:
    """(pair_doc int32 [NP], pair_bucket int32 [NP], keys list) for one bucket
    agg on one segment — deduplicated (doc, bucket) pairs, so the scatter counts
    DOCS exactly like the host's bucket masks (a doc with duplicate values
    counts once). Cached on the segment (host arrays; device copies cache on the
    PackedSegment).

    Bucket materialization is the reference's classic breaker customer (a
    terms agg over a high-cardinality field): on a cache miss the pair-array
    build is reserved on the request breaker through `ctx` — transient
    (estimate during build, release after), host-side only."""
    field = agg.spec.get("field")
    ckey = bucket_cache_key(agg)
    cached = seg._device_cache.get(ckey)
    if cached is not None:
        return cached
    breaker = ctx.breaker("request") if ctx is not None \
        and getattr(ctx, "breakers", None) is not None else None
    col = seg.dv_num.get(field) if field else None
    n_vals = len(col[1]) if col is not None else 0
    # per-doc pair slots + per-value intermediates (int64 pair keys, int32
    # outputs, masks) — a deliberate over-estimate, like the reference's
    # per-bucket overhead constant
    with reserve(breaker, (seg.doc_count + n_vals) * 24,
                 f"<bucket_cols>[{type(agg).__name__}]"):
        return _bucket_cols_build(agg, seg, ctx, ckey, field)


def _bucket_cols_build(agg: Agg, seg, ctx, ckey, field) -> tuple:
    empty = (np.zeros(0, np.int32), np.zeros(0, np.int32), [])
    if isinstance(agg, (FilterAgg, FiltersAgg, MissingAgg)):
        # mask-shaped buckets: host-evaluated per segment via the filter cache
        # (same masks the host collectors use), one pair per matching doc
        from .filters import MissingFilter

        if isinstance(agg, MissingAgg):
            masks = [("missing", segment_mask(seg, MissingFilter(field), ctx))]
        elif isinstance(agg, FilterAgg):
            masks = [("filter", segment_mask(seg, parse_filter(agg.spec), ctx))]
        else:
            fspecs = agg.spec.get("filters", {})
            items = fspecs.items() if isinstance(fspecs, dict) else \
                enumerate(fspecs)
            masks = [(key, segment_mask(seg, parse_filter(fs), ctx))
                     for key, fs in items]
        keys = [k for k, _m in masks]
        pair_parts = [np.nonzero(m)[0] * max(len(masks), 1) + mi
                      for mi, (_k, m) in enumerate(masks)]
        pairs = (np.concatenate(pair_parts).astype(np.int64)
                 if pair_parts else np.zeros(0, np.int64))
        out = ((pairs // max(len(masks), 1)).astype(np.int32),
               (pairs % max(len(masks), 1)).astype(np.int32), keys)
        return _bucket_cache_put(seg._device_cache, ckey, out)
    if isinstance(agg, (GeoDistanceAgg, GeohashGridAgg)):
        # geo buckets: distances/cells computed host-side per value (static
        # origin/precision per spec — covered by the cache key), then the same
        # deduplicated pair machinery
        field2 = agg.spec.get("field")
        lat_col = seg.dv_num.get(f"{field2}.lat")
        lon_col = seg.dv_num.get(f"{field2}.lon")
        if lat_col is None or lon_col is None or not len(lat_col[1]):
            out = (empty[0], empty[1],
                   [r.get("key") or f"{r.get('from', '*')}-{r.get('to', '*')}"
                    for r in agg.spec.get("ranges", [])]
                   if isinstance(agg, GeoDistanceAgg) else [])
            return _bucket_cache_put(seg._device_cache, ckey, out)
        off, lats = lat_col
        _, lons = lon_col
        counts = np.diff(off)
        doc_of_val = np.repeat(np.arange(seg.doc_count, dtype=np.int64), counts)
        if isinstance(agg, GeohashGridAgg):
            cells = agg._cells(lats, lons)
            uniq_c = sorted(set(cells))
            cpos = {c: i for i, c in enumerate(uniq_c)}
            inv = np.asarray([cpos[c] for c in cells], dtype=np.int64)
            pairs = np.unique(doc_of_val * len(uniq_c) + inv)
            out = ((pairs // len(uniq_c)).astype(np.int32),
                   (pairs % len(uniq_c)).astype(np.int32), uniq_c)
            return _bucket_cache_put(seg._device_cache, ckey, out)
        d = agg._distances(lats, lons)
        ranges = agg.spec.get("ranges", [])
        keys = [agg._range_key(r) for r in ranges]
        pair_parts = [
            doc_of_val[agg._range_sel(d, r)] * max(len(ranges), 1) + ri
            for ri, r in enumerate(ranges)
        ]
        pairs = (np.unique(np.concatenate(pair_parts)) if pair_parts
                 else np.zeros(0, np.int64))
        out = ((pairs // max(len(ranges), 1)).astype(np.int32),
               (pairs % max(len(ranges), 1)).astype(np.int32), keys)
        return _bucket_cache_put(seg._device_cache, ckey, out)
    if isinstance(agg, RangeAgg):
        # range buckets: a value can fall in several (overlapping) ranges —
        # one (doc, range) pair per membership, deduplicated per doc; every
        # range emits a bucket even at zero docs (host collect does too)
        ranges = agg.spec.get("ranges", [])
        keys = [r.get("key") or f"{r.get('from', '*')}-{r.get('to', '*')}"
                for r in ranges]
        col = seg.dv_num.get(field)
        if col is None or not len(col[1]) or not ranges:
            out = (empty[0], empty[1], keys)
            return _bucket_cache_put(seg._device_cache, ckey, out)
        off, vals = col
        counts = np.diff(off)
        doc_of_val = np.repeat(np.arange(seg.doc_count, dtype=np.int64), counts)
        pair_parts = [
            doc_of_val[agg._selector(vals, r)[0]] * len(ranges) + ri
            for ri, r in enumerate(ranges)
        ]
        pairs = np.unique(np.concatenate(pair_parts))
        out = ((pairs // len(ranges)).astype(np.int32),
               (pairs % len(ranges)).astype(np.int32), keys)
        return _bucket_cache_put(seg._device_cache, ckey, out)
    if isinstance(agg, TermsAgg) and field in seg.dv_str:
        uniq, off, ords = seg.dv_str[field]
        if not len(uniq):
            return _bucket_cache_put(seg._device_cache, ckey, empty)
        counts = np.diff(off)
        doc_of_val = np.repeat(np.arange(seg.doc_count, dtype=np.int64), counts)
        pairs = np.unique(doc_of_val * len(uniq) + ords)
        out = ((pairs // len(uniq)).astype(np.int32),
               (pairs % len(uniq)).astype(np.int32), list(uniq))
    else:
        col = seg.dv_num.get(field)
        if col is None or not len(col[1]):
            return _bucket_cache_put(seg._device_cache, ckey, empty)
        off, vals = col
        counts = np.diff(off)
        doc_of_val = np.repeat(np.arange(seg.doc_count, dtype=np.int64), counts)
        if isinstance(agg, HistogramAgg):  # incl. DateHistogramAgg
            kv = agg._key_for(vals)
            uniq_k, inv = np.unique(kv, return_inverse=True)
            keys = [float(k) for k in uniq_k]
        else:
            uniq_k, inv = np.unique(vals, return_inverse=True)
            keys = [int(v) if float(v).is_integer() else float(v) for v in uniq_k]
        pairs = np.unique(doc_of_val * len(uniq_k) + inv)
        out = ((pairs // len(uniq_k)).astype(np.int32),
               (pairs % len(uniq_k)).astype(np.int32), keys)
    return _bucket_cache_put(seg._device_cache, ckey, out)


def _sig_bg_counts(seg, field: str) -> dict:
    """Per-term BACKGROUND doc counts (live parent docs, deduplicated) for
    significant_terms — depends on tombstones, so cached per live generation."""
    ck = ("sig_bg", field)
    cached = seg._device_cache.get(ck)
    if cached is not None and cached[0] == seg.live_gen:
        return cached[1]
    col = seg.dv_str.get(field)
    out: dict = {}
    if col is not None and len(col[0]):
        uniq, off, ords = col
        bg = seg.live & seg.parent_mask
        counts = np.diff(off)
        doc_of_val = np.repeat(np.arange(seg.doc_count, dtype=np.int64), counts)
        sel = bg[doc_of_val]
        pairs = np.unique(doc_of_val[sel] * len(uniq) + ords[sel])
        ord_counts = np.bincount((pairs % len(uniq)).astype(np.int64),
                                 minlength=len(uniq))
        out = {uniq[i]: int(ord_counts[i]) for i in range(len(uniq))}
    seg._device_cache[ck] = (seg.live_gen, out)
    return out


def device_bucket_partial(agg: Agg, keys: list, counts: np.ndarray,
                          seg=None, sub_data=None) -> list:
    """Kernel counts → the SAME partial shape _BucketAgg.collect produces.
    Range and mask-shaped aggs keep zero-count buckets (the host emits every
    range/filter); ranges carry their converted bounds; significant_terms
    attaches per-term background counts. sub_data = (sub_aggs, field_of,
    field_order, sub_cnt [Fs, NB] int, sub_stats [Fs, NB, 4]) when metric
    sub-aggs rode the kernel — their partials assemble in the host shapes via
    device_partial, so merge/finalize nest unchanged."""
    sub_rows = None
    if sub_data is not None:
        sub_aggs, field_of, order, scnt, sstats = sub_data
        fpos = {f: i for i, f in enumerate(order)}
        sub_rows = [(n, s, fpos[field_of[n]]) for n, s in sub_aggs.items()]

    def mk(bi: int, key, c) -> dict:
        subs = {}
        if sub_rows is not None:
            subs = {n: device_partial(s, scnt[fi, bi], sstats[fi, bi])
                    for n, s, fi in sub_rows}
        return {"key": key, "doc_count": int(c), "subs": subs}

    if isinstance(agg, RangeAgg):
        out = []
        for bi, (k, c, r) in enumerate(zip(keys, counts,
                                           agg.spec.get("ranges", []))):
            b = mk(bi, k, c)
            b["from"] = agg._convert(r.get("from"))
            b["to"] = agg._convert(r.get("to"))
            out.append(b)
        return out
    if isinstance(agg, (FilterAgg, FiltersAgg, MissingAgg, GeoDistanceAgg)):
        return [mk(bi, k, c) for bi, (k, c) in enumerate(zip(keys, counts))]
    if isinstance(agg, SignificantTermsAgg):
        field = agg.spec.get("field")
        bg = _sig_bg_counts(seg, field) if seg is not None and \
            field in seg.dv_str else {}
        out = []
        for bi, (k, c) in enumerate(zip(keys, counts)):
            if c > 0:
                b = mk(bi, k, c)
                # numeric columns / unknown keys: host falls back to bg == fg
                b["bg_count"] = int(bg.get(k, c))
                out.append(b)
        return out
    return [mk(bi, k, c)
            for bi, (k, c) in enumerate(zip(keys, counts)) if c > 0]


class CardinalityAgg(Agg):
    """Distinct count via a HyperLogLog++ sketch — bounded memory (2^p bytes) on
    arbitrarily-high-cardinality fields, near-exact up to `precision_threshold`
    (default 3000; the small range is served by linear counting, which is exact
    while register load stays low). Shard partials are sketches; cross-shard merge
    is a register max, so distributed counts don't double-count overlap."""

    def collect(self, seg, ctx, mask, scores=None):
        from ..common.sketches import HyperLogLogPlusPlus, precision_from_threshold

        field = self.spec.get("field")
        threshold = int(self.spec.get("precision_threshold", 3000))
        sketch = HyperLogLogPlusPlus(precision_from_threshold(threshold))
        if field in seg.dv_str:
            _, vals = _str_values(seg, field, mask)
            sketch.add_values(vals)
        else:
            _, vals = _field_values(seg, field, mask)
            sketch.add_values(vals)
        return sketch

    def merge(self, partials):
        out = None
        for p in partials:
            if out is None:
                out = p
            else:
                out.merge(p)
        return out

    def finalize(self, merged):
        return {"value": int(merged.cardinality()) if merged is not None else 0}


class PercentilesAgg(_NumericAgg):
    """Percentiles via a merging t-digest — O(compression) memory regardless of hit
    count, tails kept sharp by the k1 scale function. Shard partials are digests;
    the reduce side merges centroids (exact concatenation + re-compression)."""

    DEFAULT_PERCENTS = (1.0, 5.0, 25.0, 50.0, 75.0, 95.0, 99.0)

    def _compression(self) -> float:
        # later-ES accepts both a flat `compression` and `tdigest.compression`
        td = self.spec.get("tdigest") or {}
        return float(self.spec.get("compression", td.get("compression", 100.0)))

    def collect(self, seg, ctx, mask, scores=None):
        from ..common.sketches import TDigest

        digest = TDigest(self._compression())
        digest.add_values(self._values(seg, ctx, mask))
        return digest

    def merge(self, partials):
        out = None
        for p in partials:
            if out is None:
                out = p
            else:
                out.merge(p)
        return out

    def finalize(self, merged):
        percents = self.spec.get("percents", list(self.DEFAULT_PERCENTS))
        values = {}
        for p in percents:
            q = merged.quantile(float(p) / 100.0) if merged is not None else None
            values[f"{float(p)}"] = q
        return {"values": values}


class TopHitsAgg(Agg):
    def collect(self, seg, ctx, mask, scores=None):
        size = int(self.spec.get("size", 3))
        idx = np.nonzero(mask)[0]
        s = scores[idx] if scores is not None else np.zeros(len(idx), np.float32)
        order = np.lexsort((idx, -s))[:size]
        return [
            {"_id": seg.ids[int(idx[i])], "_type": seg.types[int(idx[i])],
             "_score": float(s[i]), "_source": seg.stored[int(idx[i])]}
            for i in order
        ]

    def merge(self, partials):
        size = int(self.spec.get("size", 3))
        all_hits = [h for p in partials for h in p]
        all_hits.sort(key=lambda h: (-h["_score"], h["_id"]))
        return all_hits[:size]

    def finalize(self, merged):
        return {"hits": {"total": len(merged), "hits": merged}}


class GeoBoundsAgg(Agg):
    def collect(self, seg, ctx, mask, scores=None):
        field = self.spec.get("field")
        _, lats = _field_values(seg, f"{field}.lat", mask)
        _, lons = _field_values(seg, f"{field}.lon", mask)
        if not len(lats):
            return None
        return (float(lats.max()), float(lons.min()), float(lats.min()), float(lons.max()))

    def merge(self, partials):
        ps = [p for p in partials if p is not None]
        if not ps:
            return None
        return (max(p[0] for p in ps), min(p[1] for p in ps),
                min(p[2] for p in ps), max(p[3] for p in ps))

    def finalize(self, merged):
        if merged is None:
            return {}
        top, left, bottom, right = merged
        return {"bounds": {"top_left": {"lat": top, "lon": left},
                           "bottom_right": {"lat": bottom, "lon": right}}}


# ---------------------------------------------------------------------------
# buckets
# ---------------------------------------------------------------------------


class _BucketAgg(Agg):
    """Buckets = named doc masks; sub-aggs collect within each bucket mask."""

    def _bucket_partial(self, seg, ctx, key, mask, scores):
        return {
            "key": key,
            "doc_count": int(mask.sum()),
            "subs": self._collect_subs(seg, ctx, mask, scores),
        }

    def _merge_buckets(self, partial_list: list[list[dict]], key_order=None):
        by_key: dict = {}
        for partial in partial_list:
            for b in partial:
                e = by_key.setdefault(b["key"], {"key": b["key"], "doc_count": 0, "subs": []})
                e["doc_count"] += b["doc_count"]
                e["subs"].append(b["subs"])
        for e in by_key.values():
            e["subs"] = self._merge_subs(e["subs"]) if e["subs"] else {}
        return by_key

    def _finalize_bucket(self, e: dict, key_name: str = "key") -> dict:
        out = {key_name: e["key"], "doc_count": e["doc_count"]}
        out.update(self._finalize_subs(e["subs"]))
        return out


class TermsAgg(_BucketAgg):
    def collect(self, seg, ctx, mask, scores=None):
        field = self.spec.get("field")
        buckets = []
        if field in seg.dv_str:
            docs, vals = _str_values(seg, field, mask)
            by_term: dict[str, list[int]] = {}
            for d, v in zip(docs, vals):
                by_term.setdefault(v, []).append(int(d))
        else:
            docs, nvals = _field_values(seg, field, mask)
            by_term = {}
            for d, v in zip(docs, nvals):
                key = int(v) if float(v).is_integer() else float(v)
                by_term.setdefault(key, []).append(int(d))
        for term, doc_list in by_term.items():
            bmask = np.zeros(seg.doc_count, dtype=bool)
            bmask[doc_list] = True
            bmask &= mask
            buckets.append(self._bucket_partial(seg, ctx, term, bmask, scores))
        return buckets

    def merge(self, partials):
        return self._merge_buckets(partials)

    def finalize(self, merged):
        size = int(self.spec.get("size", 10) or 0) or len(merged)
        order_spec = self.spec.get("order", {"_count": "desc"})
        (okey, odir), = order_spec.items() if isinstance(order_spec, dict) else [("_count", "desc")]
        reverse = str(odir).lower() == "desc"
        entries = list(merged.values())
        if okey == "_count":
            # secondary key: term ascending (stable tiebreak like the reference)
            entries.sort(key=lambda e: e["key"])
            entries.sort(key=lambda e: e["doc_count"], reverse=reverse)
        elif okey in ("_term", "_key"):
            entries.sort(key=lambda e: e["key"], reverse=reverse)
        else:
            # order by sub-agg value, e.g. "avg_price" or "stats.max"
            path = okey.split(".")

            def subval(e):
                sub = self.subs.get(path[0])
                if sub is None:
                    return float("-inf")
                d = sub.finalize(e["subs"][path[0]])
                v = d.get(path[1]) if len(path) > 1 else d.get("value")
                return v if v is not None else float("-inf")

            entries.sort(key=subval, reverse=reverse)
        min_count = int(self.spec.get("min_doc_count", 1))
        entries = [e for e in entries if e["doc_count"] >= min_count]
        return {"buckets": [self._finalize_bucket(e) for e in entries[:size]]}


class RangeAgg(_BucketAgg):
    key_is_date = False

    def _convert(self, v):
        if v is None:
            return None
        if self.key_is_date and isinstance(v, str):
            return float(parse_date_math(v))
        return float(v)

    def _selector(self, vals: np.ndarray, r: dict):
        """(membership bool over vals, from, to) — the ONE half-open range
        predicate, shared with the device pair builder (bucket_cols_for)."""
        frm = self._convert(r.get("from"))
        to = self._convert(r.get("to"))
        sel = np.ones(len(vals), dtype=bool)
        if frm is not None:
            sel &= vals >= frm
        if to is not None:
            sel &= vals < to
        return sel, frm, to

    def collect(self, seg, ctx, mask, scores=None):
        field = self.spec.get("field")
        docs, vals = _field_values(seg, field, mask)
        buckets = []
        for r in self.spec.get("ranges", []):
            sel, frm, to = self._selector(vals, r)
            bmask = np.zeros(seg.doc_count, dtype=bool)
            bmask[docs[sel]] = True
            bmask &= mask
            key = r.get("key") or f"{r.get('from', '*')}-{r.get('to', '*')}"
            p = self._bucket_partial(seg, ctx, key, bmask, scores)
            p["from"] = frm
            p["to"] = to
            buckets.append(p)
        return buckets

    def merge(self, partials):
        merged = self._merge_buckets(partials)
        # carry from/to through
        for partial in partials:
            for b in partial:
                if b["key"] in merged:
                    merged[b["key"]].setdefault("from", b.get("from"))
                    merged[b["key"]].setdefault("to", b.get("to"))
        return merged

    def finalize(self, merged):
        buckets = []
        for e in merged.values():
            out = self._finalize_bucket(e)
            if e.get("from") is not None:
                out["from"] = e["from"]
            if e.get("to") is not None:
                out["to"] = e["to"]
            buckets.append(out)
        return {"buckets": buckets}


class DateRangeAgg(RangeAgg):
    key_is_date = True


class IpRangeAgg(RangeAgg):
    def _convert(self, v):
        from ..mapper.core import parse_ip

        if v is None:
            return None
        return float(parse_ip(v)) if isinstance(v, str) else float(v)


class HistogramAgg(_BucketAgg):
    def _interval(self) -> float:
        return float(self.spec.get("interval", 1))

    def _key_for(self, vals: np.ndarray) -> np.ndarray:
        interval = self._interval()
        return np.floor(vals / interval) * interval

    def collect(self, seg, ctx, mask, scores=None):
        field = self.spec.get("field")
        docs, vals = _field_values(seg, field, mask)
        keys = self._key_for(vals)
        buckets = []
        for key in np.unique(keys):
            sel = keys == key
            bmask = np.zeros(seg.doc_count, dtype=bool)
            bmask[docs[sel]] = True
            bmask &= mask
            buckets.append(self._bucket_partial(seg, ctx, float(key), bmask, scores))
        return buckets

    def merge(self, partials):
        return self._merge_buckets(partials)

    def finalize(self, merged):
        entries = sorted(merged.values(), key=lambda e: e["key"])
        min_count = int(self.spec.get("min_doc_count", 0 if "extended_bounds" in self.spec else 1))
        if min_count == 0 and entries:
            # fill empty buckets between min and max keys
            interval = self._interval()
            lo, hi = entries[0]["key"], entries[-1]["key"]
            eb = self.spec.get("extended_bounds") or {}
            lo = min(lo, eb["min"]) if "min" in eb else lo
            hi = max(hi, eb["max"]) if "max" in eb else hi
            have = {e["key"] for e in entries}
            k = lo
            while k <= hi + 1e-9:
                if k not in have:
                    entries.append({"key": k, "doc_count": 0,
                                    "subs": self._merge_subs([])})
                k += interval
            entries.sort(key=lambda e: e["key"])
        entries = [e for e in entries if e["doc_count"] >= min_count]
        return {"buckets": [self._finalize_bucket(e) for e in entries]}


_CAL_INTERVALS = {
    "year": 365 * 86400_000, "quarter": 91 * 86400_000, "month": 30 * 86400_000,
    "week": 7 * 86400_000, "day": 86400_000, "hour": 3600_000,
    "minute": 60_000, "second": 1000,
}


class DateHistogramAgg(HistogramAgg):
    def _interval(self) -> float:
        spec = str(self.spec.get("interval", "day"))
        if spec in _CAL_INTERVALS:
            return float(_CAL_INTERVALS[spec])
        from ..common.units import parse_time

        return parse_time(spec) * 1000.0

    def _key_for(self, vals: np.ndarray) -> np.ndarray:
        spec = str(self.spec.get("interval", "day"))
        if spec in ("month", "year", "quarter"):
            # calendar-aware bucketing
            import datetime as dt

            out = np.empty(len(vals))
            for i, v in enumerate(vals):
                d = dt.datetime.fromtimestamp(v / 1000.0, dt.timezone.utc)
                if spec == "year":
                    d2 = dt.datetime(d.year, 1, 1, tzinfo=dt.timezone.utc)
                elif spec == "quarter":
                    d2 = dt.datetime(d.year, ((d.month - 1) // 3) * 3 + 1, 1,
                                     tzinfo=dt.timezone.utc)
                else:
                    d2 = dt.datetime(d.year, d.month, 1, tzinfo=dt.timezone.utc)
                out[i] = d2.timestamp() * 1000.0
            return out
        return super()._key_for(vals)

    def finalize(self, merged):
        out = super().finalize(merged)
        import datetime as dt

        for b in out["buckets"]:
            b["key_as_string"] = dt.datetime.fromtimestamp(
                b["key"] / 1000.0, dt.timezone.utc
            ).strftime("%Y-%m-%dT%H:%M:%S.000Z")
        return out


class FilterAgg(_BucketAgg):
    def collect(self, seg, ctx, mask, scores=None):
        f = parse_filter(self.spec)
        bmask = mask & segment_mask(seg, f, ctx)
        return [self._bucket_partial(seg, ctx, "filter", bmask, scores)]

    def merge(self, partials):
        return self._merge_buckets(partials)

    def finalize(self, merged):
        e = next(iter(merged.values())) if merged else {"key": "filter", "doc_count": 0, "subs": self._merge_subs([])}
        out = {"doc_count": e["doc_count"]}
        out.update(self._finalize_subs(e["subs"]))
        return out


class FiltersAgg(_BucketAgg):
    def collect(self, seg, ctx, mask, scores=None):
        buckets = []
        fspecs = self.spec.get("filters", {})
        items = fspecs.items() if isinstance(fspecs, dict) else enumerate(fspecs)
        for key, fs in items:
            f = parse_filter(fs)
            bmask = mask & segment_mask(seg, f, ctx)
            buckets.append(self._bucket_partial(seg, ctx, key, bmask, scores))
        return buckets

    def merge(self, partials):
        return self._merge_buckets(partials)

    def finalize(self, merged):
        return {"buckets": {
            e["key"]: {k: v for k, v in self._finalize_bucket(e).items() if k != "key"}
            for e in merged.values()
        }}


class GlobalAgg(_BucketAgg):
    def collect(self, seg, ctx, mask, scores=None):
        gmask = seg.live & seg.parent_mask
        return [self._bucket_partial(seg, ctx, "global", gmask, scores)]

    def merge(self, partials):
        return self._merge_buckets(partials)

    def finalize(self, merged):
        e = next(iter(merged.values())) if merged else {"key": "global", "doc_count": 0, "subs": {}}
        out = {"doc_count": e["doc_count"]}
        out.update(self._finalize_subs(e["subs"]) if e["subs"] else {})
        return out


class MissingAgg(_BucketAgg):
    def collect(self, seg, ctx, mask, scores=None):
        from .filters import MissingFilter

        f = MissingFilter(self.spec.get("field"))
        bmask = mask & segment_mask(seg, f, ctx)
        return [self._bucket_partial(seg, ctx, "missing", bmask, scores)]

    def merge(self, partials):
        return self._merge_buckets(partials)

    def finalize(self, merged):
        e = next(iter(merged.values())) if merged else {"key": "missing", "doc_count": 0, "subs": {}}
        out = {"doc_count": e["doc_count"]}
        out.update(self._finalize_subs(e["subs"]) if e["subs"] else {})
        return out


class NestedAgg(_BucketAgg):
    """Switches the collection scope to nested child docs of `path` whose parents
    match (ref: search/aggregations/bucket/nested/)."""

    def collect(self, seg, ctx, mask, scores=None):
        from .execute import _parent_of_map

        path = self.spec.get("path")
        child_sel = np.asarray([p == path for p in seg.nested_paths], dtype=bool)
        parents = _parent_of_map(seg)
        cmask = np.zeros(seg.doc_count, dtype=bool)
        idx = np.nonzero(child_sel)[0]
        if len(idx):
            pidx = parents[idx]
            ok = pidx >= 0
            cmask[idx[ok]] = mask[pidx[ok]]
        return [self._bucket_partial(seg, ctx, "nested", cmask, scores)]

    def merge(self, partials):
        return self._merge_buckets(partials)

    def finalize(self, merged):
        e = next(iter(merged.values())) if merged else {"key": "nested", "doc_count": 0, "subs": {}}
        out = {"doc_count": e["doc_count"]}
        out.update(self._finalize_subs(e["subs"]) if e["subs"] else {})
        return out


class GeoDistanceAgg(_BucketAgg):
    def _distances(self, lats: np.ndarray, lons: np.ndarray) -> np.ndarray:
        """Per-value distance from the spec origin in spec units — the ONE
        origin-parse + haversine, shared with the device pair builder."""
        origin = self.spec.get("origin") or self.spec.get("point") \
            or self.spec.get("center")
        if isinstance(origin, dict):
            lat0, lon0 = float(origin["lat"]), float(origin["lon"])
        elif isinstance(origin, str):
            lat0, lon0 = (float(x) for x in origin.split(","))
        else:
            lon0, lat0 = float(origin[0]), float(origin[1])
        unit = parse_distance("1" + self.spec.get("unit", "m"))
        return haversine_m(lat0, lon0, lats, lons) / unit

    @staticmethod
    def _range_sel(d: np.ndarray, r: dict) -> np.ndarray:
        sel = np.ones(len(d), dtype=bool)
        if r.get("from") is not None:
            sel &= d >= float(r["from"])
        if r.get("to") is not None:
            sel &= d < float(r["to"])
        return sel

    @staticmethod
    def _range_key(r: dict) -> str:
        frm, to = r.get("from"), r.get("to")
        return r.get("key") or \
            f"{frm if frm is not None else '*'}-{to if to is not None else '*'}"

    def collect(self, seg, ctx, mask, scores=None):
        field = self.spec.get("field")
        docs_lat, lats = _field_values(seg, f"{field}.lat", mask)
        _, lons = _field_values(seg, f"{field}.lon", mask)
        d = self._distances(lats, lons)
        buckets = []
        for r in self.spec.get("ranges", []):
            sel = self._range_sel(d, r)
            bmask = np.zeros(seg.doc_count, dtype=bool)
            bmask[docs_lat[sel]] = True
            bmask &= mask
            buckets.append(self._bucket_partial(seg, ctx, self._range_key(r),
                                                bmask, scores))
        return buckets

    def merge(self, partials):
        return self._merge_buckets(partials)

    def finalize(self, merged):
        return {"buckets": [self._finalize_bucket(e) for e in merged.values()]}


class GeohashGridAgg(_BucketAgg):
    """Buckets per geohash cell at `precision`, doc-deduplicated counts, ordered
    by count desc then cell asc (ref:
    search/aggregations/bucket/geogrid/GeoHashGridParser.java)."""

    def _cells(self, lats: np.ndarray, lons: np.ndarray) -> list:
        from ..common.geo import geohash_encode

        precision = int(self.spec.get("precision", 5))
        return [geohash_encode(float(la), float(lo), precision)
                for la, lo in zip(lats, lons)]

    def collect(self, seg, ctx, mask, scores=None):
        field = self.spec.get("field")
        docs, lats = _field_values(seg, f"{field}.lat", mask)
        _, lons = _field_values(seg, f"{field}.lon", mask)
        by_cell: dict[str, set] = {}
        for d, cell in zip(docs, self._cells(lats, lons)):
            by_cell.setdefault(cell, set()).add(int(d))
        buckets = []
        for cell, ds in by_cell.items():
            if not self.subs:
                # docs are already mask-filtered; the per-cell mask is only
                # needed to drive sub-agg collection
                buckets.append({"key": cell, "doc_count": len(ds), "subs": {}})
                continue
            bmask = np.zeros(seg.doc_count, dtype=bool)
            bmask[list(ds)] = True
            buckets.append(self._bucket_partial(seg, ctx, cell, bmask, scores))
        return buckets

    def merge(self, partials):
        return self._merge_buckets(partials)

    def finalize(self, merged):
        entries = sorted(merged.values(),
                         key=lambda e: (-e["doc_count"], e["key"]))
        size = int(self.spec.get("size", 10000) or 10000)
        return {"buckets": [self._finalize_bucket(e) for e in entries[:size]]}


class SignificantTermsAgg(TermsAgg):
    """Simplified significance: foreground/background frequency ratio scoring
    (the reference uses JLH; same monotone intent, documented deviation)."""

    def collect(self, seg, ctx, mask, scores=None):
        buckets = super().collect(seg, ctx, mask, scores)
        bg = seg.live & seg.parent_mask
        field = self.spec.get("field")
        for b in buckets:
            if field in seg.dv_str:
                uniq, off, ords = seg.dv_str[field]
                try:
                    o = uniq.index(b["key"]) if isinstance(uniq, list) else None
                except ValueError:
                    o = None
                if o is not None:
                    counts = np.diff(off)
                    doc_of_val = np.repeat(np.arange(seg.doc_count), counts)
                    sel = (ords == o) & bg[doc_of_val]
                    b["bg_count"] = int(np.unique(doc_of_val[sel]).size)
                else:
                    b["bg_count"] = b["doc_count"]
            else:
                b["bg_count"] = b["doc_count"]
        return buckets

    def merge(self, partials):
        merged = super().merge(partials)
        for partial in partials:
            for b in partial:
                if b["key"] in merged:
                    e = merged[b["key"]]
                    e["bg_count"] = e.get("bg_count", 0) + b.get("bg_count", 0)
        return merged

    def finalize(self, merged):
        entries = list(merged.values())
        for e in entries:
            bg = max(e.get("bg_count", e["doc_count"]), 1)
            e["_score"] = e["doc_count"] / bg
        entries.sort(key=lambda e: (-e["_score"], -e["doc_count"]))
        size = int(self.spec.get("size", 10))
        out = []
        for e in entries[:size]:
            b = self._finalize_bucket(e)
            b["score"] = e["_score"]
            b["bg_count"] = e.get("bg_count", e["doc_count"])
            out.append(b)
        return {"buckets": out}


_AGG_REGISTRY: dict[str, type] = {
    "sum": SumAgg,
    "avg": AvgAgg,
    "min": MinAgg,
    "max": MaxAgg,
    "value_count": ValueCountAgg,
    "stats": StatsAgg,
    "extended_stats": ExtendedStatsAgg,
    "cardinality": CardinalityAgg,
    "percentiles": PercentilesAgg,
    "top_hits": TopHitsAgg,
    "geo_bounds": GeoBoundsAgg,
    "terms": TermsAgg,
    "significant_terms": SignificantTermsAgg,
    "range": RangeAgg,
    "date_range": DateRangeAgg,
    "ip_range": IpRangeAgg,
    "histogram": HistogramAgg,
    "date_histogram": DateHistogramAgg,
    "filter": FilterAgg,
    "filters": FiltersAgg,
    "global": GlobalAgg,
    "missing": MissingAgg,
    "nested": NestedAgg,
    "geo_distance": GeoDistanceAgg,
    "geohash_grid": GeohashGridAgg,
}


# ---------------------------------------------------------------------------
# facets (legacy API) — mapped onto the agg framework (ref: search/facet/, 15k LoC,
# superseded by aggs in the reference but still first-class in this snapshot)
# ---------------------------------------------------------------------------


def parse_facets(spec: dict) -> dict[str, tuple[Agg, str]]:
    out = {}
    for name, body in (spec or {}).items():
        kinds = [k for k in body if k not in ("facet_filter", "global", "nested")]
        if not kinds:
            raise QueryParsingError(f"facet [{name}] missing type")
        kind = kinds[0]
        fspec = body[kind]
        if kind == "terms":
            agg = TermsAgg(name, fspec)
        elif kind == "statistical":
            agg = ExtendedStatsAgg(name, fspec)
        elif kind in ("histogram",):
            agg = HistogramAgg(name, fspec)
        elif kind == "date_histogram":
            agg = DateHistogramAgg(name, fspec)
        elif kind == "range":
            agg = RangeAgg(name, fspec)
        elif kind == "geo_distance":
            agg = GeoDistanceAgg(name, fspec)
        elif kind in ("query",):
            agg = FilterAgg(name, {"query": fspec})
        elif kind in ("filter",):
            agg = FilterAgg(name, fspec)
        elif kind == "terms_stats":
            agg = TermsAgg(name, {"field": fspec.get("key_field"),
                                  "size": fspec.get("size", 10)},
                           subs={"stats": StatsAgg("stats", {"field": fspec.get("value_field")})})
        else:
            raise QueryParsingError(f"unknown facet type [{kind}]")
        out[name] = (agg, kind)
    return out


def facet_response(agg: Agg, kind: str, reduced: dict) -> dict:
    """Convert an agg result into the legacy facet response shape."""
    if kind == "terms":
        return {"_type": "terms", "terms": [
            {"term": b["key"], "count": b["doc_count"]} for b in reduced["buckets"]
        ]}
    if kind == "statistical":
        return {"_type": "statistical", **{k: v for k, v in reduced.items()}}
    if kind in ("histogram", "date_histogram"):
        return {"_type": kind, "entries": [
            {"key": b["key"], "count": b["doc_count"]} for b in reduced["buckets"]
        ]}
    if kind == "range":
        return {"_type": "range", "ranges": [
            {**b, "count": b.pop("doc_count")} for b in [dict(b) for b in reduced["buckets"]]
        ]}
    if kind in ("query", "filter"):
        return {"_type": kind, "count": reduced["doc_count"]}
    if kind == "geo_distance":
        return {"_type": "geo_distance", "ranges": [
            {**b, "count": b.pop("doc_count")} for b in [dict(b) for b in reduced["buckets"]]
        ]}
    if kind == "terms_stats":
        return {"_type": "terms_stats", "entries": [
            {"term": b["key"], "count": b["doc_count"], **b.get("stats", {})}
            for b in reduced["buckets"]
        ]}
    return reduced
