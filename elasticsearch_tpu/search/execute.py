"""Per-shard query planning + execution.

The analogue of the reference's QueryPhase + Lucene Weight/Scorer machinery
(search/query/QueryPhase.java:95-137, SURVEY.md §3.3 "north-star path"). Two paths:

- **Device path** (the common case: match / term / terms / flat bool over terms —
  exactly the queries in BASELINE.md configs): the query lowers to a flat clause list;
  clauses from a whole QUERY BATCH are fused into one TermBatch per segment and executed
  by ops/scoring.py in a single device program (gather → FMA → scatter → top_k).

- **Host path** (everything else: phrase/positions, multi-term expansion, joins,
  function_score internals, scripts): recursive numpy evaluation per segment producing
  dense (scores float32[D], match bool[D]) with the SAME similarity math, so device and
  host paths rank identically on queries both can run.

Weight normalization mirrors Lucene: a pre-pass collects the sum of squared term weights
(createWeight), queryNorm = 1/sqrt(ssw) if the index default similarity is TF-IDF
(BM25Similarity.queryNorm ≡ 1), coord applied per matched-clause count.
Term statistics (df, sumTotalTermFreq, maxDoc) are SHARD-level — summed over segments
before weighting, like IndexSearcher's top-level stats; in multi-shard search the DFS
phase swaps in cluster-level stats (parallel/dfs.py), the analogue of
SearchPhaseController.aggregateDfs.
"""

from __future__ import annotations

import math
import re
import time
from dataclasses import dataclass, field as dc_field

import numpy as np

from ..common import profile as _profile
from ..common.errors import QueryParsingError
from ..index.engine import Searcher
from ..index.segment import FrozenSegment
from .filters import Filter, MatchAllFilter, segment_mask
from .queries import (
    BoolQuery,
    BoostingQuery,
    CommonTermsQuery,
    ConstantScoreQuery,
    DisMaxQuery,
    FilteredQuery,
    FunctionScoreQuery,
    FuzzyLikeThisQuery,
    FuzzyQuery,
    HasChildQuery,
    HasParentQuery,
    IdsQuery,
    IndicesQuery,
    MatchAllQuery,
    MatchQuery,
    MoreLikeThisQuery,
    MultiMatchQuery,
    NestedQuery,
    PhraseQuery,
    PrefixQuery,
    Query,
    QueryStringQuery,
    SimpleQueryStringQuery,
    RangeQuery,
    RegexpQuery,
    FieldMaskingSpanQuery,
    SpanFirstQuery,
    SpanMultiTermQuery,
    SpanNearQuery,
    SpanNotQuery,
    SpanOrQuery,
    SpanTermQuery,
    TermQuery,
    WildcardQuery,
)
from ..common.breaker import reserve
from ..common.devicehealth import tag_domain as _tag_domain
from ..common.jaxenv import compile_tag
from ..transport.faults import DEVICE_FAULTS as _DEVICE_FAULTS
from ..transport.faults import DEVICE_PULL as _DEVICE_PULL
from .similarity import (
    BM25Similarity,
    FreqNormSimilarity,
    SimilarityService,
    TFIDFSimilarity,
)

GROUP_SHOULD, GROUP_MUST, GROUP_MUST_NOT = 0, 1, 2
MODE_BM25, MODE_TFIDF, MODE_CONST = 0, 1, 2


class ShardContext:
    """Shard-level stats + mapping access shared by planner and scorers."""

    def __init__(self, searcher: Searcher, mapper_service, similarity_service=None,
                 global_stats: dict | None = None, index_name: str | None = None,
                 breakers=None, batcher=None, filter_cache=None):
        self.searcher = searcher
        self.mapper_service = mapper_service
        self.similarity_service = similarity_service or SimilarityService(
            mapper_service=mapper_service
        )
        # DFS-phase override: {"df": {(field, term): df}, "max_doc": N,
        #                      "field_stats": {field: FieldStats}}
        self.global_stats = global_stats or {}
        # which index this shard belongs to (indices query/filter targeting);
        # None = unknown → indices-targeted constructs assume a match
        self.index_name = index_name
        # the node's CircuitBreakerService (None in unwired contexts — unit
        # tests, standalone shard work): allocation hot spots reserve through
        # breaker(name) and every charge site tolerates the None no-op
        self.breakers = breakers
        # the node's cross-request DeviceBatcher (search/batcher.py), or None
        # in unwired contexts — single-plan device launches coalesce with
        # concurrent searches when present (service._execute_flat_single)
        self.batcher = batcher
        # the node's device-resident filter/bitset cache
        # (ops/device_index.DeviceFilterCache), or None in unwired contexts —
        # hot filters' packed doc masks stay in HBM so cached filtered plans
        # skip mask construction + transfer (_filter_mask_matrix)
        self.filter_cache = filter_cache

    def breaker(self, name: str):
        """The named circuit breaker, or None when no service is wired."""
        return None if self.breakers is None else self.breakers.breaker(name)

    @property
    def max_doc(self) -> int:
        return self.global_stats.get("max_doc", self.searcher.max_doc)

    def doc_freq(self, field: str, term: str) -> int:
        dfs = self.global_stats.get("df")
        if dfs is not None and (field, term) in dfs:
            return dfs[(field, term)]
        return self.searcher.doc_freq(field, term)

    def field_stats(self, field: str):
        fs = self.global_stats.get("field_stats")
        if fs is not None and field in fs:
            return fs[field]
        return self.searcher.field_stats(field)

    def field_type(self, field: str):
        return self.mapper_service.field_type(field)

    def analyze(self, field: str, text: str) -> list[str]:
        return self.mapper_service.search_analyzer_for(field).terms(text)

    def analyze_tokens(self, field: str, text: str):
        return self.mapper_service.search_analyzer_for(field).analyze(text)

    def similarity_for(self, field: str):
        return self.similarity_service.for_field(field)

    @property
    def default_similarity(self):
        return self.similarity_service.default

    def all_terms(self, field: str) -> list[str]:
        terms: set[str] = set()
        for seg in self.searcher.segments:
            terms.update(seg.term_dict.get(field, ()))
        return sorted(terms)


@dataclass
class TopDocs:
    total: int
    hits: list  # [(score, global_doc)]
    max_score: float
    # the shard's time budget ran out mid-collection: hits/total cover only the
    # segments scored before expiry (ref: TimeLimitingCollector partial results)
    timed_out: bool = False


@dataclass
class Clause:
    field: str
    term: str
    boost: float
    group: int  # GROUP_*


@dataclass
class FlatPlan:
    """A query lowered to one flat weighted-term batch (device-executable)."""

    clauses: list  # list[Clause]
    msm: int
    n_must: int
    coord_enabled: bool
    boost: float
    query_norm: float = 1.0
    # function_score plans: the wrapping FunctionScoreQuery (kernel applies the
    # function tail), the original query (host rerun on script-badness fallback),
    # and the outer boost — which participates in the TF-IDF queryNorm pre-pass
    # (execute._weight_prepass walks through FunctionScoreQuery with the outer
    # boost folded in) but NOT in the sub-query clause weights
    fs: object = None  # FunctionScoreQuery | None (also the host-fallback query)
    fs_kind: str | None = None  # "rows" | "script" (classified at lower time)
    norm_boost: float = 1.0
    # FilteredQuery: the filter gates MATCHING only (host: match &= mask, scores
    # untouched for matched docs — HostScorer FilteredQuery branch); evaluated
    # host-side per segment via the filter cache and shipped as a mask row
    filt: object = None  # Filter | None


# ---------------------------------------------------------------------------
# minimum_should_match parsing (ref: common/lucene/search/Queries.calculateMinShouldMatch)
# ---------------------------------------------------------------------------


def calculate_msm(spec, clause_count: int) -> int:
    if spec is None:
        return 0
    if isinstance(spec, int):
        result = spec
    else:
        s = str(spec).strip()
        if "<" in s:
            # "3<90%" — conditional combos separated by spaces
            result = clause_count
            for combo in s.split():
                cond, _, value = combo.partition("<")
                if clause_count > int(cond):
                    result = _msm_value(value, clause_count)
                    break
            else:
                result = clause_count
        else:
            result = _msm_value(s, clause_count)
    # no upper clamp: msm > clause_count matches nothing (Lucene semantics)
    return max(0, result)


def _msm_value(s: str, clause_count: int) -> int:
    s = s.strip()
    if s.endswith("%"):
        pct = float(s[:-1])
        if pct < 0:
            return clause_count + int(clause_count * pct / 100.0)
        return int(clause_count * pct / 100.0)
    v = int(s)
    return clause_count + v if v < 0 else v


# ---------------------------------------------------------------------------
# flat lowering (device path)
# ---------------------------------------------------------------------------


def lower_flat(query: Query, ctx: ShardContext) -> FlatPlan | None:
    """Lower a query to a flat clause list, or None if it needs the host path.
    Fields scored by a freq/norm-generic similarity (DFR/IB/LM*) always take the host
    path — the device kernel's fused modes are BM25/TF-IDF only."""
    plan = _lower_flat_inner(query, ctx)
    if plan is not None:
        for c in plan.clauses:
            if not isinstance(ctx.similarity_for(c.field),
                              (BM25Similarity, TFIDFSimilarity)):
                return None
    return plan


def _lower_flat_inner(query: Query, ctx: ShardContext) -> FlatPlan | None:
    if isinstance(query, TermQuery):
        ft = ctx.field_type(query.field)
        if ft is not None and ft.is_numeric:
            return None  # numeric term → columnar filter, host path
        return FlatPlan([Clause(query.field, str(query.value), query.boost, GROUP_SHOULD)],
                        msm=1, n_must=0, coord_enabled=False, boost=1.0)
    if isinstance(query, MatchQuery):
        if query.fuzziness is not None:
            return None
        terms = ctx.analyze(query.field, query.text)
        if not terms:
            return FlatPlan([], msm=0, n_must=0, coord_enabled=False, boost=query.boost)
        group = GROUP_MUST if query.operator == "and" else GROUP_SHOULD
        clauses = [Clause(query.field, t, 1.0, group) for t in terms]
        n_must = len(clauses) if group == GROUP_MUST else 0
        msm = calculate_msm(query.minimum_should_match, len(clauses)) if group == GROUP_SHOULD else 0
        if group == GROUP_SHOULD and msm == 0:
            msm = 1
        coord = len(clauses) > 1
        return FlatPlan(clauses, msm=msm, n_must=n_must, coord_enabled=coord,
                        boost=query.boost)
    if isinstance(query, BoolQuery):
        if query.filter:
            return None
        clauses: list[Clause] = []
        n_scoring = 0
        n_should = 0
        for sub, group in (
            [(q, GROUP_MUST) for q in query.must]
            + [(q, GROUP_SHOULD) for q in query.should]
            + [(q, GROUP_MUST_NOT) for q in query.must_not]
        ):
            term = _single_term(sub, ctx)
            if term is None:
                return None
            field, t, boost = term
            clauses.append(Clause(field, t, boost * (1.0 if group == GROUP_MUST_NOT else 1.0), group))
            if group != GROUP_MUST_NOT:
                n_scoring += 1
            if group == GROUP_SHOULD:
                n_should += 1
        if n_scoring == 0:
            # must_not-only bool matches all non-excluded docs — the kernel's
            # "matched at least one scoring clause" gate can't express that; host path
            return None
        n_must = sum(1 for c in clauses if c.group == GROUP_MUST)
        msm = calculate_msm(query.minimum_should_match, n_should)
        if msm == 0 and n_should > 0 and n_must == 0:
            msm = 1
        coord = not query.disable_coord and n_scoring > 1
        return FlatPlan(clauses, msm=msm, n_must=n_must, coord_enabled=coord,
                        boost=query.boost)
    if isinstance(query, FunctionScoreQuery):
        # device function_score: sub query must lower flat, and the functions must
        # classify as "rows" or "script" (see _classify_fs); the function tail is
        # fused into the dense kernel (ops/scoring._fs_rows_impl/_fs_script_impl,
        # ref: common/lucene/search/function/FunctionScoreQuery.java)
        if query.query is None:
            return None
        sub = _lower_flat_inner(query.query, ctx)
        if sub is None or sub.fs is not None or sub.filt is not None:
            return None
        kind = _classify_fs(query)
        if kind is None:
            return None
        return FlatPlan(sub.clauses, msm=sub.msm, n_must=sub.n_must,
                        coord_enabled=sub.coord_enabled, boost=sub.boost,
                        fs=query, fs_kind=kind, norm_boost=query.boost)
    if isinstance(query, FilteredQuery):
        # the reference's canonical query+filter idiom (ES 1.x `filtered`):
        # boost folds into the sub clauses (host: eval(q.query, b)), the filter
        # becomes a match-gating mask row in the dense kernel
        sub = _lower_flat_inner(query.query, ctx)
        if sub is None or sub.fs is not None or sub.filt is not None:
            return None
        return FlatPlan(sub.clauses, msm=sub.msm, n_must=sub.n_must,
                        coord_enabled=sub.coord_enabled,
                        boost=sub.boost * query.boost, filt=query.filter)
    return None


def _classify_fs(q: FunctionScoreQuery):
    """Device eligibility for a function_score spec:
      "rows"   — no function reads _score: values fold to host-combined f32 rows
      "script" — exactly one function, a _score-reading script_score inside the
                 vectorizable AST subset: traced into the kernel
      None     — host path."""
    from ..common.errors import ScriptError
    from ..script import compile_script, script_uses_score, script_vectorizable

    score_readers = 0
    for sf in q.functions:
        if sf.kind == "script_score":
            try:
                cs = compile_script(sf.script, sf.params)
            except ScriptError:
                return None
            if script_uses_score(cs):
                score_readers += 1
    if score_readers == 0:
        return "rows"
    if score_readers == 1 and len(q.functions) == 1 and script_vectorizable(
            compile_script(q.functions[0].script, q.functions[0].params)):
        return "script"
    return None


def _single_term(query: Query, ctx: ShardContext):
    """A sub-query usable as one flat clause: a term query or single-token match."""
    if isinstance(query, TermQuery):
        ft = ctx.field_type(query.field)
        if ft is not None and ft.is_numeric:
            return None
        return (query.field, str(query.value), query.boost)
    if isinstance(query, MatchQuery) and query.fuzziness is None:
        terms = ctx.analyze(query.field, query.text)
        if len(terms) == 1:
            return (query.field, terms[0], query.boost)
    return None


# ---------------------------------------------------------------------------
# profile API support: plan shape + fallback-reason classification
# ---------------------------------------------------------------------------

_GROUP_NAMES = {GROUP_SHOULD: "should", GROUP_MUST: "must",
                GROUP_MUST_NOT: "must_not"}


def plan_profile(plan: FlatPlan, query: Query) -> dict:
    """The resolved plan shape a profiled request reports: per-clause
    (field, term, boost, group), bool semantics, and the fused tail kind.
    Plain scalars only — this dict crosses the wire through the binary codec
    and renders as JSON unchanged."""
    return {
        "query_type": type(query).__name__,
        "clauses": [{"field": c.field, "term": c.term,
                     "boost": float(c.boost), "group": _GROUP_NAMES[c.group]}
                    for c in plan.clauses],
        "msm": int(plan.msm),
        "n_must": int(plan.n_must),
        "coord": bool(plan.coord_enabled),
        "boost": float(plan.boost),
        "function_score": plan.fs_kind,  # None | "rows" | "script"
        "filtered": plan.filt is not None,
    }


def lower_fallback_reason(query: Query, ctx: ShardContext) -> str:
    """Why lower_flat declined this query — the profile API's fallback-reason
    vocabulary (common/profile.py docstring, ARCHITECTURE.md "Profile API").
    Profiled-request only: it re-walks the query, which the hot path never
    pays. The classification mirrors _lower_flat_inner's decline points; when
    the inner lowering actually SUCCEEDS, the decline was lower_flat's
    similarity gate (DFR/IB/LM fields score host-side)."""
    if _lower_flat_inner(query, ctx) is not None:
        return "similarity_not_fused"
    if isinstance(query, TermQuery):
        return "numeric_term"
    if isinstance(query, MatchQuery):
        # the only non-lowering match query: fuzzy (empty analysis still
        # lowers — to an empty flat plan that scores nothing on-device)
        return "fuzzy_match"
    if isinstance(query, BoolQuery):
        if query.filter:
            return "bool_filter_clause"
        subs = query.must + query.should + query.must_not
        if any(_single_term(sub, ctx) is None for sub in subs):
            return "non_term_subclause"
        return "must_not_only"
    if isinstance(query, FunctionScoreQuery):
        if query.query is None:
            return "function_score_no_query"
        if _lower_flat_inner(query.query, ctx) is None:
            return "non_flat_subquery"
        return "function_score_ineligible"
    if isinstance(query, FilteredQuery):
        return "non_flat_subquery"
    return f"unsupported_query:{type(query).__name__}"


def finalize_flat(plan: FlatPlan, ctx: ShardContext):
    """Resolve clause weights against shard/global stats; returns per-clause arrays +
    per-field norm caches, exactly the kernel's inputs."""
    max_doc = ctx.max_doc
    fields: list[str] = []
    caches: list[np.ndarray] = []
    field_idx: dict[str, int] = {}
    resolved = []  # (field, term, weight, fidx, group, mode)
    ssw = 0.0
    for c in plan.clauses:
        sim = ctx.similarity_for(c.field)
        df = ctx.doc_freq(c.field, c.term)
        if c.field not in field_idx:
            field_idx[c.field] = len(fields)
            fields.append(c.field)
            caches.append(sim.norm_cache(ctx.field_stats(c.field), max_doc))
        fi = field_idx[c.field]
        if df <= 0:
            resolved.append((c.field, c.term, 0.0, fi, c.group, MODE_BM25, 0))
            continue
        if isinstance(sim, BM25Similarity):
            idf = sim.idf(df, max_doc)
            w = np.float32(idf * c.boost * plan.boost * (sim.k1 + 1.0))
            mode = MODE_BM25
        else:
            idf = TFIDFSimilarity.idf(df, max_doc)
            w = np.float32(idf * idf * c.boost * plan.boost)  # queryNorm folded later
            mode = MODE_TFIDF
        if c.group != GROUP_MUST_NOT:
            ssw += float((idf * c.boost * plan.boost * plan.norm_boost) ** 2)
        resolved.append((c.field, c.term, float(w), fi, c.group, mode, df))
    qn = 1.0
    if isinstance(ctx.default_similarity, TFIDFSimilarity) and ssw > 0:
        qn = float(TFIDFSimilarity.query_norm(ssw))
    out = []
    for (f, t, w, fi, g, mode, df) in resolved:
        out.append((f, t, w * qn if mode == MODE_TFIDF else w, fi, g, mode, df))
    n_scoring = sum(1 for c in plan.clauses if c.group != GROUP_MUST_NOT)
    coord = np.ones(max(n_scoring, 1) + 1, dtype=np.float32)
    if plan.coord_enabled and isinstance(ctx.default_similarity, TFIDFSimilarity) and n_scoring > 0:
        coord = np.arange(n_scoring + 1, dtype=np.float32) / np.float32(n_scoring)
    return out, fields, np.stack(caches) if caches else None, coord


# ---------------------------------------------------------------------------
# batched device execution
# ---------------------------------------------------------------------------


def execute_flat_batch(plans: list[FlatPlan], ctx: ShardContext, k: int) -> list[TopDocs]:
    """Run a batch of flat plans through the device kernels. Plain plans ride the
    sparse candidate-centric path; function_score plans are grouped by spec and
    ride the dense kernel with the function tail fused in (_execute_flat_fs);
    filtered plans ride the dense kernel with per-query mask rows
    (_execute_flat_filtered)."""
    if all(p.fs is None and p.filt is None for p in plans):
        return _execute_flat_plain(plans, ctx, k)
    out: list[TopDocs | None] = [None] * len(plans)
    plain_idx = [i for i, p in enumerate(plans) if p.fs is None and p.filt is None]
    if plain_idx:
        for i, td in zip(plain_idx,
                         _execute_flat_plain([plans[i] for i in plain_idx], ctx, k)):
            out[i] = td
    filt_idx = [i for i, p in enumerate(plans) if p.filt is not None]
    if filt_idx:
        for i, td in zip(filt_idx,
                         _execute_flat_filtered([plans[i] for i in filt_idx], ctx, k)):
            out[i] = td
    groups: dict = {}
    for i, p in enumerate(plans):
        if p.fs is not None:
            groups.setdefault(_fs_group_key(p.fs), []).append(i)
    for idxs in groups.values():
        for i, td in zip(idxs, _execute_flat_fs([plans[i] for i in idxs], ctx, k)):
            out[i] = td
    return out  # type: ignore[return-value]


def _fs_group_key(fsq) -> tuple:
    """Queries whose function_score spec is VALUE-identical share kernel launches
    (the spec's scalars are baked per launch). Dataclass reprs are content reprs."""
    return (repr(fsq.functions), fsq.score_mode, fsq.boost_mode, fsq.max_boost,
            fsq.min_score, fsq.boost)


def _assemble_batch(plans: list[FlatPlan], finals: list):
    """Field/cache tables + per-query bool-semantics arrays for a batch of
    finalized plans — single construction site for both the plain and the
    function_score batch paths (the coord padding rule is kernel ABI)."""
    Q = len(plans)
    all_fields: list[str] = []
    field_idx: dict[str, int] = {}
    cache_rows: list[np.ndarray] = []
    for (_resolved, fields, caches, _coord) in finals:
        for i, f in enumerate(fields):
            if f not in field_idx:
                field_idx[f] = len(all_fields)
                all_fields.append(f)
                cache_rows.append(caches[i])
    caches_stack = np.stack(cache_rows) if cache_rows else np.ones((1, 256), np.float32)
    max_clauses = max(1, max(
        (sum(1 for c in p.clauses if c.group != GROUP_MUST_NOT) for p in plans),
        default=1))
    coord_tbl = np.ones((Q, max_clauses + 1), dtype=np.float32)
    n_must = np.zeros(Q, np.int32)
    msm = np.zeros(Q, np.int32)
    for qi, (plan, (_resolved, _fields, _caches, coord)) in enumerate(zip(plans, finals)):
        coord_tbl[qi, : len(coord)] = coord
        if len(coord) <= max_clauses:
            coord_tbl[qi, len(coord):] = coord[-1]
        n_must[qi] = plan.n_must
        msm[qi] = plan.msm
    return all_fields, field_idx, cache_rows, caches_stack, coord_tbl, n_must, msm


class _PendingFlat:
    """Device work in flight for one plain-plan batch: every segment's sparse
    bucket launches (+ dense-overflow launches) with NO host pull yet.
    merge() performs the batch's ONE explicit jax.device_get and the host
    top-k merge — the dispatch/merge split the cross-request batcher overlaps
    (search/batcher.py: batch N+1 dispatches while batch N merges)."""

    __slots__ = ("Q", "k", "breaker", "seg_work", "releases",
                 "pull_t0", "pull_t1", "index")

    def __init__(self, Q: int, k: int, breaker, seg_work: list, releases: list,
                 index: str | None = None):
        self.Q = Q
        self.k = k
        self.breaker = breaker
        # owning index (ShardContext.index_name) — stall-injection matching
        # and capacity-ledger attribution; None in unwired contexts
        self.index = index
        # per segment: (seg, base, doc_pad, launches, dense)
        self.seg_work = seg_work
        # scratch-pool release callbacks — invoked by merge() AFTER the pull
        # (staging arrays must stay untouched while transfers are in flight)
        self.releases = releases
        # host-monotonic endpoints of the batch's single device_get, stamped
        # by merge(): the tracing layer's device span rides THIS existing
        # pull instead of adding any sync of its own (common/tracing.py)
        self.pull_t0: float | None = None
        self.pull_t1: float | None = None

    def merge(self) -> list[TopDocs]:
        return _merge_flat_plain(self)

    def sync(self):
        """Block until every dispatched launch completes — ESTPU_TRACE_SYNC=1
        precise device timing ONLY (bench/debug); the serving path never calls
        this, its one sync is the batched pull in merge()."""
        import jax

        for (_seg, _base, _doc_pad, launches, dense) in self.seg_work:
            for (_sb, r) in launches:
                jax.block_until_ready(r)
            if dense is not None:
                jax.block_until_ready(dense[1])


class _PendingDone:
    """Already-merged results behind the pending interface — the fs/filtered
    plan families execute synchronously inside the dispatch half (they are
    rare on the serving hot path and their kernels pull per launch)."""

    __slots__ = ("results",)

    def __init__(self, results: list):
        self.results = results

    def merge(self) -> list[TopDocs]:
        return self.results


def dispatch_flat_batch(plans: list[FlatPlan], ctx: ShardContext, k: int):
    """Dispatch half of execute_flat_batch for the cross-request batcher:
    returns a pending handle whose merge() yields the per-plan TopDocs.
    Plain plans enqueue device work without syncing; batches carrying
    function_score/filtered plans run whole (synchronously) here."""
    if plans and all(p.fs is None and p.filt is None for p in plans):
        return _dispatch_flat_plain(plans, ctx, k)
    return _PendingDone(execute_flat_batch(plans, ctx, k))


def _dispatch_flat_plain(plans: list[FlatPlan], ctx: ShardContext,
                         k: int) -> _PendingFlat:
    """Plan + launch a batch of plain flat plans across every segment WITHOUT
    any host pull (the merge half does the batch's single device_get).

    The common case rides the sparse candidate-centric kernel (ops/scoring.py
    launch_flat_sparse — work scales with postings touched, not corpus size);
    queries whose terms cover too many postings blocks (tb_max) fall back to
    the dense scatter kernel, which is O(Q·doc_pad) but block-count-insensitive.
    Sparse staging buffers are pooled per segment and accounted per batch on
    the request breaker (see launch_flat_sparse)."""
    from ..ops.device_index import (
        TFN_BM25, TFN_TFIDF, ensure_sim_tables, packed_for)
    from ..ops.scoring import launch_flat_sparse

    Q = len(plans)
    finals = [finalize_flat(p, ctx) for p in plans]
    (all_fields, field_idx, cache_rows, caches_stack,
     coord_tbl, n_must, msm) = _assemble_batch(plans, finals)
    sim_tables = {
        f: (TFN_BM25 if isinstance(ctx.similarity_for(f), BM25Similarity)
            else TFN_TFIDF, cache_rows[field_idx[f]])
        for f in all_fields
    }
    # zero-df clauses (w=0, no postings anywhere) can't affect results — don't let
    # them demote the batch off the simple fast path
    simple = bool(
        np.all(n_must == 0) and np.all(msm <= 1) and np.all(coord_tbl == 1.0)
        and all(g == GROUP_SHOULD and mode == MODE_BM25 and w > 0
                for (resolved, _f, _c, _coord) in finals
                for (_f2, _t, w, _fi, g, mode, df) in resolved if df > 0))

    prof = _profile.current()
    seg_work = []  # (seg, base, doc_pad, launches, dense)
    releases = []
    for seg, base in zip(ctx.searcher.segments, ctx.searcher.bases):
        t_seg = time.monotonic() if prof is not None else 0.0
        packed = packed_for(seg, breaker=ctx.breaker("fielddata"),
                            owner=ctx.index_name)
        # cheap LUT swap (1 KB/field), not a postings re-bake: the quantized
        # scan decodes tf→tfn in-kernel against these stacked cache rows
        sim = ensure_sim_tables(packed, sim_tables)
        clause_lists = []
        blocks_scanned = postings_scanned = 0
        for (resolved, _f, _c, _coord) in finals:
            cl = []
            for (f, t, w, _fi, g, mode, df) in resolved:
                tid = seg.term_id(f, t)
                if tid is None:
                    continue
                b0, b1 = packed.blocks_for_term(tid)
                cl.append((b0, b1, w, g, mode == MODE_CONST, sim.fid[f]))
                if prof is not None:
                    blocks_scanned += b1 - b0
                    postings_scanned += int(seg.post_offsets[tid + 1]
                                            - seg.post_offsets[tid])
            clause_lists.append(cl)
        # compile_tag: backend compiles triggered by these launches land in
        # the capacity ledger's per-family attribution (common/jaxenv).
        # Launch failures are tagged with their compile-family fault domain
        # (and the seeded DEVICE_FAULTS seam injects here) so the circuit
        # tracker attributes the trip to the right domain.
        try:
            if _DEVICE_FAULTS.active:
                _DEVICE_FAULTS.check("compile:sparse")
            with compile_tag("sparse"):
                launches, overflow, release = launch_flat_sparse(
                    packed, clause_lists, n_must, msm, coord_tbl, k,
                    simple=simple, breaker=ctx.breaker("request"), sim=sim)
        except Exception as e:  # noqa: BLE001 — re-raised tagged
            raise _tag_domain(e, "compile:sparse")
        releases.append(release)
        dense = None
        if overflow:
            try:
                if _DEVICE_FAULTS.active:
                    _DEVICE_FAULTS.check("compile:dense")
                with compile_tag("dense"):
                    dense = _launch_dense_fallback(
                        overflow, finals, field_idx, all_fields, caches_stack,
                        n_must, msm, coord_tbl, packed, seg, k,
                        breaker=ctx.breaker("fielddata"))
            except Exception as e:  # noqa: BLE001 — re-raised tagged
                raise _tag_domain(e, "compile:dense")
        seg_work.append((seg, base, packed.doc_pad, launches, dense))
        if prof is not None:
            from ..ops.pallas_kernels import estpu_pallas_enabled
            from ..ops.scoring import SparseScratchPool

            prof.segment(
                seg.gen, docs=int(seg.doc_count),
                path=("sparse_fused" if estpu_pallas_enabled()
                      else "sparse_composed"),
                tf_layout=packed.tf_layout,
                blocks_scanned=int(blocks_scanned),
                postings_scanned=int(postings_scanned),
                staged_bytes=sum(
                    SparseScratchPool.staging_bytes(*sb.qblk.shape)
                    for (sb, _r) in launches),
                buckets=len(launches),
                dense_overflow=len(overflow),
                ms=(time.monotonic() - t_seg) * 1000.0)
    return _PendingFlat(Q=Q, k=k, breaker=ctx.breaker("request"),
                        seg_work=seg_work, releases=releases,
                        index=ctx.index_name)


def _merge_flat_plain(pending: _PendingFlat) -> list[TopDocs]:
    """Merge half: ONE explicit device_get drains every launch of the batch
    (sparse buckets + dense overflow across all segments), then the pure-host
    cross-segment top-k merge. This is the only pull on the plain serving
    path — per-bucket np.asarray pulls would be a transfer per array, which
    the transfer_guard("disallow") sanitizer rejects."""
    import jax

    from ..ops.scoring import collect_flat_sparse, finalize_score_result

    Q, k = pending.Q, pending.k
    refs = []
    for (_seg, _base, _doc_pad, launches, dense) in pending.seg_work:
        refs.extend(r for (_sb, r) in launches)
        if dense is not None:
            refs.append(dense[1])
    # chaos hook (transport/faults.DEVICE_PULL): one plain attribute read
    # when disarmed; armed, the stall-watchdog tests wedge THIS pull the way
    # a hung runtime would (the sleep happens before the guard-legal pull)
    if _DEVICE_PULL.active:
        stall = _DEVICE_PULL.delay_for(pending.index)
        if stall > 0.0:
            time.sleep(stall)
    try:
        # seeded device-error seam (transport/faults.DEVICE_FAULTS): same
        # one-attr-read gate; armed, the batch pull raises the injected
        # XlaRuntimeError exactly where a real transfer failure would
        if _DEVICE_FAULTS.active:
            _DEVICE_FAULTS.check(f"pull:{pending.index}")
        # stamp the pull window for tracing (host clocks around the pull the
        # serving path performs anyway — the device span's end rides this)
        pending.pull_t0 = time.monotonic()
        pulled = iter(jax.device_get(refs) if refs else [])
        pending.pull_t1 = time.monotonic()
    except Exception as e:  # noqa: BLE001 — abandoning the batch
        # drain whatever the device will still write into the staging
        # buffers, then hand them back: a poisoned pull (this failure path is
        # cold — syncing here is legal) must not leak the scratch pool while
        # the batcher replays members individually
        for r in refs:
            try:
                jax.block_until_ready(r)
            except Exception:  # noqa: BLE001 — the launch itself may be poisoned
                pass
        for release in pending.releases:
            try:
                release()
            except Exception:  # noqa: BLE001 — best-effort cleanup
                pass
        raise _tag_domain(e, f"pull:{pending.index}")
    # results are on the host — the borrowed staging arrays are reusable now
    for release in pending.releases:
        release()
    totals = np.zeros(Q, dtype=np.int64)
    seg_hits = []  # (scores [Q,k] f32, global_docs [Q,k] int64) per segment
    for (seg, base, doc_pad, launches, dense) in pending.seg_work:
        sparse_pulled = [next(pulled) for _ in launches]
        scores, docs, tq = collect_flat_sparse(launches, sparse_pulled, Q, k,
                                               doc_pad)
        if dense is not None:
            sub, _ref = dense
            # already host arrays — the batch's single device_get pulled them
            ts, td, tt = next(pulled)
            res = finalize_score_result(ts, td, tt, doc_pad)
            kk = res.scores.shape[1]
            scores[sub, :kk] = res.scores
            docs[sub, :kk] = res.docs
            scores[sub, kk:] = -np.inf
            docs[sub, kk:] = doc_pad
            tq[sub] = res.total_hits
        totals += tq
        valid = (docs < min(doc_pad, seg.doc_count)) & np.isfinite(scores)
        gdocs = np.where(valid, docs.astype(np.int64) + base, np.int64(2**62))
        seg_hits.append((np.where(valid, scores, -np.inf), gdocs))
    return _merge_seg_hits(seg_hits, totals, Q, k, breaker=pending.breaker)


def _execute_flat_plain(plans: list[FlatPlan], ctx: ShardContext, k: int) -> list[TopDocs]:
    """Run a batch of flat plans through the device kernels: dispatch every
    segment's launches, then merge per-segment top-k host-side (score desc,
    global doc asc — Lucene order). Synchronous composition of the
    dispatch/merge halves the batcher overlaps.

    A PROFILED request (common/profile.py — it bypassed the batcher, so this
    runs on the request thread) additionally syncs on the dispatched launches
    between dispatch and merge: that per-request sync is the opt-in that buys
    precise dispatch/device/pull/merge phase attribution; the unprofiled path
    takes the early return and adds zero syncs."""
    prof = _profile.current()
    if prof is None:
        return _dispatch_flat_plain(plans, ctx, k).merge()
    t0 = time.monotonic()
    pending = _dispatch_flat_plain(plans, ctx, k)
    t1 = time.monotonic()
    # the profiled request's explicit sync: device phase = dispatch end →
    # every launch complete (legal ONLY here — the request opted in)
    pending.sync()
    t2 = time.monotonic()
    out = pending.merge()
    t3 = time.monotonic()
    prof.phase_s("dispatch", t1 - t0)
    prof.phase_s("device", t2 - t1)
    pull_s = (pending.pull_t1 - pending.pull_t0) \
        if pending.pull_t0 is not None else 0.0
    prof.phase_s("pull", pull_s)
    prof.phase_s("merge", max(t3 - t2 - pull_s, 0.0))
    return out


def _merge_seg_hits(seg_hits, totals, Q: int, k: int,
                    breaker=None) -> list[TopDocs]:
    """Cross-segment top-k merge: score desc, global doc asc — the Lucene
    tie-break order (single site; shared by the plain and function_score paths).

    The host-side merge buffers (concatenated score/doc canvases plus the
    per-query negated-score copy for lexsort) are reserved on the request
    breaker BEFORE np.concatenate allocates them — a wide batch over many
    segments is exactly the allocation the reference's request breaker guards."""
    if not seg_hits:
        return [TopDocs(total=0, hits=[], max_score=float("nan")) for _ in range(Q)]
    width = sum(s.shape[1] for (s, _d) in seg_hits)
    # f32 scores + i64 docs concatenated, + one negated f32 row per lexsort
    est = Q * width * (4 + 8) + width * 4
    with reserve(breaker, est, "<merge_seg_hits>"):
        all_scores = np.concatenate([s for (s, _d) in seg_hits], axis=1)
        all_docs = np.concatenate([d for (_s, d) in seg_hits], axis=1)
        out = []
        totals_h = totals.tolist()
        for qi in range(Q):
            order = np.lexsort((all_docs[qi], -all_scores[qi]))[:k]
            order = order[np.isfinite(all_scores[qi, order])]
            # one batched pull per query, not 2k scalar conversions (tpulint TPU001)
            hits = list(zip(all_scores[qi, order].tolist(),
                            all_docs[qi, order].tolist()))
            out.append(TopDocs(
                total=totals_h[qi],
                hits=hits,
                max_score=hits[0][0] if hits else float("nan"),
            ))
    return out


def _ensure_norm_rows(packed, all_fields, breaker=None):
    """Dense-launch prologue (every dense path funnels through here): fault in
    the lazy f32 freqs plane under the fielddata `breaker` (the blk_freqs-drop
    rule — sparse-only segments never allocated it), and zero-fill norms_stack
    rows for queried fields this segment never indexed."""
    import jax.numpy as jnp

    from ..ops.device_index import ensure_blk_freqs

    ensure_blk_freqs(packed, breaker=breaker)
    for f in all_fields:
        if f not in packed.norm_bytes:
            packed.norm_bytes[f] = jnp.zeros(packed.doc_pad, dtype=jnp.uint8)


def _dense_entries(finals, seg, packed, field_idx) -> list:
    """(qidx, block_row, weight, fidx, group, mode) triples for the dense kernel,
    qidx = position in `finals`."""
    entries = []
    for qi, (resolved, _f, _c, _coord) in enumerate(finals):
        for (f, t, w, _fi, g, mode, df) in resolved:
            tid = seg.term_id(f, t)
            if tid is None:
                continue
            b0, b1 = packed.blocks_for_term(tid)
            for b in range(b0, b1):
                entries.append((qi, b, w, field_idx[f], g, mode))
    return entries


def _launch_dense_fallback(overflow, finals, field_idx, all_fields, caches_stack,
                           n_must, msm, coord_tbl, packed, seg, k,
                           breaker=None):
    """Launch overflow queries (block count past the sparse planner's tb_max)
    on the dense scatter kernel WITHOUT syncing; returns (sub indices, device
    result triple) for the merge half, or None when no entries resolved."""
    from ..ops.scoring import build_term_batch, score_term_batch_async

    _ensure_norm_rows(packed, all_fields, breaker=breaker)
    entries = _dense_entries([finals[qi] for qi in overflow], seg, packed, field_idx)
    if not entries:
        return None
    sub = np.asarray(overflow, dtype=np.int64)
    batch = build_term_batch(entries, len(overflow), n_must[sub], msm[sub],
                             coord_tbl[sub], list(all_fields), caches_stack,
                             nb_pad_row=packed.blk_docs.shape[0] - 1)
    return sub, score_term_batch_async(packed, batch, k)


def _prof_dense_segment(prof, seg, packed, entries, path: str, t_seg: float):
    """Per-segment profile record for the dense kernel families (fs /
    filtered / sorted / aggs) — entries are one (query, block) triple per
    scanned block, so len(entries) IS the blocks-scanned count."""
    if prof is None:
        return
    prof.segment(seg.gen, docs=int(seg.doc_count), path=path,
                 tf_layout=packed.tf_layout, blocks_scanned=len(entries),
                 launches=1, ms=(time.monotonic() - t_seg) * 1000.0)


_FS_CHUNK = 256  # dense accumulator is O(Q·doc_pad) — bound the launch width


def _execute_flat_fs(plans: list[FlatPlan], ctx: ShardContext, k: int) -> list[TopDocs]:
    """Execute a group of function_score plans sharing ONE spec (see _fs_group_key)
    through the dense kernel with the function tail fused in.

    "rows": the spec's doc-only function values are host-combined once per segment
    (functions.combined_doc_rows — float32, bit-identical to the host tail) and
    shipped as a row. "script": the single _score-reading script is traced into
    the kernel; queries flagged bad (missing columns / non-finite values on parent
    docs) rerun on the host so error semantics are preserved."""
    from ..common.errors import ScriptError
    from ..ops.device_index import packed_for
    from ..ops.scoring import (build_term_batch, score_fs_rows_batch,
                               score_fs_script_batch)
    from ..script import compile_script, script_vector_info
    from .functions import _column_first_value, combined_doc_rows
    from .filters import segment_mask

    if len(plans) > _FS_CHUNK:
        out: list[TopDocs] = []
        for start in range(0, len(plans), _FS_CHUNK):
            out.extend(_execute_flat_fs(plans[start: start + _FS_CHUNK], ctx, k))
        return out

    fsq = plans[0].fs
    kind = plans[0].fs_kind  # classified once at lower time
    Q = len(plans)
    finals = [finalize_flat(p, ctx) for p in plans]
    (all_fields, field_idx, _cache_rows, caches_stack,
     coord_tbl, n_must, msm) = _assemble_batch(plans, finals)

    script = used_fields = sf = None
    if kind == "script":
        sf = fsq.functions[0]
        script = compile_script(sf.script, sf.params)
        used_fields = script_vector_info(script)[1]

    host_idx: set[int] = set()
    totals = np.zeros(Q, dtype=np.int64)
    seg_hits = []
    prof = _profile.current()
    try:
        for seg, base in zip(ctx.searcher.segments, ctx.searcher.bases):
            t_seg = time.monotonic() if prof is not None else 0.0
            packed = packed_for(seg, breaker=ctx.breaker("fielddata"),
                                owner=ctx.index_name)
            _ensure_norm_rows(packed, all_fields,
                              breaker=ctx.breaker("fielddata"))
            entries = _dense_entries(finals, seg, packed, field_idx)
            batch = build_term_batch(entries, Q, n_must, msm, coord_tbl,
                                     list(all_fields), caches_stack,
                                     nb_pad_row=packed.blk_docs.shape[0] - 1)
            D, doc_pad = seg.doc_count, packed.doc_pad
            if kind == "rows":
                if fsq.functions:
                    g_seg, applies_seg = combined_doc_rows(
                        fsq, np.zeros(D, np.float32), seg, ctx)
                else:
                    g_seg = np.ones(D, np.float32)
                    applies_seg = np.zeros(D, bool)
                g_row = np.ones(doc_pad, np.float32)
                g_row[:D] = g_seg
                applies_row = np.zeros(doc_pad, bool)
                applies_row[:D] = applies_seg
                with compile_tag("function_score"):
                    scores, docs, tq = score_fs_rows_batch(
                        packed, batch, k, g_row, applies_row, fsq.max_boost,
                        fsq.boost, fsq.min_score, fsq.boost_mode,
                        no_functions=not fsq.functions)
            else:
                col_rows = []
                colmiss = np.zeros(D, bool)
                for f in used_fields:
                    col = _column_first_value(seg, f)
                    colmiss |= np.isnan(col)
                    row = np.full(doc_pad, np.nan, np.float32)
                    row[:D] = col.astype(np.float32)
                    col_rows.append(row)
                parent_row = np.zeros(doc_pad, bool)
                parent_row[:D] = seg.parent_mask
                bad_row = np.zeros(doc_pad, bool)
                bad_row[:D] = seg.parent_mask & colmiss
                if sf.filter is not None:
                    fmask_row = np.zeros(doc_pad, bool)
                    fmask_row[:D] = segment_mask(seg, sf.filter, ctx)
                else:
                    fmask_row = np.zeros(doc_pad, bool)
                with compile_tag("function_score"):
                    scores, docs, tq, bad = score_fs_script_batch(
                        packed, batch, k, script, used_fields, col_rows,
                        fmask_row, bad_row, parent_row, sf.weight,
                        fsq.max_boost, fsq.boost, fsq.min_score,
                        fsq.boost_mode, has_filter=sf.filter is not None)
                host_idx.update(int(qi) for qi in np.nonzero(bad)[0])
            totals += tq
            valid = (docs < min(doc_pad, D)) & np.isfinite(scores)
            gdocs = np.where(valid, docs.astype(np.int64) + base, np.int64(2**62))
            seg_hits.append((np.where(valid, scores, -np.inf), gdocs))
            _prof_dense_segment(prof, seg, packed, entries,
                                "dense_function_score", t_seg)
    except ScriptError:
        # a host-side per-doc evaluation raised while building rows — the host
        # path is authoritative for error semantics; rerun the whole group there
        host_idx = set(range(Q))
        seg_hits = []

    merged = _merge_seg_hits(seg_hits, totals, Q, k,
                             breaker=ctx.breaker("request"))
    return [
        _host_search(ctx, plans[qi].fs, k) if (qi in host_idx or not seg_hits)
        else merged[qi]
        for qi in range(Q)
    ]


def _filter_mask_matrix(filters: list, seg, packed, ctx: ShardContext):
    """The [Q, Dpad] FilteredQuery mask the dense kernels consume — the ONE
    assembly site for the filtered/sorted paths.

    Per query: a resident device row from the node's filter cache when the
    (segment, filter-key) mask is already in HBM (zero host evaluation, zero
    transfer), else host evaluation via the per-segment host filter cache
    (`segment_mask`) with sighting-based promotion to device residency
    (DeviceFilterCache.maybe_store — build outside locks, device_put once,
    publish under the leaf lock). Mask VALUES are identical either way, so
    cached filtered plans score bitwise-identically to the uncached path.

    Returns a host bool [Q, Dpad] when every row stayed host-side (the
    pre-cache behavior, one implicit-free jnp.asarray commit at dispatch) or
    a device [Q, Dpad] stack when any row is resident (host stragglers are
    device_put explicitly)."""
    from .filters import segment_mask

    fc = ctx.filter_cache
    rows = []
    any_dev = False
    for f in filters:
        row = None
        key = None
        if fc is not None and fc.enabled and f.cacheable():
            key = f.key()
            row = fc.lookup(seg, key)
        if row is None:
            m = np.zeros(packed.doc_pad, dtype=bool)
            m[: seg.doc_count] = segment_mask(seg, f, ctx)
            if key is not None:
                row = fc.maybe_store(seg, key, m)
            if row is None:
                row = m
        if not isinstance(row, np.ndarray):
            any_dev = True
        rows.append(row)
    if not any_dev:
        return np.stack(rows)
    import jax
    import jax.numpy as jnp

    # compile_tag: the eager stack fuses cached device rows with fresh host
    # masks for the filtered kernels — outermost scope wins, so launches from
    # inside dense/sorted paths keep their own family.
    with compile_tag("filtered"):
        return jnp.stack([row if not isinstance(row, np.ndarray)
                          else jax.device_put(row) for row in rows])


def _execute_flat_filtered(plans: list[FlatPlan], ctx: ShardContext,
                           k: int) -> list[TopDocs]:
    """Filtered plans: per-query filter masks (host-evaluated via the per-segment
    filter cache — the same masks the host scorer uses) gate matching inside the
    dense kernel. Scores/weights are untouched, so sub-query scoring parity is
    inherited from the plain path."""
    from ..ops.device_index import packed_for
    from ..ops.scoring import build_term_batch, score_filtered_batch

    if len(plans) > _FS_CHUNK:
        out: list[TopDocs] = []
        for start in range(0, len(plans), _FS_CHUNK):
            out.extend(_execute_flat_filtered(plans[start: start + _FS_CHUNK],
                                              ctx, k))
        return out

    Q = len(plans)
    finals = [finalize_flat(p, ctx) for p in plans]
    (all_fields, field_idx, _cache_rows, caches_stack,
     coord_tbl, n_must, msm) = _assemble_batch(plans, finals)
    totals = np.zeros(Q, dtype=np.int64)
    seg_hits = []
    prof = _profile.current()
    for seg, base in zip(ctx.searcher.segments, ctx.searcher.bases):
        t_seg = time.monotonic() if prof is not None else 0.0
        packed = packed_for(seg, breaker=ctx.breaker("fielddata"),
                            owner=ctx.index_name)
        _ensure_norm_rows(packed, all_fields,
                          breaker=ctx.breaker("fielddata"))
        fmask = _filter_mask_matrix([plan.filt for plan in plans], seg,
                                    packed, ctx)
        entries = _dense_entries(finals, seg, packed, field_idx)
        batch = build_term_batch(entries, Q, n_must, msm, coord_tbl,
                                 list(all_fields), caches_stack,
                                 nb_pad_row=packed.blk_docs.shape[0] - 1)
        with compile_tag("filtered"):
            scores, docs, tq = score_filtered_batch(packed, batch, k, fmask)
        totals += tq
        valid = (docs < min(packed.doc_pad, seg.doc_count)) & np.isfinite(scores)
        gdocs = np.where(valid, docs.astype(np.int64) + base, np.int64(2**62))
        seg_hits.append((np.where(valid, scores, -np.inf), gdocs))
        _prof_dense_segment(prof, seg, packed, entries, "dense_filtered",
                            t_seg)
    return _merge_seg_hits(seg_hits, totals, Q, k,
                           breaker=ctx.breaker("request"))


def execute_flat_sorted(plan: FlatPlan, ctx: ShardContext, k: int, spec):
    """Single-plan field-sorted dense execution: returns
    (total, max_score, ordered entries [(key, gdoc, seg_idx, local, score)])
    or None when any segment's column refuses device keys
    (sorting.device_sort_key_row). Ordering: (key asc/desc, global doc asc) —
    the host lexsort order."""
    import jax.numpy as jnp

    from ..ops.device_index import packed_for
    from ..ops.scoring import build_term_batch, score_sorted_batch
    from .sorting import device_sort_key_row

    finals = [finalize_flat(plan, ctx)]
    (all_fields, field_idx, _cache_rows, caches_stack,
     coord_tbl, n_must, msm) = _assemble_batch([plan], finals)
    # validate EVERY segment's eligibility before the first launch — a
    # late-segment refusal must not waste completed kernel work
    packeds = [packed_for(seg, breaker=ctx.breaker("fielddata"),
                          owner=ctx.index_name)
               for seg in ctx.searcher.segments]
    key_rows = [device_sort_key_row(spec, seg, p.doc_pad)
                for seg, p in zip(ctx.searcher.segments, packeds)]
    if any(r is None for r in key_rows):
        return None
    total = 0
    max_score = float("nan")
    cand = []  # (key, gdoc, seg_idx, local, score)
    prof = _profile.current()
    for si, (seg, base, packed, key_row) in enumerate(zip(
            ctx.searcher.segments, ctx.searcher.bases, packeds, key_rows)):
        t_seg = time.monotonic() if prof is not None else 0.0
        _ensure_norm_rows(packed, all_fields,
                          breaker=ctx.breaker("fielddata"))
        fmask = None
        if plan.filt is not None:
            fmask = _filter_mask_matrix([plan.filt], seg, packed, ctx)
        entries = _dense_entries(finals, seg, packed, field_idx)
        batch = build_term_batch(entries, 1, n_must, msm, coord_tbl,
                                 list(all_fields), caches_stack,
                                 nb_pad_row=packed.blk_docs.shape[0] - 1)
        with compile_tag("sorted"):
            keys, docs, scores, qmax, tq = score_sorted_batch(
                packed, batch, max(k, 1), jnp.asarray(key_row), spec.reverse,
                fmask=fmask)
        # batched host pulls: one .tolist() per row instead of a float()/int()
        # scalar conversion per hit (tpulint TPU001)
        (seg_total,) = tq.tolist()
        total += seg_total
        if seg_total:
            (m,) = qmax.tolist()
            max_score = m if max_score != max_score else max(max_score, m)
        n = min(seg_total, keys.shape[1])
        cand.extend(
            (ki, base + di, si, di, sc)
            for ki, di, sc in zip(keys[0, :n].tolist(), docs[0, :n].tolist(),
                                  scores[0, :n].tolist()))
        _prof_dense_segment(prof, seg, packed, entries, "dense_sorted", t_seg)
    cand.sort(key=lambda e: (-e[0] if spec.reverse else e[0], e[1]))
    return total, max_score, cand[: max(k, 0)]


def execute_flat_aggs(plan: FlatPlan, ctx: ShardContext, k: int,
                      fields: list[str], bucket_aggs: list = ()):
    """Single-plan dense execution with aggregations fused into the kernel:
    returns (TopDocs, per-segment (counts int [F], stats float32 [F, 4],
    bucket list of (keys, counts, sub_cnt|None, sub_stats|None))) with
    F = len(fields), stats = (sum, min, max, sumsq) over matched docs.
    bucket_aggs: (Agg, sub_field_order|None) pairs whose (doc, bucket) pairs
    ride the kernel's scatter (aggregations.bucket_cols_for); metric sub-agg
    folds scatter along the same pairs. Serving uses this when every
    aggregation is device-eligible (service.execute_query_phase →
    aggregations.device_agg_fields / device_bucket_eligible)."""
    import jax
    import jax.numpy as jnp

    from ..ops.device_index import _pow2_bucket, ensure_agg_rows, packed_for
    from ..ops.scoring import build_term_batch, score_agg_batch
    from .aggregations import bucket_cache_key, bucket_cols_for

    finals = [finalize_flat(plan, ctx)]
    (all_fields, field_idx, _cache_rows, caches_stack,
     coord_tbl, n_must, msm) = _assemble_batch([plan], finals)
    totals = np.zeros(1, dtype=np.int64)
    seg_hits = []
    seg_stats = []
    prof = _profile.current()
    for seg, base in zip(ctx.searcher.segments, ctx.searcher.bases):
        t_seg = time.monotonic() if prof is not None else 0.0
        packed = packed_for(seg, breaker=ctx.breaker("fielddata"),
                            owner=ctx.index_name)
        _ensure_norm_rows(packed, all_fields,
                          breaker=ctx.breaker("fielddata"))
        stack = ensure_agg_rows(seg, packed, fields,
                                breaker=ctx.breaker("fielddata"))
        if stack is None:
            return None, None  # column not f32-exact → host collectors
        pair_args = []
        seg_keys = []
        for agg, sub_order in bucket_aggs:
            pdoc, pbucket, keys = bucket_cols_for(agg, seg, ctx)
            ck = bucket_cache_key(agg)  # same constructor as the host cache
            dev = packed.bucket_cols.get(ck)
            if dev is None:
                from .aggregations import _bucket_cache_put

                # explicit device_put: eager jnp.zeros builds its fill scalar
                # through an implicit host→device transfer, which the
                # transfer_guard("disallow") sanitizer rejects. The NB dim
                # rides the pow-2 ladder — it shapes the scatter outputs
                # inside the jit, so a raw len(keys) would compile one
                # executable per distinct bucket-key count; every consumer
                # zips counts against `keys` and ignores the padding.
                dev = _bucket_cache_put(
                    packed.bucket_cols, ck,
                    (jnp.asarray(pdoc), jnp.asarray(pbucket),
                     jax.device_put(np.zeros(_pow2_bucket(len(keys), 1),
                                             np.int32))))
            sub_stack = None
            if sub_order:
                sub_stack = ensure_agg_rows(seg, packed, sub_order,
                                            breaker=ctx.breaker("fielddata"))
                if sub_stack is None:
                    return None, None  # sub column not f32-exact → host
            pair_args.append((dev[0], dev[1], dev[2], sub_stack))
            seg_keys.append(keys)
        entries = _dense_entries(finals, seg, packed, field_idx)
        batch = build_term_batch(entries, 1, n_must, msm, coord_tbl,
                                 list(all_fields), caches_stack,
                                 nb_pad_row=packed.blk_docs.shape[0] - 1)
        fmask = None
        if plan.filt is not None:
            fmask = _filter_mask_matrix([plan.filt], seg, packed, ctx)
        with compile_tag("aggs"):
            scores, docs, tq, counts, stats, bcounts = score_agg_batch(
                packed, batch, k, stack, tuple(pair_args), fmask=fmask)
        totals += tq
        valid = (docs < min(packed.doc_pad, seg.doc_count)) & np.isfinite(scores)
        gdocs = np.where(valid, docs.astype(np.int64) + base, np.int64(2**62))
        seg_hits.append((np.where(valid, scores, -np.inf), gdocs))
        seg_stats.append((counts[0], stats[0], [
            (keys, bc[0],
             None if sc is None else sc[0],
             None if ss is None else ss[0])
            for keys, (bc, sc, ss) in zip(seg_keys, bcounts)
        ]))
        _prof_dense_segment(prof, seg, packed, entries, "dense_aggs", t_seg)
    return _merge_seg_hits(seg_hits, totals, 1, k,
                           breaker=ctx.breaker("request"))[0], seg_stats


# ---------------------------------------------------------------------------
# host scorer (general path)
# ---------------------------------------------------------------------------


def _weight_prepass(query: Query, ctx: ShardContext) -> float:
    """Sum of squared leaf weights (Lucene getValueForNormalization pre-pass)."""

    def walk(q: Query, boost: float) -> float:
        b = boost * getattr(q, "boost", 1.0)
        if isinstance(q, TermQuery):
            ft = ctx.field_type(q.field)
            if ft is not None and ft.is_numeric:
                return 0.0
            df = ctx.doc_freq(q.field, str(q.value))
            if df <= 0:
                return 0.0
            sim = ctx.similarity_for(q.field)
            idf = sim.idf(df, ctx.max_doc)
            return float((idf * b) ** 2)
        if isinstance(q, MatchQuery):
            total = 0.0
            for t in ctx.analyze(q.field, q.text):
                df = ctx.doc_freq(q.field, t)
                if df > 0:
                    sim = ctx.similarity_for(q.field)
                    total += float((sim.idf(df, ctx.max_doc) * b) ** 2)
            return total
        if isinstance(q, PhraseQuery):
            terms = [t.term for t in ctx.analyze_tokens(q.field, q.text)]
            sim = ctx.similarity_for(q.field)
            idf_sum = sum(
                float(sim.idf(max(ctx.doc_freq(q.field, t), 0), ctx.max_doc))
                for t in terms if ctx.doc_freq(q.field, t) > 0
            )
            return float((idf_sum * b) ** 2)
        if isinstance(q, BoolQuery):
            return sum(walk(s, b) for s in q.must + q.should)
        if isinstance(q, DisMaxQuery):
            return sum(walk(s, b) for s in q.queries)
        if isinstance(q, FilteredQuery):
            return walk(q.query, b)
        if isinstance(q, (ConstantScoreQuery, MatchAllQuery, RangeQuery, PrefixQuery,
                          WildcardQuery, RegexpQuery, FuzzyQuery, IdsQuery)):
            return float(b * b)
        if isinstance(q, FunctionScoreQuery) and q.query is not None:
            return walk(q.query, b)
        if isinstance(q, NestedQuery):
            return walk(q.query, b)
        return float(b * b)

    return walk(query, 1.0)


def query_norm_for(query: Query, ctx: ShardContext) -> float:
    if not isinstance(ctx.default_similarity, TFIDFSimilarity):
        return 1.0
    ssw = _weight_prepass(query, ctx)
    return float(TFIDFSimilarity.query_norm(ssw)) if ssw > 0 else 1.0


class HostScorer:
    """Recursive dense evaluation of one query against one segment.
    Produces (scores float32[D], match bool[D]); live/parent masking happens in the
    caller so nested/child evaluation can see non-parent docs."""

    def __init__(self, ctx: ShardContext, seg: FrozenSegment, query_norm: float = 1.0):
        self.ctx = ctx
        self.seg = seg
        self.qn = np.float32(query_norm)
        self.D = seg.doc_count

    # -- leaf helpers --------------------------------------------------------
    def _term_scores(self, field: str, term: str, boost: float) -> tuple[np.ndarray, np.ndarray]:
        seg, ctx = self.seg, self.ctx
        scores = np.zeros(self.D, dtype=np.float32)
        match = np.zeros(self.D, dtype=bool)
        df = ctx.doc_freq(field, term)
        docs, freqs = seg.postings(field, term)
        if df <= 0 or len(docs) == 0:
            return scores, match
        sim = ctx.similarity_for(field)
        norms = seg.norms.get(field)
        nb = norms[docs] if norms is not None else np.zeros(len(docs), np.uint8)
        cache = sim.norm_cache(ctx.field_stats(field), ctx.max_doc)
        if isinstance(sim, BM25Similarity):
            w = np.float32(sim.idf(df, ctx.max_doc) * boost * (sim.k1 + 1.0))
            # tf factor first, then weight — bit-parity with the device kernels'
            # in-scan tfn (ops/scoring.sparse_candidates)
            vals = w * (freqs / (freqs + cache[nb]))
        elif isinstance(sim, FreqNormSimilarity):
            # generic freq/doc-len similarities (DFR, IB, LM*) — host-only path
            from ..common.smallfloat import decode_norm_doclen

            dl = decode_norm_doclen(nb)
            ttf = sum(int(s.postings(field, term)[1].sum())
                      for s in ctx.searcher.segments
                      if s.doc_freq(field, term) > 0)
            vals = sim.score_freqs(freqs, dl, df, ttf, ctx.field_stats(field),
                                   ctx.max_doc, boost)
        else:
            idf = TFIDFSimilarity.idf(df, ctx.max_doc)
            w = np.float32(idf * idf * boost) * self.qn
            vals = w * (np.sqrt(freqs, dtype=np.float32) * cache[nb])
        scores[docs] = vals.astype(np.float32)
        match[docs] = True
        return scores, match

    def _const(self, mask: np.ndarray, boost: float) -> tuple[np.ndarray, np.ndarray]:
        scores = np.where(mask, np.float32(boost * self.qn), np.float32(0.0)).astype(np.float32)
        return scores, mask.copy()

    def _mask(self, f: Filter) -> np.ndarray:
        return segment_mask(self.seg, f, self.ctx)

    # -- main dispatch -------------------------------------------------------
    def eval(self, q: Query, boost: float = 1.0) -> tuple[np.ndarray, np.ndarray]:
        b = boost * getattr(q, "boost", 1.0)
        seg, ctx = self.seg, self.ctx

        if isinstance(q, MatchAllQuery):
            return self._const(np.ones(self.D, dtype=bool), b)

        if isinstance(q, TermQuery):
            ft = ctx.field_type(q.field)
            if ft is not None and ft.is_numeric:
                from .filters import TermFilter

                return self._const(self._mask(TermFilter(q.field, q.value)), b)
            return self._term_scores(q.field, str(q.value), b)

        if isinstance(q, MatchQuery):
            if q.fuzziness is not None:
                terms = ctx.analyze(q.field, q.text)
                subs = [FuzzyQuery(q.field, t, q.fuzziness, 0, q.max_expansions) for t in terms]
                return self.eval(BoolQuery(should=subs, minimum_should_match=1), b)
            terms = ctx.analyze(q.field, q.text)
            if not terms:
                return np.zeros(self.D, np.float32), np.zeros(self.D, bool)
            sub = (BoolQuery(must=[TermQuery(q.field, t) for t in terms])
                   if q.operator == "and"
                   else BoolQuery(should=[TermQuery(q.field, t) for t in terms],
                                  minimum_should_match=q.minimum_should_match or 1))
            return self.eval(sub, b)

        if isinstance(q, MultiMatchQuery):
            subs = []
            for fspec in q.fields:
                if "^" in fspec:
                    fname, fboost = fspec.split("^")
                    fboost = float(fboost)
                else:
                    fname, fboost = fspec, 1.0
                subs.append(MatchQuery(fname, q.text, operator=q.operator,
                                       minimum_should_match=q.minimum_should_match,
                                       boost=fboost))
            if q.type in ("best_fields", "phrase", "phrase_prefix"):
                return self.eval(DisMaxQuery(queries=subs, tie_breaker=q.tie_breaker), b)
            return self.eval(BoolQuery(should=subs, minimum_should_match=1,
                                       disable_coord=True), b)

        if isinstance(q, BoolQuery):
            return self._eval_bool(q, b)

        if isinstance(q, FilteredQuery):
            scores, match = self.eval(q.query, b)
            fmask = self._mask(q.filter)
            return np.where(fmask, scores, 0).astype(np.float32), match & fmask

        if isinstance(q, ConstantScoreQuery):
            if q.filter is not None:
                return self._const(self._mask(q.filter), b)
            _, match = self.eval(q.query, 1.0)
            return self._const(match, b)

        if isinstance(q, DisMaxQuery):
            scores = np.zeros(self.D, np.float32)
            best = np.zeros(self.D, np.float32)
            total = np.zeros(self.D, np.float32)
            match = np.zeros(self.D, bool)
            for sub in q.queries:
                s, m = self.eval(sub, b)
                s = np.where(m, s, 0).astype(np.float32)
                best = np.maximum(best, s)
                total += s
                match |= m
            tie = np.float32(q.tie_breaker)
            scores = best + tie * (total - best)
            return np.where(match, scores, 0).astype(np.float32), match

        if isinstance(q, RangeQuery):
            from .filters import RangeFilter

            return self._const(self._mask(RangeFilter(q.field, q.gte, q.gt, q.lte, q.lt)), b)

        if isinstance(q, (PrefixQuery, WildcardQuery, RegexpQuery)):
            return self._const(self._multi_term_mask(q), b)

        if isinstance(q, FuzzyQuery):
            terms = self._fuzzy_terms(q)
            mask = np.zeros(self.D, bool)
            for t in terms:
                docs, _ = seg.postings(q.field, t)
                mask[docs] = True
            return self._const(mask, b)

        if isinstance(q, IdsQuery):
            from .filters import IdsFilter

            return self._const(self._mask(IdsFilter(q.ids, q.types)), b)

        if isinstance(q, PhraseQuery):
            return self._eval_phrase(q, b)

        if isinstance(q, QueryStringQuery):
            return self.eval(parse_query_string(q, self.ctx), b)

        if isinstance(q, CommonTermsQuery):
            return self.eval(self._rewrite_common(q), b)

        if isinstance(q, FunctionScoreQuery):
            return self._eval_function_score(q, b)

        if isinstance(q, NestedQuery):
            mask, scores = child_match_to_parents(
                seg, ctx, q.path, q.query, score_mode=q.score_mode, query_norm=float(self.qn)
            )
            return (scores * np.float32(b)).astype(np.float32), mask

        if isinstance(q, (HasChildQuery, HasParentQuery)):
            # resolved at shard level (cross-segment join) — executor special-cases;
            # segment-local fallback: no match
            return np.zeros(self.D, np.float32), np.zeros(self.D, bool)

        if isinstance(q, BoostingQuery):
            scores, match = self.eval(q.positive, b)
            _, neg = self.eval(q.negative, 1.0)
            scores = np.where(neg, scores * np.float32(q.negative_boost), scores)
            return scores.astype(np.float32), match

        if isinstance(q, MoreLikeThisQuery):
            return self.eval(self._rewrite_mlt(q), b)

        if isinstance(q, SpanTermQuery):
            return self._term_scores(q.field, q.value, b)

        if isinstance(q, (SpanNearQuery, SpanOrQuery, SpanFirstQuery, SpanNotQuery,
                          SpanMultiTermQuery, FieldMaskingSpanQuery)):
            return self._eval_spans(q, b)

        if isinstance(q, IndicesQuery):
            # ref: IndicesQueryParser — the query applies on the named indices,
            # no_match_query (default all, "none" = nothing) elsewhere
            import fnmatch

            name = getattr(self.ctx, "index_name", None)
            if name is None or any(fnmatch.fnmatch(name, p)
                                   for p in (q.indices or [])):
                return self.eval(q.query, b * q.boost)
            if q.no_match_none:
                return (np.zeros(self.D, np.float32), np.zeros(self.D, bool))
            if q.no_match_query is None:
                return self.eval(MatchAllQuery(), b * q.boost)
            return self.eval(q.no_match_query, b * q.boost)

        if isinstance(q, SimpleQueryStringQuery):
            return self.eval(parse_simple_query_string(q), b)

        if isinstance(q, FuzzyLikeThisQuery):
            return self.eval(self._rewrite_flt(q), b)

        raise QueryParsingError(f"unsupported query type {type(q).__name__}")

    # -- bool ---------------------------------------------------------------
    def _eval_bool(self, q: BoolQuery, boost: float):
        D = self.D
        scores = np.zeros(D, np.float32)
        matched_count = np.zeros(D, np.int32)
        must_ok = np.ones(D, bool)
        excluded = np.zeros(D, bool)
        should_count = np.zeros(D, np.int32)
        n_scoring = 0
        for sub in q.must:
            s, m = self.eval(sub, boost)
            scores += np.where(m, s, 0).astype(np.float32)
            must_ok &= m
            matched_count += m
            n_scoring += 1
        for sub in q.should:
            s, m = self.eval(sub, boost)
            scores += np.where(m, s, 0).astype(np.float32)
            should_count += m
            matched_count += m
            n_scoring += 1
        for sub in q.must_not:
            _, m = self.eval(sub, 1.0)
            excluded |= m
        fmask = np.ones(D, bool)
        for f in q.filter:
            fmask &= self._mask(f)
        msm = calculate_msm(q.minimum_should_match, len(q.should))
        if msm == 0 and q.should and not q.must:
            msm = 1
        match = must_ok & ~excluded & fmask & (should_count >= msm)
        if not q.must and not q.should:
            match = fmask & ~excluded  # filter/must_not-only bool matches all remaining
            scores = np.where(match, np.float32(boost * q.boost * self.qn), 0).astype(np.float32)
            return scores, match
        match &= matched_count > 0
        if (not q.disable_coord and n_scoring > 1
                and isinstance(self.ctx.default_similarity, TFIDFSimilarity)):
            coord = matched_count.astype(np.float32) / np.float32(n_scoring)
            scores = scores * coord
        return np.where(match, scores, 0).astype(np.float32), match

    # -- spans ---------------------------------------------------------------
    # The span family enumerates (start, end) position windows per doc, composed
    # recursively — the host-plane equivalent of Lucene's Spans enumerations
    # (ref: SpanOrQueryParser.java:1, SpanFirstQueryParser.java:1,
    # SpanNotQueryParser.java:1, SpanMultiTermQueryParser.java:1,
    # FieldMaskingSpanQueryParser.java:1). Scoring mirrors this framework's phrase
    # convention: freq = number of matching spans (exact for adjacent matches;
    # documented approximation of Lucene's sloppyFreq weighting otherwise).

    def _span_tree(self, q):
        """Returns (field, {local_doc: sorted [(start, end)]}, contributing terms)."""
        seg = self.seg
        if isinstance(q, SpanTermQuery):
            docs, _ = seg.postings(q.field, q.value)
            pos_lists = seg.term_positions(q.field, q.value)
            spans = {int(d): [(int(p), int(p) + 1) for p in np.sort(pl)]
                     for d, pl in zip(docs, pos_lists) if len(pl)}
            return q.field, spans, {(q.field, q.value)}
        if isinstance(q, SpanMultiTermQuery):
            inner = q.match
            if isinstance(inner, (PrefixQuery, WildcardQuery, RegexpQuery)):
                if isinstance(inner, PrefixQuery):
                    pred = lambda t: t.startswith(inner.prefix)  # noqa: E731
                elif isinstance(inner, WildcardQuery):
                    rex = re.compile(_wildcard_to_regex(inner.pattern))
                    pred = lambda t: rex.fullmatch(t) is not None  # noqa: E731
                else:
                    rex = re.compile(inner.pattern)
                    pred = lambda t: rex.fullmatch(t) is not None  # noqa: E731
                terms = [t for t in seg.terms_for_field(inner.field) if pred(t)]
                field = inner.field
            elif isinstance(inner, FuzzyQuery):
                terms = self._fuzzy_terms(inner)
                field = inner.field
            else:
                raise QueryParsingError(
                    f"span_multi does not support [{type(inner).__name__}]")
            spans: dict = {}
            termset = set()
            for t in terms:
                _f, s2, t2 = self._span_tree(SpanTermQuery(field, t))
                termset |= t2
                for d, sp in s2.items():
                    spans.setdefault(d, []).extend(sp)
            return field, {d: sorted(set(sp)) for d, sp in spans.items()}, termset
        if isinstance(q, FieldMaskingSpanQuery):
            _f, spans, terms = self._span_tree(q.query)
            return q.field, spans, terms
        if isinstance(q, SpanOrQuery):
            field, spans, termset = None, {}, set()
            for c in q.clauses:
                f2, s2, t2 = self._span_tree(c)
                field = field or f2
                if f2 != field:
                    raise QueryParsingError("span_or clauses must share a field")
                termset |= t2
                for d, sp in s2.items():
                    spans.setdefault(d, []).extend(sp)
            return field, {d: sorted(set(sp)) for d, sp in spans.items()}, termset
        if isinstance(q, SpanFirstQuery):
            field, spans, terms = self._span_tree(q.match)
            out = {d: [s for s in sp if s[1] <= q.end] for d, sp in spans.items()}
            return field, {d: sp for d, sp in out.items() if sp}, terms
        if isinstance(q, SpanNotQuery):
            field, inc, terms = self._span_tree(q.include)
            f2, exc, _t2 = self._span_tree(q.exclude)
            if f2 != field:
                raise QueryParsingError("span_not include/exclude must share a field")
            out = {}
            for d, sp in inc.items():
                ex = exc.get(d)
                keep = sp if not ex else [
                    s for s in sp
                    if not any(e[0] < s[1] and s[0] < e[1] for e in ex)]
                if keep:
                    out[d] = keep
            # Lucene SpanNotQuery extracts only include terms into the weight
            return field, out, terms
        if isinstance(q, SpanNearQuery):
            field, children, termset = None, [], set()
            for c in q.clauses:
                f2, s2, t2 = self._span_tree(c)
                field = field or f2
                if f2 != field:
                    raise QueryParsingError("span_near clauses must share a field")
                children.append(s2)
                termset |= t2
            if not children:
                return field, {}, termset
            docs = set(children[0])
            for s2 in children[1:]:
                docs &= set(s2)
            spans = {}
            for d in docs:
                found = _near_spans([s2[d] for s2 in children], q.slop, q.in_order)
                if found:
                    spans[d] = found
            return field, spans, termset
        raise QueryParsingError(f"not a span query: {type(q).__name__}")

    def _eval_spans(self, q, boost: float):
        seg, ctx = self.seg, self.ctx
        scores = np.zeros(self.D, np.float32)
        match = np.zeros(self.D, bool)
        field, spans, termset = self._span_tree(q)
        if not spans or field is None:
            return scores, match
        sim = ctx.similarity_for(field)
        cache = sim.norm_cache(ctx.field_stats(field), ctx.max_doc)
        norms = seg.norms.get(field)
        idf_sum = np.float32(sum(
            float(sim.idf(ctx.doc_freq(f, t), ctx.max_doc))
            for (f, t) in sorted(termset) if ctx.doc_freq(f, t) > 0))
        for d, sp in spans.items():
            freq = len(sp)
            nb = norms[d] if norms is not None else 0
            if isinstance(sim, BM25Similarity):
                w = np.float32(idf_sum * boost * (sim.k1 + 1.0))
                scores[d] = w * (np.float32(freq) / (np.float32(freq) + cache[nb]))
            else:
                w = np.float32(idf_sum * idf_sum * boost) * self.qn
                scores[d] = w * (np.sqrt(np.float32(freq)) * cache[nb])
            match[d] = True
        return scores, match

    # -- multi-term ----------------------------------------------------------
    def _multi_term_mask(self, q) -> np.ndarray:
        seg = self.seg
        mask = np.zeros(self.D, bool)
        if isinstance(q, PrefixQuery):
            pred = lambda t: t.startswith(q.prefix)  # noqa: E731
        elif isinstance(q, WildcardQuery):
            rex = re.compile(_wildcard_to_regex(q.pattern))
            pred = lambda t: rex.fullmatch(t) is not None  # noqa: E731
        else:
            rex = re.compile(q.pattern)
            pred = lambda t: rex.fullmatch(t) is not None  # noqa: E731
        for term in seg.terms_for_field(q.field):
            if pred(term):
                docs, _ = seg.postings(q.field, term)
                mask[docs] = True
        return mask

    def _fuzzy_terms(self, q: FuzzyQuery) -> list[str]:
        max_edits = _fuzzy_max_edits(q.fuzziness, q.value)
        out = []
        for term in self.seg.terms_for_field(q.field):
            if q.prefix_length and not term.startswith(q.value[: q.prefix_length]):
                continue
            if _within_edits(q.value, term, max_edits):
                out.append(term)
                if len(out) >= q.max_expansions:
                    break
        return out

    # -- phrase --------------------------------------------------------------
    def _eval_phrase(self, q: PhraseQuery, boost: float, in_order: bool = True):
        seg, ctx = self.seg, self.ctx
        scores = np.zeros(self.D, np.float32)
        match = np.zeros(self.D, bool)
        if hasattr(q, "_pre_analyzed"):
            terms = list(q._pre_analyzed)  # type: ignore[attr-defined]
            rel_pos = list(range(len(terms)))
        else:
            toks = ctx.analyze_tokens(q.field, q.text)
            terms = [t.term for t in toks]
            rel_pos = [t.position for t in toks]
        if not terms:
            return scores, match
        if len(terms) == 1 and not q.prefix:
            return self._term_scores(q.field, terms[0], boost)
        last_terms = [terms[-1]]
        if q.prefix:
            last_terms = [t for t in seg.terms_for_field(q.field)
                          if t.startswith(terms[-1])][: q.max_expansions] or []
            if not last_terms:
                return scores, match
        # candidate docs: intersection of postings
        doc_sets = []
        for t in terms[:-1]:
            docs, _ = seg.postings(q.field, t)
            doc_sets.append(set(docs.tolist()))
        last_docs: set = set()
        for lt in last_terms:
            docs, _ = seg.postings(q.field, lt)
            last_docs.update(docs.tolist())
        doc_sets.append(last_docs)
        candidates = sorted(set.intersection(*doc_sets)) if doc_sets else []
        if not candidates:
            return scores, match
        # positions check
        pos_maps = []
        for t in terms[:-1]:
            pos_maps.append(_positions_by_doc(seg, q.field, t))
        last_pos: dict[int, set] = {}
        for lt in last_terms:
            for d, ps in _positions_by_doc(seg, q.field, lt).items():
                last_pos.setdefault(d, set()).update(ps)
        sim = ctx.similarity_for(q.field)
        norms = seg.norms.get(q.field)
        cache = sim.norm_cache(ctx.field_stats(q.field), ctx.max_doc)
        idf_sum = np.float32(sum(
            float(sim.idf(ctx.doc_freq(q.field, t), ctx.max_doc))
            for t in terms if ctx.doc_freq(q.field, t) > 0
        ))
        for d in candidates:
            freq = _phrase_freq(
                [pm.get(d, set()) for pm in pos_maps] + [last_pos.get(d, set())],
                rel_pos, q.slop, in_order,
            )
            if freq <= 0:
                continue
            nb = norms[d] if norms is not None else 0
            if isinstance(sim, BM25Similarity):
                w = np.float32(idf_sum * boost * (sim.k1 + 1.0))
                scores[d] = w * (np.float32(freq) / (np.float32(freq) + cache[nb]))
            else:
                w = np.float32(idf_sum * idf_sum * boost) * self.qn
                scores[d] = w * (np.sqrt(np.float32(freq)) * cache[nb])
            match[d] = True
        return scores, match

    # -- rewrites ------------------------------------------------------------
    def _rewrite_common(self, q: CommonTermsQuery) -> Query:
        ctx = self.ctx
        terms = ctx.analyze(q.field, q.text)
        max_doc = max(ctx.max_doc, 1)
        low, high = [], []
        for t in terms:
            df = ctx.doc_freq(q.field, t)
            cutoff = q.cutoff_frequency
            threshold = cutoff * max_doc if cutoff < 1.0 else cutoff
            (high if df > threshold else low).append(TermQuery(q.field, t))
        if not low:
            op_group = q.high_freq_operator
            return BoolQuery(must=high if op_group == "and" else [],
                             should=high if op_group != "and" else [],
                             minimum_should_match=q.minimum_should_match)
        low_bool = BoolQuery(must=low if q.low_freq_operator == "and" else [],
                             should=low if q.low_freq_operator != "and" else [],
                             minimum_should_match=q.minimum_should_match)
        if not high:
            return low_bool
        return BoolQuery(must=[low_bool], should=high, disable_coord=True)

    def _rewrite_mlt(self, q: MoreLikeThisQuery) -> Query:
        from collections import Counter

        ctx = self.ctx
        shoulds = []
        for field in q.fields:
            counts = Counter(ctx.analyze(field, q.like_text))
            scored = []
            for t, tf in counts.items():
                if tf < q.min_term_freq:
                    continue
                df = ctx.doc_freq(field, t)
                if df < q.min_doc_freq or df <= 0:
                    continue
                idf = TFIDFSimilarity.idf(df, ctx.max_doc)
                scored.append((float(tf * idf), t))
            scored.sort(reverse=True)
            for _, t in scored[: q.max_query_terms]:
                shoulds.append(TermQuery(field, t))
        return BoolQuery(should=shoulds, minimum_should_match=q.minimum_should_match)

    def _rewrite_flt(self, q: FuzzyLikeThisQuery) -> Query:
        """ref: FuzzyLikeThisQueryParser.java:1 — like_text analyzed per field,
        each term OR-expanded to its fuzzy neighborhood. Legacy float
        fuzziness < 1 is a min-similarity: edits = min(2, ⌊(1-sim)·len⌋) — the
        classic Lucene FuzzyQuery conversion."""
        ctx = self.ctx
        fields = q.fields or ["_all"]
        shoulds: list = []
        budget = max(int(q.max_query_terms), 1)
        for field in fields:
            terms = list(dict.fromkeys(ctx.analyze(field, q.like_text)))[:budget]
            for t in terms:
                fz = q.fuzziness
                try:
                    f_val = float(fz)
                    if 0 < f_val < 1:
                        fz = min(2, int((1.0 - f_val) * len(t)))
                except (TypeError, ValueError):
                    pass
                shoulds.append(FuzzyQuery(field, t, fz, q.prefix_length))
        return BoolQuery(should=shoulds, minimum_should_match=1, boost=q.boost)

    # -- function score ------------------------------------------------------
    def _eval_function_score(self, q: FunctionScoreQuery, boost: float):
        from .functions import apply_functions

        if q.query is not None:
            sub_scores, match = self.eval(q.query, 1.0)
        elif q.filter is not None:
            sub_scores, match = self._const(self._mask(q.filter), 1.0)
        else:
            sub_scores, match = self._const(np.ones(self.D, bool), 1.0)
        scores = apply_functions(q, sub_scores, match, self.seg, self.ctx)
        scores = (scores * np.float32(boost)).astype(np.float32)
        if q.min_score is not None:
            match = match & (scores >= np.float32(q.min_score))
        return scores, match


def _positions_by_doc(seg: FrozenSegment, field: str, term: str) -> dict[int, set]:
    tid = seg.term_id(field, term)
    if tid is None:
        return {}
    s, e = int(seg.post_offsets[tid]), int(seg.post_offsets[tid + 1])
    out = {}
    docs = seg.post_docs[s:e].tolist()  # one batched pull, not int() per doc
    for i, d in zip(range(s, e), docs):
        out[d] = set(seg.positions[seg.pos_offsets[i]: seg.pos_offsets[i + 1]].tolist())
    return out


def _near_spans(lists: list[list[tuple[int, int]]], slop: int,
                in_order: bool) -> list[tuple[int, int]]:
    """Compose child span lists into near-spans with total gap <= slop.

    Ordered: one span per clause, each starting at or after the previous clause's
    end (Lucene NearSpansOrdered's non-overlap rule), gap = sum of inter-span
    distances. Unordered: any one span per clause, gap = covering width minus total
    child length (overlaps clamp to 0). Enumeration is bounded (the per-doc span
    count is small); combos past the cap are dropped rather than searched."""
    out: set[tuple[int, int]] = set()
    if in_order:
        budget = [20000]  # recursion guard for pathological position lists

        def rec(i: int, start: int, prev_end: int, gap: int):
            if budget[0] <= 0:
                return
            if i == len(lists):
                out.add((start, prev_end))
                return
            for (s, e) in lists[i]:
                if i > 0 and s < prev_end:
                    continue
                g = gap + (s - prev_end if i > 0 else 0)
                if g > slop:
                    continue
                budget[0] -= 1
                rec(i + 1, start if i > 0 else s, e, g)

        rec(0, 0, 0, 0)
    else:
        import itertools

        for combo in itertools.islice(itertools.product(*lists), 20000):
            mn = min(s for s, _e in combo)
            mx = max(e for _s, e in combo)
            gap = max((mx - mn) - sum(e - s for s, e in combo), 0)
            if gap <= slop:
                out.add((mn, mx))
    return sorted(out)


def _phrase_freq(pos_sets: list[set], rel_pos: list[int], slop: int, in_order: bool) -> int:
    """Count phrase occurrences. slop=0: exact relative positions. slop>0: alignments
    whose total displacement ≤ slop (greedy per anchor — matches Lucene for common
    cases; documented approximation for pathological overlaps)."""
    if not pos_sets or any(not s for s in pos_sets):
        return 0
    first = pos_sets[0]
    count = 0
    for p0 in sorted(first):
        if slop == 0:
            if all((p0 + rel_pos[i] - rel_pos[0]) in pos_sets[i] for i in range(1, len(pos_sets))):
                count += 1
        else:
            total_disp = 0
            ok = True
            prev = p0
            for i in range(1, len(pos_sets)):
                expected = p0 + rel_pos[i] - rel_pos[0]
                cands = pos_sets[i]
                if in_order:
                    cands = {c for c in cands if c > prev}
                if not cands:
                    ok = False
                    break
                nearest = min(cands, key=lambda c: abs(c - expected))
                total_disp += abs(nearest - expected)
                prev = nearest
            if ok and total_disp <= slop:
                count += 1
    return count


def _wildcard_to_regex(pattern: str) -> str:
    out = []
    for ch in pattern:
        if ch == "*":
            out.append(".*")
        elif ch == "?":
            out.append(".")
        else:
            out.append(re.escape(ch))
    return "".join(out)


def _fuzzy_max_edits(fuzziness, value: str) -> int:
    if fuzziness in ("AUTO", "auto", None):
        n = len(value)
        return 0 if n <= 2 else (1 if n <= 5 else 2)
    try:
        return int(float(fuzziness))
    except (TypeError, ValueError):
        return 1


def _within_edits(a: str, b: str, max_edits: int) -> bool:
    if abs(len(a) - len(b)) > max_edits:
        return False
    prev = list(range(len(b) + 1))
    for i, ca in enumerate(a, 1):
        cur = [i] + [0] * len(b)
        row_min = i
        for j, cb in enumerate(b, 1):
            cur[j] = min(prev[j] + 1, cur[j - 1] + 1, prev[j - 1] + (ca != cb))
            row_min = min(row_min, cur[j])
        if row_min > max_edits:
            return False
        prev = cur
    return prev[-1] <= max_edits


# ---------------------------------------------------------------------------
# query_string mini-parser (subset of Lucene syntax)
# ---------------------------------------------------------------------------

_QS_TOKEN = re.compile(
    r"\s*(?:(\()|(\))|(AND\b|&&)|(OR\b|\|\|)|(NOT\b|!)|([+-])?"
    r"(?:(\w[\w.]*):)?(?:\"([^\"]*)\"|([^\s()]+)))"
)


_SQS_TOKEN = re.compile(
    r'\s*(?:(\|)|(\+)|(-)|"([^"]*)"(?:~(\d+))?|([^\s|+\-][^\s|+]*))'
)


def parse_simple_query_string(q: "SimpleQueryStringQuery") -> Query:
    """The degraded-gracefully syntax (ref: SimpleQueryStringParser.java:1 /
    Lucene SimpleQueryParser): whitespace-separated terms joined by the default
    operator, `+` forces AND, `|` forces OR, leading `-` negates, `"..."` is a
    phrase (optional ~slop), a trailing `*` is a prefix. Invalid syntax never
    errors — stray operators degrade to plain text handling."""
    fields = q.fields or ["_all"]

    def node_for(phrase, slop, word):
        subs: list = []
        for f in fields:
            fname, _, fboost = f.partition("^")
            boost = float(fboost) if fboost else 1.0
            if phrase is not None:
                subs.append(PhraseQuery(fname, phrase, slop=int(slop or 0),
                                        boost=boost))
            elif word.endswith("*") and len(word) > 1:
                subs.append(PrefixQuery(fname, word[:-1].lower(), boost))
            else:
                subs.append(MatchQuery(fname, word, boost=boost))
        if len(subs) == 1:
            return subs[0]
        return BoolQuery(should=subs, minimum_should_match=1,
                         disable_coord=True)

    must, should, must_not = [], [], []
    pending = None  # explicit connective seen since the last term
    negate = False
    for m in _SQS_TOKEN.finditer(q.query):
        bar, plus, minus, phrase, slop, word = m.groups()
        if bar:
            # "a | b": explicit OR releases its LEFT operand from must (the
            # default_operator=and case) — Lucene's SimpleQueryParser OR wins
            if must:
                should.append(must.pop())
            pending = "or"
            continue
        if plus:
            pending = "and"
            continue
        if minus:
            negate = True
            continue
        node = node_for(phrase, slop, word)
        if negate:
            must_not.append(node)
        elif pending == "and" or (pending is None
                                  and q.default_operator == "and"):
            if pending == "and" and should:
                must.append(should.pop())  # "a + b": AND binds its left operand
            must.append(node)
        else:
            should.append(node)
        pending = None
        negate = False
    if not must and not should and not must_not:
        return MatchAllQuery()
    if len(should) == 1 and not must and not must_not:
        out = should[0]
        out.boost = out.boost * q.boost
        return out
    return BoolQuery(must=must, should=should, must_not=must_not, boost=q.boost)


def parse_query_string(q: QueryStringQuery, ctx: ShardContext) -> Query:
    """field:term, AND/OR/NOT, +/-, "phrases", wild*cards, (grouping — flattened)."""
    default_fields = q.fields or [q.default_field]
    must, should, must_not = [], [], []
    pending_op = None
    for m in _QS_TOKEN.finditer(q.query):
        lparen, rparen, and_, or_, not_, sign, fname, phrase, word = m.groups()
        if lparen or rparen:
            continue
        if and_:
            # "a AND b": the left operand becomes required too
            if should:
                must.append(should.pop())
            pending_op = "and"
            continue
        if or_:
            pending_op = "or"
            continue
        if not_:
            pending_op = "not"
            continue
        target_fields = [fname] if fname else default_fields
        subs: list[Query] = []
        for f in target_fields:
            if phrase is not None:
                subs.append(PhraseQuery(f, phrase))
            elif word == "*":
                subs.append(MatchAllQuery())
            elif word and ("*" in word or "?" in word):
                subs.append(WildcardQuery(f, word))
            elif word and "~" in word:
                base, _, fuzz = word.partition("~")
                subs.append(FuzzyQuery(f, base, fuzz or "AUTO"))
            elif word:
                subs.append(MatchQuery(f, word))
            else:
                continue
        node = subs[0] if len(subs) == 1 else DisMaxQuery(queries=subs)
        if sign == "+" or pending_op == "and" or (pending_op is None and q.default_operator == "and"):
            must.append(node)
        elif sign == "-" or pending_op == "not":
            must_not.append(node)
        else:
            should.append(node)
        pending_op = None
    if not must and not should and not must_not:
        return MatchAllQuery()
    if len(should) == 1 and not must and not must_not:
        out = should[0]
        out.boost = out.boost * q.boost
        return out
    return BoolQuery(must=must, should=should, must_not=must_not, boost=q.boost)


# ---------------------------------------------------------------------------
# nested / parent-child joins
# ---------------------------------------------------------------------------


def _parent_of_map(seg: FrozenSegment) -> np.ndarray:
    cache = seg._device_cache
    pm = cache.get("parent_of")
    if pm is None:
        pm = np.zeros(seg.doc_count, dtype=np.int64)
        parent = -1
        for local in range(seg.doc_count - 1, -1, -1):
            if seg.parent_mask[local]:
                parent = local
            pm[local] = parent
        cache["parent_of"] = pm
    return pm


def child_match_to_parents(seg: FrozenSegment, ctx: ShardContext, path: str, inner,
                           score_mode: str = "none", query_norm: float = 1.0):
    """Block-join: evaluate `inner` over nested child docs of `path`, aggregate to
    parents (ref: index/search/nested/ block-join queries)."""
    child_sel = np.asarray(
        [p == path for p in seg.nested_paths], dtype=bool
    )
    if isinstance(inner, Filter):
        cmask = segment_mask(seg, inner, ctx)
        cscores = cmask.astype(np.float32)
    else:
        scorer = HostScorer(ctx, seg, query_norm)
        cscores, cmask = scorer.eval(inner)
    cmask = cmask & child_sel
    parents = _parent_of_map(seg)
    pmask = np.zeros(seg.doc_count, dtype=bool)
    pscores = np.zeros(seg.doc_count, dtype=np.float32)
    pcounts = np.zeros(seg.doc_count, dtype=np.int32)
    idx = np.nonzero(cmask)[0]
    if len(idx):
        pidx = parents[idx]
        valid = pidx >= 0
        idx, pidx = idx[valid], pidx[valid]
        pmask[pidx] = True
        if score_mode in ("sum", "avg", "total"):
            np.add.at(pscores, pidx, cscores[idx])
            np.add.at(pcounts, pidx, 1)
            if score_mode == "avg":
                nz = pcounts > 0
                pscores[nz] = pscores[nz] / pcounts[nz]
        elif score_mode == "max":
            np.maximum.at(pscores, pidx, cscores[idx])
        else:
            pscores[pidx] = 1.0
    return pmask, pscores


def host_match_mask(query: Query, seg: FrozenSegment, ctx: ShardContext) -> np.ndarray:
    _, match = HostScorer(ctx, seg).eval(query)
    return match


# ---------------------------------------------------------------------------
# shard-level entry points
# ---------------------------------------------------------------------------


def search_shard(ctx: ShardContext, query: Query, k: int, use_device: bool = True,
                 extra_filter: Filter | None = None, deadline=None) -> TopDocs:
    return search_shard_batch(ctx, [query], k, use_device=use_device,
                              extra_filter=extra_filter, deadline=deadline)[0]


def search_shard_batch(ctx: ShardContext, queries: list[Query], k: int,
                       use_device: bool = True,
                       extra_filter: Filter | None = None,
                       deadline=None) -> list[TopDocs]:
    """Execute a batch: flat-lowerable queries fused onto the device, the rest host.

    `deadline` (common.deadline.Deadline) clamps HOST execution at segment
    granularity; device launches are never interrupted (a deadline check cannot
    cross into traced code), so the flat path runs whole once started."""
    results: list[TopDocs | None] = [None] * len(queries)
    flat_idx: list[int] = []
    flat_plans: list[FlatPlan] = []
    if extra_filter is None:
        for i, q in enumerate(queries):
            plan = lower_flat(q, ctx) if use_device else None
            if plan is not None:
                flat_idx.append(i)
                flat_plans.append(plan)
    if flat_plans:
        for i, td in zip(flat_idx, execute_flat_batch(flat_plans, ctx, k)):
            results[i] = td
    for i, q in enumerate(queries):
        if results[i] is None:
            results[i] = _host_search(ctx, q, k, extra_filter, deadline)
    return results  # type: ignore[return-value]


def _shard_join(ctx: ShardContext, q: Query):
    """Cross-segment parent/child join: returns per-segment (scores, match) overrides
    for has_child / has_parent queries, else None."""
    if not isinstance(q, (HasChildQuery, HasParentQuery)):
        return None
    from .filters import TermFilter

    out = []
    if isinstance(q, HasChildQuery):
        # collect matching children's _parent ids across segments
        parent_ids: dict[str, float] = {}
        for seg in ctx.searcher.segments:
            scorer = HostScorer(ctx, seg, 1.0)
            s, m = scorer.eval(q.query)
            m = m & np.asarray([t == q.child_type for t in seg.types], dtype=bool)
            locs = np.nonzero(m)[0]
            # batch the matched scores in one pull; float(s[local]) per child
            # was a scalar extraction per matching doc
            for local, sval in zip(locs.tolist(), s[locs].tolist()):
                pid = (seg.str_values("_parent", local) or [None])[0]
                if pid is None:
                    continue
                prev = parent_ids.get(pid, 0.0)
                parent_ids[pid] = max(prev, sval) if q.score_mode == "max" \
                    else prev + sval
        for seg in ctx.searcher.segments:
            match = np.zeros(seg.doc_count, bool)
            scores = np.zeros(seg.doc_count, np.float32)
            for local in range(seg.doc_count):
                if seg.parent_mask[local] and seg.ids[local] in parent_ids:
                    match[local] = True
                    scores[local] = parent_ids[seg.ids[local]] if q.score_mode != "none" else 1.0
            out.append((scores * np.float32(q.boost), match))
        return out
    # has_parent: find matching parents, then select children pointing at them
    matched_parents: dict[str, float] = {}
    for seg in ctx.searcher.segments:
        scorer = HostScorer(ctx, seg, 1.0)
        s, m = scorer.eval(q.query)
        m = m & np.asarray([t == q.parent_type for t in seg.types], dtype=bool)
        locs = np.nonzero(m)[0]
        for local, sval in zip(locs.tolist(), s[locs].tolist()):
            matched_parents[str(seg.ids[local])] = sval
    for seg in ctx.searcher.segments:
        match = np.zeros(seg.doc_count, bool)
        scores = np.zeros(seg.doc_count, np.float32)
        for local in range(seg.doc_count):
            pid = (seg.str_values("_parent", local) or [None])[0]
            if pid is not None and pid in matched_parents:
                match[local] = True
                scores[local] = matched_parents[pid] if q.score_mode != "none" else 1.0

        out.append((scores * np.float32(q.boost), match))
    return out


def _host_search(ctx: ShardContext, query: Query, k: int,
                 extra_filter: Filter | None = None, deadline=None) -> TopDocs:
    qn = query_norm_for(query, ctx)
    all_scores: list[np.ndarray] = []
    all_docs: list[np.ndarray] = []
    total = 0
    timed_out = False
    join = _shard_join(ctx, query)
    prof = _profile.current()
    for si, (seg, base) in enumerate(zip(ctx.searcher.segments, ctx.searcher.bases)):
        # host-side segment boundary: the one legal clamp point (never inside
        # a traced region) — expiry keeps the segments already scored
        if deadline is not None and deadline.expired():
            timed_out = True
            break
        t_seg = time.monotonic() if prof is not None else 0.0
        if join is not None:
            scores, match = join[si]
        else:
            scorer = HostScorer(ctx, seg, qn)
            scores, match = scorer.eval(query)
        if prof is not None:
            prof.segment(seg.gen, docs=int(seg.doc_count), path="host",
                         ms=(time.monotonic() - t_seg) * 1000.0)
        match = match & seg.live & seg.parent_mask
        if extra_filter is not None:
            match = match & segment_mask(seg, extra_filter, ctx)
        idx = np.nonzero(match)[0]
        total += len(idx)
        if len(idx):
            all_scores.append(scores[idx])
            all_docs.append(idx + base)
    if not all_scores:
        return TopDocs(0, [], float("nan"), timed_out=timed_out)
    scores = np.concatenate(all_scores)
    docs = np.concatenate(all_docs)
    order = np.lexsort((docs, -scores))[:k]
    hits = list(zip(scores[order].tolist(), docs[order].tolist()))
    return TopDocs(total, hits, float(scores.max()), timed_out=timed_out)


def count_shard(ctx: ShardContext, query: Query, extra_filter: Filter | None = None) -> int:
    total = 0
    for seg in ctx.searcher.segments:
        match = host_match_mask(query, seg, ctx) & seg.live & seg.parent_mask
        if extra_filter is not None:
            match &= segment_mask(seg, extra_filter, ctx)
        total += int(match.sum())
    return total


def iter_match_masks(ctx: ShardContext, query: Query,
                     extra_filter: Filter | None = None):
    """Lazily yield per-segment (scores, match): deadline-aware callers
    (execute_query_phase's general path) stop consuming at segment granularity
    and keep the segments already scored as a partial result."""
    qn = query_norm_for(query, ctx)
    join = _shard_join(ctx, query)
    for si, seg in enumerate(ctx.searcher.segments):
        if join is not None:
            scores, match = join[si]
        else:
            scorer = HostScorer(ctx, seg, qn)
            scores, match = scorer.eval(query)
        match = match & seg.live & seg.parent_mask
        if extra_filter is not None:
            match = match & segment_mask(seg, extra_filter, ctx)
        yield (scores, match)


def match_masks(ctx: ShardContext, query: Query, extra_filter: Filter | None = None):
    """Per-segment (scores, match) for aggregation/fetch sub-phases."""
    return list(iter_match_masks(ctx, query, extra_filter))
