"""Suggesters: term (spellcheck), phrase, completion.

Analogue of search/suggest/ (SURVEY.md §2.5). The term suggester mirrors Lucene's
DirectSpellChecker contract: candidate terms within max_edits of the input, ranked by
(similarity desc, doc_freq desc, term asc), respecting prefix_length / min_word_length /
suggest_mode. The phrase suggester composes term candidates with a bigram-ish score.
The completion suggester serves prefix lookups from a sorted in-memory table (the
reference builds an FST postings format — same contract, simpler structure; flagged for
a packed-trie upgrade round)."""

from __future__ import annotations

import numpy as np

from .execute import _within_edits


def _edit_distance(a: str, b: str) -> int:
    prev = list(range(len(b) + 1))
    for i, ca in enumerate(a, 1):
        cur = [i] + [0] * len(b)
        for j, cb in enumerate(b, 1):
            cur[j] = min(prev[j] + 1, cur[j - 1] + 1, prev[j - 1] + (ca != cb))
        prev = cur
    return prev[-1]


def term_suggest(ctx, spec: dict, global_text: str | None = None) -> dict:
    text = spec.get("text", global_text or "")
    term_spec = spec.get("term", {})
    field = term_spec.get("field", "_all")
    size = int(term_spec.get("size", 5))
    max_edits = int(term_spec.get("max_edits", 2))
    prefix_len = int(term_spec.get("prefix_length", term_spec.get("prefix_len", 1)))
    min_word_length = int(term_spec.get("min_word_length", 4))
    suggest_mode = term_spec.get("suggest_mode", "missing")
    analyzer = ctx.mapper_service.search_analyzer_for(field)
    out_entries = []
    for tok in analyzer.analyze(text):
        word = tok.term
        options = []
        word_df = ctx.doc_freq(field, word)
        if suggest_mode == "missing" and word_df > 0:
            out_entries.append({"text": word, "offset": tok.start,
                                "length": tok.end - tok.start, "options": []})
            continue
        if len(word) >= min_word_length:
            seen = {}
            for term in ctx.all_terms(field):
                if term == word:
                    continue
                if prefix_len and term[:prefix_len] != word[:prefix_len]:
                    continue
                if abs(len(term) - len(word)) > max_edits:
                    continue
                if not _within_edits(word, term, max_edits):
                    continue
                df = ctx.doc_freq(field, term)
                if df <= 0:
                    continue
                if suggest_mode == "popular" and df <= word_df:
                    continue
                dist = _edit_distance(word, term)
                score = 1.0 - dist / max(len(word), len(term))
                seen[term] = (score, df)
            options = [
                {"text": t, "score": round(s, 6), "freq": df}
                for t, (s, df) in sorted(
                    seen.items(), key=lambda kv: (-kv[1][0], -kv[1][1], kv[0])
                )[:size]
            ]
        out_entries.append({
            "text": word, "offset": tok.start, "length": tok.end - tok.start,
            "options": options,
        })
    return {"entries": out_entries}


def phrase_suggest(ctx, spec: dict, global_text: str | None = None) -> dict:
    text = spec.get("text", global_text or "")
    pspec = spec.get("phrase", {})
    field = pspec.get("field", "_all")
    size = int(pspec.get("size", 5))
    analyzer = ctx.mapper_service.search_analyzer_for(field)
    tokens = [t.term for t in analyzer.analyze(text)]
    if not tokens:
        return {"entries": [{"text": text, "offset": 0, "length": len(text), "options": []}]}
    per_token: list[list[tuple[str, float]]] = []
    max_doc = max(ctx.max_doc, 1)
    for word in tokens:
        cands = [(word, ctx.doc_freq(field, word))]
        tspec = {"term": {"field": field, "size": 3, "suggest_mode": "always"},
                 "text": word}
        sugg = term_suggest(ctx, tspec)
        for opt in sugg["entries"][0]["options"]:
            cands.append((opt["text"], opt["freq"]))
        scored = [(t, (df + 0.5) / max_doc) for t, df in cands]
        scored.sort(key=lambda x: -x[1])
        per_token.append(scored[:3])
    # beam over candidate combinations
    beams: list[tuple[float, list[str]]] = [(1.0, [])]
    for cands in per_token:
        new_beams = []
        for score, words in beams:
            for term, p in cands:
                new_beams.append((score * p, words + [term]))
        new_beams.sort(key=lambda b: -b[0])
        beams = new_beams[: max(size * 2, 10)]
    options = []
    seen = set()
    for score, words in beams:
        phrase = " ".join(words)
        if phrase in seen:
            continue
        seen.add(phrase)
        options.append({"text": phrase, "score": round(score, 9)})
        if len(options) >= size:
            break
    # drop the identity suggestion if it ranks first and equals input
    return {"entries": [{
        "text": text, "offset": 0, "length": len(text), "options": options,
    }]}


class _TrieNode:
    __slots__ = ("children", "max_weight", "outputs")

    def __init__(self):
        self.children: dict[str, _TrieNode] = {}
        self.max_weight = float("-inf")
        self.outputs: list[tuple[float, str, dict | None]] = []  # terminal entries


class CompletionIndex:
    """Weighted prefix trie with per-node max-weight — the FST analogue of Lucene's
    Completion090PostingsFormat (ref: search/suggest/completion/): top-k prefix
    lookup is best-first over max_weight, touching O(k · depth) nodes instead of
    scanning every completion under the prefix. Optional fuzzy prefix matching via a
    banded edit-distance walk (the suggester's XFuzzySuggester role)."""

    def __init__(self):
        self.root = _TrieNode()
        self.count = 0

    def add(self, input_text: str, output: str, weight: float = 1.0, payload=None):
        w = float(weight)
        node = self.root
        node.max_weight = max(node.max_weight, w)
        for ch in input_text.lower():
            node = node.children.setdefault(ch, _TrieNode())
            node.max_weight = max(node.max_weight, w)
        node.outputs.append((w, output, payload))
        self.count += 1

    # ------------------------------------------------------------------ lookup
    def _descend(self, prefix: str) -> _TrieNode | None:
        node = self.root
        for ch in prefix:
            node = node.children.get(ch)
            if node is None:
                return None
        return node

    def _fuzzy_roots(self, prefix: str, fuzziness: int,
                     prefix_length: int) -> list[tuple[_TrieNode, str]]:
        """All trie nodes reachable by consuming `prefix` with ≤ fuzziness edits;
        the first prefix_length chars must match exactly (ES fuzzy completion
        defaults: fuzziness 1, prefix_length 1)."""
        node = self.root
        exact, rest = prefix[:prefix_length], prefix[prefix_length:]
        for ch in exact:
            node = node.children.get(ch)
            if node is None:
                return []
        # banded Levenshtein over the remaining prefix
        results: dict[int, tuple[_TrieNode, str]] = {}
        start_row = list(range(len(rest) + 1))
        stack = [(node, exact, start_row)]
        while stack:
            n, path, row = stack.pop()
            if row[-1] <= fuzziness:
                key = id(n)
                if key not in results:
                    results[key] = (n, path)
            if min(row) > fuzziness:
                continue
            for ch, child in n.children.items():
                new_row = [row[0] + 1]
                for i in range(1, len(rest) + 1):
                    cost = 0 if rest[i - 1] == ch else 1
                    new_row.append(min(new_row[i - 1] + 1, row[i] + 1,
                                       row[i - 1] + cost))
                stack.append((child, path + ch, new_row))
        return list(results.values())

    def suggest(self, prefix: str, size: int = 5,
                fuzzy: dict | None = None) -> list[dict]:
        import heapq

        prefix = prefix.lower()
        if fuzzy:
            fz = fuzzy.get("fuzziness", 1)
            if fz in ("AUTO", "auto"):
                fz = 0 if len(prefix) < 3 else (1 if len(prefix) < 6 else 2)
            roots = self._fuzzy_roots(prefix, int(fz),
                                      int(fuzzy.get("prefix_length", 1)))
        else:
            node = self._descend(prefix)
            roots = [(node, prefix)] if node is not None else []
        if not roots:
            return []
        # best-first: heap over (-max_weight) of frontier nodes and found entries
        seq = 0
        heap = []
        for node, _path in roots:
            heap.append((-node.max_weight, seq := seq + 1, node))
        heapq.heapify(heap)
        result: list[dict] = []
        seen: set[str] = set()
        candidates: list[tuple[float, str, dict | None]] = []
        while heap and len(result) < size:
            neg_w, _, node = heapq.heappop(heap)
            # flush any found entries at least as good as the rest of the frontier
            for w, output, payload in sorted(node.outputs, reverse=True,
                                             key=lambda e: e[0]):
                heapq.heappush(heap, (-w, seq := seq + 1,
                                      _Terminal(w, output, payload)))
            if isinstance(node, _Terminal):
                if node.output not in seen:
                    seen.add(node.output)
                    opt = {"text": node.output, "score": node.weight}
                    if node.payload is not None:
                        opt["payload"] = node.payload
                    result.append(opt)
                continue
            for child in node.children.values():
                heapq.heappush(heap, (-child.max_weight, seq := seq + 1, child))
        return result


class _Terminal:
    """Heap entry for a completed suggestion (weight is exact, not an upper bound)."""

    __slots__ = ("weight", "output", "payload", "children", "outputs", "max_weight")

    def __init__(self, weight: float, output: str, payload):
        self.weight = weight
        self.output = output
        self.payload = payload
        self.children = {}
        self.outputs = []
        self.max_weight = weight


def segment_completion_trie(seg, field: str) -> CompletionIndex:
    """Build (and cache on the write-once segment) the completion trie for one
    completion-typed field, from stored sources. Entry forms per the reference's
    CompletionFieldMapper: "text", ["a","b"], or
    {"input": [...], "output": "...", "weight": N, "payload": {...}}."""
    cache = getattr(seg, "_completion_tries", None)
    if cache is None:
        cache = {}
        seg._completion_tries = cache
    trie = cache.get(field)
    if trie is not None:
        return trie
    trie = CompletionIndex()
    from .fetch import extract_field

    for local in range(seg.doc_count):
        if not seg.live[local] or seg.stored[local] is None:
            continue
        for v in extract_field(seg.stored[local], field):
            if isinstance(v, dict):
                inputs = v.get("input", [])
                inputs = [inputs] if isinstance(inputs, str) else list(inputs)
                output = v.get("output") or (inputs[0] if inputs else "")
                weight = float(v.get("weight", 1.0))
                payload = v.get("payload")
                for inp in inputs:
                    trie.add(str(inp), str(output), weight, payload)
            elif isinstance(v, list):
                for inp in v:
                    trie.add(str(inp), str(inp))
            elif v is not None:
                trie.add(str(v), str(v))
    cache[field] = trie
    return trie


def completion_suggest(ctx, name: str, spec: dict,
                       global_text: str | None = None) -> dict:
    """Completion across segments: per-segment tries merged by weight."""
    comp_spec = spec.get("completion") or {}
    prefix = spec.get("text", spec.get("prefix", global_text or ""))
    field = comp_spec.get("field", name)
    size = int(comp_spec.get("size", 5))
    fuzzy = comp_spec.get("fuzzy")
    if fuzzy is True:
        fuzzy = {}
    options: list[dict] = []
    # legacy hook: a shard-level index set on the context wins (tests / percolator)
    shard_index = getattr(ctx, "completion_index", None)
    if shard_index is not None:
        options = shard_index.suggest(prefix, size, fuzzy=fuzzy)
    else:
        merged: dict[str, dict] = {}
        for seg in ctx.searcher.segments:
            for opt in segment_completion_trie(seg, field).suggest(
                    prefix, size, fuzzy=fuzzy):
                prev = merged.get(opt["text"])
                if prev is None or opt["score"] > prev["score"]:
                    merged[opt["text"]] = opt
        options = sorted(merged.values(), key=lambda o: (-o["score"], o["text"]))[:size]
    return {"entries": [{"text": prefix, "offset": 0, "length": len(prefix),
                         "options": options}]}


def run_suggest(ctx, suggest_body: dict) -> dict:
    out = {}
    global_text = suggest_body.get("text")
    for name, spec in suggest_body.items():
        if name == "text":
            continue
        if "term" in spec:
            r = term_suggest(ctx, spec, global_text)
        elif "phrase" in spec:
            r = phrase_suggest(ctx, spec, global_text)
        elif "completion" in spec:
            r = completion_suggest(ctx, name, spec, global_text)
        else:
            continue
        out[name] = r["entries"]
    return out
