"""Suggesters: term (spellcheck), phrase, completion.

Analogue of search/suggest/ (SURVEY.md §2.5). The term suggester mirrors Lucene's
DirectSpellChecker contract: candidate terms within max_edits of the input, ranked by
(similarity desc, doc_freq desc, term asc), respecting prefix_length / min_word_length /
suggest_mode. The phrase suggester composes term candidates with a bigram-ish score.
The completion suggester serves prefix lookups from a sorted in-memory table (the
reference builds an FST postings format — same contract, simpler structure; flagged for
a packed-trie upgrade round)."""

from __future__ import annotations

import numpy as np

from .execute import _within_edits


def _edit_distance(a: str, b: str) -> int:
    prev = list(range(len(b) + 1))
    for i, ca in enumerate(a, 1):
        cur = [i] + [0] * len(b)
        for j, cb in enumerate(b, 1):
            cur[j] = min(prev[j] + 1, cur[j - 1] + 1, prev[j - 1] + (ca != cb))
        prev = cur
    return prev[-1]


def term_suggest(ctx, spec: dict, global_text: str | None = None) -> dict:
    text = spec.get("text", global_text or "")
    term_spec = spec.get("term", {})
    field = term_spec.get("field", "_all")
    size = int(term_spec.get("size", 5))
    max_edits = int(term_spec.get("max_edits", 2))
    prefix_len = int(term_spec.get("prefix_length", term_spec.get("prefix_len", 1)))
    min_word_length = int(term_spec.get("min_word_length", 4))
    suggest_mode = term_spec.get("suggest_mode", "missing")
    analyzer = ctx.mapper_service.search_analyzer_for(field)
    out_entries = []
    for tok in analyzer.analyze(text):
        word = tok.term
        options = []
        word_df = ctx.doc_freq(field, word)
        if suggest_mode == "missing" and word_df > 0:
            out_entries.append({"text": word, "offset": tok.start,
                                "length": tok.end - tok.start, "options": []})
            continue
        if len(word) >= min_word_length:
            seen = {}
            for term in ctx.all_terms(field):
                if term == word:
                    continue
                if prefix_len and term[:prefix_len] != word[:prefix_len]:
                    continue
                if abs(len(term) - len(word)) > max_edits:
                    continue
                if not _within_edits(word, term, max_edits):
                    continue
                df = ctx.doc_freq(field, term)
                if df <= 0:
                    continue
                if suggest_mode == "popular" and df <= word_df:
                    continue
                dist = _edit_distance(word, term)
                score = 1.0 - dist / max(len(word), len(term))
                seen[term] = (score, df)
            options = [
                {"text": t, "score": round(s, 6), "freq": df}
                for t, (s, df) in sorted(
                    seen.items(), key=lambda kv: (-kv[1][0], -kv[1][1], kv[0])
                )[:size]
            ]
        out_entries.append({
            "text": word, "offset": tok.start, "length": tok.end - tok.start,
            "options": options,
        })
    return {"entries": out_entries}


def phrase_suggest(ctx, spec: dict, global_text: str | None = None) -> dict:
    text = spec.get("text", global_text or "")
    pspec = spec.get("phrase", {})
    field = pspec.get("field", "_all")
    size = int(pspec.get("size", 5))
    analyzer = ctx.mapper_service.search_analyzer_for(field)
    tokens = [t.term for t in analyzer.analyze(text)]
    if not tokens:
        return {"entries": [{"text": text, "offset": 0, "length": len(text), "options": []}]}
    per_token: list[list[tuple[str, float]]] = []
    max_doc = max(ctx.max_doc, 1)
    for word in tokens:
        cands = [(word, ctx.doc_freq(field, word))]
        tspec = {"term": {"field": field, "size": 3, "suggest_mode": "always"},
                 "text": word}
        sugg = term_suggest(ctx, tspec)
        for opt in sugg["entries"][0]["options"]:
            cands.append((opt["text"], opt["freq"]))
        scored = [(t, (df + 0.5) / max_doc) for t, df in cands]
        scored.sort(key=lambda x: -x[1])
        per_token.append(scored[:3])
    # beam over candidate combinations
    beams: list[tuple[float, list[str]]] = [(1.0, [])]
    for cands in per_token:
        new_beams = []
        for score, words in beams:
            for term, p in cands:
                new_beams.append((score * p, words + [term]))
        new_beams.sort(key=lambda b: -b[0])
        beams = new_beams[: max(size * 2, 10)]
    options = []
    seen = set()
    for score, words in beams:
        phrase = " ".join(words)
        if phrase in seen:
            continue
        seen.add(phrase)
        options.append({"text": phrase, "score": round(score, 9)})
        if len(options) >= size:
            break
    # drop the identity suggestion if it ranks first and equals input
    return {"entries": [{
        "text": text, "offset": 0, "length": len(text), "options": options,
    }]}


class CompletionIndex:
    """Per-shard completion suggester storage: sorted (input → payload) entries.
    Fed by `completion`-typed fields at index time (ref: Completion090PostingsFormat)."""

    def __init__(self):
        self.entries: list[tuple[str, str, float, dict | None]] = []
        self._sorted = False

    def add(self, input_text: str, output: str, weight: float = 1.0, payload=None):
        self.entries.append((input_text.lower(), output, weight, payload))
        self._sorted = False

    def suggest(self, prefix: str, size: int = 5) -> list[dict]:
        if not self._sorted:
            self.entries.sort()
            self._sorted = True
        prefix = prefix.lower()
        import bisect

        lo = bisect.bisect_left(self.entries, (prefix,))
        out = []
        seen = set()
        i = lo
        while i < len(self.entries) and self.entries[i][0].startswith(prefix):
            out.append(self.entries[i])
            i += 1
        out.sort(key=lambda e: (-e[2], e[1]))
        result = []
        for _, output, weight, payload in out:
            if output in seen:
                continue
            seen.add(output)
            opt = {"text": output, "score": weight}
            if payload is not None:
                opt["payload"] = payload
            result.append(opt)
            if len(result) >= size:
                break
        return result


def run_suggest(ctx, suggest_body: dict) -> dict:
    out = {}
    global_text = suggest_body.get("text")
    for name, spec in suggest_body.items():
        if name == "text":
            continue
        if "term" in spec:
            r = term_suggest(ctx, spec, global_text)
        elif "phrase" in spec:
            r = phrase_suggest(ctx, spec, global_text)
        elif "completion" in spec:
            comp: CompletionIndex | None = getattr(ctx, "completion_index", None)
            prefix = spec.get("text", global_text or "")
            opts = comp.suggest(prefix, int(spec["completion"].get("size", 5))) if comp else []
            r = {"entries": [{"text": prefix, "offset": 0, "length": len(prefix),
                              "options": opts}]}
        else:
            continue
        out[name] = r["entries"]
    return out
