"""Per-shard search service: full request bodies → query phase / fetch phase, scroll
contexts, rescore — the analogue of search/SearchService.java + DefaultSearchContext
(SURVEY.md §2.5): parse once, execute query phase (top docs + agg partials + suggest),
keep the context alive for fetch/scroll, reap on keep-alive expiry.

The query/fetch split exists for the same reason as the reference's: in multi-shard
search only the GLOBAL top-k winners get hydrated (fetch), so the query phase returns
doc ids + sort tuples only (TransportSearchQueryThenFetchAction — SURVEY.md §3.3)."""

from __future__ import annotations

import itertools
import threading
import time
from dataclasses import dataclass, field as dc_field

import numpy as np

from ..common import insights as _insights
from ..common import profile as _profile
from ..common.breaker import reserve as breaker_reserve
from ..common.deadline import NO_DEADLINE, Deadline, parse_timevalue
from ..common.devicehealth import DEVICE_HEALTH
from ..common.errors import (
    CircuitBreakingError,
    QueryParsingError,
    RejectedExecutionError,
    SearchContextMissingError,
    SearchEngineError,
)
from .aggregations import facet_response, parse_aggs, parse_facets, reduce_aggs
from .execute import (
    HostScorer,
    ShardContext,
    TopDocs,
    lower_flat,
    execute_flat_batch,
    iter_match_masks,
    match_masks,
    query_norm_for,
    search_shard,
)
from .fetch import build_hit
from .filters import Filter, segment_mask
from .queries import MatchAllQuery, Query, parse_filter, parse_query
from .sorting import (
    SortSpec,
    apply_missing,
    compare_sort_values,
    parse_sort,
    sort_key_column,
    sort_values_for_docs,
)
from .suggest import run_suggest


@dataclass
class ParsedSearchRequest:
    query: Query
    post_filter: Filter | None
    from_: int
    size: int
    sort: list  # list[SortSpec]
    aggs: dict
    facets: dict
    suggest: dict | None
    rescore: list
    min_score: float | None
    body: dict
    track_scores: bool = False
    explain: bool = False
    timeout_s: float | None = None
    # `"profile": true` / `?profile=true`: arm the white-box execution
    # profiler for this request (common/profile.py — per-shard collectors,
    # merged into a top-level `profile` response section by the coordinator)
    profile: bool = False


def parse_search_body(body: dict | None) -> ParsedSearchRequest:
    body = body or {}
    try:
        timeout_s = parse_timevalue(body.get("timeout"))
    except ValueError as e:
        raise QueryParsingError(str(e)) from None  # malformed timeout is a 400
    query = parse_query(body.get("query")) if body.get("query") else MatchAllQuery()
    # top-level "filter" is the POST filter (applied to hits, not aggs/facets) —
    # ref: DefaultSearchContext.parsedPostFilter
    post_filter = parse_filter(body["filter"]) if body.get("filter") else \
        parse_filter(body["post_filter"]) if body.get("post_filter") else None
    rescore = body.get("rescore") or []
    if isinstance(rescore, dict):
        rescore = [rescore]
    return ParsedSearchRequest(
        query=query,
        post_filter=post_filter,
        from_=int(body.get("from", 0)),
        size=int(body.get("size", 10)),
        sort=parse_sort(body.get("sort")),
        aggs=parse_aggs(body.get("aggs") or body.get("aggregations") or {}),
        facets=parse_facets(body.get("facets") or {}),
        suggest=body.get("suggest"),
        rescore=rescore,
        min_score=body.get("min_score"),
        body=body,
        track_scores=bool(body.get("track_scores", False)),
        explain=bool(body.get("explain", False)),
        # ref: the request-body `timeout` TimeValue ("50ms"/"2s"; bare ms) that
        # bounds the query phase — enforced at segment granularity on the host
        timeout_s=timeout_s,
        profile=bool(body.get("profile", False)),
    )


@dataclass
class ShardQueryResult:
    """Query-phase output for ONE shard (what travels back to the coordinating node
    before the reduce — ref: QuerySearchResult)."""

    total: int
    # [(score, global_doc, sort_values|None)] — length ≤ from+size
    docs: list
    max_score: float
    agg_partials: list = dc_field(default_factory=list)  # one partial dict per segment
    facet_partials: list = dc_field(default_factory=list)
    suggest: dict | None = None
    context_id: int | None = None
    shard_id: int = 0
    # deadline expired mid-collection: docs/total/partials cover the segments
    # scored before expiry (the coordinator surfaces this as `timed_out: true`)
    timed_out: bool = False
    # white-box execution profile of this shard's query phase (plain scalars —
    # rides the wire like the span list does; None when unprofiled)
    profile: dict | None = None
    # served by the host fallback because the device path failed or its fault
    # domain is open (common/devicehealth) — bitwise-identical hits, but the
    # coordinator's `_shards` rollup must not count this copy as fully healthy
    degraded: bool = False


# process-wide serving-path counters (which executor served the query phase —
# surfaced via nodes stats "search_serving"; in-process test clusters share the
# process, so treat these as process rollups, like the script registry)
SERVING_COUNTERS = {
    "device_sparse": 0,  # flat top-k via the sparse candidate kernel
    "device_filtered": 0,  # filtered dense kernel
    "device_function_score": 0,  # fs rows/script kernels
    "device_aggs": 0,  # fused agg launch (metric/bucket)
    "device_sort": 0,  # field-sort kernel (incl. sort+aggs composition)
    "device_percolate": 0,  # batched percolation launches
    "device_percolate_fallbacks": 0,  # batch failed → host loop
    "device_errors": 0,  # device launch failed → host fallback (see _device_failed)
    "degraded": 0,  # served host-side on device failure OR an open fault domain
    "host": 0,  # host scorer / mask path
}

_device_error_logged: set = set()


def _count(path: str):
    SERVING_COUNTERS[path] += 1
    prof = _profile.current()
    if prof is not None:
        prof.outcome(path)  # the resolved execution path, recorded once
    obs = _insights.current()
    if obs is not None and obs.outcome is None:
        obs.outcome = path  # always-on query-shape outcome mix (one
        # thread-local read + attribute write — the insights hook contract)


def _device_failed(e: BaseException, ctx: "ShardContext | None" = None):
    """A device launch failed (broken backend, OOM, plugin init): the search
    must still answer — count it, log each distinct error once, serve host.
    Mirrors mesh_serving's any-mesh-failure-must-not-fail-the-search rule.

    Classified jax/XLA errors also advance the owning fault domain's circuit
    (common/devicehealth): the raiser tags the exception with its narrowest
    domain (`_estpu_device_domain`, stamped at the pack/launch/pull seams);
    untagged device errors attribute to the index's batch-pull domain."""
    from ..common.logging import get_logger

    SERVING_COUNTERS["device_errors"] += 1
    SERVING_COUNTERS["degraded"] += 1
    domain = getattr(e, "_estpu_device_domain", None)
    if domain is None and ctx is not None:
        domain = f"pull:{ctx.index_name}"
    if domain is not None:
        DEVICE_HEALTH.record_failure(domain, e)
    prof = _profile.current()
    if prof is not None:
        prof.event("device_error", error=type(e).__name__)
        prof.fallback(f"device_error:{type(e).__name__}")
    key = type(e).__name__
    if key not in _device_error_logged:
        _device_error_logged.add(key)
        get_logger("search.device").warning(
            f"device serving failed ({key}: {e}); falling back to the host "
            f"scorer (logged once per error type)")


def _domains_for(ctx: "ShardContext", families: tuple) -> tuple:
    """The fault domains one device attempt on this shard exercises: the
    index's pack + batch-pull domains plus each compile family it may launch
    (the devicehealth domain taxonomy)."""
    idx = str(ctx.index_name)
    return (f"pack:{idx}",) + tuple(f"compile:{f}" for f in families) \
        + (f"pull:{idx}",)


def _blocked_domain(ctx: "ShardContext", families: tuple) -> str | None:
    """The open fault domain that routes this query host-side before any
    launch, or None (all closed, or this caller was admitted as the probe).
    One plain attr read when every domain is closed — the standing hot-path
    contract."""
    if not DEVICE_HEALTH.any_open:
        return None
    return DEVICE_HEALTH.blocked(_domains_for(ctx, families))


def _device_degraded(domain: str):
    """An open fault domain skipped the device path: count + profile the
    degrade (the result is still bitwise-identical host-scored hits)."""
    SERVING_COUNTERS["degraded"] += 1
    prof = _profile.current()
    if prof is not None:
        prof.event("device_degraded", domain=domain)
        prof.fallback(f"device_degraded:{domain}")


def _note_device_ok(ctx: "ShardContext", families: tuple):
    """Clean device outcome: close a half-open domain this query just probed
    (one attr read when no device failure was ever recorded)."""
    if DEVICE_HEALTH.dirty:
        DEVICE_HEALTH.note_success(_domains_for(ctx, families))


def _execute_flat_single(ctx: ShardContext, plan, k: int,
                         deadline: Deadline) -> TopDocs:
    """One plan's device execution — through the node's cross-request
    DeviceBatcher when one is wired (coalescing with concurrent searches into
    one bucketed launch; search/batcher.py), else a direct single-plan launch.
    DFS-stats requests always launch directly: their per-request global stats
    change clause weights, which a shared batch cannot express.

    PROFILED requests bypass the batcher explicitly (recorded as
    `batcher: {bypassed, reason: "profile"}`): a coalesced batch's device
    phases belong to the batch, not to one member, and the per-request sync
    the profiler performs must never serialize innocent neighbors' launches.
    The bypass also keeps the collector single-writer — execution never
    leaves this thread."""
    if ctx.batcher is not None and not ctx.global_stats:
        prof = _profile.current()
        if prof is None:
            return ctx.batcher.execute(plan, ctx, k, deadline=deadline)
        # recorded ONLY when the batcher would actually have served this
        # request — a DFS search or batcher-less node launches directly
        # either way, and must not claim (or count) a profile bypass
        prof.batcher_bypass("profile")
        ctx.batcher.note_profile_bypass()
    return execute_flat_batch([plan], ctx, k)[0]


def _prof_record_plan(prof, plan, req: ParsedSearchRequest, ctx: ShardContext,
                      use_device: bool):
    """Record the resolved plan shape (or the host-fallback reason when the
    query would not lower flat) — profiled requests only."""
    from .execute import lower_fallback_reason, plan_profile

    if plan is not None:
        prof.set_plan(plan_profile(plan, req.query))
    else:
        prof.set_plan({"query_type": type(req.query).__name__})
        prof.fallback("device_disabled" if not use_device
                      else lower_fallback_reason(req.query, ctx))


def _prof_host_features(prof, req: ParsedSearchRequest):
    """The general host path was taken because of mask-needing request
    features — record which ones (set-if-unset: a lowering-level reason
    already recorded wins)."""
    feats = [name for name, present in (
        ("aggs", bool(req.aggs)), ("facets", bool(req.facets)),
        ("sort", bool(req.sort)), ("post_filter", req.post_filter is not None),
        ("rescore", bool(req.rescore)),
        ("min_score", req.min_score is not None), ("explain", req.explain),
    ) if present]
    if feats:
        prof.fallback("features:" + ",".join(feats))


def execute_query_phase(ctx: ShardContext, req: ParsedSearchRequest,
                        use_device: bool = True, shard_id: int = 0,
                        deadline: Deadline | None = None) -> ShardQueryResult:
    # the shard's time budget: coordinator-supplied remaining budget when the
    # request came over transport, else the request's own `timeout`. Enforced
    # ONLY at host-side segment boundaries — a device launch, once started,
    # always completes whole (deadline checks never cross into traced code).
    if deadline is None:
        deadline = Deadline.after(req.timeout_s) if req.timeout_s is not None \
            else NO_DEADLINE
    k = req.from_ + req.size
    needs_masks = bool(req.aggs or req.facets or req.sort or req.post_filter
                       or req.rescore or req.min_score is not None)
    suggest_out = run_suggest(ctx, req.suggest) if req.suggest else None
    if deadline.expired():
        # budget gone before any segment was scored: legal partial = nothing
        return ShardQueryResult(total=0, docs=[], max_score=float("nan"),
                                suggest=suggest_out, shard_id=shard_id,
                                timed_out=True)

    # profile hooks (one thread-local read when unprofiled): lowering wall
    # time + the resolved plan shape, with the fallback reason whenever the
    # fused path is declined (execute.lower_fallback_reason vocabulary)
    prof = _profile.current()

    if not needs_masks:
        t_low = time.monotonic() if prof is not None else 0.0
        plan = lower_flat(req.query, ctx) if use_device else None
        if prof is not None:
            prof.phase_s("lower", time.monotonic() - t_low)
            _prof_record_plan(prof, plan, req, ctx, use_device)
        degraded = False
        if plan is not None:
            fams = ("function_score",) if plan.fs is not None else \
                ("filtered",) if plan.filt is not None else ("sparse", "dense")
            dom = _blocked_domain(ctx, fams)
            if dom is not None:
                _device_degraded(dom)  # open fault domain: host serves, no launch
                degraded = True
            else:
                try:
                    td = _execute_flat_single(ctx, plan, max(k, 1), deadline)
                except CircuitBreakingError as e:
                    if getattr(e, "breaker", None) != "fielddata":
                        raise  # request/parent trip: load-shed (429), not degradable
                    _device_failed(e, ctx)  # out of device-pack budget → host serves
                    degraded = True
                except SearchEngineError:
                    raise  # domain errors (scripts, parsing) are the answer itself
                except Exception as e:  # noqa: BLE001 — device trouble must not
                    _device_failed(e, ctx)  # fail the search; the host scorer answers
                    degraded = True
                else:
                    _note_device_ok(ctx, fams)
                    _count("device_function_score" if plan.fs is not None
                           else "device_filtered" if plan.filt is not None
                           else "device_sparse")
                    return ShardQueryResult(
                        total=td.total, docs=[(s, d, None) for s, d in td.hits],
                        max_score=td.max_score, suggest=suggest_out,
                        shard_id=shard_id,
                    )
        _count("host")
        td = _host_topk(ctx, req, k, deadline)
        return ShardQueryResult(total=td.total, docs=[(s, d, None) for s, d in td.hits],
                                max_score=td.max_score, suggest=suggest_out,
                                shard_id=shard_id, timed_out=td.timed_out,
                                degraded=degraded)

    if prof is not None:
        # profiled-only pre-lowering: the mask-needing branches below lower
        # again internally; this records the plan shape (or the lowering
        # fallback reason) once, before any branch runs
        t_low = time.monotonic()
        _prof_record_plan(prof, lower_flat(req.query, ctx) if use_device
                          else None, req, ctx, use_device)
        prof.phase_s("lower", time.monotonic() - t_low)

    # device fault-domain state for the mask-needing branches: an open domain
    # (or a device failure below) degrades to the general host path, which
    # marks its ShardQueryResult so `_shards` stays honest
    degraded = False

    # device metric-agg path: when the ONLY mask consumer is a set of
    # device-eligible metric aggs, the agg reduction fuses into the scoring
    # kernel (execute.execute_flat_aggs) instead of materializing host masks
    if (use_device and req.aggs and not req.facets and not req.sort
            and req.post_filter is None and not req.rescore
            and req.min_score is None and not req.explain):
        dom = _blocked_domain(ctx, ("aggs",))
        if dom is not None:
            _device_degraded(dom)
            degraded = True
            device = None
        else:
            try:
                device = _try_device_aggs(ctx, req, k, suggest_out, shard_id)
            except CircuitBreakingError as e:
                if getattr(e, "breaker", None) != "fielddata":
                    raise  # request/parent trip: load-shed (429), not degradable
                _device_failed(e, ctx)  # out of device-pack budget → host collectors
                degraded = True
                device = None
            except SearchEngineError:
                raise  # domain errors (scripts, parsing) are the answer itself
            except Exception as e:  # noqa: BLE001
                _device_failed(e, ctx)
                degraded = True
                device = None
        if device is not None:
            _note_device_ok(ctx, ("aggs",))
            _count("device_aggs")
            return device

    # device min_score path: the function_score rows kernel with no functions IS
    # a score threshold gate — synthesize an empty fs wrapper around the query
    if (use_device and req.min_score is not None and not req.aggs
            and not req.facets and not req.sort and req.post_filter is None
            and not req.rescore and not req.explain):
        from .queries import FunctionScoreQuery

        wrapped = FunctionScoreQuery(query=req.query, min_score=req.min_score)
        plan = lower_flat(wrapped, ctx)
        if plan is not None:
            dom = _blocked_domain(ctx, ("function_score",))
            if dom is not None:
                _device_degraded(dom)
                degraded = True
            else:
                try:
                    td = _execute_flat_single(ctx, plan, max(k, 1), deadline)
                except CircuitBreakingError as e:
                    if getattr(e, "breaker", None) != "fielddata":
                        raise  # request/parent trip: load-shed (429), not degradable
                    _device_failed(e, ctx)  # out of device-pack budget → host serves
                    degraded = True
                except SearchEngineError:
                    raise  # domain errors are the answer itself
                except Exception as e:  # noqa: BLE001
                    _device_failed(e, ctx)
                    degraded = True
                else:
                    _note_device_ok(ctx, ("function_score",))
                    _count("device_filtered")
                    return ShardQueryResult(
                        total=td.total,
                        docs=[(s, d, None) for s, d in td.hits[: max(k, 0)]],
                        max_score=td.max_score, suggest=suggest_out,
                        shard_id=shard_id,
                    )

    # device post_filter path: aggs (if any) reduce over the FULL match set while
    # hits gate on the post filter — two composed launches sharing the dense core
    # (the reference's faceting idiom: post_filter never affects aggregations)
    if (use_device and req.post_filter is not None and not req.sort
            and not req.facets and not req.rescore and req.min_score is None
            and not req.explain):
        dom = _blocked_domain(ctx, ("filtered", "aggs"))
        if dom is not None:
            _device_degraded(dom)
            degraded = True
            device = None
        else:
            try:
                device = _try_device_post_filter(ctx, req, k, suggest_out,
                                                 shard_id)
            except CircuitBreakingError as e:
                if getattr(e, "breaker", None) != "fielddata":
                    raise  # request/parent trip: load-shed (429), not degradable
                _device_failed(e, ctx)  # out of device-pack budget → host serves
                degraded = True
                device = None
            except SearchEngineError:
                raise  # domain errors (scripts, parsing) are the answer itself
            except Exception as e:  # noqa: BLE001
                _device_failed(e, ctx)
                degraded = True
                device = None
        if device is not None:
            _note_device_ok(ctx, ("filtered", "aggs"))
            _count("device_filtered")
            return device

    # device field-sort path: single numeric field sort, top-k over pre-folded
    # key rows inside the kernel (execute.execute_flat_sorted); combines with
    # device-eligible aggs (agg launch supplies partials, sort launch ordering)
    if (use_device and req.sort and len(req.sort) == 1
            and not req.facets and req.post_filter is None and not req.rescore
            and req.min_score is None and not req.explain):
        dom = _blocked_domain(ctx, ("sorted", "aggs"))
        if dom is not None:
            _device_degraded(dom)
            degraded = True
            device = None
        else:
            try:
                device = _try_device_sort(ctx, req, k, suggest_out, shard_id)
            except CircuitBreakingError as e:
                if getattr(e, "breaker", None) != "fielddata":
                    raise  # request/parent trip: load-shed (429), not degradable
                _device_failed(e, ctx)  # out of device-pack budget → host serves
                degraded = True
                device = None
            except SearchEngineError:
                raise  # domain errors (scripts, parsing) are the answer itself
            except Exception as e:  # noqa: BLE001
                _device_failed(e, ctx)
                degraded = True
                device = None
        if device is not None:
            _note_device_ok(ctx, ("sorted", "aggs"))
            _count("device_sort")
            return device

    # general path: the whole host materialization (per-segment score/match
    # arrays, agg/facet bucket state, the sort-entry list) is reserved on the
    # request breaker UP FRONT — this is the node's "wide aggregation"
    # overload face; the reservation holds until the partials are built and
    # releases on exit (estimate-before-allocate; all host-side, never traced)
    _mask_est = ctx.searcher.max_doc * (
        5 + 16 * (len(req.aggs) + len(req.facets)))
    with breaker_reserve(ctx.breaker("request"), _mask_est, "<query_phase_host>"):
        # general path: dense per-segment masks drive sort/aggs/rescore. Masks are
        # consumed lazily so the deadline clamps BETWEEN segments: expiry keeps the
        # segments already scored as an honest partial (timed_out below)
        _count("host")
        if prof is not None:
            _prof_host_features(prof, req)
        timed_out = False
        seg_results = []
        masks_iter = iter_match_masks(ctx, req.query)
        seg_masks_for_aggs = []
        all_entries = []  # (sortkeys..., score, global_doc, seg_idx, local)
        total = 0
        max_score = float("nan")
        for si, (seg, base) in enumerate(
            zip(ctx.searcher.segments, ctx.searcher.bases)
        ):
            if si > 0 and deadline.expired():
                timed_out = True
                break
            t_seg = time.monotonic() if prof is not None else 0.0
            scores, match = next(masks_iter)
            if prof is not None:
                prof.segment(seg.gen, docs=int(seg.doc_count), path="host",
                             ms=(time.monotonic() - t_seg) * 1000.0)
            seg_results.append((scores, match))
            if req.min_score is not None:
                match = match & (scores >= np.float32(req.min_score))
            seg_masks_for_aggs.append((seg, match, scores))
            hit_mask = match
            if req.post_filter is not None:
                hit_mask = match & segment_mask(seg, req.post_filter, ctx)
            idx = np.nonzero(hit_mask)[0]
            total += len(idx)
            if not len(idx):
                continue
            seg_scores = scores[idx]
            if len(seg_scores):
                m = float(seg_scores.max())
                max_score = m if max_score != max_score else max(max_score, m)
            if req.sort:
                keycols = []
                for spec in req.sort:
                    col = apply_missing(sort_key_column(spec, seg, ctx, scores), spec)
                    keycols.append(col[idx] * (-1.0 if spec.reverse else 1.0))
                for j, local in enumerate(idx):
                    all_entries.append(
                        (tuple(kc[j] for kc in keycols), float(seg_scores[j]),
                         base + int(local), si, int(local))
                    )
            else:
                for j, local in enumerate(idx):
                    all_entries.append(
                        ((-float(seg_scores[j]),), float(seg_scores[j]),
                         base + int(local), si, int(local))
                    )
        all_entries.sort(key=lambda e: (e[0], e[2]))
        top = all_entries[: max(k, 0)]

        # rescore: re-rank the top window with the rescore queries
        if req.rescore and top:
            top = _apply_rescore(ctx, req, top)

        docs = []
        # per-segment grouped sort-value extraction for response "sort" arrays
        if req.sort:
            sort_vals_by_rank = _sort_values_by_rank(
                req.sort, ctx, [(si, local) for (_, _s, _g, si, local) in top],
                scores_by_seg={si: r[0] for si, r in enumerate(seg_results)})
            for rank, (_, s, g, si, local) in enumerate(top):
                score = s if req.track_scores or _score_in_sort(req.sort) else float("nan")
                docs.append((score, g, sort_vals_by_rank[rank]))
        else:
            docs = [(s, g, None) for (_, s, g, _si, _l) in top]

        agg_partials = []
        facet_partials = []
        if req.aggs:
            agg_partials = [
                {n: a.collect(seg, ctx, mask, scores) for n, a in req.aggs.items()}
                for seg, mask, scores in seg_masks_for_aggs
            ]
        if req.facets:
            facet_partials = [
                {n: agg.collect(seg, ctx, mask, scores)
                 for n, (agg, _kind) in req.facets.items()}
                for seg, mask, scores in seg_masks_for_aggs
            ]
        return ShardQueryResult(
            total=total, docs=docs, max_score=max_score, agg_partials=agg_partials,
            facet_partials=facet_partials, suggest=suggest_out, shard_id=shard_id,
            timed_out=timed_out, degraded=degraded,
        )


def _try_device_aggs(ctx: ShardContext, req: ParsedSearchRequest, k: int,
                     suggest_out, shard_id: int) -> "ShardQueryResult | None":
    """Serve query + aggregations in one fused device program per segment; None
    when any agg (or the query) needs the host path. Metric aggs reduce to
    masked stats, bucket aggs (terms/histogram/date_histogram) to exact
    scatter-add doc counts over host-computed keys."""
    from .aggregations import (device_agg_field, device_bucket_eligible,
                               device_bucket_partial, device_bucket_subs,
                               device_partial)
    from .execute import execute_flat_aggs

    metric_fields = {}
    bucket_names = []
    bucket_subs: dict[str, dict] = {}
    for name, agg in req.aggs.items():
        f = device_agg_field(agg, ctx)
        if f is not None:
            metric_fields[name] = f
        elif device_bucket_eligible(agg):
            subs = device_bucket_subs(agg, ctx) if agg.subs else {}
            if subs is None:
                return None  # a sub-agg can't ride the kernel
            bucket_names.append(name)
            # the ONE field-order used for both the kernel stack layout and
            # partial-assembly row lookup
            bucket_subs[name] = (subs, sorted(set(subs.values())))
        else:
            return None
    plan = lower_flat(req.query, ctx)
    if plan is None or plan.fs is not None:
        return None
    fields = sorted(set(metric_fields.values()))
    fpos = {f: i for i, f in enumerate(fields)}
    bucket_aggs = [
        (req.aggs[n], bucket_subs[n][1] or None) for n in bucket_names
    ]
    # kernel k is at least 1 so max_score stays observable; hits trim to the
    # requested size below (size=0 agg-only requests return no docs, like the
    # host mask path)
    td, seg_stats = execute_flat_aggs(plan, ctx, max(k, 1), fields, bucket_aggs)
    if td is None:
        return None  # a column wasn't f32-exact — host path
    bpos = {n: i for i, n in enumerate(bucket_names)}

    def bucket_partial(name, agg, buckets, seg):
        keys, bcounts, sub_cnt, sub_stats = buckets[bpos[name]]
        sub_data = None
        field_of, order = bucket_subs[name]
        if field_of:
            sub_data = (agg.subs, field_of, order, sub_cnt, sub_stats)
        return device_bucket_partial(agg, keys, bcounts, seg=seg,
                                     sub_data=sub_data)

    agg_partials = [
        {name: (device_partial(agg, counts[fpos[metric_fields[name]]],
                               stats[fpos[metric_fields[name]]])
                if name in metric_fields
                else bucket_partial(name, agg, buckets, seg))
         for name, agg in req.aggs.items()}
        for (counts, stats, buckets), seg in zip(seg_stats,
                                                 ctx.searcher.segments)
    ]
    return ShardQueryResult(
        total=td.total, docs=[(s, d, None) for s, d in td.hits[:max(k, 0)]],
        max_score=td.max_score, agg_partials=agg_partials, suggest=suggest_out,
        shard_id=shard_id,
    )


def _try_device_post_filter(ctx: ShardContext, req: ParsedSearchRequest, k: int,
                            suggest_out, shard_id: int) -> "ShardQueryResult | None":
    """post_filter requests: the hit launch gates on (query filter AND post
    filter); the agg launch (when aggs exist and are device-eligible) sees only
    the query's own match set — exactly the host mask path's split."""
    import dataclasses

    from .execute import lower_flat
    from .filters import BoolFilter

    plan = lower_flat(req.query, ctx)
    if plan is None or plan.fs is not None:
        return None
    agg_result = None
    if req.aggs:
        agg_result = _try_device_aggs(ctx, req, 0, None, shard_id)
        if agg_result is None:
            return None
    hit_filter = req.post_filter if plan.filt is None else \
        BoolFilter(must=[plan.filt, req.post_filter])
    hit_plan = dataclasses.replace(plan, filt=hit_filter)
    td = execute_flat_batch([hit_plan], ctx, max(k, 1))[0]
    return ShardQueryResult(
        total=td.total, docs=[(s, d, None) for s, d in td.hits[: max(k, 0)]],
        max_score=td.max_score,
        agg_partials=agg_result.agg_partials if agg_result is not None else [],
        suggest=suggest_out, shard_id=shard_id,
    )


def _try_device_sort(ctx: ShardContext, req: ParsedSearchRequest, k: int,
                     suggest_out, shard_id: int) -> "ShardQueryResult | None":
    """Field-sorted top-k in the fused kernel; None when the spec/columns/query
    need the host path. Sort VALUES in the response come from the host extractor
    (exact f64 / None-for-missing), only the ORDERING rides the device. Requests
    that ALSO carry device-eligible aggs get a second fused launch for the
    partials (same match set — both kernels share the dense core)."""
    from .execute import execute_flat_sorted, lower_flat

    spec = req.sort[0]
    if spec.kind != "field":
        return None
    agg_result = None
    if req.aggs:
        agg_result = _try_device_aggs(ctx, req, 0, None, shard_id)
        if agg_result is None:
            return None  # any host-only agg sends the whole request host-side
    plan = lower_flat(req.query, ctx)
    if plan is None or plan.fs is not None:
        return None
    res = execute_flat_sorted(plan, ctx, max(k, 1), spec)
    if res is None:
        return None
    total, max_score, entries = res
    values_by_rank = _sort_values_by_rank(
        req.sort, ctx, [(si, local) for (_key, _g, si, local, _s) in entries])
    docs = [
        (s if req.track_scores else float("nan"), g, values_by_rank[rank])
        for rank, (_key, g, _si, _local, s) in enumerate(entries)
    ][: max(k, 0)]
    return ShardQueryResult(
        total=total, docs=docs, max_score=max_score,
        agg_partials=agg_result.agg_partials if agg_result is not None else [],
        suggest=suggest_out, shard_id=shard_id,
    )


def _sort_values_by_rank(specs: list, ctx: ShardContext, seg_locals: list,
                         scores_by_seg: dict | None = None) -> dict:
    """rank -> sort-value list, extracted per segment so column reads vectorize
    — the ONE site for response "sort" arrays (host mask path AND device sort
    path). seg_locals: (seg_idx, local) per rank; scores_by_seg supplies dense
    score arrays for _score-kind specs (host path only)."""
    by_seg: dict[int, list[int]] = {}
    for rank, (si, _local) in enumerate(seg_locals):
        by_seg.setdefault(si, []).append(rank)
    out: dict[int, list] = {}
    for si, ranks in by_seg.items():
        seg = ctx.searcher.segments[si]
        locals_ = np.asarray([seg_locals[r][1] for r in ranks])
        scores = scores_by_seg.get(si) if scores_by_seg else None
        vals = sort_values_for_docs(specs, seg, ctx, locals_, scores)
        for r, v in zip(ranks, vals):
            out[r] = v
    return out


def _score_in_sort(sort: list) -> bool:
    return any(s.kind == "score" for s in sort)


def _host_topk(ctx: ShardContext, req: ParsedSearchRequest, k: int,
               deadline: Deadline = NO_DEADLINE) -> TopDocs:
    return search_shard(ctx, req.query, max(k, 1), use_device=False,
                        deadline=deadline)


def _apply_rescore(ctx: ShardContext, req: ParsedSearchRequest, top: list) -> list:
    """ref: search/rescore/QueryRescorer — window top-N re-scored, combined by
    score_mode with query/rescore weights, then re-sorted within the window."""
    for rspec in req.rescore:
        window = int(rspec.get("window_size", 10))
        qspec = rspec.get("query", {})
        rq = parse_query(qspec.get("rescore_query"))
        qw = float(qspec.get("query_weight", 1.0))
        rw = float(qspec.get("rescore_query_weight", 1.0))
        mode = qspec.get("score_mode", "total")
        qn = query_norm_for(rq, ctx)
        window_entries = top[:window]
        rest = top[window:]
        by_seg: dict[int, list[int]] = {}
        for i, (_, _s, _g, si, local) in enumerate(window_entries):
            by_seg.setdefault(si, []).append(i)
        new_entries = list(window_entries)
        for si, idxs in by_seg.items():
            seg = ctx.searcher.segments[si]
            scorer = HostScorer(ctx, seg, qn)
            rscores, rmatch = scorer.eval(rq)
            for i in idxs:
                key0, s, g, si2, local = window_entries[i]
                if rmatch[local]:
                    rs = float(rscores[local])
                    if mode == "total":
                        ns = s * qw + rs * rw
                    elif mode == "multiply":
                        ns = s * qw * rs * rw
                    elif mode == "avg":
                        ns = (s * qw + rs * rw) / 2.0
                    elif mode == "max":
                        ns = max(s * qw, rs * rw)
                    elif mode == "min":
                        ns = min(s * qw, rs * rw)
                    else:
                        raise QueryParsingError(f"unknown rescore score_mode [{mode}]")
                else:
                    ns = s * qw
                new_entries[i] = ((-ns,), ns, g, si2, local)
        new_entries.sort(key=lambda e: (e[0], e[2]))
        top = new_entries + rest
    return top


def execute_fetch_phase(ctx: ShardContext, req: ParsedSearchRequest,
                        docs: list, index_name: str = "index",
                        shard_id: int | None = None) -> list[dict]:
    """docs: [(score, global_doc, sort_values|None)] — the winners to hydrate."""
    hits = []
    for score, g, sort_values in docs:
        seg, local = ctx.searcher.resolve(g)
        hits.append(build_hit(seg, local, score, req.body, req.query, ctx,
                              index_name=index_name, sort_values=sort_values,
                              shard_id=shard_id))
    return hits


def reduce_and_respond(ctx: ShardContext, req: ParsedSearchRequest,
                       result: ShardQueryResult, took_ms: int = 0,
                       index_name: str = "index") -> dict:
    """Single-shard convenience: query result → full response body."""
    page = result.docs[req.from_: req.from_ + req.size]
    hits = execute_fetch_phase(ctx, req, page, index_name=index_name)
    resp: dict = {
        "took": took_ms,
        "timed_out": False,
        "_shards": {"total": 1, "successful": 1, "failed": 0},
        "hits": {
            "total": result.total,
            "max_score": None if result.max_score != result.max_score else result.max_score,
            "hits": hits,
        },
    }
    if req.aggs:
        resp["aggregations"] = reduce_aggs(req.aggs, result.agg_partials)
    if req.facets:
        resp["facets"] = {
            name: facet_response(agg, kind, agg.finalize(agg.merge(
                [p[name] for p in result.facet_partials])))
            for name, (agg, kind) in req.facets.items()
        }
    if result.suggest is not None:
        resp["suggest"] = result.suggest
    return resp


# ---------------------------------------------------------------------------
# search contexts (scroll / two-phase) — ref: SearchService's active contexts map
# ---------------------------------------------------------------------------


@dataclass
class SearchContextEntry:
    ctx: ShardContext
    req: ParsedSearchRequest
    ordered_docs: list  # full sorted [(score, global_doc, sort_values)]
    position: int
    keep_alive_s: float
    last_access: float
    index_name: str = "index"


class SearchService:
    """Holds long-lived shard search contexts keyed by id (scroll); reaps expired ones
    (ref: SearchService keep-alive reaper)."""

    def __init__(self):
        self._contexts: dict[int, SearchContextEntry] = {}
        self._ids = itertools.count(1)
        self._lock = threading.Lock()

    def create_scroll(self, ctx: ShardContext, req: ParsedSearchRequest,
                      keep_alive_s: float = 300.0, use_device: bool = True,
                      index_name: str = "index") -> tuple[int, ShardQueryResult]:
        # materialize the FULL ordering once; scroll pages through it
        big = ParsedSearchRequest(**{**req.__dict__, "from_": 0,
                                     "size": max(ctx.searcher.max_doc, 1)})
        result = execute_query_phase(ctx, big, use_device=use_device)
        cid = next(self._ids)
        with self._lock:
            self._contexts[cid] = SearchContextEntry(
                ctx=ctx, req=req, ordered_docs=result.docs, position=0,
                keep_alive_s=keep_alive_s, last_access=time.monotonic(),
                index_name=index_name,
            )
        first = ShardQueryResult(
            total=result.total, docs=result.docs[: req.size],
            max_score=result.max_score, agg_partials=result.agg_partials,
            facet_partials=result.facet_partials, suggest=result.suggest,
            context_id=cid,
        )
        with self._lock:
            self._contexts[cid].position = req.size
        return cid, first

    def scroll(self, cid: int) -> tuple[ShardQueryResult, bool]:
        with self._lock:
            entry = self._contexts.get(cid)
            if entry is None:
                raise SearchContextMissingError(cid)
            entry.last_access = time.monotonic()
            page = entry.ordered_docs[entry.position: entry.position + entry.req.size]
            entry.position += entry.req.size
            done = entry.position >= len(entry.ordered_docs)
        return ShardQueryResult(
            total=len(entry.ordered_docs), docs=page, max_score=float("nan"),
            context_id=cid,
        ), done

    def entry(self, cid: int) -> SearchContextEntry:
        with self._lock:
            e = self._contexts.get(cid)
            if e is None:
                raise SearchContextMissingError(cid)
            return e

    def free(self, cid: int) -> bool:
        with self._lock:
            return self._contexts.pop(cid, None) is not None

    def reap_expired(self):
        now = time.monotonic()
        with self._lock:
            for cid, e in list(self._contexts.items()):
                if now - e.last_access > e.keep_alive_s:
                    del self._contexts[cid]

    def active_contexts(self) -> int:
        return len(self._contexts)


# ---------------------------------------------------------------------------
# deadline-aware admission control (coordinator side)
# ---------------------------------------------------------------------------


class SearchAdmissionController:
    """Reject unservable searches BEFORE the fan-out.

    A request whose remaining Deadline budget is smaller than the node's
    recent shard-phase latency cannot finish in time — executing it anyway
    burns a search worker, transport slots, and breaker headroom to produce
    an answer the client has already given up on. The coordinator tracks
    observed shard-phase latency in a MeanMetric (common/metrics.py) and
    turns those requests into an immediate 429 with a Retry-After hint.

    Unbounded requests (no `timeout`) are always admitted, and nothing is
    rejected before `min_samples` observations — a cold node (whose first
    searches include multi-second XLA compiles) must not poison admission
    for everyone.

    The admit() signal is an EWMA over the MeanMetric's samples, not the
    lifetime mean: one slow failover chain must stop poisoning admission
    within ~1/alpha further observations, while a lifetime mean would shed
    servable load for hundreds of requests after a single 5s outlier.
    """

    EWMA_ALPHA = 0.2  # ~5-sample memory

    def __init__(self, min_samples: int = 10):
        from ..common.metrics import CounterMetric, HistogramMetric, MeanMetric

        self.min_samples = min_samples
        self.latency = MeanMetric()  # lifetime rollup (stats/observability)
        # tail view of the same signal: the EWMA decides admission, the
        # histogram answers "what does p99 shard-phase latency look like"
        # (p50/p95/p99 in /_nodes/stats + the Prometheus exposition)
        self.histogram = HistogramMetric()
        self.rejected = CounterMetric()
        self._ewma = 0.0  # the decaying signal admit() compares against
        self._ewma_lock = threading.Lock()

    def observe(self, seconds: float):
        s = max(0.0, float(seconds))
        self.latency.inc(s)
        self.histogram.observe(s)
        with self._ewma_lock:
            self._ewma = s if self.latency.count <= 1 else \
                self.EWMA_ALPHA * s + (1.0 - self.EWMA_ALPHA) * self._ewma

    def admit(self, deadline: Deadline):
        """Raise RejectedExecutionError (429) when the remaining budget cannot
        cover one expected shard phase; no-op while unbounded or cold."""
        remaining = deadline.remaining()
        if remaining is None or self.latency.count < self.min_samples:
            return
        expected = self._ewma
        if remaining < expected:
            self.rejected.inc()
            err = RejectedExecutionError(
                f"rejected before fan-out: remaining budget "
                f"[{remaining * 1000:.0f}ms] < expected shard phase "
                f"[{expected * 1000:.0f}ms]")
            # hint when the request WOULD be servable: one expected phase
            err.retry_after_s = max(expected, 0.001)
            raise err

    def stats(self) -> dict:
        return {
            "observed": self.latency.count,
            "mean_shard_phase_ms": round(self.latency.mean * 1000.0, 3),
            "ewma_shard_phase_ms": round(self._ewma * 1000.0, 3),
            "rejected": self.rejected.count,
            # tail percentiles of the same observations (HistogramMetric)
            "shard_phase": self.histogram.stats(),
        }
