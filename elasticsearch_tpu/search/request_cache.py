"""Shard request cache: fingerprint of the normalized request + point-in-time
view → serialized partial shard response.

Analogue of the reference's shard request cache (indices/cache/query/
IndicesQueryCache hung off the layer-1 recycled/paged-array + breaker
substrate): at millions-of-users scale the hottest queries repeat, and the
cheapest device launch is the one never dispatched. A hit in
`actions._s_query_phase` returns the stored partial BEFORE
`execute_query_phase` runs — zero device launches, zero device syncs, zero
kernel work; only the fetch phase (hydrating the global winners) still runs.

Semantics (reference parity):

- **Key** = (index, shard, searcher view version, fingerprint). The view
  version advances whenever the engine installs a new point-in-time Searcher
  (refresh with changes / merge / optimize / recovery), so a cached partial
  can never outlive the segment view it was computed against — the NRT
  invariant "search results cannot change without a refresh" is exactly what
  makes view-keyed caching sound. The fingerprint is a stable
  re-serialization of the normalized request body (sorted keys, volatile
  knobs stripped), covering query/filter/from/size/sort/aggs — the (k, from,
  agg signature) of the partial.
- **Default scope**: only `size == 0` requests (counts, agg-only dashboards)
  are cached unless the request opts in with `?request_cache=true`;
  `?request_cache=false` opts out entirely; `indices.requests.cache.enable`
  kills the tier node-wide. This is the reference's rule — hit-bearing pages
  are personal, count/agg rollups are shared.
- **Value** = the partial shard response serialized through the binary wire
  codec (common/stream.py) — the same bytes that would cross the transport,
  so breaker accounting is honest and a hit hands back an isolated copy (no
  shared mutable state between requests).
- **Accounting**: every stored entry's bytes are charged on the node's
  `request` breaker and held until the entry is evicted/invalidated/cleared
  — `POST /_cache/clear?request=true` drains the tier back to 0. A breaker
  trip at store time skips caching (counted), never fails the search.
- **Bounds**: LRU over `indices.requests.cache.size` (ratio of the breaker
  budget or absolute bytes; default 1%).
- **Invalidation**: the engine's view listeners call `invalidate_shard` on
  every searcher install, dropping entries from superseded views eagerly
  (the view component of the key already makes them unreachable — eager
  invalidation is what returns their bytes).

Lock discipline (PR 6): `_lock` is a LEAF — only dict/counter mutation
happens under it; serialization happens before `put` is called, breaker
release happens after the lock is dropped, and nothing under the lock ever
blocks or dispatches device work.
"""

from __future__ import annotations

import hashlib
import json
import threading
import zlib
from collections import OrderedDict

from ..common.errors import CircuitBreakingError
from ..common.units import parse_ratio_or_bytes

# request-body keys that must not change the cache identity: execution knobs
# (profiling, tracing, the cache flag itself, the time budget) select HOW a
# request runs, not WHAT it computes
_VOLATILE_KEYS = ("profile", "request_cache", "timeout")


def canonical_body(body: dict | None) -> bytes:
    """The canonical serialized form fingerprints hash: sorted-keys compact
    JSON of the body minus volatile execution knobs. Also what the warmer
    replays — the stored blob re-parses to a body that fingerprints
    identically to the live request it warmed for."""
    core = {k: v for k, v in (body or {}).items() if k not in _VOLATILE_KEYS}
    return json.dumps(core, sort_keys=True, separators=(",", ":"),
                      default=str).encode("utf-8")


def request_fingerprint(body: dict | None) -> str:
    """Stable fingerprint of a normalized search body: canonical JSON
    re-serialization (sorted keys, compact separators) of the body minus
    volatile execution knobs, hashed. Two dicts that differ only in key
    order — or in profile/timeout/request_cache flags — fingerprint
    identically; any semantic difference (query, filter, from/size, sort,
    aggs, suggest) changes it."""
    return hashlib.blake2b(canonical_body(body), digest_size=16).hexdigest()


def cache_policy(body: dict | None) -> bool:
    """Whether a request body is request-cache ELIGIBLE (the reference's
    rule): explicit `request_cache: true` always, explicit false never,
    otherwise only size == 0 requests (counts / agg-only). The ONE policy
    shared by the shard serving path and the coordinator's cache-affinity
    routing — drift between them would route for a cache the shard never
    consults."""
    body = body or {}
    explicit = body.get("request_cache")
    if explicit is not None:
        return bool(explicit)
    try:
        return int(body.get("size", 10) or 0) == 0
    except (TypeError, ValueError):
        return False


class ShardRequestCache:
    """Node-level LRU of serialized partial shard responses.

    Thread-safe; `_lock` is a leaf (see module docstring). Counter attributes
    are plain ints read unlocked by the load-signal piggyback — exact enough
    for a decayed routing signal, and the serving path gains no locks."""

    # per-entry bookkeeping overhead charged beyond the value bytes (key
    # tuple, OrderedDict node, breaker slack)
    ENTRY_OVERHEAD = 256
    # hot-key memory per shard (warmer follow-on): fingerprint → [hit count,
    # canonical body blob], LRU-bounded. Hit counts SURVIVE view-advance
    # invalidation — that is the whole point: the warmer replays the
    # previous view's hottest bodies against the new view so the first
    # post-refresh sighting is a hit, not a miss
    HOT_PER_SHARD = 32

    def __init__(self, settings=None, breaker=None,
                 total_budget: int = 8 << 30):
        from ..common.settings import Settings

        settings = settings or Settings.EMPTY
        self.enabled = bool(
            settings.get_bool("indices.requests.cache.enable", True))
        self.size_bytes = int(parse_ratio_or_bytes(
            settings.get("indices.requests.cache.size"), int(total_budget),
            default="1%"))
        # stored-partial compression floor: values at/above it are
        # zlib-deflated before insertion and the BREAKER is charged the
        # compressed size — the cache budget buys entries, not padding.
        # Negative disables; small partials (count-only bodies, a hundred
        # bytes) stay raw — deflate overhead would beat the win
        self.compress_min_bytes = settings.get_bytes(
            "indices.requests.cache.compress_min_bytes", 1024)
        self.breaker = breaker
        self._lock = threading.Lock()
        # key -> (blob, charged size, raw_len); raw_len > 0 marks a
        # zlib-compressed blob (its decompressed length); OrderedDict
        # insertion order IS the LRU order (move_to_end on hit)
        self._entries: "OrderedDict[tuple, tuple[bytes, int, int]]" = \
            OrderedDict()
        # secondary index (index, shard) -> {keys}: invalidation runs on
        # EVERY searcher install of every shard, under the engine lock — it
        # must touch only that shard's entries, not scan the node-wide LRU
        # (150k+ entries at default sizing) while holding the serving lock
        self._by_shard: dict[tuple, set] = {}
        # (index, shard) -> OrderedDict[fingerprint -> [hits, body blob]]
        # (see HOT_PER_SHARD); bodies are the canonical fingerprint blobs, a
        # few hundred bytes each, bounded — not breaker-accounted
        self._hot: dict[tuple, "OrderedDict[str, list]"] = {}
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        self.stores = 0
        self.evictions = 0
        self.invalidations = 0
        self.rejections = 0  # stores skipped on breaker trip / oversize
        self.compressions = 0  # lifetime compressed stores
        # live gauges over the CURRENT compressed entries (drop-adjusted):
        # stored compressed bytes vs what those entries would occupy raw
        self._comp_bytes = 0
        self._comp_raw_bytes = 0

    # -- lookup --------------------------------------------------------------
    def get(self, key: tuple) -> bytes | None:
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            # hot-key accounting: the fingerprint's hit count drives the
            # warmer's top-N replay on the next refresh
            hot = self._hot.get(key[:2])
            if hot is not None:
                h = hot.get(key[3])
                if h is not None:
                    h[0] += 1
                    hot.move_to_end(key[3])
            blob, _charged, raw_len = entry
        # inflate OUTSIDE the leaf lock: a hot 100 KiB partial must not
        # serialize every other cache access behind its decompress
        return zlib.decompress(blob) if raw_len else blob

    def peek(self, key: tuple) -> bool:
        """Presence check WITHOUT hit/miss accounting or LRU touch — the
        profiled path records what would have happened without perturbing
        the stats the unprofiled traffic builds."""
        with self._lock:
            return key in self._entries

    # -- store ---------------------------------------------------------------
    def put(self, key: tuple, data: bytes, body: dict | None = None) -> bool:
        """Store one serialized partial. Charges the request breaker BEFORE
        insertion (estimate-before-allocate); a trip or an oversized value
        skips caching and counts a rejection. Returns True when stored.

        `body` (the normalized request dict, passed by the live query phase
        but NOT by the warmer's re-prime) registers the fingerprint in the
        shard's hot-key memory so future hits can be counted and the body
        replayed after a refresh."""
        if body is not None:
            blob = canonical_body(body)
            with self._lock:
                hot = self._hot.setdefault(key[:2], OrderedDict())
                h = hot.get(key[3])
                if h is None:
                    hot[key[3]] = [0, blob]
                    while len(hot) > self.HOT_PER_SHARD:
                        hot.popitem(last=False)
                else:
                    h[1] = blob
                    hot.move_to_end(key[3])
        # deflate above the floor (outside the lock — CPU work), keep raw when
        # zlib loses (already-compact partials): the breaker and the LRU
        # budget are charged what is actually RESIDENT
        blob, raw_len = data, 0
        if 0 <= self.compress_min_bytes <= len(data):
            packed = zlib.compress(data, 1)  # level 1: ~90% of the win, ~5x faster
            if len(packed) < len(data):
                blob, raw_len = packed, len(data)
        size = len(blob) + self.ENTRY_OVERHEAD
        if size > self.size_bytes:
            self.rejections += 1
            return False
        if self.breaker is not None:
            try:
                self.breaker.add_estimate_and_maybe_break(
                    size, "<request_cache>")
            except CircuitBreakingError:
                self.rejections += 1
                return False
        released = 0
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
                released += old[1]
                self._drop_comp_locked(old)
            self._entries[key] = (blob, size, raw_len)
            self._by_shard.setdefault(key[:2], set()).add(key)
            self._bytes += size
            if raw_len:
                self.compressions += 1
                self._comp_bytes += len(blob)
                self._comp_raw_bytes += raw_len
            while self._bytes > self.size_bytes and len(self._entries) > 1:
                k, dropped_entry = self._entries.popitem(last=False)
                self._drop_index_locked(k)
                self._bytes -= dropped_entry[1]
                released += dropped_entry[1]
                self._drop_comp_locked(dropped_entry)
                self.evictions += 1
            self.stores += 1
        if released and self.breaker is not None:
            self.breaker.release(released)  # outside the leaf lock
        return True

    # -- warmer hot keys -----------------------------------------------------
    def has_hot(self, index: str, shard_id: int) -> bool:
        """Whether this shard has any HIT-bearing hot entry — the cheap
        pre-check the warmer listener makes (under the engine lock) before
        scheduling a re-prime task at all."""
        with self._lock:
            hot = self._hot.get((index, shard_id))
            return hot is not None and any(h[0] > 0 for h in hot.values())

    def hot_bodies(self, index: str, shard_id: int, n: int = 8) -> list[dict]:
        """The shard's top-`n` cached request bodies by hit count (hits > 0
        only — a body stored once and never re-seen is not worth a warm
        execution), decoded from their canonical blobs. The warmer replays
        these against a freshly installed view."""
        with self._lock:
            hot = self._hot.get((index, shard_id))
            if not hot:
                return []
            ranked = sorted((h for h in hot.values() if h[0] > 0),
                            key=lambda h: -h[0])[:max(0, n)]
            blobs = [h[1] for h in ranked]
        out = []
        for blob in blobs:
            try:
                out.append(json.loads(blob.decode("utf-8")))
            except (ValueError, UnicodeDecodeError):  # pragma: no cover
                continue
        return out

    def _drop_comp_locked(self, entry: tuple) -> None:
        """Keep the compressed-bytes gauges honest when an entry leaves."""
        if entry[2]:
            self._comp_bytes -= len(entry[0])
            self._comp_raw_bytes -= entry[2]

    # -- invalidation --------------------------------------------------------
    def _drop_index_locked(self, key: tuple):
        keys = self._by_shard.get(key[:2])
        if keys is not None:
            keys.discard(key)
            if not keys:
                del self._by_shard[key[:2]]

    def invalidate_shard(self, index: str, shard_id: int,
                         current_view: int | None) -> int:
        """Drop every entry of (index, shard) whose view is not
        `current_view` (None = drop all, the shard is going away). Called by
        the engine's view listeners on every searcher install (UNDER the
        engine lock) and by shard removal — the per-shard key index keeps
        this proportional to the shard's own entries, never a scan of the
        node-wide LRU while holding the serving leaf lock. Returns the
        number of entries dropped."""
        released = 0
        dropped = 0
        with self._lock:
            if current_view is None:
                # the shard is leaving this node: its hot-key memory goes
                # too (view advances keep it — that drives the warmer)
                self._hot.pop((index, shard_id), None)
            shard_keys = self._by_shard.get((index, shard_id))
            for k in [k for k in (shard_keys or ())
                      if current_view is None or k[2] != current_view]:
                entry = self._entries.pop(k)
                self._drop_index_locked(k)
                self._bytes -= entry[1]
                released += entry[1]
                self._drop_comp_locked(entry)
                dropped += 1
            self.invalidations += dropped
        if released and self.breaker is not None:
            self.breaker.release(released)
        return dropped

    def clear(self, index: str | None = None) -> int:
        """`POST /_cache/clear?request=true`: drop all entries (or one
        index's); the breaker drains by exactly the released bytes."""
        released = 0
        dropped = 0
        with self._lock:
            keys = [k for k in self._entries
                    if index is None or k[0] == index]
            for k in keys:
                entry = self._entries.pop(k)
                self._drop_index_locked(k)
                self._bytes -= entry[1]
                released += entry[1]
                self._drop_comp_locked(entry)
                dropped += 1
        if released and self.breaker is not None:
            self.breaker.release(released)
        return dropped

    # -- observability -------------------------------------------------------
    def hit_rate(self) -> float:
        """Lifetime hit rate from plain attribute reads (the load-signal
        piggyback reads this unlocked on the serving path)."""
        n = self.hits + self.misses
        return (self.hits / n) if n else 0.0

    def stats(self) -> dict:
        with self._lock:
            return {
                "enabled": self.enabled,
                "memory_size_in_bytes": self._bytes,
                "limit_size_in_bytes": self.size_bytes,
                "entries": len(self._entries),
                "hits": self.hits,
                "misses": self.misses,
                "stores": self.stores,
                "evictions": self.evictions,
                "invalidations": self.invalidations,
                "rejections": self.rejections,
                "hit_rate": round(self.hit_rate(), 4),
                "compressions": self.compressions,
                # resident compressed footprint vs its inflated size; ratio
                # 1.0 = nothing currently compressed
                "compressed_bytes": self._comp_bytes,
                "compressed_raw_bytes": self._comp_raw_bytes,
                "compression_ratio": (
                    round(self._comp_bytes / self._comp_raw_bytes, 4)
                    if self._comp_raw_bytes else 1.0),
            }
