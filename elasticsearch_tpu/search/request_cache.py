"""Shard request cache: fingerprint of the normalized request + point-in-time
view → serialized partial shard response.

Analogue of the reference's shard request cache (indices/cache/query/
IndicesQueryCache hung off the layer-1 recycled/paged-array + breaker
substrate): at millions-of-users scale the hottest queries repeat, and the
cheapest device launch is the one never dispatched. A hit in
`actions._s_query_phase` returns the stored partial BEFORE
`execute_query_phase` runs — zero device launches, zero device syncs, zero
kernel work; only the fetch phase (hydrating the global winners) still runs.

Semantics (reference parity):

- **Key** = (index, shard, searcher view version, fingerprint). The view
  version advances whenever the engine installs a new point-in-time Searcher
  (refresh with changes / merge / optimize / recovery), so a cached partial
  can never outlive the segment view it was computed against — the NRT
  invariant "search results cannot change without a refresh" is exactly what
  makes view-keyed caching sound. The fingerprint is a stable
  re-serialization of the normalized request body (sorted keys, volatile
  knobs stripped), covering query/filter/from/size/sort/aggs — the (k, from,
  agg signature) of the partial.
- **Default scope**: only `size == 0` requests (counts, agg-only dashboards)
  are cached unless the request opts in with `?request_cache=true`;
  `?request_cache=false` opts out entirely; `indices.requests.cache.enable`
  kills the tier node-wide. This is the reference's rule — hit-bearing pages
  are personal, count/agg rollups are shared.
- **Value** = the partial shard response serialized through the binary wire
  codec (common/stream.py) — the same bytes that would cross the transport,
  so breaker accounting is honest and a hit hands back an isolated copy (no
  shared mutable state between requests).
- **Accounting**: every stored entry's bytes are charged on the node's
  `request` breaker and held until the entry is evicted/invalidated/cleared
  — `POST /_cache/clear?request=true` drains the tier back to 0. A breaker
  trip at store time skips caching (counted), never fails the search.
- **Bounds**: LRU over `indices.requests.cache.size` (ratio of the breaker
  budget or absolute bytes; default 1%).
- **Invalidation**: the engine's view listeners call `invalidate_shard` on
  every searcher install, dropping entries from superseded views eagerly
  (the view component of the key already makes them unreachable — eager
  invalidation is what returns their bytes).

Lock discipline (PR 6): `_lock` is a LEAF — only dict/counter mutation
happens under it; serialization happens before `put` is called, breaker
release happens after the lock is dropped, and nothing under the lock ever
blocks or dispatches device work.
"""

from __future__ import annotations

import hashlib
import json
import threading
from collections import OrderedDict

from ..common.errors import CircuitBreakingError
from ..common.units import parse_ratio_or_bytes

# request-body keys that must not change the cache identity: execution knobs
# (profiling, tracing, the cache flag itself, the time budget) select HOW a
# request runs, not WHAT it computes
_VOLATILE_KEYS = ("profile", "request_cache", "timeout")


def request_fingerprint(body: dict | None) -> str:
    """Stable fingerprint of a normalized search body: canonical JSON
    re-serialization (sorted keys, compact separators) of the body minus
    volatile execution knobs, hashed. Two dicts that differ only in key
    order — or in profile/timeout/request_cache flags — fingerprint
    identically; any semantic difference (query, filter, from/size, sort,
    aggs, suggest) changes it."""
    core = {k: v for k, v in (body or {}).items() if k not in _VOLATILE_KEYS}
    blob = json.dumps(core, sort_keys=True, separators=(",", ":"),
                      default=str)
    return hashlib.blake2b(blob.encode("utf-8"), digest_size=16).hexdigest()


def cache_policy(body: dict | None) -> bool:
    """Whether a request body is request-cache ELIGIBLE (the reference's
    rule): explicit `request_cache: true` always, explicit false never,
    otherwise only size == 0 requests (counts / agg-only). The ONE policy
    shared by the shard serving path and the coordinator's cache-affinity
    routing — drift between them would route for a cache the shard never
    consults."""
    body = body or {}
    explicit = body.get("request_cache")
    if explicit is not None:
        return bool(explicit)
    try:
        return int(body.get("size", 10) or 0) == 0
    except (TypeError, ValueError):
        return False


class ShardRequestCache:
    """Node-level LRU of serialized partial shard responses.

    Thread-safe; `_lock` is a leaf (see module docstring). Counter attributes
    are plain ints read unlocked by the load-signal piggyback — exact enough
    for a decayed routing signal, and the serving path gains no locks."""

    # per-entry bookkeeping overhead charged beyond the value bytes (key
    # tuple, OrderedDict node, breaker slack)
    ENTRY_OVERHEAD = 256

    def __init__(self, settings=None, breaker=None,
                 total_budget: int = 8 << 30):
        from ..common.settings import Settings

        settings = settings or Settings.EMPTY
        self.enabled = bool(
            settings.get_bool("indices.requests.cache.enable", True))
        self.size_bytes = int(parse_ratio_or_bytes(
            settings.get("indices.requests.cache.size"), int(total_budget),
            default="1%"))
        self.breaker = breaker
        self._lock = threading.Lock()
        # key -> (data bytes, charged size); OrderedDict insertion order IS
        # the LRU order (move_to_end on hit)
        self._entries: "OrderedDict[tuple, tuple[bytes, int]]" = OrderedDict()
        # secondary index (index, shard) -> {keys}: invalidation runs on
        # EVERY searcher install of every shard, under the engine lock — it
        # must touch only that shard's entries, not scan the node-wide LRU
        # (150k+ entries at default sizing) while holding the serving lock
        self._by_shard: dict[tuple, set] = {}
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        self.stores = 0
        self.evictions = 0
        self.invalidations = 0
        self.rejections = 0  # stores skipped on breaker trip / oversize

    # -- lookup --------------------------------------------------------------
    def get(self, key: tuple) -> bytes | None:
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            return entry[0]

    def peek(self, key: tuple) -> bool:
        """Presence check WITHOUT hit/miss accounting or LRU touch — the
        profiled path records what would have happened without perturbing
        the stats the unprofiled traffic builds."""
        with self._lock:
            return key in self._entries

    # -- store ---------------------------------------------------------------
    def put(self, key: tuple, data: bytes) -> bool:
        """Store one serialized partial. Charges the request breaker BEFORE
        insertion (estimate-before-allocate); a trip or an oversized value
        skips caching and counts a rejection. Returns True when stored."""
        size = len(data) + self.ENTRY_OVERHEAD
        if size > self.size_bytes:
            self.rejections += 1
            return False
        if self.breaker is not None:
            try:
                self.breaker.add_estimate_and_maybe_break(
                    size, "<request_cache>")
            except CircuitBreakingError:
                self.rejections += 1
                return False
        released = 0
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
                released += old[1]
            self._entries[key] = (data, size)
            self._by_shard.setdefault(key[:2], set()).add(key)
            self._bytes += size
            while self._bytes > self.size_bytes and len(self._entries) > 1:
                k, (_d, sz) = self._entries.popitem(last=False)
                self._drop_index_locked(k)
                self._bytes -= sz
                released += sz
                self.evictions += 1
            self.stores += 1
        if released and self.breaker is not None:
            self.breaker.release(released)  # outside the leaf lock
        return True

    # -- invalidation --------------------------------------------------------
    def _drop_index_locked(self, key: tuple):
        keys = self._by_shard.get(key[:2])
        if keys is not None:
            keys.discard(key)
            if not keys:
                del self._by_shard[key[:2]]

    def invalidate_shard(self, index: str, shard_id: int,
                         current_view: int | None) -> int:
        """Drop every entry of (index, shard) whose view is not
        `current_view` (None = drop all, the shard is going away). Called by
        the engine's view listeners on every searcher install (UNDER the
        engine lock) and by shard removal — the per-shard key index keeps
        this proportional to the shard's own entries, never a scan of the
        node-wide LRU while holding the serving leaf lock. Returns the
        number of entries dropped."""
        released = 0
        dropped = 0
        with self._lock:
            shard_keys = self._by_shard.get((index, shard_id))
            for k in [k for k in (shard_keys or ())
                      if current_view is None or k[2] != current_view]:
                _d, sz = self._entries.pop(k)
                self._drop_index_locked(k)
                self._bytes -= sz
                released += sz
                dropped += 1
            self.invalidations += dropped
        if released and self.breaker is not None:
            self.breaker.release(released)
        return dropped

    def clear(self, index: str | None = None) -> int:
        """`POST /_cache/clear?request=true`: drop all entries (or one
        index's); the breaker drains by exactly the released bytes."""
        released = 0
        dropped = 0
        with self._lock:
            keys = [k for k in self._entries
                    if index is None or k[0] == index]
            for k in keys:
                _d, sz = self._entries.pop(k)
                self._drop_index_locked(k)
                self._bytes -= sz
                released += sz
                dropped += 1
        if released and self.breaker is not None:
            self.breaker.release(released)
        return dropped

    # -- observability -------------------------------------------------------
    def hit_rate(self) -> float:
        """Lifetime hit rate from plain attribute reads (the load-signal
        piggyback reads this unlocked on the serving path)."""
        n = self.hits + self.misses
        return (self.hits / n) if n else 0.0

    def stats(self) -> dict:
        with self._lock:
            return {
                "enabled": self.enabled,
                "memory_size_in_bytes": self._bytes,
                "limit_size_in_bytes": self.size_bytes,
                "entries": len(self._entries),
                "hits": self.hits,
                "misses": self.misses,
                "stores": self.stores,
                "evictions": self.evictions,
                "invalidations": self.invalidations,
                "rejections": self.rejections,
                "hit_rate": round(self.hit_rate(), 4),
            }
