"""Query DSL: JSON → query tree.

Analogue of the reference's 38 query parsers + registry (index/query/*QueryParser.java,
IndexQueryParserService — SURVEY.md §2.3). Queries are data; planning/execution lives in
search/execute.py so the same tree drives the device kernel, the host fallback scorer,
and filters (via QueryWrapperFilter).

Supported (parity-relevant subset, grown over rounds): match, multi_match, match_all,
term, terms, bool, filtered, constant_score, dis_max, range, prefix, wildcard, regexp,
fuzzy, ids, phrase (match_phrase / match_phrase_prefix), query_string (subset),
common (common_terms), function_score, nested, has_child/has_parent (via join),
more_like_this, boosting, span_term/span_near (host), geo wrappers, indices, type.
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from typing import Any

from ..common.errors import QueryParsingError
from .filters import (
    BoolFilter,
    ExistsFilter,
    Filter,
    GeoBoundingBoxFilter,
    GeoDistanceFilter,
    GeoDistanceRangeFilter,
    GeohashCellFilter,
    GeoPolygonFilter,
    GeoShapeFilter,
    HasChildFilter,
    IdsFilter,
    IndicesFilter,
    MatchAllFilter,
    MissingFilter,
    NestedFilter,
    NotFilter,
    PrefixFilter,
    QueryWrapperFilter,
    RangeFilter,
    RegexpFilter,
    ScriptFilter,
    TermFilter,
    TermsFilter,
    TypeFilter,
    parse_distance,
)


class Query:
    boost: float = 1.0


@dataclass
class MatchAllQuery(Query):
    boost: float = 1.0


@dataclass
class TermQuery(Query):
    field: str
    value: Any
    boost: float = 1.0


@dataclass
class MatchQuery(Query):
    field: str
    text: str
    operator: str = "or"  # or | and
    minimum_should_match: Any = None
    analyzer: str | None = None
    boost: float = 1.0
    type: str = "boolean"  # boolean | phrase | phrase_prefix
    slop: int = 0
    fuzziness: Any = None
    max_expansions: int = 50
    lenient: bool = False


@dataclass
class MultiMatchQuery(Query):
    fields: list  # ["title^2", "body"]
    text: str
    operator: str = "or"
    minimum_should_match: Any = None
    type: str = "best_fields"
    tie_breaker: float = 0.0
    analyzer: str | None = None
    boost: float = 1.0


@dataclass
class BoolQuery(Query):
    must: list = dc_field(default_factory=list)
    should: list = dc_field(default_factory=list)
    must_not: list = dc_field(default_factory=list)
    filter: list = dc_field(default_factory=list)
    minimum_should_match: Any = None
    disable_coord: bool = False
    boost: float = 1.0


@dataclass
class FilteredQuery(Query):
    query: Query
    filter: Filter
    boost: float = 1.0


@dataclass
class ConstantScoreQuery(Query):
    filter: Filter | None = None
    query: Query | None = None
    boost: float = 1.0


@dataclass
class DisMaxQuery(Query):
    queries: list = dc_field(default_factory=list)
    tie_breaker: float = 0.0
    boost: float = 1.0


@dataclass
class RangeQuery(Query):
    field: str
    gte: Any = None
    gt: Any = None
    lte: Any = None
    lt: Any = None
    boost: float = 1.0


@dataclass
class PrefixQuery(Query):
    field: str
    prefix: str
    boost: float = 1.0
    rewrite: str | None = None


@dataclass
class WildcardQuery(Query):
    field: str
    pattern: str
    boost: float = 1.0


@dataclass
class RegexpQuery(Query):
    field: str
    pattern: str
    boost: float = 1.0


@dataclass
class FuzzyQuery(Query):
    field: str
    value: str
    fuzziness: Any = "AUTO"
    prefix_length: int = 0
    max_expansions: int = 50
    boost: float = 1.0


@dataclass
class IdsQuery(Query):
    ids: list = dc_field(default_factory=list)
    types: list = dc_field(default_factory=list)
    boost: float = 1.0


@dataclass
class PhraseQuery(Query):
    field: str
    text: str
    slop: int = 0
    analyzer: str | None = None
    boost: float = 1.0
    prefix: bool = False  # phrase_prefix
    max_expansions: int = 50


@dataclass
class QueryStringQuery(Query):
    query: str
    default_field: str = "_all"
    default_operator: str = "or"
    fields: list = dc_field(default_factory=list)
    analyzer: str | None = None
    boost: float = 1.0


@dataclass
class CommonTermsQuery(Query):
    field: str
    text: str
    cutoff_frequency: float = 0.01
    low_freq_operator: str = "or"
    high_freq_operator: str = "or"
    minimum_should_match: Any = None
    analyzer: str | None = None
    boost: float = 1.0


@dataclass
class ScoreFunction:
    kind: str  # script_score | boost_factor | random_score | gauss | exp | linear | field_value_factor
    filter: Filter | None = None
    # decay params
    field: str | None = None
    origin: Any = None
    scale: Any = None
    offset: Any = 0
    decay: float = 0.5
    # others
    script: str | None = None
    params: dict = dc_field(default_factory=dict)
    factor: float = 1.0
    modifier: str = "none"
    missing: float | None = None
    seed: int | None = None
    weight: float | None = None


@dataclass
class FunctionScoreQuery(Query):
    query: Query | None = None
    filter: Filter | None = None
    functions: list = dc_field(default_factory=list)  # list[ScoreFunction]
    score_mode: str = "multiply"  # multiply sum avg first max min
    boost_mode: str = "multiply"  # multiply replace sum avg max min
    max_boost: float = float("inf")
    min_score: float | None = None
    boost: float = 1.0


@dataclass
class NestedQuery(Query):
    path: str
    query: Query
    score_mode: str = "avg"  # avg | sum | max | total | none
    boost: float = 1.0


@dataclass
class HasChildQuery(Query):
    child_type: str
    query: Query
    score_mode: str = "none"
    boost: float = 1.0


@dataclass
class HasParentQuery(Query):
    parent_type: str
    query: Query
    score_mode: str = "none"
    boost: float = 1.0


@dataclass
class BoostingQuery(Query):
    positive: Query
    negative: Query
    negative_boost: float = 0.2
    boost: float = 1.0


@dataclass
class MoreLikeThisQuery(Query):
    fields: list
    like_text: str
    min_term_freq: int = 2
    min_doc_freq: int = 5
    max_query_terms: int = 25
    minimum_should_match: Any = "30%"
    boost: float = 1.0


@dataclass
class SpanTermQuery(Query):
    field: str
    value: str
    boost: float = 1.0


@dataclass
class SpanNearQuery(Query):
    clauses: list
    slop: int = 0
    in_order: bool = True
    boost: float = 1.0


@dataclass
class SpanOrQuery(Query):
    """ref: SpanOrQueryParser.java:1 — union of clause spans."""

    clauses: list
    boost: float = 1.0


@dataclass
class SpanFirstQuery(Query):
    """ref: SpanFirstQueryParser.java:1 — match spans ending within [0, end)."""

    match: Query = None
    end: int = 0
    boost: float = 1.0


@dataclass
class SpanNotQuery(Query):
    """ref: SpanNotQueryParser.java:1 — include spans not overlapping exclude."""

    include: Query = None
    exclude: Query = None
    boost: float = 1.0


@dataclass
class SpanMultiTermQuery(Query):
    """ref: SpanMultiTermQueryParser.java:1 — a multi-term query (prefix/wildcard/
    fuzzy/regexp) as a span: union of the expanded terms' position spans."""

    match: Query = None
    boost: float = 1.0


@dataclass
class FieldMaskingSpanQuery(Query):
    """ref: FieldMaskingSpanQueryParser.java:1 — inner spans reported under another
    field name, so span_near can compose across fields indexed in lockstep."""

    query: Query = None
    field: str = ""
    boost: float = 1.0


@dataclass
class IndicesQuery(Query):
    indices: list
    query: Query = None
    no_match_query: Query | None = None  # None = match_all (the reference default)
    boost: float = 1.0
    no_match_none: bool = False  # "no_match_query": "none"


@dataclass
class SimpleQueryStringQuery(Query):
    """ref: index/query/SimpleQueryStringParser.java:1 — the degraded-gracefully
    query syntax (+ | - "phrase" prefix*); resolved against the analyzer at
    execution time like QueryStringQuery (execute.parse_simple_query_string)."""

    query: str = ""
    fields: list = dc_field(default_factory=list)  # empty = _all
    default_operator: str = "or"
    analyzer: str | None = None
    boost: float = 1.0


@dataclass
class FuzzyLikeThisQuery(Query):
    """ref: index/query/FuzzyLikeThisQueryParser.java:1 (+ the _field variant) —
    like_text analyzed, each term expanded to its fuzzy index-term neighborhood,
    OR-combined. Rewritten in HostScorer._rewrite_flt."""

    fields: list = dc_field(default_factory=list)  # empty = _all
    like_text: str = ""
    fuzziness: Any = 0.5  # min_similarity legacy float or edit distance
    prefix_length: int = 0
    max_query_terms: int = 25
    ignore_tf: bool = False
    analyzer: str | None = None
    boost: float = 1.0


# ---------------------------------------------------------------------------
# parsing
# ---------------------------------------------------------------------------


def parse_query(body: Any) -> Query:
    """Parse a query DSL dict (the object under "query")."""
    if body is None:
        return MatchAllQuery()
    if not isinstance(body, dict) or len(body) != 1:
        if isinstance(body, dict) and len(body) == 0:
            return MatchAllQuery()
        raise QueryParsingError(f"expected single-key query object, got {body!r}")
    kind, spec = next(iter(body.items()))
    parser = _QUERY_PARSERS.get(kind)
    if parser is None:
        raise QueryParsingError(f"unknown query type [{kind}]")
    return parser(spec)


def parse_filter(body: Any) -> Filter:
    if body is None:
        return MatchAllFilter()
    if not isinstance(body, dict) or len(body) != 1:
        if isinstance(body, dict) and len(body) == 0:
            return MatchAllFilter()
        raise QueryParsingError(f"expected single-key filter object, got {body!r}")
    kind, spec = next(iter(body.items()))
    parser = _FILTER_PARSERS.get(kind)
    if parser is None:
        raise QueryParsingError(f"unknown filter type [{kind}]")
    return parser(spec)


def _field_spec(spec: dict, value_key: str) -> tuple[str, dict]:
    """`{"field": "value"}` or `{"field": {value_key: ..., "boost": ...}}`."""
    if len(spec) != 1:
        # allow extra top-level options like boost alongside the field
        fields = [k for k in spec if k not in ("boost", "_name")]
        if len(fields) != 1:
            raise QueryParsingError(f"expected one field, got {list(spec)}")
        fname = fields[0]
        opts = {"boost": spec.get("boost", 1.0)}
        v = spec[fname]
        if isinstance(v, dict):
            opts.update(v)
        else:
            opts[value_key] = v
        return fname, opts
    fname, v = next(iter(spec.items()))
    if isinstance(v, dict):
        return fname, dict(v)
    return fname, {value_key: v}


def _parse_match(spec) -> Query:
    fname, opts = _field_spec(spec, "query")
    mtype = opts.get("type", "boolean")
    if mtype in ("phrase", "phrase_prefix"):
        return PhraseQuery(
            field=fname, text=str(opts.get("query", "")), slop=int(opts.get("slop", 0)),
            analyzer=opts.get("analyzer"), boost=float(opts.get("boost", 1.0)),
            prefix=(mtype == "phrase_prefix"),
            max_expansions=int(opts.get("max_expansions", 50)),
        )
    return MatchQuery(
        field=fname, text=str(opts.get("query", "")),
        operator=str(opts.get("operator", "or")).lower(),
        minimum_should_match=opts.get("minimum_should_match"),
        analyzer=opts.get("analyzer"), boost=float(opts.get("boost", 1.0)),
        fuzziness=opts.get("fuzziness"),
        max_expansions=int(opts.get("max_expansions", 50)),
        lenient=bool(opts.get("lenient", False)),
    )


def _parse_match_phrase(spec) -> Query:
    fname, opts = _field_spec(spec, "query")
    return PhraseQuery(field=fname, text=str(opts.get("query", "")),
                       slop=int(opts.get("slop", 0)), analyzer=opts.get("analyzer"),
                       boost=float(opts.get("boost", 1.0)))


def _parse_match_phrase_prefix(spec) -> Query:
    fname, opts = _field_spec(spec, "query")
    return PhraseQuery(field=fname, text=str(opts.get("query", "")),
                       slop=int(opts.get("slop", 0)), analyzer=opts.get("analyzer"),
                       boost=float(opts.get("boost", 1.0)), prefix=True,
                       max_expansions=int(opts.get("max_expansions", 50)))


def _parse_multi_match(spec) -> Query:
    return MultiMatchQuery(
        fields=list(spec.get("fields", [])), text=str(spec.get("query", "")),
        operator=str(spec.get("operator", "or")).lower(),
        minimum_should_match=spec.get("minimum_should_match"),
        type=spec.get("type", "best_fields"),
        tie_breaker=float(spec.get("tie_breaker", 0.0)),
        analyzer=spec.get("analyzer"), boost=float(spec.get("boost", 1.0)),
    )


def _parse_term(spec) -> Query:
    fname, opts = _field_spec(spec, "value")
    value = opts.get("value", opts.get("term"))
    return TermQuery(field=fname, value=value, boost=float(opts.get("boost", 1.0)))


def _parse_terms(spec) -> Query:
    spec = dict(spec)
    msm = spec.pop("minimum_should_match", spec.pop("minimum_match", None))
    boost = float(spec.pop("boost", 1.0))
    spec.pop("disable_coord", None)
    if len(spec) != 1:
        raise QueryParsingError("terms query requires exactly one field")
    fname, values = next(iter(spec.items()))
    q = BoolQuery(should=[TermQuery(fname, v) for v in values],
                  minimum_should_match=msm, boost=boost)
    return q


def _parse_bool(spec) -> Query:
    def as_list(v):
        if v is None:
            return []
        return v if isinstance(v, list) else [v]

    return BoolQuery(
        must=[parse_query(q) for q in as_list(spec.get("must"))],
        should=[parse_query(q) for q in as_list(spec.get("should"))],
        must_not=[parse_query(q) for q in as_list(spec.get("must_not"))],
        filter=[parse_filter(f) for f in as_list(spec.get("filter"))],
        minimum_should_match=spec.get("minimum_should_match", spec.get("minimum_number_should_match")),
        disable_coord=bool(spec.get("disable_coord", False)),
        boost=float(spec.get("boost", 1.0)),
    )


def _parse_filtered(spec) -> Query:
    return FilteredQuery(
        query=parse_query(spec.get("query")),
        filter=parse_filter(spec.get("filter")),
        boost=float(spec.get("boost", 1.0)),
    )


def _parse_constant_score(spec) -> Query:
    return ConstantScoreQuery(
        filter=parse_filter(spec["filter"]) if "filter" in spec else None,
        query=parse_query(spec["query"]) if "query" in spec else None,
        boost=float(spec.get("boost", 1.0)),
    )


def _parse_dis_max(spec) -> Query:
    return DisMaxQuery(
        queries=[parse_query(q) for q in spec.get("queries", [])],
        tie_breaker=float(spec.get("tie_breaker", 0.0)),
        boost=float(spec.get("boost", 1.0)),
    )


def _parse_range_q(spec) -> Query:
    fname, opts = _field_spec(spec, "value")
    conv = {"from": "gte", "to": "lte"}
    kw = {}
    for k in ("gte", "gt", "lte", "lt", "from", "to"):
        if k in opts:
            kw[conv.get(k, k)] = opts[k]
    if "include_lower" in opts and not opts["include_lower"] and "gte" in kw:
        kw["gt"] = kw.pop("gte")
    if "include_upper" in opts and not opts["include_upper"] and "lte" in kw:
        kw["lt"] = kw.pop("lte")
    return RangeQuery(field=fname, boost=float(opts.get("boost", 1.0)), **kw)


def _parse_function_score(spec) -> Query:
    functions = []
    for fspec in spec.get("functions", [spec] if any(
        k in spec for k in ("script_score", "boost_factor", "random_score", "gauss",
                            "exp", "linear", "field_value_factor")
    ) else []):
        functions.append(_parse_score_function(fspec))
    return FunctionScoreQuery(
        query=parse_query(spec["query"]) if "query" in spec else None,
        filter=parse_filter(spec["filter"]) if "filter" in spec else None,
        functions=functions,
        score_mode=spec.get("score_mode", "multiply"),
        boost_mode=spec.get("boost_mode", "multiply"),
        max_boost=float(spec.get("max_boost", float("inf"))),
        min_score=spec.get("min_score"),
        boost=float(spec.get("boost", 1.0)),
    )


def _parse_score_function(fspec: dict) -> ScoreFunction:
    filt = parse_filter(fspec["filter"]) if "filter" in fspec else None
    weight = fspec.get("weight")
    if "script_score" in fspec:
        ss = fspec["script_score"]
        return ScoreFunction("script_score", filt, script=ss.get("script"),
                             params=ss.get("params", {}), weight=weight)
    if "boost_factor" in fspec:
        return ScoreFunction("boost_factor", filt, factor=float(fspec["boost_factor"]),
                             weight=weight)
    if "random_score" in fspec:
        return ScoreFunction("random_score", filt,
                             seed=fspec["random_score"].get("seed"), weight=weight)
    if "field_value_factor" in fspec:
        fv = fspec["field_value_factor"]
        return ScoreFunction("field_value_factor", filt, field=fv.get("field"),
                             factor=float(fv.get("factor", 1.0)),
                             modifier=fv.get("modifier", "none"),
                             missing=fv.get("missing"), weight=weight)
    for decay in ("gauss", "exp", "linear"):
        if decay in fspec:
            dspec = fspec[decay]
            (fname, params), = dspec.items()
            return ScoreFunction(
                decay, filt, field=fname, origin=params.get("origin"),
                scale=params.get("scale"), offset=params.get("offset", 0),
                decay=float(params.get("decay", 0.5)), weight=weight,
            )
    if weight is not None:
        return ScoreFunction("boost_factor", filt, factor=float(weight))
    raise QueryParsingError(f"unknown score function {list(fspec)}")


def _parse_nested_q(spec) -> Query:
    # a nested "filter" spec must go through the FILTER parser (filter-only constructs
    # like missing/exists aren't queries; names that collide, like term, have different
    # semantics) — child_match_to_parents accepts either a Query or a Filter
    inner = (parse_query(spec["query"]) if "query" in spec
             else parse_filter(spec.get("filter")))
    return NestedQuery(
        path=spec["path"], query=inner,
        score_mode=spec.get("score_mode", "avg"), boost=float(spec.get("boost", 1.0)),
    )


def _parse_query_string(spec) -> Query:
    if isinstance(spec, str):
        spec = {"query": spec}
    return QueryStringQuery(
        query=spec.get("query", "*"),
        default_field=spec.get("default_field", "_all"),
        default_operator=str(spec.get("default_operator", "or")).lower(),
        fields=list(spec.get("fields", [])),
        analyzer=spec.get("analyzer"),
        boost=float(spec.get("boost", 1.0)),
    )


def _parse_simple_query_string(spec) -> Query:
    if isinstance(spec, str):
        spec = {"query": spec}
    return SimpleQueryStringQuery(
        query=str(spec.get("query", "")),
        fields=list(spec.get("fields", [])),
        default_operator=str(spec.get("default_operator", "or")).lower(),
        analyzer=spec.get("analyzer"),
        boost=float(spec.get("boost", 1.0)),
    )


def _parse_flt(spec) -> Query:
    return FuzzyLikeThisQuery(
        fields=list(spec.get("fields", [])),
        like_text=str(spec.get("like_text", "")),
        fuzziness=spec.get("fuzziness", spec.get("min_similarity", 0.5)),
        prefix_length=int(spec.get("prefix_length", 0)),
        max_query_terms=int(spec.get("max_query_terms", 25)),
        ignore_tf=bool(spec.get("ignore_tf", False)),
        analyzer=spec.get("analyzer"),
        boost=float(spec.get("boost", 1.0)),
    )


def _parse_flt_field(spec) -> Query:
    """{field: {like_text: ...}} — ref: FuzzyLikeThisFieldQueryParser.java:1."""
    (fname, opts), = spec.items()
    return _parse_flt({**(opts if isinstance(opts, dict) else {"like_text": opts}),
                       "fields": [fname]})


def _parse_mlt_field(spec) -> Query:
    """{field: {like_text: ...}} — ref: MoreLikeThisFieldQueryParser.java:1."""
    (fname, opts), = spec.items()
    if not isinstance(opts, dict):
        opts = {"like_text": opts}
    return _QUERY_PARSERS["more_like_this"]({**opts, "fields": [fname]})


def _unwrap_wrapper(spec) -> Any:
    """ref: WrapperQueryParser.java:1 — {"query": <base64 JSON or raw JSON str>}."""
    import base64
    import json as _json

    raw = spec.get("query") if isinstance(spec, dict) else spec
    if isinstance(raw, (dict, list)):
        return raw
    s = str(raw)
    try:
        s = base64.b64decode(s, validate=True).decode("utf-8")
    except Exception:  # noqa: BLE001 — not base64: treat as raw JSON
        pass
    try:
        return _json.loads(s)
    except ValueError as e:
        raise QueryParsingError(f"wrapper: malformed embedded query: {e}")


def _parse_indices_common(spec, parse_inner, none_obj):
    """Shared indices query/filter shape (ref: IndicesQueryParser/
    IndicesFilterParser): no_match accepts "all" (default), "none", or a spec."""
    inner = parse_inner(spec.get("query") if "query" in spec else spec.get("filter"))
    nm = spec.get("no_match_query", spec.get("no_match_filter"))
    no_match_none = isinstance(nm, str) and nm.lower() == "none"
    no_match = parse_inner(nm) if isinstance(nm, dict) else None
    return inner, no_match, no_match_none, _as_list(spec.get("indices", spec.get("index")))


def _parse_template(spec) -> Query:
    """Template query (ref: index/query/TemplateQueryParser): mustache-substitute
    `params` into `query` (an object tree or a JSON string), then parse the result."""
    import json as _json

    tpl = spec.get("query")
    params = spec.get("params") or {}

    def subst(s: str) -> str:
        for k, v in params.items():
            s = s.replace("{{%s}}" % k, str(v))
        return s

    if isinstance(tpl, str):
        rendered = _json.loads(subst(tpl))
    else:
        rendered = _json.loads(subst(_json.dumps(tpl)))
    return parse_query(rendered)


_QUERY_PARSERS = {
    "match_all": lambda s: MatchAllQuery(boost=float((s or {}).get("boost", 1.0))),
    "template": _parse_template,
    "match": _parse_match,
    "match_phrase": _parse_match_phrase,
    "match_phrase_prefix": _parse_match_phrase_prefix,
    "multi_match": _parse_multi_match,
    "term": _parse_term,
    "terms": _parse_terms,
    "in": _parse_terms,
    "bool": _parse_bool,
    "filtered": _parse_filtered,
    "constant_score": _parse_constant_score,
    "dis_max": _parse_dis_max,
    "range": _parse_range_q,
    "prefix": lambda s: (lambda f, o: PrefixQuery(f, str(o.get("value", o.get("prefix", ""))),
                                                  float(o.get("boost", 1.0))))(*_field_spec(s, "value")),
    "wildcard": lambda s: (lambda f, o: WildcardQuery(f, str(o.get("value", o.get("wildcard", ""))),
                                                      float(o.get("boost", 1.0))))(*_field_spec(s, "value")),
    "regexp": lambda s: (lambda f, o: RegexpQuery(f, str(o.get("value", "")),
                                                  float(o.get("boost", 1.0))))(*_field_spec(s, "value")),
    "fuzzy": lambda s: (lambda f, o: FuzzyQuery(f, str(o.get("value", "")),
                                                o.get("fuzziness", "AUTO"),
                                                int(o.get("prefix_length", 0)),
                                                int(o.get("max_expansions", 50)),
                                                float(o.get("boost", 1.0))))(*_field_spec(s, "value")),
    "ids": lambda s: IdsQuery(ids=[str(i) for i in s.get("values", [])],
                              types=_as_list(s.get("type", s.get("types"))),
                              boost=float(s.get("boost", 1.0))),
    "query_string": _parse_query_string,
    "field": lambda s: (lambda f, o: QueryStringQuery(str(o.get("query", "")), default_field=f,
                                                      boost=float(o.get("boost", 1.0))))(*_field_spec(s, "query")),
    "common": lambda s: (lambda f, o: CommonTermsQuery(
        f, str(o.get("query", "")), float(o.get("cutoff_frequency", 0.01)),
        str(o.get("low_freq_operator", "or")).lower(),
        str(o.get("high_freq_operator", "or")).lower(),
        o.get("minimum_should_match"), o.get("analyzer"),
        float(o.get("boost", 1.0))))(*_field_spec(s, "query")),
    "function_score": _parse_function_score,
    "nested": _parse_nested_q,
    "has_child": lambda s: HasChildQuery(s.get("type", s.get("child_type")),
                                         parse_query(s.get("query") or s.get("filter")),
                                         s.get("score_mode", s.get("score_type", "none")),
                                         float(s.get("boost", 1.0))),
    "has_parent": lambda s: HasParentQuery(s.get("parent_type", s.get("type")),
                                           parse_query(s.get("query") or s.get("filter")),
                                           s.get("score_mode", s.get("score_type", "none")),
                                           float(s.get("boost", 1.0))),
    "boosting": lambda s: BoostingQuery(parse_query(s["positive"]), parse_query(s["negative"]),
                                        float(s.get("negative_boost", 0.2)),
                                        float(s.get("boost", 1.0))),
    "more_like_this": lambda s: MoreLikeThisQuery(
        fields=list(s.get("fields", ["_all"])), like_text=s.get("like_text", ""),
        min_term_freq=int(s.get("min_term_freq", 2)),
        min_doc_freq=int(s.get("min_doc_freq", 5)),
        max_query_terms=int(s.get("max_query_terms", 25)),
        minimum_should_match=s.get("minimum_should_match", s.get("percent_terms_to_match", "30%")),
        boost=float(s.get("boost", 1.0))),
    "mlt": lambda s: _QUERY_PARSERS["more_like_this"](s),
    "span_term": lambda s: (lambda f, o: SpanTermQuery(f, str(o.get("value", "")),
                                                       float(o.get("boost", 1.0))))(*_field_spec(s, "value")),
    "span_near": lambda s: SpanNearQuery([parse_query(c) for c in s.get("clauses", [])],
                                         int(s.get("slop", 0)), bool(s.get("in_order", True))),
    "span_or": lambda s: SpanOrQuery([parse_query(c) for c in s.get("clauses", [])],
                                     float(s.get("boost", 1.0))),
    "span_first": lambda s: SpanFirstQuery(parse_query(s.get("match")),
                                           int(s.get("end", 0)),
                                           float(s.get("boost", 1.0))),
    "span_not": lambda s: SpanNotQuery(parse_query(s.get("include")),
                                       parse_query(s.get("exclude")),
                                       float(s.get("boost", 1.0))),
    "span_multi": lambda s: SpanMultiTermQuery(parse_query(s.get("match")),
                                               float(s.get("boost", 1.0))),
    "field_masking_span": lambda s: FieldMaskingSpanQuery(
        parse_query(s.get("query")), str(s.get("field", "")),
        float(s.get("boost", 1.0))),
    "geo_shape": lambda s: ConstantScoreQuery(
        filter=_parse_geo_shape_f({k: v for k, v in s.items() if k != "boost"}),
        boost=float(s.get("boost", 1.0))),
    "indices": lambda s: (lambda inner, nm, nmn, idx: IndicesQuery(
        idx, inner, nm, float(s.get("boost", 1.0)), no_match_none=nmn))(
        *_parse_indices_common(s, parse_query, None)),
    "type": lambda s: ConstantScoreQuery(filter=TypeFilter(s.get("value"))),
    "top_children": lambda s: HasChildQuery(s.get("type"), parse_query(s.get("query")),
                                            s.get("score", "max"), float(s.get("boost", 1.0))),
    "simple_query_string": _parse_simple_query_string,
    "fuzzy_like_this": _parse_flt,
    "flt": _parse_flt,
    "fuzzy_like_this_field": _parse_flt_field,
    "flt_field": _parse_flt_field,
    "more_like_this_field": _parse_mlt_field,
    "mlt_field": _parse_mlt_field,
    "wrapper": lambda s: parse_query(_unwrap_wrapper(s)),
}


def _as_list(v):
    if v is None:
        return []
    return v if isinstance(v, list) else [v]


_LOOKUP_META = ("index", "type", "id", "path", "routing", "cache")


def resolve_terms_lookups(body, get_fn):
    """Rewrite terms-LOOKUP specs in a raw request body into plain value lists
    by fetching the referenced document (ref: TermsFilterParser.java:1 — the
    lookup resolves against the get path; IndicesTermsFilterCache.java:1 caches
    per node; here the coordinating node resolves once per request, so every
    shard sees identical values even mid-reindex).

    get_fn(index, type, id, routing) -> get-response dict (or None). A missing
    document resolves to NO terms (the reference's behavior). Returns the
    original object when nothing needed rewriting."""
    def walk(obj):
        if isinstance(obj, list):
            new = [walk(v) for v in obj]
            return new if any(a is not b for a, b in zip(new, obj)) else obj
        if not isinstance(obj, dict):
            return obj
        out = {}
        changed = False
        for k, v in obj.items():
            if k in ("terms", "in") and isinstance(v, dict):
                fields = {fk: fv for fk, fv in v.items()
                          if not fk.startswith("_") and fk not in
                          ("execution", "minimum_should_match",
                           "minimum_match", "boost", "disable_coord")}
                if len(fields) == 1:
                    (fk, fv), = fields.items()
                    if isinstance(fv, dict) and "id" in fv and "path" in fv:
                        values = _fetch_lookup_terms(fv, get_fn)
                        out[k] = {**{ok: ov for ok, ov in v.items() if ok != fk},
                                  fk: values}
                        changed = True
                        continue
            nv = walk(v)
            changed = changed or (nv is not v)
            out[k] = nv
        return out if changed else obj

    return walk(body)


def _fetch_lookup_terms(spec: dict, get_fn) -> list:
    index = spec.get("index")
    if not index:
        raise QueryParsingError("terms lookup requires [index]")
    doc = get_fn(index, spec.get("type"), str(spec["id"]), spec.get("routing"))
    src = (doc or {}).get("_source")
    if not doc or not doc.get("found") or src is None:
        return []
    values: list = []

    def extract(node, parts):
        if not parts:
            if isinstance(node, list):
                values.extend(node)
            elif node is not None:
                values.append(node)
            return
        head, rest = parts[0], parts[1:]
        if isinstance(node, list):
            for item in node:
                extract(item, parts)
        elif isinstance(node, dict) and head in node:
            extract(node[head], rest)

    extract(src, str(spec.get("path", "")).split("."))
    return values


def _parse_terms_f(spec) -> Filter:
    spec = {k: v for k, v in spec.items() if k not in ("execution", "_cache", "_cache_key", "_name")}
    if len(spec) != 1:
        raise QueryParsingError("terms filter requires exactly one field")
    fname, values = next(iter(spec.items()))
    if isinstance(values, dict):
        # terms LOOKUP (values live in another document — ref:
        # TermsFilterParser.java:1 + IndicesTermsFilterCache.java:1): the
        # coordinating node resolves it against the get path BEFORE shard
        # fan-out (actions.resolve_terms_lookups); reaching this parser
        # unresolved means there was no coordinator (embedded/percolator use)
        raise QueryParsingError(
            f"terms lookup on [{fname}] must be resolved by the coordinating "
            f"node (index/type/id/path get) before shard execution")
    return TermsFilter(fname, list(values))


def _parse_range_f(spec) -> Filter:
    spec = {k: v for k, v in spec.items() if k not in ("_cache", "_cache_key", "_name", "execution")}
    fname, opts = _field_spec(spec, "value")
    conv = {"from": "gte", "to": "lte"}
    kw = {}
    for k in ("gte", "gt", "lte", "lt", "from", "to"):
        if k in opts:
            kw[conv.get(k, k)] = opts[k]
    if "include_lower" in opts and not opts["include_lower"] and "gte" in kw:
        kw["gt"] = kw.pop("gte")
    if "include_upper" in opts and not opts["include_upper"] and "lte" in kw:
        kw["lt"] = kw.pop("lte")
    return RangeFilter(field=fname, **kw)


def _parse_geo_point(point):
    """The reference's three point spellings: {lat, lon} | "lat,lon" | [lon, lat]."""
    if isinstance(point, dict):
        return float(point["lat"]), float(point["lon"])
    if isinstance(point, str):
        lat, lon = (float(x) for x in point.split(","))
        return lat, lon
    return float(point[1]), float(point[0])  # geojson order


def _parse_geo_polygon_f(spec) -> Filter:
    """ref: GeoPolygonFilterParser.java:1 — {field: {points: [...]}}."""
    spec = {k: v for k, v in spec.items() if k not in ("_cache", "_cache_key", "_name")}
    (fname, body), = spec.items()
    pts = tuple(_parse_geo_point(p) for p in body.get("points", []))
    if len({p for p in pts}) < 3:
        raise QueryParsingError("geo_polygon requires at least 3 distinct points")
    return GeoPolygonFilter(fname, pts)


def _parse_geo_distance_range_f(spec) -> Filter:
    """ref: GeoDistanceRangeFilterParser.java:1 — geo_distance with
    from/to/gt/gte/lt/lte distance bounds around the origin point."""
    spec = {k: v for k, v in spec.items()
            if k not in ("_cache", "_cache_key", "_name", "distance_type",
                         "optimize_bbox", "unit")}
    from_m = to_m = None
    include_lower = include_upper = True
    for k in ("from", "gte", "gt"):
        if k in spec:
            from_m = parse_distance(spec.pop(k))
            include_lower = k != "gt"
    for k in ("to", "lte", "lt"):
        if k in spec:
            to_m = parse_distance(spec.pop(k))
            include_upper = k != "lt"
    if "include_lower" in spec:
        include_lower = bool(spec.pop("include_lower"))
    if "include_upper" in spec:
        include_upper = bool(spec.pop("include_upper"))
    (fname, point), = spec.items()
    lat, lon = _parse_geo_point(point)
    return GeoDistanceRangeFilter(fname, lat, lon, from_m, to_m,
                                  include_lower, include_upper)


def _parse_has_child_f(spec) -> Filter:
    """ref: HasChildFilterParser.java:1 — parent docs with a matching child;
    never scores (score_mode none). The cross-segment join lives in
    filters.HasChildFilter (a QueryWrapperFilter would evaluate segment-local
    and match nothing)."""
    inner = (parse_query(spec["query"]) if "query" in spec
             else ConstantScoreQuery(filter=parse_filter(spec.get("filter"))))
    return HasChildFilter(
        HasChildQuery(spec.get("type", spec.get("child_type")), inner, "none"))


def _parse_has_parent_f(spec) -> Filter:
    """ref: HasParentFilterParser.java:1."""
    inner = (parse_query(spec["query"]) if "query" in spec
             else ConstantScoreQuery(filter=parse_filter(spec.get("filter"))))
    return HasChildFilter(
        HasParentQuery(spec.get("parent_type", spec.get("type")), inner, "none"))


def _parse_geo_distance_f(spec) -> Filter:
    spec = {k: v for k, v in spec.items() if k not in ("_cache", "_name", "distance_type", "optimize_bbox")}
    dist = parse_distance(spec.pop("distance"))
    unit = spec.pop("unit", None)
    if unit and isinstance(dist, float) and str(dist) == spec.get("distance"):
        pass
    (fname, point), = spec.items()
    if isinstance(point, dict):
        lat, lon = float(point["lat"]), float(point["lon"])
    elif isinstance(point, str):
        lat, lon = (float(x) for x in point.split(","))
    else:
        lon, lat = float(point[0]), float(point[1])
    return GeoDistanceFilter(fname, lat, lon, dist)


def _parse_geo_bbox_f(spec) -> Filter:
    spec = {k: v for k, v in spec.items() if k not in ("_cache", "_name", "type")}
    (fname, box), = spec.items()
    if "top_left" in box:
        tl, br = box["top_left"], box["bottom_right"]
        if isinstance(tl, dict):
            top, left = tl["lat"], tl["lon"]
            bottom, right = br["lat"], br["lon"]
        else:
            left, top = tl[0], tl[1]
            right, bottom = br[0], br[1]
    else:
        top, left, bottom, right = box["top"], box["left"], box["bottom"], box["right"]
    return GeoBoundingBoxFilter(fname, float(top), float(left), float(bottom), float(right))


def _parse_geo_shape_f(spec) -> Filter:
    """ref: GeoShapeQueryParser.java:1 — {field: {shape: {...}, relation}}."""
    from ..common.geo import normalize_shape

    spec = {k: v for k, v in spec.items() if k not in ("_cache", "_name")}
    (fname, body), = spec.items()
    shape_spec = body.get("shape")
    if shape_spec is None:
        raise QueryParsingError("geo_shape requires [shape]")
    try:
        shape = normalize_shape(shape_spec)
    except ValueError as e:
        raise QueryParsingError(str(e))
    relation = str(body.get("relation", "intersects")).lower()
    if relation not in ("intersects", "within", "disjoint"):
        raise QueryParsingError(f"unknown geo_shape relation [{relation}]")
    return GeoShapeFilter(fname, shape, relation)


def _parse_geohash_cell_f(spec) -> Filter:
    """ref: GeohashCellFilter.java:1 — {field: pin, precision, neighbors}."""
    from ..common.geo import geohash_encode

    spec = {k: v for k, v in spec.items() if k not in ("_cache", "_name")}
    neighbors = bool(spec.pop("neighbors", False))
    precision = spec.pop("precision", None)
    (fname, pin), = spec.items()
    if isinstance(pin, dict):
        h = geohash_encode(float(pin["lat"]), float(pin["lon"]),
                           int(precision or 12))
    elif isinstance(pin, str) and "," in pin:
        lat, lon = (float(x) for x in pin.split(","))
        h = geohash_encode(lat, lon, int(precision or 12))
    elif isinstance(pin, str):
        h = pin.strip().lower()
        if precision is not None:
            h = h[: int(precision)]
    else:  # [lon, lat]
        h = geohash_encode(float(pin[1]), float(pin[0]), int(precision or 12))
    if not h:
        raise QueryParsingError("geohash_cell requires a non-empty cell")
    return GeohashCellFilter(fname, h, neighbors)


_FILTER_PARSERS = {
    "term": lambda s: (lambda f, o: TermFilter(f, o.get("value")))(
        *_field_spec({k: v for k, v in s.items() if not k.startswith("_")}, "value")),
    "terms": _parse_terms_f,
    "in": _parse_terms_f,
    "range": _parse_range_f,
    "numeric_range": _parse_range_f,
    "exists": lambda s: ExistsFilter(s["field"] if isinstance(s, dict) else s),
    "missing": lambda s: MissingFilter(s["field"] if isinstance(s, dict) else s),
    "ids": lambda s: IdsFilter(ids=[str(i) for i in s.get("values", [])],
                               types=_as_list(s.get("type", s.get("types")))),
    "type": lambda s: TypeFilter(s.get("value")),
    "match_all": lambda s: MatchAllFilter(),
    "bool": lambda s: BoolFilter(
        must=[parse_filter(f) for f in _as_list(s.get("must"))],
        should=[parse_filter(f) for f in _as_list(s.get("should"))],
        must_not=[parse_filter(f) for f in _as_list(s.get("must_not"))]),
    "and": lambda s: BoolFilter(must=[parse_filter(f) for f in
                                      (s.get("filters", s) if isinstance(s, dict) else s)]),
    "or": lambda s: BoolFilter(should=[parse_filter(f) for f in
                                       (s.get("filters", s) if isinstance(s, dict) else s)]),
    "not": lambda s: NotFilter(parse_filter(s.get("filter", s) if isinstance(s, dict) else s)),
    "prefix": lambda s: (lambda f, o: PrefixFilter(f, str(o.get("value", o.get("prefix", "")))))(
        *_field_spec({k: v for k, v in s.items() if not k.startswith("_")}, "value")),
    "regexp": lambda s: (lambda f, o: RegexpFilter(f, str(o.get("value", ""))))(
        *_field_spec({k: v for k, v in s.items() if not k.startswith("_")}, "value")),
    "query": lambda s: QueryWrapperFilter(parse_query(s)),
    "fquery": lambda s: QueryWrapperFilter(parse_query(s.get("query"))),
    "nested": lambda s: NestedFilter(s["path"], parse_query(s.get("query")) if "query" in s
                                     else parse_filter(s.get("filter"))),
    "geo_distance": _parse_geo_distance_f,
    "geo_bounding_box": _parse_geo_bbox_f,
    "geo_shape": _parse_geo_shape_f,
    "geohash_cell": _parse_geohash_cell_f,
    "script": lambda s: ScriptFilter(s.get("script", ""), s.get("params", {})),
    "limit": lambda s: MatchAllFilter(),  # limit filter is best-effort in the reference too
    "geo_polygon": _parse_geo_polygon_f,
    "geo_distance_range": _parse_geo_distance_range_f,
    "has_child": _parse_has_child_f,
    "has_parent": _parse_has_parent_f,
    "indices": lambda s: (lambda inner, nm, nmn, idx: IndicesFilter(
        tuple(idx), inner, nm, no_match_none=nmn))(
        *_parse_indices_common(s, parse_filter, None)),
    "wrapper": lambda s: parse_filter(_unwrap_wrapper(s)),
}
