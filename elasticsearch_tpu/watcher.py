"""Resource watcher: periodic file-change notification for hot-reloadable config.

ref: watcher/ResourceWatcherService.java:42 (scheduled poll of registered watchers,
watcher.enabled / watcher.interval settings) + watcher/FileWatcher.java (mtime-diff
tree walk firing onFileCreated/Changed/Deleted). The flagship consumer is the script
service: files in config/scripts become named scripts, reloaded live — exactly the
reference's ScriptService(...ResourceWatcherService) wiring."""

from __future__ import annotations

import os
import threading

from .common.logging import get_logger


class FileChangesListener:
    def on_file_created(self, path: str):  # pragma: no cover - interface default
        pass

    def on_file_changed(self, path: str):  # pragma: no cover
        pass

    def on_file_deleted(self, path: str):  # pragma: no cover
        pass


class FileWatcher:
    """Watches one directory tree; diffing (mtime, size) snapshots per check."""

    def __init__(self, path: str, listener: FileChangesListener):
        self.path = path
        self.listener = listener
        self._state: dict[str, tuple[float, int]] = {}
        self._primed = False

    def _snapshot(self) -> dict[str, tuple[float, int]]:
        snap: dict[str, tuple[float, int]] = {}
        if not os.path.isdir(self.path):
            return snap
        for root, _dirs, files in os.walk(self.path):
            for f in files:
                p = os.path.join(root, f)
                try:
                    st = os.stat(p)
                except OSError:
                    continue
                snap[p] = (st.st_mtime, st.st_size)
        return snap

    def init(self):
        """First scan: existing files fire on_file_created (the reference's
        FileWatcher.init does the same so startup and hot-add share one path)."""
        self._state = {}
        self._primed = True
        self.check()

    def check(self):
        if not self._primed:
            self.init()
            return
        snap = self._snapshot()
        for p, sig in snap.items():
            old = self._state.get(p)
            if old is None:
                self.listener.on_file_created(p)
            elif old != sig:
                self.listener.on_file_changed(p)
        for p in self._state:
            if p not in snap:
                self.listener.on_file_deleted(p)
        self._state = snap


class ResourceWatcherService:
    """Polls registered watchers on a fixed interval; disabled via
    watcher.enabled=false (ref: ResourceWatcherService.java:42)."""

    def __init__(self, settings, threadpool=None):
        self.enabled = settings.get_bool("watcher.enabled", True)
        self.interval = float(settings.get("watcher.interval", 60.0))
        self.logger = get_logger("watcher")
        self._watchers: list[FileWatcher] = []
        self._lock = threading.Lock()
        self._task = None
        self._threadpool = threadpool

    def add(self, watcher: FileWatcher) -> FileWatcher:
        watcher.init()
        with self._lock:
            self._watchers.append(watcher)
        return watcher

    def remove(self, watcher: FileWatcher):
        with self._lock:
            if watcher in self._watchers:
                self._watchers.remove(watcher)

    def notify_now(self):
        """Immediate check of every watcher (tests; REST-triggered reloads)."""
        with self._lock:
            watchers = list(self._watchers)
        for w in watchers:
            try:
                w.check()
            except Exception as e:  # noqa: BLE001 — one bad watcher can't stop the rest
                self.logger.warning(f"resource watcher [{w.path}] failed: {e}")

    def start(self):
        if not self.enabled or self._threadpool is None:
            return self
        self._task = self._threadpool.schedule_with_fixed_delay(
            self.interval, self.notify_now, name="generic")
        return self

    def stop(self):
        if self._task is not None:
            self._task.cancel()
            self._task = None


class ScriptDirectoryListener(FileChangesListener):
    """config/scripts/<name>.<ext> → named script <name> (ref: ScriptService's
    ScriptChangesListener: file scripts compile on sight, reload on change)."""

    def __init__(self, script_service, logger=None):
        self.scripts = script_service
        self.logger = logger or get_logger("watcher.scripts")

    @staticmethod
    def _name(path: str) -> str:
        return os.path.splitext(os.path.basename(path))[0]

    def on_file_created(self, path: str):
        try:
            with open(path) as fh:
                self.scripts.put(self._name(path), fh.read().strip())
            self.logger.info("loaded script [%s]", self._name(path))
        except OSError as e:
            self.logger.warning(f"failed loading script [{path}]: {e}")

    def on_file_changed(self, path: str):
        self.on_file_created(path)

    def on_file_deleted(self, path: str):
        self.scripts.remove(self._name(path))
        self.logger.info("removed script [%s]", self._name(path))
