from .device_index import PackedSegment, pack_segment  # noqa: F401
from .scoring import TermBatch, score_term_batch, ScoreResult  # noqa: F401
