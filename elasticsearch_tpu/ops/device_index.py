"""Device-resident packed postings.

This is the TPU replacement for Lucene's on-heap postings traversal (SURVEY.md §2.8:
"device-resident packed postings blocks, vmapped BM25 scoring, lax.top_k"). A frozen
segment's CSR postings are re-blocked into fixed-shape device tensors:

    blk_docs  : int32 [NB, B]   — local doc ids, padded with `doc_pad` (out of range)
    blk_freqs : float32 [NB, B] — term frequencies, padded with 0

Each term owns a contiguous run of blocks (`term_blk_start[t] .. term_blk_start[t+1]`),
so a query term's postings are a static-shape slice of block indices — the host builds
flat (query, block, weight) triples and the scoring kernel is pure gather + FMA +
scatter-add, no data-dependent shapes (XLA-friendly by construction).

Shapes are padded to power-of-two buckets (NB rows, D docs) so recompilation stops once
the shape buckets stabilize — segment churn from NRT refresh reuses cached executables.

Norm bytes stay uint8 on device; similarity-specific 256-entry decode tables are gathered
at score time, preserving Lucene's exact 1-byte quantization.
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field

import numpy as np

from ..index.segment import FrozenSegment

BLOCK = 128  # lane width


def _pow2_bucket(n: int, minimum: int = 128) -> int:
    b = minimum
    while b < n:
        b *= 2
    return b


@dataclass
class PackedSegment:
    """Device tensors + host lookup tables for one frozen segment."""

    gen: int
    doc_count: int  # real docs
    doc_pad: int  # padded D (bucketed)
    blk_docs: object  # jnp int32 [NBpad, B]
    blk_freqs: object  # jnp float32 [NBpad, B]
    term_blk_start: np.ndarray  # host int64 [T+1]
    live_parent: object  # jnp bool [Dpad] — live & parent (searchable docs)
    norm_bytes: dict  # field -> jnp uint8 [Dpad]
    dv_single: dict = dc_field(default_factory=dict)  # field -> jnp float32/float64 [Dpad] single-valued fast path (NaN missing)
    live_version: int = 0

    def blocks_for_term(self, tid: int) -> tuple[int, int]:
        return int(self.term_blk_start[tid]), int(self.term_blk_start[tid + 1])


def pack_segment(seg: FrozenSegment, fields: list[str] | None = None,
                 device_put=None) -> PackedSegment:
    """Pack a frozen segment's postings + norms + single-valued numeric columns for
    device execution. `fields` limits norm upload (None = all text fields)."""
    import jax.numpy as jnp

    put = device_put or (lambda x: jnp.asarray(x))

    T = len(seg.post_offsets) - 1
    counts = np.diff(seg.post_offsets)
    nblks = (counts + BLOCK - 1) // BLOCK
    blk_start = np.zeros(T + 1, dtype=np.int64)
    np.cumsum(nblks, out=blk_start[1:])
    NB = int(blk_start[-1])
    # +1 guarantees at least one all-sentinel row past the real blocks — the scoring
    # batch points its padding triples at row NBpad-1, which must never hold postings
    NBpad = _pow2_bucket(NB + 1, 64)
    Dpad = _pow2_bucket(max(seg.doc_count, 1), 128)

    flat_docs = np.full(NBpad * BLOCK, Dpad, dtype=np.int32)  # pad → out-of-range slot
    flat_freqs = np.zeros(NBpad * BLOCK, dtype=np.float32)
    if len(seg.post_docs):
        # slot of entry j of term t = (blk_start[t]*B) + (j - post_offsets[t])
        within = np.arange(len(seg.post_docs), dtype=np.int64) - np.repeat(
            seg.post_offsets[:-1], counts
        )
        slots = np.repeat(blk_start[:-1] * BLOCK, counts) + within
        flat_docs[slots] = seg.post_docs
        flat_freqs[slots] = seg.post_freqs

    live_parent = np.zeros(Dpad, dtype=bool)
    live_parent[: seg.doc_count] = seg.live & seg.parent_mask

    norm_bytes = {}
    for f, arr in seg.norms.items():
        if fields is not None and f not in fields:
            continue
        padded = np.zeros(Dpad, dtype=np.uint8)
        padded[: seg.doc_count] = arr
        norm_bytes[f] = put(padded)

    dv_single = {}
    for f, (off, vals) in seg.dv_num.items():
        counts_dv = np.diff(off)
        if counts_dv.max(initial=0) <= 1:
            col = np.full(Dpad, np.nan, dtype=np.float64)
            has = counts_dv == 1
            col[: seg.doc_count][has] = vals
            dv_single[f] = put(col)

    return PackedSegment(
        gen=seg.gen,
        doc_count=seg.doc_count,
        doc_pad=Dpad,
        blk_docs=put(flat_docs.reshape(NBpad, BLOCK)),
        blk_freqs=put(flat_freqs.reshape(NBpad, BLOCK)),
        term_blk_start=blk_start,
        live_parent=put(live_parent),
        norm_bytes=norm_bytes,
        dv_single=dv_single,
    )


def packed_for(seg: FrozenSegment) -> PackedSegment:
    """Per-segment cached packing; refreshes the live mask when tombstones changed."""
    cache = seg._device_cache
    packed: PackedSegment | None = cache.get("packed")
    if packed is None:
        packed = pack_segment(seg)
        cache["packed"] = packed
        cache["live"] = True
    elif cache.get("live") is None:
        import jax.numpy as jnp

        live_parent = np.zeros(packed.doc_pad, dtype=bool)
        live_parent[: seg.doc_count] = seg.live & seg.parent_mask
        packed.live_parent = jnp.asarray(live_parent)
        cache["live"] = True
    return packed
