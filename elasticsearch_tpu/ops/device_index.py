"""Device-resident packed postings — quantized layout.

This is the TPU replacement for Lucene's on-heap postings traversal (SURVEY.md §2.8:
"device-resident packed postings blocks, vmapped BM25 scoring, lax.top_k"), playing the
role of Lucene's packed postings codecs (PAPER.md §0): the resident form is quantized,
not raw floats. A frozen segment's CSR postings are re-blocked into fixed-shape device
tensors:

    blk_docs : int32 [NB, B]      — local doc ids, padded with `doc_pad` (out of range)
    blk_tf   : uint8/int16 [NB, B] — term frequencies, quantized (raw tf is a
               small integer; segments whose tf overflows the int ladder take the
               float32 escape hatch — see choose_tf_layout)
    blk_nb   : uint8 [NB, B]      — the posting's doc norm byte for the block's
               owning field (Lucene's byte315 encoding, decoded IN the scan via a
               256-entry similarity LUT — common/smallfloat.py)

6 B/posting resident in the common uint8 layout (docs 4 + tf 1 + nb 1), down from the
12 B/posting of the former f32 (freqs + baked-tfn) planes. The dense-fallback kernels
still want an f32 freqs plane; it is NOT packed — `ensure_blk_freqs` uploads it lazily
from the host copy the first time a segment actually feeds the dense path
(ARCHITECTURE.md "HBM budget": the `blk_freqs`-drop rule).

Each term owns a contiguous run of blocks (`term_blk_start[t] .. term_blk_start[t+1]`),
so a query term's postings are a static-shape slice of block indices — the host builds
flat (query, block, weight) triples and the scoring kernel is pure gather + decode +
FMA + scatter-add, no data-dependent shapes (XLA-friendly by construction).

Shapes are padded to power-of-two buckets (NB rows, D docs) so recompilation stops once
the shape buckets stabilize — segment churn from NRT refresh reuses cached executables.

Norm bytes stay uint8 on device; similarity-specific 256-entry decode tables
(ensure_sim_tables) are gathered at score time, preserving Lucene's exact 1-byte
quantization.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field as dc_field

import numpy as np

from ..common import profile as _profile
from ..common.breaker import reserve
from ..common.devicehealth import tag_domain as _tag_domain
from ..common.errors import CircuitBreakingError
from ..index.segment import FrozenSegment
from ..transport.faults import DEVICE_FAULTS as _DEVICE_FAULTS

BLOCK = 128  # lane width


def _pow2_bucket(n: int, minimum: int = 128) -> int:
    b = minimum
    while b < n:
        b *= 2
    return b


def _ladder_bucket(dim: str, n: int, minimum: int) -> int:
    """Autotuned bucket ladder (common/compilecache.LADDERS): records n into
    the dimension's shape histogram and returns its committed rung, with the
    exact `_pow2_bucket` as the cold fallback — bit-identical to the fixed
    pow-2 ladder until a warm-cycle autotune commits a fitted one. Every
    shape-relevant bucket site routes through here (or _pow2_bucket): the
    compile-surface lattice (tools/tpulint TPU018+) classifies both as
    `bucketed`."""
    from ..common.compilecache import LADDERS

    return LADDERS.bucket(dim, n, minimum)


def expand_ranges(starts: np.ndarray, counts: np.ndarray) -> np.ndarray:
    """Flatten half-open ranges [starts[i], starts[i]+counts[i]) into one int64 array
    — the CSR expansion idiom (repeat + within-range offset) shared by segment
    packing, the mesh assembler, and the bench."""
    total = int(counts.sum())
    excl = np.zeros(len(counts), dtype=np.int64)
    if len(counts) > 1:
        np.cumsum(counts[:-1], out=excl[1:])
    within = np.arange(total, dtype=np.int64) - np.repeat(excl, counts)
    return np.repeat(np.asarray(starts, dtype=np.int64), counts) + within


# tf-plane layout ladder: uint8 covers real-text term frequencies (tf ≤ 255 for
# essentially every (term, doc)); int16 is the overflow rung; float32 the escape
# hatch for non-integral or >2^15-1 frequencies (synthetic corpora, index-time
# boost folding). One dtype per segment plane — the decode in the scan is a
# plain astype either way.
TF_U8, TF_I16, TF_F32 = "u8", "i16", "f32"
_TF_DTYPE = {TF_U8: np.uint8, TF_I16: np.int16, TF_F32: np.float32}


def choose_tf_layout(post_freqs: np.ndarray) -> str:
    """Pick the narrowest exact tf-plane dtype for a segment's raw frequencies.

    Allocation-light on purpose — this runs inside pack_estimate_bytes, i.e.
    BEFORE the breaker reservation: max() allocates nothing, and the
    integrality scan works in bounded chunks (≤ 4 MB of temporaries) instead
    of materializing floor/compare arrays over all postings at once."""
    if len(post_freqs) == 0:
        return TF_U8
    mx = float(post_freqs.max())
    if mx > 32767:
        return TF_F32
    if post_freqs.dtype.kind not in "iu":
        chunk = 1 << 20
        for i in range(0, len(post_freqs), chunk):
            c = post_freqs[i: i + chunk]
            if not np.all(c == np.floor(c)):
                return TF_F32
    return TF_U8 if mx <= 255 else TF_I16


def tf_plane_itemsize(layout: str) -> int:
    return np.dtype(_TF_DTYPE[layout]).itemsize


def tf_plane_integral(post_freqs: np.ndarray, layout: str) -> bool:
    """Whether a segment's raw tf values are all integers. u8/i16 rungs are
    integral by construction; the f32 escape covers both huge-but-integral
    tf (> 2^15-1) and genuinely fractional tf (index-time boost folding) —
    only the former is exactly reconstructible from positions, which is
    what gates the compaction concat path (merge_segments rebuilds freq as
    the position count). Chunked like choose_tf_layout's scan."""
    if layout != TF_F32:
        return True
    chunk = 1 << 20
    for i in range(0, len(post_freqs), chunk):
        c = post_freqs[i: i + chunk]
        if not np.all(c == np.floor(c)):
            return False
    return True


@dataclass
class SimTables:
    """Stacked per-field similarity decode state for the quantized sparse scan:
    one 256-entry f32 cache row + TFN_* mode per field. Replaces the old
    per-posting baked-tfn plane — a table swap on avgdl drift costs 1 KB/field
    instead of a full postings re-bake + HBM upload."""

    fields: list  # field order = fid
    fid: dict  # field -> row index
    modes: object  # jnp int32 [F]
    caches: object  # jnp float32 [F, 256]
    key: dict  # field -> (mode, cache bytes) — staleness fingerprint


@dataclass
class PackedSegment:
    """Device tensors + host lookup tables for one frozen segment."""

    gen: int
    doc_count: int  # real docs
    doc_pad: int  # padded D (bucketed)
    blk_docs: object  # jnp int32 [NBpad, B] — dead/non-parent docs masked to doc_pad
    term_blk_start: np.ndarray  # host int64 [T+1]
    live_parent: object  # jnp bool [Dpad] — live & parent (searchable docs)
    norm_bytes: dict  # field -> jnp uint8 [Dpad]
    dv_single: dict = dc_field(default_factory=dict)  # field -> jnp float32/float64 [Dpad] single-valued fast path (NaN missing)
    live_version: int = 0
    # quantized sparse-path planes (the resident layout — see module docstring):
    # tf decoded + normalized INSIDE the scan via the SimTables LUT, so no
    # second f32 plane and no per-(field, similarity) re-bake
    blk_tf: object = None  # jnp uint8/int16/float32 [NBpad, B]
    blk_nb: object = None  # jnp uint8 [NBpad, B] — per-posting norm byte
    tf_layout: str = TF_U8  # TF_U8 | TF_I16 | TF_F32
    # raw tf values are all integers (always true for the u8/i16 rungs; the
    # f32 escape scans once at pack time). Merge compaction may device-concat
    # source planes ONLY when every source is integral — merge_segments
    # rebuilds freq as the position count, so fractional tf would diverge
    tf_integral: bool = True
    sim: SimTables | None = None  # ensure_sim_tables state
    # dense-fallback plane, uploaded LAZILY (ensure_blk_freqs): most segments
    # only ever serve the sparse path and never pay these 4 B/posting
    blk_freqs: object = None  # jnp float32 [NBpad, B] or None until dense use
    # device metric-agg state: per-doc (count, sum, min, max, sumsq) rows per
    # numeric field, exact for MULTI-valued columns because the per-doc folds
    # happen host-side at build time (ops/scoring.score_agg_batch reduces them
    # under the match mask — SURVEY §5.7 "shard-level parallel reduce")
    agg_rows: dict = dc_field(default_factory=dict)  # field -> HOST f32 [5, Dpad] | None (not f32-exact)
    agg_stacks: dict = dc_field(default_factory=dict)  # fields-tuple -> device [F, 5, Dpad], FIFO-bounded
    bucket_cols: dict = dc_field(default_factory=dict)  # bucket-agg cache key -> device (pair_doc, pair_bucket, zeros[NB])
    # reusable [Qb, TB] staging arrays for the sparse planner (scoring.
    # SparseScratchPool, lazily created) — the per-bucket padding scratch lives
    # WITH the segment cache so warmed repeat batches re-pad in place instead
    # of re-materializing four arrays per bucket per launch
    sparse_scratch: object = None
    # host copies for re-bakes (live-mask refresh / similarity-stats drift)
    host_docs: np.ndarray | None = None  # int32 [NBpad*B] RAW (unmasked) doc ids
    host_freqs: np.ndarray | None = None  # float32 [NBpad*B]
    blk_field: np.ndarray | None = None  # int32 [NBpad] field ordinal per block (-1 pad)
    field_names: list = dc_field(default_factory=list)  # ordinal -> field name

    def blocks_for_term(self, tid: int) -> tuple[int, int]:
        return int(self.term_blk_start[tid]), int(self.term_blk_start[tid + 1])


def pack_shape_math(seg: FrozenSegment) -> tuple[int, int, str]:
    """(NBpad, Dpad, tf_layout) — the one shape+layout derivation shared by
    pack_estimate_bytes and pack_segment, so the breaker estimate can never
    drift from what the pack actually allocates. Memoized on the segment's
    device cache: the estimate→pack sequence (packed_for) derives it once,
    not once per caller (the layout scan is O(postings))."""
    cache = getattr(seg, "_device_cache", None)
    if cache is not None:
        sm = cache.get("shape_math")
        if sm is not None:
            return sm
    counts = np.diff(seg.post_offsets)
    nblks = (counts + BLOCK - 1) // BLOCK
    NBpad = _ladder_bucket("nb", int(nblks.sum()) + 1, 64)
    Dpad = _ladder_bucket("docs", max(seg.doc_count, 1), 128)
    sm = (NBpad, Dpad, choose_tf_layout(seg.post_freqs))
    if cache is not None:
        cache["shape_math"] = sm
    return sm


# pack-time host transients per slot, beyond the retained/uploaded planes:
# the live-masked doc-id np.where result (4 B) plus the fid_per_slot ordinal
# expansion (4 B) and the boolean gather/select masks (~4 B across real/sel
# temps) — freed by the end of the pack but live at its allocation peak,
# which is what the breaker reservation must cover
PACK_TRANSIENT_SLOT_BYTES = 12


def pack_estimate_bytes(seg: FrozenSegment) -> int:
    """Host-staging + device-upload bytes pack_segment will allocate — the
    estimate the fielddata breaker checks BEFORE the first np.full. Derived
    from the same shape+layout math as the pack itself (pack_shape_math):
    docs i32 and freqs f32 are staged host-side (kept for live-mask re-masks
    and the lazy dense plane); the DEVICE copy is the quantized layout —
    docs i32 + tf (u8/i16/f32 per choose_tf_layout) + norm byte u8 — plus the
    quantize/nb staging, a PACK_TRANSIENT_SLOT_BYTES allowance for the
    masking/ordinal temps live at the pack's peak, and the Dpad-wide
    masks/columns. The lazy dense plane is NOT in here — ensure_blk_freqs
    reserves it at its own allocation site."""
    NBpad, Dpad, layout = pack_shape_math(seg)
    tf_b = tf_plane_itemsize(layout)
    n_norm_fields = len(seg.norms)
    n_dv = len(seg.dv_num)
    # host staging: docs i32 + freqs f32 + tf + nb;  device: docs i32 + tf + nb
    per_slot = (4 + 4 + tf_b + 1) + (4 + tf_b + 1) + PACK_TRANSIENT_SLOT_BYTES
    # + live mask (host + device) + norms u8 + single-valued dv f64 columns
    return (NBpad * BLOCK * per_slot + Dpad * 2
            + Dpad * n_norm_fields + Dpad * 8 * n_dv)


def packed_resident_bytes(packed: PackedSegment) -> int:
    """Actual device-RESIDENT postings-plane bytes of a packed segment (docs +
    tf + nb, plus the dense f32 plane if it has been faulted in) — what the
    bench `kernel` row and the breaker-estimate test compare against."""
    total = 0
    for plane in (packed.blk_docs, packed.blk_tf, packed.blk_nb,
                  packed.blk_freqs):
        if plane is not None:
            total += int(np.prod(plane.shape)) * np.dtype(plane.dtype).itemsize
    return total


def _plane_bytes(plane) -> int:
    return 0 if plane is None else \
        int(np.prod(plane.shape)) * np.dtype(plane.dtype).itemsize


def packed_tier_bytes(packed: PackedSegment) -> dict:
    """Device-resident bytes of one packed segment broken down by TIER — the
    device capacity ledger's taxonomy (ARCHITECTURE.md "Observability"):

      postings     the quantized sparse planes (blk_docs i32 + blk_tf + blk_nb)
      dense_plane  the lazily-faulted f32 freqs plane (0 until dense use)
      sim_tables   the stacked per-field similarity LUTs (modes + caches)
      agg_rows     FIFO-bounded device metric-agg stacks
      norms        per-field norm-byte columns + live mask + dv columns

    Pure host arithmetic over already-known shapes — no device sync, no
    packing side effects. `filter_masks` is accounted separately (the holder
    lives on the segment, not the PackedSegment — see capacity walk callers)."""
    postings = (_plane_bytes(packed.blk_docs) + _plane_bytes(packed.blk_tf)
                + _plane_bytes(packed.blk_nb))
    sim = 0
    if packed.sim is not None:
        sim = _plane_bytes(packed.sim.caches) + _plane_bytes(packed.sim.modes)
    agg = sum(_plane_bytes(stack) for stack in packed.agg_stacks.values())
    norms = _plane_bytes(packed.live_parent)
    for col in packed.norm_bytes.values():
        norms += _plane_bytes(col)
    for col in packed.dv_single.values():
        norms += _plane_bytes(col)
    return {
        "postings": postings,
        "dense_plane": _plane_bytes(packed.blk_freqs),
        "sim_tables": sim,
        "agg_rows": agg,
        "norms": norms,
    }


def _pool_label() -> str:
    """Which named threadpool is running the current thread — the ledger's
    pack attribution. Pool workers are named "estpu[<pool>]_N"
    (threadpool._BoundedPool's thread_name_prefix); anything else (a test's
    main thread, a raw Thread) reads as "other". One string parse on the
    already-cold pack path."""
    name = threading.current_thread().name
    if name.startswith("estpu[") and "]" in name:
        return name[len("estpu["): name.index("]")]
    return "other"


# ledger kind= vocabulary: "pack" (initial/full pack), "delta_pack" (a
# refresh-frozen increment — bounded by the buffer, not the index),
# "remask" (tombstone-driven live-mask refresh), "compact" (a merged
# segment's pack, device-concat from the sources' resident planes when
# eligible, host-staged otherwise — the event's method= field says which)
PACK_KINDS = ("pack", "delta_pack", "remask", "compact")
_KIND_COUNTER = {"pack": "packs", "delta_pack": "delta_packs",
                 "remask": "remasks", "compact": "compacts"}


class PackLedger:
    """Process-wide pack/repack timing ledger, keyed by index.

    `packed_for` records every segment pack (delta pack, compaction pack,
    and live-mask remask) here with its wall time, resident bytes, tf
    layout, the PACK_KINDS kind, the threadpool that did the work (pool=
    "warmer"/"merge"/"search"/"other" — the query-path-vs-background
    attribution the writes acceptance pins), and for compaction packs the
    method ("concat" = device-side plane concat, "staged" = host re-stage).
    The capacity report joins these against the live per-segment tier walk.
    Process-wide like search/service.SERVING_COUNTERS (in-process test
    clusters share it); bounded: at most MAX_INDICES index entries (LRU)
    each holding cumulative counters + a RING of recent events. `_lock` is
    a LEAF (dict mutation only) and recording happens on the already-cold
    pack path — the warmed serving loop never touches it."""

    MAX_INDICES = 256
    RING = 16

    def __init__(self):
        self._lock = threading.Lock()
        self._by_index: "OrderedDict[str, dict]" = OrderedDict()

    def record(self, index: str | None, gen: int, ms: float, nbytes: int,
               layout: str, kind: str = "pack", pool: str | None = None,
               method: str | None = None) -> None:
        index = index or "_unattributed"
        pool = pool or _pool_label()
        with self._lock:
            entry = self._by_index.get(index)
            if entry is None:
                entry = {"packs": 0, "delta_packs": 0, "remasks": 0,
                         "compacts": 0, "pack_ms_total": 0.0,
                         "pools": {}, "recent": []}
                self._by_index[index] = entry
                while len(self._by_index) > self.MAX_INDICES:
                    self._by_index.popitem(last=False)
            else:
                self._by_index.move_to_end(index)
            entry[_KIND_COUNTER.get(kind, "packs")] += 1
            entry["pack_ms_total"] += ms
            pools = entry["pools"]
            pools[pool] = pools.get(pool, 0) + 1
            recent = entry["recent"]
            event = {"kind": kind, "generation": int(gen),
                     "ms": round(ms, 3), "bytes": int(nbytes),
                     "tf_layout": layout, "pool": pool}
            if method is not None:
                event["method"] = method
            recent.append(event)
            if len(recent) > self.RING:
                del recent[: len(recent) - self.RING]

    def forget(self, index: str) -> None:
        """An index deleted from the cluster releases its ledger entry —
        label cardinality tracks LIVE indices, not history."""
        with self._lock:
            self._by_index.pop(index, None)

    @staticmethod
    def _row(e: dict) -> dict:
        return {"packs": e["packs"], "delta_packs": e["delta_packs"],
                "remasks": e["remasks"], "compacts": e["compacts"],
                "pack_ms_total": round(e["pack_ms_total"], 3),
                "pools": dict(e["pools"]),
                "recent": list(e["recent"])}

    def stats(self, index: str | None = None) -> dict:
        with self._lock:
            if index is not None:
                e = self._by_index.get(index)
                return {} if e is None else self._row(e)
            return {idx: self._row(e) for idx, e in self._by_index.items()}


PACK_LEDGER = PackLedger()


def segment_capacity(seg: FrozenSegment) -> dict | None:
    """The ledger row for one live segment: tier bytes + filter-mask holder
    bytes, or None when the segment never packed (nothing resident). Pure
    host reads — safe from any stats/scrape path."""
    packed = getattr(seg, "_device_cache", {}).get("packed")
    holder = getattr(seg, "_device_cache", {}).get("filter_masks")
    mask_bytes = int(holder.bytes) if holder is not None else 0
    if packed is None and mask_bytes == 0:
        return None
    tiers = packed_tier_bytes(packed) if packed is not None else {
        "postings": 0, "dense_plane": 0, "sim_tables": 0, "agg_rows": 0,
        "norms": 0}
    tiers["filter_masks"] = mask_bytes
    return {
        "generation": int(seg.gen),
        "tf_layout": packed.tf_layout if packed is not None else None,
        "tiers": tiers,
        "total_bytes": int(sum(tiers.values())),
    }


def capacity_report(indices_service, index=None) -> dict:
    """The device capacity ledger: per-index, per-segment HBM residency by
    tier + the pack/repack timing rollup — `/_nodes/stats` `device` section
    and the `/{index}/_stats` device stanza. Walks this NODE's live shard
    searchers (host arithmetic only; acquire_searcher on a closed engine is
    skipped, same as the Prometheus HBM gauge). `index` narrows the walk to
    one name or a collection of names — an index-scoped stats call must not
    pay the whole node's segment walk."""
    from ..common.errors import SearchEngineError

    wanted = None
    if index is not None:
        wanted = (set(index) if isinstance(index, (set, frozenset, list,
                                                   tuple))
                  else {index})
    indices_out = {}
    node_totals: dict[str, int] = {}
    for name, svc in list(indices_service.indices.items()):
        if wanted is not None and name not in wanted:
            continue
        shards_out = {}
        idx_totals: dict[str, int] = {}
        for sid, shard in sorted(svc.shards.items()):
            try:
                searcher = shard.engine.acquire_searcher()
            except SearchEngineError:
                continue
            segs = []
            for seg in searcher.segments:
                row = segment_capacity(seg)
                if row is None:
                    continue
                segs.append(row)
                for tier, b in row["tiers"].items():
                    idx_totals[tier] = idx_totals.get(tier, 0) + b
            if segs:
                shards_out[str(sid)] = segs
        entry = {
            "shards": shards_out,
            "totals": dict(idx_totals),
            "total_bytes": int(sum(idx_totals.values())),
            "pack": PACK_LEDGER.stats(name),
        }
        indices_out[name] = entry
        for tier, b in idx_totals.items():
            node_totals[tier] = node_totals.get(tier, 0) + b
    return {
        "indices": indices_out,
        "totals": dict(node_totals),
        "total_bytes": int(sum(node_totals.values())),
    }


def bytes_per_posting(layout: str, dense_resident: bool = False) -> int:
    """Resident bytes per posting slot for a tf layout: docs i32 + tf + nb
    (+ the lazy dense f32 plane when faulted in)."""
    return 4 + tf_plane_itemsize(layout) + 1 + (4 if dense_resident else 0)


def _host_columns(seg: FrozenSegment, Dpad: int, put,
                  fields: list[str] | None = None):
    """The O(D) per-doc columns every pack uploads — live mask, per-field
    norm bytes, single-valued numeric dv — shared by pack_segment and the
    compaction concat pack (which re-stages ONLY these small columns from
    host; the O(P) postings planes concat device-side)."""
    live_parent = np.zeros(Dpad, dtype=bool)
    live_parent[: seg.doc_count] = seg.live & seg.parent_mask

    norm_bytes = {}
    for f, arr in seg.norms.items():
        if fields is not None and f not in fields:
            continue
        padded = np.zeros(Dpad, dtype=np.uint8)
        padded[: seg.doc_count] = arr
        norm_bytes[f] = put(padded)

    dv_single = {}
    for f, (off, vals) in seg.dv_num.items():
        counts_dv = np.diff(off)
        if counts_dv.max(initial=0) <= 1:
            col = np.full(Dpad, np.nan, dtype=np.float64)
            has = counts_dv == 1
            col[: seg.doc_count][has] = vals
            dv_single[f] = put(col)
    return live_parent, norm_bytes, dv_single


def pack_segment(seg: FrozenSegment, fields: list[str] | None = None,
                 device_put=None) -> PackedSegment:
    """Pack a frozen segment's postings + norms + single-valued numeric columns for
    device execution. `fields` limits norm upload (None = all text fields).
    Breaker-guarded callers (packed_for) reserve pack_estimate_bytes around
    this call — estimate-before-allocate; the pack itself is host-side numpy +
    device_put, never traced."""
    import jax.numpy as jnp

    put = device_put or (lambda x: jnp.asarray(x))

    T = len(seg.post_offsets) - 1
    counts = np.diff(seg.post_offsets)
    nblks = (counts + BLOCK - 1) // BLOCK
    blk_start = np.zeros(T + 1, dtype=np.int64)
    np.cumsum(nblks, out=blk_start[1:])
    NB = int(blk_start[-1])
    # +1 guarantees at least one all-sentinel row past the real blocks — the scoring
    # batch points its padding triples at row NBpad-1, which must never hold postings
    # (shape+layout math shared with pack_estimate_bytes via pack_shape_math)
    NBpad, Dpad, tf_layout = pack_shape_math(seg)

    flat_docs = np.full(NBpad * BLOCK, Dpad, dtype=np.int32)  # pad → out-of-range slot
    flat_freqs = np.zeros(NBpad * BLOCK, dtype=np.float32)
    if len(seg.post_docs):
        # slot of entry j of term t = (blk_start[t]*B) + (j - post_offsets[t])
        slots = expand_ranges(blk_start[:-1] * BLOCK, counts)
        flat_docs[slots] = seg.post_docs
        flat_freqs[slots] = seg.post_freqs

    # block -> owning field ordinal (blocks never span terms, terms never span fields)
    field_names = list(seg.term_dict.keys())
    fid_of_tid = np.full(T, -1, dtype=np.int32)
    for fo, f in enumerate(field_names):
        tids = np.fromiter(seg.term_dict[f].values(), dtype=np.int64,
                           count=len(seg.term_dict[f]))
        fid_of_tid[tids] = fo
    blk_field = np.full(NBpad, -1, dtype=np.int32)
    if NB:
        blk_field[:NB] = np.repeat(fid_of_tid, nblks)

    live_parent, norm_bytes, dv_single = _host_columns(seg, Dpad, put,
                                                       fields=fields)

    # dead/non-parent docs are masked to the sentinel IN the uploaded postings, so no
    # scoring path needs a per-posting live gather; host_docs keeps the raw ids for
    # re-masking when tombstones change
    masked_docs = np.where(live_parent[np.minimum(flat_docs, Dpad - 1)]
                           & (flat_docs < Dpad), flat_docs,
                           Dpad).astype(np.int32, copy=False)

    # quantized tf plane (exact by layout choice: u8/i16 for small-int tf,
    # f32 escape otherwise) + per-posting norm byte of the block's owning
    # field — the two 1-byte planes the sparse scan decodes on device
    flat_tf = flat_freqs.astype(_TF_DTYPE[tf_layout])
    flat_nb = np.zeros(NBpad * BLOCK, dtype=np.uint8)
    fid_per_slot = np.repeat(blk_field, BLOCK)
    real = flat_docs < seg.doc_count
    for fo, fname in enumerate(field_names):
        norms = seg.norms.get(fname)
        if norms is None:
            continue  # norm-less field (meta fields): byte stays 0
        sel = (fid_per_slot == fo) & real
        if sel.any():
            flat_nb[sel] = norms[flat_docs[sel]]

    return PackedSegment(
        gen=seg.gen,
        doc_count=seg.doc_count,
        doc_pad=Dpad,
        blk_docs=put(masked_docs.reshape(NBpad, BLOCK)),
        blk_tf=put(flat_tf.reshape(NBpad, BLOCK)),
        blk_nb=put(flat_nb.reshape(NBpad, BLOCK)),
        tf_layout=tf_layout,
        tf_integral=tf_plane_integral(seg.post_freqs, tf_layout),
        term_blk_start=blk_start,
        live_parent=put(live_parent),
        norm_bytes=norm_bytes,
        dv_single=dv_single,
        host_docs=flat_docs,
        host_freqs=flat_freqs,
        blk_field=blk_field,
        field_names=field_names,
    )


# ---------------------------------------------------------------------------
# compaction concat pack: a merged segment's planes from its sources' planes
# ---------------------------------------------------------------------------

# per-slot transient allowance for the concat program's live gather/select
# buffers on device (a handful of [NB, B] i32/f32 temporaries alive at the
# fused program's peak, amortized per output slot)
CONCAT_TRANSIENT_SLOT_BYTES = 8

_TF_RANK = {TF_U8: 0, TF_I16: 1, TF_F32: 2}


def concat_source_packs(sources) -> list[PackedSegment] | None:
    """The sources' resident packs when the compaction concat path is legal,
    else None (callers fall back to the host-staged pack_segment):

    - every source must be fully live (a tombstoned source's postings are
      dropped by merge_segments, so j-th-posting alignment breaks) and its
      pack resident + current (no pending remask);
    - every source's tf plane must be integral (merge rebuilds freq as the
      position count — fractional f32 tf would diverge bitwise).
    """
    packs = []
    for src in sources:
        cache = getattr(src, "_device_cache", None)
        packed = cache.get("packed") if cache is not None else None
        if packed is None or cache.get("live") is None:
            return None
        if not bool(src.live.all()):
            return None
        if not packed.tf_integral:
            return None
        packs.append(packed)
    return packs


def concat_estimate_bytes(merged: FrozenSegment, sources) -> int:
    """Host-staging + device-allocation bytes pack_segment_concat will use —
    the fielddata-breaker estimate for the compaction pack, exact for the
    concat layout the way pack_estimate_bytes is for the staged one. The
    O(P) postings planes are DEVICE outputs plus retained host copies (for
    future remasks / the lazy dense plane) — the host→device upload is only
    the O(NB + W·T + D) tables and columns, which is the whole point."""
    NBpad, Dpad, layout = pack_shape_math(merged)
    tf_b = tf_plane_itemsize(layout)
    W = len(sources)
    T = len(merged.post_offsets) - 1
    n_norm_fields = len(merged.norms)
    n_dv = len(merged.dv_num)
    # retained host planes + device output planes + fused-program transients
    per_slot = (4 + 4) + (4 + tf_b + 1) + CONCAT_TRANSIENT_SLOT_BYTES
    # + blk_term/blk_j0 rows, the [W+1,T] cum + [W,T] start tables (host
    # build + device copy), and the Dpad-wide masks/columns
    return (NBpad * BLOCK * per_slot + NBpad * 4 * 2
            + (2 * W + 1) * T * 4 * 2 + Dpad * 2
            + Dpad * n_norm_fields + Dpad * 8 * n_dv)


def pack_segment_concat(merged: FrozenSegment,
                        sources) -> PackedSegment | None:
    """Assemble a merged segment's pack by CONCATENATING its sources'
    already-resident device planes — re-blocked to the merged term layout by
    one fused gather/select program (ops/scoring.concat_pack_planes), tf
    rungs widened per the choose_tf_layout ladder — instead of re-staging
    O(postings) bytes from host. Legal exactly when merge preserved every
    posting in source order (concat_source_packs); the result is bitwise
    identical to pack_segment(merged) by construction, pinned by the writes
    parity tests. Returns None when ineligible or when the layout cross-check
    fails (callers fall back to the staged pack)."""
    import jax.numpy as jnp

    from ..common.jaxenv import compile_tag
    from .scoring import concat_pack_planes

    packs = concat_source_packs(sources)
    if packs is None or not len(merged.post_docs):
        return None
    if merged.doc_count != sum(s.doc_count for s in sources):
        return None  # docs were dropped: source order ≠ merged order
    NBpad, Dpad, layout = pack_shape_math(merged)
    widest = max((p.tf_layout for p in packs), key=lambda l: _TF_RANK[l])
    if layout != widest:
        return None  # freq drift between sources and merged CSR: re-stage

    T = len(merged.post_offsets) - 1
    counts_m = np.diff(merged.post_offsets)
    W = len(sources)
    # per (source, merged-term): posting count + the source's block start.
    # Terms resolve by name through each source's term dict (O(T·W) dict
    # lookups — proportional to vocabulary, not postings)
    cnt = np.zeros((W, T), dtype=np.int32)
    starts = np.zeros((W, T), dtype=np.int32)
    for s, (src, packed_s) in enumerate(zip(sources, packs)):
        src_counts = np.diff(src.post_offsets)
        for f, td_m in merged.term_dict.items():
            td_s = src.term_dict.get(f)
            if not td_s:
                continue
            for term, tid_m in td_m.items():
                tid_s = td_s.get(term)
                if tid_s is not None:
                    cnt[s, tid_m] = src_counts[tid_s]
                    starts[s, tid_m] = packed_s.term_blk_start[tid_s]
    cum = np.zeros((W + 1, T), dtype=np.int32)
    np.cumsum(cnt, axis=0, out=cum[1:])
    if not np.array_equal(cum[-1], counts_m):
        return None  # per-term counts disagree with the merged CSR

    nblks = (counts_m + BLOCK - 1) // BLOCK
    blk_start = np.zeros(T + 1, dtype=np.int64)
    np.cumsum(nblks, out=blk_start[1:])
    NB = int(blk_start[-1])
    blk_term = np.zeros(NBpad, dtype=np.int32)
    # pad rows: a huge within-term offset makes every select miss, so the
    # outputs keep their sentinel/zero initializers — same bytes the staged
    # pack writes there
    blk_j0 = np.full(NBpad, 1 << 30, dtype=np.int32)
    if NB:
        blk_term[:NB] = np.repeat(np.arange(T, dtype=np.int32), nblks)
        blk_j0[:NB] = ((np.arange(NB, dtype=np.int64)
                        - np.repeat(blk_start[:-1], nblks))
                       * BLOCK).astype(np.int32)
    bases = np.asarray(
        np.cumsum([0] + [s.doc_count for s in sources[:-1]]), dtype=np.int32)
    doc_pads = np.asarray([p.doc_pad for p in packs], dtype=np.int32)

    with compile_tag("compact"):
        out_docs, out_tf, out_nb = concat_pack_planes(
            jnp.asarray(blk_term), jnp.asarray(blk_j0), jnp.asarray(cum),
            jnp.asarray(starts), jnp.asarray(bases), jnp.asarray(doc_pads),
            tuple(p.blk_docs for p in packs),
            tuple(p.blk_tf for p in packs),
            tuple(p.blk_nb for p in packs),
            doc_pad_new=Dpad, tf_layout=layout)

    # retained host copies (live-mask remasks, the lazy dense plane) — host
    # numpy only, never uploaded here
    flat_docs = np.full(NBpad * BLOCK, Dpad, dtype=np.int32)
    flat_freqs = np.zeros(NBpad * BLOCK, dtype=np.float32)
    slots = expand_ranges(blk_start[:-1] * BLOCK, counts_m)
    flat_docs[slots] = merged.post_docs
    flat_freqs[slots] = merged.post_freqs

    field_names = list(merged.term_dict.keys())
    fid_of_tid = np.full(T, -1, dtype=np.int32)
    for fo, f in enumerate(field_names):
        tids = np.fromiter(merged.term_dict[f].values(), dtype=np.int64,
                           count=len(merged.term_dict[f]))
        fid_of_tid[tids] = fo
    blk_field = np.full(NBpad, -1, dtype=np.int32)
    if NB:
        blk_field[:NB] = np.repeat(fid_of_tid, nblks)

    put = jnp.asarray
    live_parent, norm_bytes, dv_single = _host_columns(merged, Dpad, put)
    return PackedSegment(
        gen=merged.gen,
        doc_count=merged.doc_count,
        doc_pad=Dpad,
        blk_docs=out_docs,
        blk_tf=out_tf,
        blk_nb=out_nb,
        tf_layout=layout,
        tf_integral=True,  # gated on every source being integral
        term_blk_start=blk_start,
        live_parent=put(live_parent),
        norm_bytes=norm_bytes,
        dv_single=dv_single,
        host_docs=flat_docs,
        host_freqs=flat_freqs,
        blk_field=blk_field,
        field_names=field_names,
    )


def ensure_blk_freqs(packed: PackedSegment, breaker=None):
    """Lazily fault in the dense-fallback f32 freqs plane (the `blk_freqs`-drop
    rule: pack_segment no longer uploads it, so sparse-only segments stay at
    the quantized 6 B/posting). Idempotent; a concurrent double-upload is
    benign (same values, last assignment wins).

    `breaker` (fielddata) reserves the plane's bytes around the upload — the
    same transient estimate-before-allocate contract as packed_for, and the
    same graceful degradation: a trip raises CircuitBreakingError and serving
    falls back to the host scorer. The dense call sites in search/execute.py
    pass it; the unaccounted default exists only for the direct-kernel tests
    and for segments whose plane is already resident."""
    prof = _profile.current()
    if packed.blk_freqs is None:
        import jax.numpy as jnp

        with reserve(breaker, packed.host_freqs.nbytes, "<dense_freqs>"):
            packed.blk_freqs = jnp.asarray(
                packed.host_freqs.reshape(-1, BLOCK))
        if prof is not None:
            prof.event("blk_freqs", cache="fault",
                       bytes=int(packed.host_freqs.nbytes))
    elif prof is not None:
        prof.event("blk_freqs", cache="resident")
    return packed.blk_freqs


def agg_doc_rows(seg: FrozenSegment, field: str) -> np.ndarray | None:
    """Per-doc metric folds of one numeric column: float32 [5, doc_count] rows
    (count, sum, min, max, sumsq), or None when the column is INTEGER-valued but
    not exactly float32-representable (longs/dates past 2^24: integers are
    semantically exact — epoch millis shifted by f32 rounding would be a wrong
    answer, so those columns stay on the exact host collectors). Fractional
    columns are inherently approximate reals and take the f32 kernel (~1e-7
    relative rounding, same as an ES `float`-typed field).

    Multi-valued docs fold exactly (cumsum difference / reduceat over the CSR);
    docs with no value carry count 0 and ±inf min/max so the kernel's masked
    reductions ignore them."""
    D = seg.doc_count
    rows = np.zeros((5, D), dtype=np.float32)
    rows[2] = np.inf
    rows[3] = -np.inf
    col = seg.dv_num.get(field)
    if col is None:
        return rows
    off, vals = col
    if len(vals) and not np.array_equal(
            vals.astype(np.float32).astype(np.float64), vals) \
            and np.all(vals == np.floor(vals)):
        return None
    counts = np.diff(off)
    c = np.zeros(len(vals) + 1)
    np.cumsum(vals, out=c[1:])
    sums = c[off[1:]] - c[off[:-1]]
    c2 = np.zeros(len(vals) + 1)
    np.cumsum(np.asarray(vals, dtype=np.float64) ** 2, out=c2[1:])
    sumsq = c2[off[1:]] - c2[off[:-1]]
    has = counts > 0
    if len(vals):
        # reduceat over the value-holding docs' true start offsets: consecutive
        # starts delimit exactly each such doc's value run (clipping off[:-1]
        # would TRUNCATE the previous doc's run when trailing docs are empty)
        starts = off[:-1][has]
        rows[2][has] = np.minimum.reduceat(vals, starts)
        rows[3][has] = np.maximum.reduceat(vals, starts)
    rows[0] = counts
    rows[1] = sums
    rows[4] = sumsq
    return rows


def _pad_agg_rows(rows: np.ndarray, doc_pad: int, base: int = 0,
                  out: np.ndarray | None = None) -> np.ndarray:
    """Place [5, D] rows at `base` inside a [5, doc_pad] canvas (empty slots:
    count 0, ±inf min/max)."""
    if out is None:
        out = np.zeros((5, doc_pad), dtype=np.float32)
        out[2] = np.inf
        out[3] = -np.inf
    out[:, base: base + rows.shape[1]] = rows
    return out


def ensure_agg_rows(seg: FrozenSegment, packed: PackedSegment, fields: list[str],
                    breaker=None):
    """Device-resident [F, 5, Dpad] stack for `fields`, or None when any column
    is not f32-exact (callers fall back to the host collectors). Per-field rows
    cache HOST-side; only the per-tuple device stacks (FIFO-bounded) hold device
    memory — mirroring ensure_mesh_agg_stack.

    `breaker` (fielddata) reserves the [F, 5, Dpad] f32 stack (host rows +
    device copy) before it is built — the per-doc fold columns are the
    fielddata-load analogue on this engine."""
    import jax.numpy as jnp

    key = tuple(fields)
    stack = packed.agg_stacks.get(key)
    if stack is not None:
        return stack
    est = len(fields) * 5 * packed.doc_pad * 4 * 2  # host rows + device stack
    with reserve(breaker, est, f"<agg_rows>{list(fields)}"):
        for f in fields:
            if f not in packed.agg_rows:
                rows = agg_doc_rows(seg, f)
                packed.agg_rows[f] = (None if rows is None
                                      else _pad_agg_rows(rows, packed.doc_pad))
        if any(packed.agg_rows[f] is None for f in fields):
            return None
        stack = jnp.asarray(np.stack([packed.agg_rows[f] for f in fields])
                            if fields else np.zeros((0, 5, packed.doc_pad), np.float32))
        while len(packed.agg_stacks) >= 8:
            packed.agg_stacks.pop(next(iter(packed.agg_stacks)))
        packed.agg_stacks[key] = stack
    return stack


# ---------------------------------------------------------------------------
# device-resident filter/bitset cache
# ---------------------------------------------------------------------------


class _SegmentFilterMasks:
    """Per-segment holder of device-resident filter masks, living in
    `seg._device_cache["filter_masks"]`. Copy-on-write tombstoning
    (FrozenSegment.with_deletes) shallow-copies the device cache, so views of
    one segment SHARE this holder — eviction therefore keys on the holder
    object (is it still referenced by any live segment?), not on the segment
    wrapper identity. Filter masks are live-mask independent (filters gate
    MATCHING; liveness is the kernel's separate live_parent gate), so sharing
    across tombstone views is exact."""

    __slots__ = ("masks", "seen", "bytes", "dead")

    def __init__(self):
        self.masks: dict = {}  # filter key -> (device bool [Dpad], nbytes)
        self.seen: dict = {}  # filter key -> sighting count
        self.bytes = 0
        self.dead = False  # evicted with its segment: never re-stores


class DeviceFilterCache:
    """Node-level accounting + policy for per-segment device filter masks.

    Hot filters keep their packed per-segment doc masks resident in HBM,
    keyed by (segment identity, filter fingerprint — `Filter.key()`), so a
    cached filtered plan skips host mask construction AND the host→device
    mask transfer entirely; the dense kernel consumes the resident row with
    bitwise-identical scores (the mask VALUES are identical — filters gate
    matching, never scoring). Population is sighting-based: the first
    evaluation of a filter on a segment only counts it (the Profile API's
    `bool_filter_clause` fallback counter motivated exactly this "which
    filters are hot" signal); the `min_sightings`-th (default 2nd) builds the
    padded row host-side OUTSIDE any lock, `jax.device_put`s it once under
    the transfer guard, charges the fielddata breaker (next to
    `packed_resident_bytes` — this is device-resident state), and publishes
    under the leaf lock. Masks are evicted with their segment on
    refresh/merge (the engine's view listeners) and by
    `POST /_cache/clear?filter=true`, releasing the breaker bytes.

    Lock discipline: `_lock` is a LEAF guarding dicts and counters only —
    the mask build and the device_put always happen outside it (the
    build-outside/publish-under idiom, pinned by the tpulint TPU004
    fixtures)."""

    def __init__(self, settings=None, breaker=None):
        from ..common.settings import Settings

        settings = settings or Settings.EMPTY
        self.enabled = bool(
            settings.get_bool("indices.filter_cache.enable", True))
        self.min_sightings = max(1, int(
            settings.get_int("indices.filter_cache.min_sightings", 2)))
        self.breaker = breaker
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.builds = 0
        self.evictions = 0
        self.rejections = 0  # breaker-tripped stores
        self._bytes = 0
        self._masks = 0

    @staticmethod
    def _holder(seg) -> _SegmentFilterMasks:
        holder = seg._device_cache.get("filter_masks")
        if holder is None:
            # benign setdefault race: both racers publish an empty holder,
            # one wins, neither has accounted bytes yet
            holder = seg._device_cache.setdefault("filter_masks",
                                                  _SegmentFilterMasks())
        return holder

    def lookup(self, seg, key: str):
        """The resident device row for (segment, filter key), or None. Counts
        the sighting — the miss path's counter is what promotes a filter to
        resident on its next appearance."""
        holder = self._holder(seg)
        prof = _profile.current()
        with self._lock:
            entry = holder.masks.get(key)
            if entry is not None:
                self.hits += 1
            else:
                self.misses += 1
                holder.seen[key] = holder.seen.get(key, 0) + 1
        if prof is not None:
            prof.event("filter_cache", cache="hit" if entry else "miss",
                       filter=key)
        return entry[0] if entry is not None else None

    def maybe_store(self, seg, key: str, padded_mask):
        """Promote a freshly evaluated filter mask to device residency when
        it has reached `min_sightings`. `padded_mask` is the host bool [Dpad]
        row built OUTSIDE any lock; the device_put happens here, also outside
        the leaf lock, and only the publish goes under it. Returns the device
        row (freshly stored or a concurrent winner's), or None when the
        filter is still cold / the tier is off / the breaker tripped."""
        if not self.enabled:
            return None
        holder = self._holder(seg)
        with self._lock:
            if holder.dead:
                return None  # segment already evicted: a stale searcher
                # must not repopulate bytes nobody will ever release
            entry = holder.masks.get(key)
            if entry is not None:
                return entry[0]
            if holder.seen.get(key, 0) < self.min_sightings:
                return None
        import jax

        nbytes = int(padded_mask.nbytes)
        if self.breaker is not None:
            try:
                self.breaker.add_estimate_and_maybe_break(
                    nbytes, "<filter_mask>")
            except CircuitBreakingError:
                self.rejections += 1  # out of fielddata budget: the host
                return None           # mask still serves this request
        row = jax.device_put(padded_mask)  # the ONE transfer, outside _lock
        release = 0
        with self._lock:
            if holder.dead:
                release = nbytes
                row = None
            else:
                entry = holder.masks.get(key)
                if entry is not None:
                    release = nbytes  # concurrent winner: keep theirs
                    row = entry[0]
                else:
                    holder.masks[key] = (row, nbytes)
                    holder.bytes += nbytes
                    self._bytes += nbytes
                    self._masks += 1
                    self.builds += 1
        if release and self.breaker is not None:
            self.breaker.release(release)
        if row is not None and release == 0:
            prof = _profile.current()
            if prof is not None:
                prof.event("filter_cache", cache="build", filter=key,
                           bytes=nbytes)
        return row

    # -- eviction ------------------------------------------------------------
    def evict_dropped(self, dropped, live) -> int:
        """Evict the masks of segments a new view dropped. `live` is the new
        view's segment list: a with_deletes view SHARES its predecessor's
        holder, so a holder still referenced by any live segment is retained
        (same filters, same postings — only tombstones changed)."""
        live_holders = {id(s._device_cache.get("filter_masks"))
                        for s in live
                        if s._device_cache.get("filter_masks") is not None}
        released = 0
        evicted = 0
        for seg in dropped:
            holder = seg._device_cache.get("filter_masks")
            if holder is None:
                # plant a DEAD holder so a straggler request still holding
                # the old searcher can't create a fresh one after this
                # eviction ran (its stores would be unreleasable bytes)
                dead = _SegmentFilterMasks()
                dead.dead = True
                holder = seg._device_cache.setdefault("filter_masks", dead)
                if holder is dead:
                    continue  # nothing was resident; the tombstone is planted
            if id(holder) in live_holders:
                continue
            with self._lock:
                if holder.dead:
                    continue
                holder.dead = True
                n = len(holder.masks)
                released += holder.bytes
                self._bytes -= holder.bytes
                self._masks -= n
                self.evictions += n
                evicted += n
                holder.masks.clear()
                holder.seen.clear()
                holder.bytes = 0
        if released and self.breaker is not None:
            self.breaker.release(released)
        return evicted

    def clear_segment(self, seg) -> int:
        """`POST /_cache/clear?filter=true` on a LIVE segment: drop its
        resident masks and sighting counters (rebuildable — the holder stays
        alive), returning the breaker bytes."""
        holder = seg._device_cache.get("filter_masks")
        if holder is None:
            return 0
        released = 0
        evicted = 0
        with self._lock:
            n = len(holder.masks)
            released = holder.bytes
            self._bytes -= holder.bytes
            self._masks -= n
            self.evictions += n
            evicted = n
            holder.masks.clear()
            holder.seen.clear()
            holder.bytes = 0
        if released and self.breaker is not None:
            self.breaker.release(released)
        return evicted

    # -- warmer integration --------------------------------------------------
    def hot_keys(self, segs) -> set:
        """The filter keys that earned residency (or at least the sighting
        threshold) on any of `segs` — what the warmer carries from a dropped
        view's holders onto the new view's segments."""
        keys: set = set()
        with self._lock:
            for seg in segs:
                holder = seg._device_cache.get("filter_masks")
                if holder is None:
                    continue
                keys.update(holder.masks.keys())
                keys.update(k for k, c in holder.seen.items()
                            if c >= self.min_sightings)
        return keys

    def seed(self, seg, keys) -> int:
        """Warmer pre-seeding: mark `keys` as already-seen on a segment so
        the NEXT evaluation (the warm replay, or the first live sighting)
        promotes the mask to device residency immediately instead of paying
        the min_sightings ramp on every fresh delta segment. Counter work
        only — no masks are built or uploaded here."""
        if not self.enabled or not keys:
            return 0
        holder = self._holder(seg)
        seeded = 0
        with self._lock:
            if holder.dead:
                return 0
            for k in keys:
                if k not in holder.masks \
                        and holder.seen.get(k, 0) < self.min_sightings:
                    holder.seen[k] = self.min_sightings
                    seeded += 1
        return seeded

    # -- observability -------------------------------------------------------
    def hit_rate(self) -> float:
        n = self.hits + self.misses
        return (self.hits / n) if n else 0.0

    def stats(self) -> dict:
        with self._lock:
            return {
                "enabled": self.enabled,
                "memory_size_in_bytes": self._bytes,
                "masks": self._masks,
                "hits": self.hits,
                "misses": self.misses,
                "builds": self.builds,
                "evictions": self.evictions,
                "rejections": self.rejections,
                "hit_rate": round(self.hit_rate(), 4),
            }


TFN_BM25 = 0  # tfn = f / (f + cache[norm_byte])        — weight multiplies outside
TFN_TFIDF = 1  # tfn = sqrt(f) * cache[norm_byte]


def tfn_values(freqs: np.ndarray, nb: np.ndarray, cache: np.ndarray,
               mode: int) -> np.ndarray:
    """The per-posting tfn formula — the single HOST definition of what the
    quantized scan computes on device (ops/scoring.sparse_candidates decodes
    blk_tf/blk_nb and applies exactly this, f32 op order included). Kept as
    the reference the parity tests and the bench check against."""
    cv = cache[nb]
    if mode == TFN_BM25:
        return (freqs / (freqs + cv)).astype(np.float32)
    return np.sqrt(freqs, dtype=np.float32) * cv


def ensure_sim_tables(packed: PackedSegment,
                      tables: dict[str, tuple[int, np.ndarray]]) -> SimTables:
    """Ensure the stacked per-field similarity LUTs for the given tables
    ({field: (TFN_* mode, float32[256] cache)}) and return the SimTables whose
    `fid` maps fields to cache rows for this launch.

    This replaced the per-posting tfn bake: the tf→tfn normalization now
    happens INSIDE the sparse scan (quantized tf + norm byte + this LUT), so a
    cache-table change — for BM25 whenever avgdl (sum_ttf/max_doc) moves, i.e.
    after indexing activity — costs a 1 KB/field table swap instead of a numpy
    pass over every posting plus a full-plane HBM upload. Fields accumulate
    across calls (stable fid rows per merged set); callers must use the
    RETURNED object's fid/caches for the launch they plan — a concurrent
    re-ensure swaps packed.sim but never mutates an existing SimTables."""
    prof = _profile.current()
    cur = packed.sim
    if cur is not None and all(
        f in cur.key and cur.key[f] == (mode, cache.tobytes())
        for f, (mode, cache) in tables.items()
    ):
        if prof is not None:
            prof.event("sim_tables", cache="hit", fields=len(cur.fields))
        return cur
    import jax.numpy as jnp

    merged = dict(cur.key) if cur is not None else {}
    for f, (mode, cache) in tables.items():
        merged[f] = (mode, cache.tobytes())
    fields = list(merged.keys())
    if fields:
        modes = np.array([merged[f][0] for f in fields], dtype=np.int32)
        caches = np.stack([np.frombuffer(merged[f][1], dtype=np.float32)
                           for f in fields])
    else:
        # fieldless batch (e.g. empty analyzed query): one neutral row so the
        # kernel ABI keeps its [F, 256] shape — only padding slots (zeroed by
        # the valid mask) ever read it
        modes = np.zeros(1, dtype=np.int32)
        caches = np.ones((1, 256), dtype=np.float32)
    sim = SimTables(fields=fields, fid={f: i for i, f in enumerate(fields)},
                    modes=jnp.asarray(modes), caches=jnp.asarray(caches),
                    key=merged)
    packed.sim = sim
    if prof is not None:
        prof.event("sim_tables", cache="swap", fields=len(fields))
    return sim


# coordinates the per-segment pack/remask futures: a LEAF lock guarding only
# _device_cache dict reads/writes — the pack compute, every device_put, and
# every Future wait happen OUTSIDE it (the PR-6 discipline). One module-level
# lock instead of a per-segment dial lock: it is only ever taken on the cold
# miss/publish paths, never on the warmed packed-and-live fast path
_PACK_LOCK = threading.Lock()


def begin_warm(seg: FrozenSegment):
    """Install the in-flight marker for a segment whose pack (or remask) is
    about to be scheduled off the query path. Returns the Future a racing
    search will wait on, or None when the segment is already fully live or
    another pack is in flight. Dict work only — safe to call from an engine
    view listener (which runs under the engine lock).

    The marker is CLAIMABLE: the pack is performed by whoever claims it
    first — normally the scheduled warmer/merge task, but a search (or a
    warm query) that arrives before the task starts STEALS the work and
    packs inline, resolving the same future. Waiting therefore only ever
    happens on a pack that is actively RUNNING on some thread, which
    completes without needing any pool slot — a waiter can never deadlock
    behind pack work queued on its own pool."""
    from concurrent.futures import Future

    cache = seg._device_cache
    with _PACK_LOCK:
        if cache.get("packed") is not None and cache.get("live") is not None:
            return None
        if cache.get("pack_future") is not None:
            return None
        fut: Future = Future()
        cache["pack_future"] = fut
        cache["pack_claimed"] = False
        return fut


def cancel_warm(seg: FrozenSegment, fut) -> None:
    """Withdraw a begin_warm future whose pool submission was rejected
    (node shutting down / saturated): clear the marker and resolve the
    future with None so any racer that started waiting re-enters the
    packed_for loop and packs inline with its own budget. A no-op when a
    racer already claimed the work — the claimant owns the future now."""
    cache = seg._device_cache
    with _PACK_LOCK:
        if cache.get("pack_future") is not fut or cache.get("pack_claimed"):
            return
        cache.pop("pack_future", None)
        cache.pop("pack_claimed", None)
    if not fut.done():
        fut.set_result(None)


def run_warm(seg: FrozenSegment, fut, breaker=None,
             owner: str | None = None):
    """Execute the pack/remask a `begin_warm` future stands for — the
    warmer/merge pool worker body. Returns immediately (None) when a racing
    search already claimed the work: the claimant resolves the future on
    its own thread, so parking this pool slot to wait would buy nothing.
    Exceptions (a fielddata breaker trip, a device error) resolve the
    future so query-path waiters degrade exactly as an inline pack failure
    would, and the marker is cleared so a later query retries with its own
    budget."""
    cache = seg._device_cache
    with _PACK_LOCK:
        if cache.get("pack_future") is not fut or cache.get("pack_claimed"):
            return None
        cache["pack_claimed"] = True
    return _perform_pack(seg, fut, breaker, owner)


def _perform_pack(seg: FrozenSegment, fut, breaker,
                  owner: str | None) -> PackedSegment:
    """Pack (full, delta, or compaction-concat) or remask one segment and
    publish under the leaf lock. The caller owns `fut` (installed in
    seg._device_cache["pack_future"]); every waiter observes the publish
    through it."""
    import jax.numpy as jnp

    cache = seg._device_cache
    prof = _profile.current()
    try:
        # seeded device-error seam (transport/faults.DEVICE_FAULTS): one
        # plain attr read disarmed; armed, the pack fails HERE — before any
        # publish — so the existing exception path below proves no
        # half-packed PackedSegment ever lands in the cache
        if _DEVICE_FAULTS.active:
            _DEVICE_FAULTS.check(f"pack:{owner}")
        packed: PackedSegment | None = cache.get("packed")
        if packed is None:
            hint = cache.get("pack_hint") or {}
            kind = hint.get("kind", "pack")
            sources = hint.get("sources")
            t0 = time.monotonic()
            new_packed = None
            method = "staged" if kind == "compact" else None
            if kind == "compact" and sources:
                with reserve(breaker, concat_estimate_bytes(seg, sources),
                             f"<segment_compact>[{seg.gen}]"):
                    new_packed = pack_segment_concat(seg, sources)
                if new_packed is not None:
                    method = "concat"
            if new_packed is None:
                with reserve(breaker, pack_estimate_bytes(seg),
                             f"<segment_pack>[{seg.gen}]"):
                    new_packed = pack_segment(seg)
            with _PACK_LOCK:
                cache["packed"] = new_packed
                cache["live"] = True
                cache.pop("pack_future", None)
                cache.pop("pack_claimed", None)
                cache.pop("pack_hint", None)  # drops the source refs
            ms = (time.monotonic() - t0) * 1000.0
            PACK_LEDGER.record(owner, seg.gen, ms,
                               packed_resident_bytes(new_packed),
                               new_packed.tf_layout, kind=kind, method=method)
            if prof is not None:
                prof.event("packed_segment", gen=int(seg.gen), cache=kind,
                           ms=round(ms, 4),
                           resident_bytes=int(
                               packed_resident_bytes(new_packed)),
                           tf_layout=new_packed.tf_layout)
            fut.set_result(new_packed)
            return new_packed
        # remask: the pack is resident but the view's tombstones moved
        t0 = time.monotonic()
        live_parent = np.zeros(packed.doc_pad, dtype=bool)
        live_parent[: seg.doc_count] = seg.live & seg.parent_mask
        lp_dev = jnp.asarray(live_parent)
        # postings carry the live mask inline (sparse path has no per-posting
        # live gather) — re-mask from the raw host copy
        masked = np.where(live_parent[np.minimum(packed.host_docs,
                                                 packed.doc_pad - 1)]
                          & (packed.host_docs < packed.doc_pad),
                          packed.host_docs,
                          packed.doc_pad).astype(np.int32, copy=False)
        docs_dev = jnp.asarray(masked.reshape(-1, BLOCK))
        with _PACK_LOCK:
            packed.live_parent = lp_dev
            packed.blk_docs = docs_dev
            cache["live"] = True
            cache.pop("pack_future", None)
            cache.pop("pack_claimed", None)
        PACK_LEDGER.record(owner, seg.gen, (time.monotonic() - t0) * 1000.0,
                           packed_resident_bytes(packed), packed.tf_layout,
                           kind="remask")
        if prof is not None:
            prof.event("packed_segment", gen=int(seg.gen),
                       cache="live_remask")
        fut.set_result(packed)
        return packed
    except BaseException as e:  # noqa: BLE001 — waiters must never hang
        if isinstance(e, Exception):
            _tag_domain(e, f"pack:{owner}")  # fault-domain attribution
        with _PACK_LOCK:
            if cache.get("pack_future") is fut:
                cache.pop("pack_future", None)
                cache.pop("pack_claimed", None)
        fut.set_exception(e)
        raise


def packed_for(seg: FrozenSegment, breaker=None,
               owner: str | None = None) -> PackedSegment:
    """Per-segment cached packing; refreshes the live mask when tombstones
    changed. The warmed fast path is one unlocked dict read; the cold path
    coordinates through a per-segment in-flight Future so a search racing a
    scheduled warmer/merge pack WAITS for it (the PR-6 mesh `_executor_for`
    idiom) instead of duplicating the work — in the warmed continuous-
    indexing loop, every query-path call is a cache hit and all pack work
    lands on the warmer/merge pools (PACK_LEDGER pool attribution).

    `breaker` (the node's fielddata child) is consulted ONLY when this call
    ends up owning the pack: the estimate covers the pack's host staging +
    device upload and is released once the pack lands — transient
    accounting, so a drained node reads 0. A trip raises
    CircuitBreakingError; serving falls back to the host scorer (the one
    graceful-degradation edge the reference lacks). A failed WARM pack
    propagates the same way to any waiter, and later calls retry inline.

    `owner` (the index name, from ShardContext) attributes the pack's wall
    time to the capacity ledger (PACK_LEDGER). The pack/remask paths are
    cold by construction (once per segment per view), so timing them always
    is within the zero-added-clocks contract — the cache-HIT path stays
    clock-free."""
    cache = seg._device_cache
    packed: PackedSegment | None = cache.get("packed")
    if packed is not None and cache.get("live") is not None:
        prof = _profile.current()
        if prof is not None:
            prof.event("packed_segment", gen=int(seg.gen), cache="hit")
        return packed
    while True:
        with _PACK_LOCK:
            packed = cache.get("packed")
            if packed is not None and cache.get("live") is not None:
                return packed
            fut = cache.get("pack_future")
            if fut is None:
                from concurrent.futures import Future

                fut = Future()
                cache["pack_future"] = fut
                cache["pack_claimed"] = True
                own = True
            elif not cache.get("pack_claimed"):
                # a scheduled warm pack that hasn't STARTED yet: steal it —
                # this call performs the pack inline and resolves the shared
                # future (run_warm sees the claim and returns). Waiting is
                # therefore reserved for packs actively running on another
                # thread, which finish without needing any pool slot — a
                # warm query on the warmer pool can never deadlock behind
                # pack tasks queued on that same pool
                cache["pack_claimed"] = True
                own = True
            else:
                own = False
        if own:
            return _perform_pack(seg, fut, breaker, owner)
        # another thread is actively packing: wait OUTSIDE every lock; its
        # failure (e.g. breaker trip) propagates here and degrades to the
        # host scorer exactly like an inline trip
        prof = _profile.current()
        if prof is not None:
            prof.event("packed_segment", gen=int(seg.gen), cache="pack_wait")
        fut.result()
        with _PACK_LOCK:
            # defensive: a resolved future must never be waited on twice
            # (guarantees loop progress even if a publish went missing)
            if cache.get("pack_future") is fut:
                cache.pop("pack_future", None)
                cache.pop("pack_claimed", None)
