"""Device-resident packed postings.

This is the TPU replacement for Lucene's on-heap postings traversal (SURVEY.md §2.8:
"device-resident packed postings blocks, vmapped BM25 scoring, lax.top_k"). A frozen
segment's CSR postings are re-blocked into fixed-shape device tensors:

    blk_docs  : int32 [NB, B]   — local doc ids, padded with `doc_pad` (out of range)
    blk_freqs : float32 [NB, B] — term frequencies, padded with 0

Each term owns a contiguous run of blocks (`term_blk_start[t] .. term_blk_start[t+1]`),
so a query term's postings are a static-shape slice of block indices — the host builds
flat (query, block, weight) triples and the scoring kernel is pure gather + FMA +
scatter-add, no data-dependent shapes (XLA-friendly by construction).

Shapes are padded to power-of-two buckets (NB rows, D docs) so recompilation stops once
the shape buckets stabilize — segment churn from NRT refresh reuses cached executables.

Norm bytes stay uint8 on device; similarity-specific 256-entry decode tables are gathered
at score time, preserving Lucene's exact 1-byte quantization.
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field

import numpy as np

from ..common.breaker import reserve
from ..index.segment import FrozenSegment

BLOCK = 128  # lane width


def _pow2_bucket(n: int, minimum: int = 128) -> int:
    b = minimum
    while b < n:
        b *= 2
    return b


def expand_ranges(starts: np.ndarray, counts: np.ndarray) -> np.ndarray:
    """Flatten half-open ranges [starts[i], starts[i]+counts[i]) into one int64 array
    — the CSR expansion idiom (repeat + within-range offset) shared by segment
    packing, the mesh assembler, and the bench."""
    total = int(counts.sum())
    excl = np.zeros(len(counts), dtype=np.int64)
    if len(counts) > 1:
        np.cumsum(counts[:-1], out=excl[1:])
    within = np.arange(total, dtype=np.int64) - np.repeat(excl, counts)
    return np.repeat(np.asarray(starts, dtype=np.int64), counts) + within


@dataclass
class PackedSegment:
    """Device tensors + host lookup tables for one frozen segment."""

    gen: int
    doc_count: int  # real docs
    doc_pad: int  # padded D (bucketed)
    blk_docs: object  # jnp int32 [NBpad, B] — dead/non-parent docs masked to doc_pad
    blk_freqs: object  # jnp float32 [NBpad, B]
    term_blk_start: np.ndarray  # host int64 [T+1]
    live_parent: object  # jnp bool [Dpad] — live & parent (searchable docs)
    norm_bytes: dict  # field -> jnp uint8 [Dpad]
    dv_single: dict = dc_field(default_factory=dict)  # field -> jnp float32/float64 [Dpad] single-valued fast path (NaN missing)
    live_version: int = 0
    # sparse-path state (see ops/scoring.py score_sparse_batch): tfn = the
    # weight-independent per-posting term-frequency factor, baked at pack time so the
    # kernel needs NO per-posting norm gathers (the [M·B] random uint8 gather was the
    # measured throughput ceiling: ~70 ms/batch vs ~5 ms for the row gather)
    blk_tfn: object = None  # jnp float32 [NBpad, B] or None until first bake
    tfn_tables: dict = dc_field(default_factory=dict)  # field -> (mode, cache bytes-hash)
    # device metric-agg state: per-doc (count, sum, min, max, sumsq) rows per
    # numeric field, exact for MULTI-valued columns because the per-doc folds
    # happen host-side at build time (ops/scoring.score_agg_batch reduces them
    # under the match mask — SURVEY §5.7 "shard-level parallel reduce")
    agg_rows: dict = dc_field(default_factory=dict)  # field -> HOST f32 [5, Dpad] | None (not f32-exact)
    agg_stacks: dict = dc_field(default_factory=dict)  # fields-tuple -> device [F, 5, Dpad], FIFO-bounded
    bucket_cols: dict = dc_field(default_factory=dict)  # bucket-agg cache key -> device (pair_doc, pair_bucket, zeros[NB])
    # reusable [Qb, TB] staging arrays for the sparse planner (scoring.
    # SparseScratchPool, lazily created) — the per-bucket padding scratch lives
    # WITH the segment cache so warmed repeat batches re-pad in place instead
    # of re-materializing four arrays per bucket per launch
    sparse_scratch: object = None
    # host copies for re-bakes (live-mask refresh / similarity-stats drift)
    host_docs: np.ndarray | None = None  # int32 [NBpad*B] RAW (unmasked) doc ids
    host_freqs: np.ndarray | None = None  # float32 [NBpad*B]
    blk_field: np.ndarray | None = None  # int32 [NBpad] field ordinal per block (-1 pad)
    field_names: list = dc_field(default_factory=list)  # ordinal -> field name

    def blocks_for_term(self, tid: int) -> tuple[int, int]:
        return int(self.term_blk_start[tid]), int(self.term_blk_start[tid + 1])


def pack_estimate_bytes(seg: FrozenSegment) -> int:
    """Host-staging + device-upload bytes pack_segment will allocate — the
    estimate the fielddata breaker checks BEFORE the first np.full. Derived
    from the same shape math as the pack itself (docs+freqs staged host-side
    AND uploaded, plus the Dpad-wide masks/columns)."""
    counts = np.diff(seg.post_offsets)
    nblks = (counts + BLOCK - 1) // BLOCK
    NBpad = _pow2_bucket(int(nblks.sum()) + 1, 64)
    Dpad = _pow2_bucket(max(seg.doc_count, 1), 128)
    n_norm_fields = len(seg.norms)
    n_dv = len(seg.dv_num)
    # (docs i32 + freqs f32) × (host staging + device copy) + live mask +
    # norms u8 + single-valued dv f64 columns
    return (NBpad * BLOCK * 8 * 2 + Dpad * 2
            + Dpad * n_norm_fields + Dpad * 8 * n_dv)


def pack_segment(seg: FrozenSegment, fields: list[str] | None = None,
                 device_put=None) -> PackedSegment:
    """Pack a frozen segment's postings + norms + single-valued numeric columns for
    device execution. `fields` limits norm upload (None = all text fields).
    Breaker-guarded callers (packed_for) reserve pack_estimate_bytes around
    this call — estimate-before-allocate; the pack itself is host-side numpy +
    device_put, never traced."""
    import jax.numpy as jnp

    put = device_put or (lambda x: jnp.asarray(x))

    T = len(seg.post_offsets) - 1
    counts = np.diff(seg.post_offsets)
    nblks = (counts + BLOCK - 1) // BLOCK
    blk_start = np.zeros(T + 1, dtype=np.int64)
    np.cumsum(nblks, out=blk_start[1:])
    NB = int(blk_start[-1])
    # +1 guarantees at least one all-sentinel row past the real blocks — the scoring
    # batch points its padding triples at row NBpad-1, which must never hold postings
    NBpad = _pow2_bucket(NB + 1, 64)
    Dpad = _pow2_bucket(max(seg.doc_count, 1), 128)

    flat_docs = np.full(NBpad * BLOCK, Dpad, dtype=np.int32)  # pad → out-of-range slot
    flat_freqs = np.zeros(NBpad * BLOCK, dtype=np.float32)
    if len(seg.post_docs):
        # slot of entry j of term t = (blk_start[t]*B) + (j - post_offsets[t])
        slots = expand_ranges(blk_start[:-1] * BLOCK, counts)
        flat_docs[slots] = seg.post_docs
        flat_freqs[slots] = seg.post_freqs

    # block -> owning field ordinal (blocks never span terms, terms never span fields)
    field_names = list(seg.term_dict.keys())
    fid_of_tid = np.full(T, -1, dtype=np.int32)
    for fo, f in enumerate(field_names):
        tids = np.fromiter(seg.term_dict[f].values(), dtype=np.int64,
                           count=len(seg.term_dict[f]))
        fid_of_tid[tids] = fo
    blk_field = np.full(NBpad, -1, dtype=np.int32)
    if NB:
        blk_field[:NB] = np.repeat(fid_of_tid, nblks)

    live_parent = np.zeros(Dpad, dtype=bool)
    live_parent[: seg.doc_count] = seg.live & seg.parent_mask

    norm_bytes = {}
    for f, arr in seg.norms.items():
        if fields is not None and f not in fields:
            continue
        padded = np.zeros(Dpad, dtype=np.uint8)
        padded[: seg.doc_count] = arr
        norm_bytes[f] = put(padded)

    dv_single = {}
    for f, (off, vals) in seg.dv_num.items():
        counts_dv = np.diff(off)
        if counts_dv.max(initial=0) <= 1:
            col = np.full(Dpad, np.nan, dtype=np.float64)
            has = counts_dv == 1
            col[: seg.doc_count][has] = vals
            dv_single[f] = put(col)

    # dead/non-parent docs are masked to the sentinel IN the uploaded postings, so no
    # scoring path needs a per-posting live gather; host_docs keeps the raw ids for
    # re-masking when tombstones change
    masked_docs = np.where(live_parent[np.minimum(flat_docs, Dpad - 1)]
                           & (flat_docs < Dpad), flat_docs, Dpad).astype(np.int32)

    return PackedSegment(
        gen=seg.gen,
        doc_count=seg.doc_count,
        doc_pad=Dpad,
        blk_docs=put(masked_docs.reshape(NBpad, BLOCK)),
        blk_freqs=put(flat_freqs.reshape(NBpad, BLOCK)),
        term_blk_start=blk_start,
        live_parent=put(live_parent),
        norm_bytes=norm_bytes,
        dv_single=dv_single,
        host_docs=flat_docs,
        host_freqs=flat_freqs,
        blk_field=blk_field,
        field_names=field_names,
    )


def agg_doc_rows(seg: FrozenSegment, field: str) -> np.ndarray | None:
    """Per-doc metric folds of one numeric column: float32 [5, doc_count] rows
    (count, sum, min, max, sumsq), or None when the column is INTEGER-valued but
    not exactly float32-representable (longs/dates past 2^24: integers are
    semantically exact — epoch millis shifted by f32 rounding would be a wrong
    answer, so those columns stay on the exact host collectors). Fractional
    columns are inherently approximate reals and take the f32 kernel (~1e-7
    relative rounding, same as an ES `float`-typed field).

    Multi-valued docs fold exactly (cumsum difference / reduceat over the CSR);
    docs with no value carry count 0 and ±inf min/max so the kernel's masked
    reductions ignore them."""
    D = seg.doc_count
    rows = np.zeros((5, D), dtype=np.float32)
    rows[2] = np.inf
    rows[3] = -np.inf
    col = seg.dv_num.get(field)
    if col is None:
        return rows
    off, vals = col
    if len(vals) and not np.array_equal(
            vals.astype(np.float32).astype(np.float64), vals) \
            and np.all(vals == np.floor(vals)):
        return None
    counts = np.diff(off)
    c = np.zeros(len(vals) + 1)
    np.cumsum(vals, out=c[1:])
    sums = c[off[1:]] - c[off[:-1]]
    c2 = np.zeros(len(vals) + 1)
    np.cumsum(np.asarray(vals, dtype=np.float64) ** 2, out=c2[1:])
    sumsq = c2[off[1:]] - c2[off[:-1]]
    has = counts > 0
    if len(vals):
        # reduceat over the value-holding docs' true start offsets: consecutive
        # starts delimit exactly each such doc's value run (clipping off[:-1]
        # would TRUNCATE the previous doc's run when trailing docs are empty)
        starts = off[:-1][has]
        rows[2][has] = np.minimum.reduceat(vals, starts)
        rows[3][has] = np.maximum.reduceat(vals, starts)
    rows[0] = counts
    rows[1] = sums
    rows[4] = sumsq
    return rows


def _pad_agg_rows(rows: np.ndarray, doc_pad: int, base: int = 0,
                  out: np.ndarray | None = None) -> np.ndarray:
    """Place [5, D] rows at `base` inside a [5, doc_pad] canvas (empty slots:
    count 0, ±inf min/max)."""
    if out is None:
        out = np.zeros((5, doc_pad), dtype=np.float32)
        out[2] = np.inf
        out[3] = -np.inf
    out[:, base: base + rows.shape[1]] = rows
    return out


def ensure_agg_rows(seg: FrozenSegment, packed: PackedSegment, fields: list[str],
                    breaker=None):
    """Device-resident [F, 5, Dpad] stack for `fields`, or None when any column
    is not f32-exact (callers fall back to the host collectors). Per-field rows
    cache HOST-side; only the per-tuple device stacks (FIFO-bounded) hold device
    memory — mirroring ensure_mesh_agg_stack.

    `breaker` (fielddata) reserves the [F, 5, Dpad] f32 stack (host rows +
    device copy) before it is built — the per-doc fold columns are the
    fielddata-load analogue on this engine."""
    import jax.numpy as jnp

    key = tuple(fields)
    stack = packed.agg_stacks.get(key)
    if stack is not None:
        return stack
    est = len(fields) * 5 * packed.doc_pad * 4 * 2  # host rows + device stack
    with reserve(breaker, est, f"<agg_rows>{list(fields)}"):
        for f in fields:
            if f not in packed.agg_rows:
                rows = agg_doc_rows(seg, f)
                packed.agg_rows[f] = (None if rows is None
                                      else _pad_agg_rows(rows, packed.doc_pad))
        if any(packed.agg_rows[f] is None for f in fields):
            return None
        stack = jnp.asarray(np.stack([packed.agg_rows[f] for f in fields])
                            if fields else np.zeros((0, 5, packed.doc_pad), np.float32))
        while len(packed.agg_stacks) >= 8:
            packed.agg_stacks.pop(next(iter(packed.agg_stacks)))
        packed.agg_stacks[key] = stack
    return stack


TFN_BM25 = 0  # tfn = f / (f + cache[norm_byte])        — weight multiplies outside
TFN_TFIDF = 1  # tfn = sqrt(f) * cache[norm_byte]


def tfn_values(freqs: np.ndarray, nb: np.ndarray, cache: np.ndarray,
               mode: int) -> np.ndarray:
    """The per-posting tfn formula — the single definition shared by ensure_tfn and
    bench packing, so the bench provably measures the serving bake."""
    cv = cache[nb]
    if mode == TFN_BM25:
        return (freqs / (freqs + cv)).astype(np.float32)
    return np.sqrt(freqs, dtype=np.float32) * cv


def ensure_tfn(seg: FrozenSegment, packed: PackedSegment,
               tables: dict[str, tuple[int, np.ndarray]]) -> None:
    """Bake (or re-bake) the per-posting tfn tensor for the given per-field similarity
    tables ({field: (TFN_* mode, float32[256] cache)}).

    The bake folds the norm-byte lookup into the stored postings, which is what makes
    the sparse kernel gather-free. It must re-run when a field's cache table changes —
    for BM25 that is whenever avgdl (sum_ttf/max_doc) moves, i.e. after indexing
    activity; Lucene recomputes the same table per query (BM25Similarity's norm cache),
    we recompute per stats-change and reuse across queries. Cost: one numpy pass over
    the segment's postings + one HBM upload, amortized over every batch until the next
    stats change."""
    current = packed.tfn_tables
    if packed.blk_tfn is not None and all(
        f in current and current[f][0] == mode and current[f][1] == cache.tobytes()
        for f, (mode, cache) in tables.items()
    ):
        return
    import jax.numpy as jnp

    merged = dict(current)
    for f, (mode, cache) in tables.items():
        merged[f] = (mode, cache.tobytes())
    NBpad, B = packed.host_docs.shape[0] // BLOCK, BLOCK
    flat_docs = packed.host_docs
    flat_freqs = packed.host_freqs
    flat_tfn = np.zeros(NBpad * B, dtype=np.float32)
    fid_per_slot = np.repeat(packed.blk_field, B)
    for fo, fname in enumerate(packed.field_names):
        entry = merged.get(fname)
        if entry is None:
            continue
        mode, cache_bytes = entry
        cache = np.frombuffer(cache_bytes, dtype=np.float32)
        sel = (fid_per_slot == fo) & (flat_docs < seg.doc_count)
        if not sel.any():
            continue
        d = flat_docs[sel]
        f32 = flat_freqs[sel]
        norms = seg.norms.get(fname)
        nb = norms[d] if norms is not None else np.zeros(len(d), np.uint8)
        flat_tfn[sel] = tfn_values(f32, nb, cache, mode)
    packed.blk_tfn = jnp.asarray(flat_tfn.reshape(NBpad, B))
    packed.tfn_tables = merged


def packed_for(seg: FrozenSegment, breaker=None) -> PackedSegment:
    """Per-segment cached packing; refreshes the live mask when tombstones changed.

    `breaker` (the node's fielddata child) is consulted ONLY on a cache miss:
    the estimate covers the pack's host staging + device upload and is released
    once the pack lands — transient accounting, so a drained node reads 0.
    A trip raises CircuitBreakingError; serving falls back to the host scorer
    (the one graceful-degradation edge the reference lacks)."""
    cache = seg._device_cache
    packed: PackedSegment | None = cache.get("packed")
    if packed is None:
        with reserve(breaker, pack_estimate_bytes(seg), f"<segment_pack>[{seg.gen}]"):
            packed = pack_segment(seg)
        cache["packed"] = packed
        cache["live"] = True
    elif cache.get("live") is None:
        import jax.numpy as jnp

        live_parent = np.zeros(packed.doc_pad, dtype=bool)
        live_parent[: seg.doc_count] = seg.live & seg.parent_mask
        packed.live_parent = jnp.asarray(live_parent)
        # postings carry the live mask inline (sparse path has no per-posting
        # live gather) — re-mask from the raw host copy
        masked = np.where(live_parent[np.minimum(packed.host_docs, packed.doc_pad - 1)]
                          & (packed.host_docs < packed.doc_pad),
                          packed.host_docs, packed.doc_pad).astype(np.int32)
        packed.blk_docs = jnp.asarray(masked.reshape(-1, BLOCK))
        cache["live"] = True
    return packed
